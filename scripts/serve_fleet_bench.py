#!/usr/bin/env python
"""Serving-fleet benchmark: replica scaling, delta-push cost, SIGKILL chaos.

A simulated trainer (a thread walking master weights one outer epoch at
a time) feeds a DeltaPublisher; real subprocess replicas
(``python -m opendiloco_tpu.fleet.replica``) follow the staggered
delta-push channel; a FleetRouter spreads closed-loop client load over
them. Per fleet size the bench records sustained requests/s and
client-side p50/p99 latency; the largest arm runs the chaos leg with the
obs watchdogs armed: one replica is SIGKILLed mid-load, respawned at the
same address, and must rejoin through the router probe + the publisher's
hello-handshake keyframe — with ZERO client-visible drops.

Banks SERVE_FLEET_BENCH.json at the repo root
(``ODTP_SERVE_FLEET_BENCH_OUT`` overrides)::

    python scripts/serve_fleet_bench.py              # full run: 1/4/8 replicas
    python scripts/serve_fleet_bench.py --selftest   # CI run: 1/2 replicas

Gates (SystemExit on violation):
- zero dropped requests in every arm, including across the SIGKILL
- the killed replica rejoins and serves again before the arm ends
- per-epoch delta-push bytes <= 1/4 of the fp16 full-snapshot
  equivalent, per replica
- every ready replica's reported staleness stays within
  max_stale_rounds (sampled throughout the run)
- the dead-peer watchdog named the killed replica (chaos plane armed)
- prefix-directory leg: a shared system prompt is prefilled exactly once
  fleet-wide (every later request directory-routes to the holder and
  reuses the banked prefix K/V); SIGKILLing the holder invalidates its
  directory entries and traffic re-routes with zero drops
- full runs only: requests/s scales with the fleet (>= 0.5x linear)
"""
import argparse
import json
import os
import signal
import socket
import sys
import threading
import time
import urllib.request

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

_OUT = os.environ.get("ODTP_SERVE_FLEET_BENCH_OUT") or os.path.join(
    REPO, "SERVE_FLEET_BENCH.json"
)

SERVE_GEOM = {
    "num_slots": 4,
    "max_context": 128,
    "prefill_buckets": [16, 64],
    "max_queue": 1024,
    "prefix_cache": True,
}


def _healthz(port, timeout=2.0):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/healthz", timeout=timeout
    ) as r:
        return json.loads(r.read())


def _wait(pred, t, what):
    deadline = time.monotonic() + t
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.1)
    raise SystemExit(f"timed out waiting for {what}")


class SimTrainer:
    """Stands in for the DiLoCo trainer: one outer epoch every
    ``interval_s``, each a small random walk of the masters. snapshot_fn
    copies under the lock so pusher threads never see a torn epoch."""

    def __init__(self, model_cfg, interval_s):
        import jax

        from opendiloco_tpu.models.llama import init_params

        params = init_params(jax.random.PRNGKey(0), model_cfg)
        self.masters = [
            np.array(x, np.float32) for x in jax.tree.leaves(params)
        ]
        self.epoch = 0
        self.interval_s = interval_s
        self._rng = np.random.default_rng(0)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def snapshot(self):
        with self._lock:
            return self.epoch, [m.copy() for m in self.masters]

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            with self._lock:
                for m in self.masters:
                    m += self._rng.standard_normal(m.shape).astype(
                        np.float32
                    ) * 0.01
                self.epoch += 1

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5)


class ClientPool:
    """Closed-loop JSONL clients against the router front end. Every
    request is accounted: completed with tokens, or an error string —
    nothing may vanish."""

    def __init__(self, port, n_clients, model_cfg, max_new):
        self.port = port
        self.n = n_clients
        self.vocab = model_cfg.vocab_size
        self.max_new = max_new
        self.lock = threading.Lock()
        self.submitted = 0
        self.completed = 0
        self.latencies = []
        self.errors = []
        self._stop = threading.Event()
        self._threads = []

    def _loop(self, cid):
        r = np.random.default_rng(1000 + cid)
        sysp = list(range(10, 10 + 16))  # shared prefix: affinity fodder
        conn = None
        while not self._stop.is_set():
            try:
                if conn is None:
                    conn = socket.create_connection(
                        ("127.0.0.1", self.port), timeout=120
                    )
                if r.random() < 0.3:
                    prompt = sysp + r.integers(1, self.vocab, 4).tolist()
                else:
                    prompt = r.integers(
                        1, self.vocab, int(r.integers(3, 24))
                    ).tolist()
                payload = {
                    "prompt": prompt,
                    "max_new_tokens": int(r.integers(2, self.max_new + 1)),
                }
                with self.lock:
                    self.submitted += 1
                t0 = time.perf_counter()
                conn.sendall((json.dumps(payload) + "\n").encode())
                buf = b""
                while b"\n" not in buf:
                    chunk = conn.recv(65536)
                    if not chunk:
                        raise OSError("router closed the connection")
                    buf += chunk
                out = json.loads(buf.partition(b"\n")[0].decode())
                dt = time.perf_counter() - t0
                with self.lock:
                    if out.get("tokens"):
                        self.completed += 1
                        self.latencies.append(dt)
                    else:
                        self.errors.append(str(out.get("error", out))[:200])
            except (OSError, ValueError) as e:
                with self.lock:
                    self.errors.append(f"client {cid}: {e}")
                try:
                    if conn is not None:
                        conn.close()
                except OSError:
                    pass
                conn = None

    def start(self):
        self._threads = [
            threading.Thread(target=self._loop, args=(i,), daemon=True)
            for i in range(self.n)
        ]
        for t in self._threads:
            t.start()
        return self

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=180)

    def percentile_ms(self, q):
        with self.lock:
            lat = list(self.latencies)
        if not lat:
            return None
        return round(float(np.percentile(lat, q)) * 1e3, 3)


def spawn_fleet(model_cfg, args, n_replicas, *, serve_geom=None,
                prefix_directory=False):
    """Publisher + manager + router + n subprocess replicas, all ready."""
    from opendiloco_tpu.fleet import (
        DeltaPublisher,
        FleetManager,
        FleetRouter,
        spawn_replica,
    )

    sim = SimTrainer(model_cfg, args.epoch_interval).start()
    pub = DeltaPublisher(
        sim.snapshot,
        codec=args.codec,
        fragments=args.fragments,
        keyframe_every=args.keyframe_every,
    )
    router = FleetRouter(
        port=0,
        probe_interval_s=0.25,
        request_timeout=120.0,
        prefix_directory=prefix_directory,
    )
    mgr = FleetManager(pub, router, push_interval_s=args.push_interval)

    procs, infos = {}, {}
    spawn_errs = []

    def _spawn(i):
        rid = f"r{i}"
        try:
            procs[rid], infos[rid] = spawn_replica(
                rid,
                model_cfg,
                serve=serve_geom or SERVE_GEOM,
                max_stale_rounds=args.max_stale_rounds,
            )
        except Exception as e:  # noqa: BLE001 - surfaced as a gate below
            spawn_errs.append(f"{rid}: {e}")

    threads = [
        threading.Thread(target=_spawn, args=(i,)) for i in range(n_replicas)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if spawn_errs:
        raise SystemExit(f"replica spawn failed: {spawn_errs}")
    for rid, info in sorted(infos.items()):
        mgr.attach(
            rid, "127.0.0.1", info["serve_port"], "127.0.0.1",
            info["push_port"],
        )
    _wait(
        lambda: all(
            _probe_ready(infos[rid]["serve_port"]) for rid in infos
        ),
        180,
        f"{n_replicas} replicas onboarding from keyframes",
    )
    return sim, pub, router, mgr, procs, infos


def _probe_ready(port):
    try:
        return bool(_healthz(port).get("ready"))
    except (OSError, ValueError):
        return False


def _warm(infos, vocab):
    """Compile every replica's prefill buckets + decode path off the
    clock (each subprocess has a cold jit cache)."""

    def warm_one(port):
        for plen in (3, 20):
            body = json.dumps(
                {"prompt": list(range(1, plen + 1)), "max_new_tokens": 2}
            ).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate", data=body
            )
            with urllib.request.urlopen(req, timeout=300) as r:
                r.read()

    threads = [
        threading.Thread(target=warm_one, args=(info["serve_port"],))
        for info in infos.values()
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)


class StalenessMonitor:
    """Samples every ready replica's self-reported staleness through the
    run; the bound is an acceptance gate."""

    def __init__(self, infos, bound):
        self.infos = infos
        self.bound = bound
        self.max_seen = {}
        self.violations = []
        self._stop = threading.Event()
        self._paused = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def pause(self):
        """Suspend sampling (the chaos kill/rejoin window: a respawning
        replica's jit compile starves the host for a few seconds, and the
        stale flag flipping there is the designed behavior, not a bug)."""
        self._paused.set()

    def resume(self):
        self._paused.clear()

    def _loop(self):
        while not self._stop.wait(0.5):
            if self._paused.is_set():
                continue
            for rid, info in self.infos.items():
                try:
                    h = _healthz(info["serve_port"])
                except (OSError, ValueError):
                    continue  # dead/respawning: the chaos leg's business
                if not h.get("ready"):
                    continue
                st = int(h.get("staleness", 0))
                self.max_seen[rid] = max(self.max_seen.get(rid, 0), st)
                if st > self.bound:
                    self.violations.append((rid, st))

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5)


def run_chaos_leg(args, procs, infos, mgr, router, monitor):
    """SIGKILL one replica mid-load, respawn it at the same address, and
    wait for it to take traffic again. The clients never notice."""
    from opendiloco_tpu.fleet import spawn_replica

    victim = sorted(procs)[-1]
    info = infos[victim]
    monitor.pause()
    t_kill = time.perf_counter()
    procs[victim].send_signal(signal.SIGKILL)
    procs[victim].wait(timeout=30)
    _wait(
        lambda: router.stats()["replicas"][victim]["dead"],
        60,
        f"router noticing {victim} died",
    )
    time.sleep(args.down_s)  # serve the fleet short-handed for a while

    from opendiloco_tpu.models.llama import LlamaConfig

    model_cfg = LlamaConfig.from_dict(info["_model"])
    procs[victim], new_info = spawn_replica(
        victim,
        model_cfg,
        serve=SERVE_GEOM,
        max_stale_rounds=args.max_stale_rounds,
        serve_port=info["serve_port"],
        push_port=info["push_port"],
    )
    same_addr = (
        new_info["serve_port"] == info["serve_port"]
        and new_info["push_port"] == info["push_port"]
    )
    if not same_addr:
        # ports were not reusable (rare): re-register at the new address
        mgr.detach(victim)
        infos[victim] = {**new_info, "_model": info["_model"]}
        mgr.attach(
            victim, "127.0.0.1", new_info["serve_port"], "127.0.0.1",
            new_info["push_port"],
        )
    _wait(
        lambda: not router.stats()["replicas"][victim]["dead"]
        and _probe_ready(infos[victim]["serve_port"]),
        120,
        f"{victim} rejoining after respawn",
    )
    base = router.stats()["replicas"][victim]["dispatched"]
    _wait(
        lambda: router.stats()["replicas"][victim]["dispatched"] > base,
        60,
        f"{victim} taking traffic again",
    )
    time.sleep(1.0)  # let in-flight pushes settle before sampling resumes
    monitor.resume()
    return {
        "victim": victim,
        "same_address": same_addr,
        "downtime_s": round(time.perf_counter() - t_kill, 3),
        "rejoined": True,
    }


def _router_request(port, prompt, max_new):
    """One JSONL request through the router on a fresh connection."""
    with socket.create_connection(("127.0.0.1", port), timeout=120) as conn:
        conn.sendall(
            (
                json.dumps({"prompt": prompt, "max_new_tokens": max_new})
                + "\n"
            ).encode()
        )
        buf = b""
        while b"\n" not in buf:
            chunk = conn.recv(65536)
            if not chunk:
                raise OSError("router closed the connection")
            buf += chunk
    return json.loads(buf.partition(b"\n")[0].decode())


def run_prefix_leg(args, model_cfg, n_replicas) -> dict:
    """Fleet prefix-cache directory (PR 20): a shared system prompt is
    prefilled ONCE fleet-wide — the first request cold-prefills it, the
    replica banks the prefix K/V in its host tier and advertises the hash
    through its health frames, and the router's directory sends every
    later shared-prefix request to the holder, which reuses the pages.
    SIGKILLing the holder must drop its directory entries and re-route
    the traffic to the survivors with zero drops."""
    sim, pub, router, mgr, procs, infos = spawn_fleet(
        model_cfg, args, n_replicas,
        serve_geom={
            **SERVE_GEOM,
            "kv_tier": True,
            "kv_host_slots": 16,
            # shared prefix (64) + unique suffix (8) needs a bucket past
            # the load-arm geometry's 64
            "prefill_buckets": [16, 64, 96],
        },
        prefix_directory=True,
    )
    try:
        # freeze the outer loop: prefix K/V is invalidated on every weight
        # swap (by design — cached pages must match the serving epoch), and
        # the sim trainer's 1 s epochs would purge entries faster than any
        # client could reuse them. Real fleets reuse a system prompt within
        # an outer epoch, which is minutes long; the swap-invalidation path
        # itself is pinned by the kv-tier unit tests.
        sim.stop()
        _warm(infos, model_cfg.vocab_size)
        rng = np.random.default_rng(7)
        shared = rng.integers(1, model_cfg.vocab_size, 64).tolist()

        def ask(seed):
            sr = np.random.default_rng(4000 + seed)
            prompt = shared + sr.integers(1, model_cfg.vocab_size, 8).tolist()
            out = _router_request(router.port, prompt, args.max_new)
            if not out.get("tokens"):
                raise SystemExit(f"prefix leg: request {seed} failed: {out}")

        def fleet_prefix_stats():
            per = {}
            for rid, info in infos.items():
                try:
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{info['serve_port']}/stats",
                        timeout=5,
                    ) as r:
                        s = json.loads(r.read())
                except (OSError, ValueError):
                    continue  # dead (the kill phase's business)
                tier = s.get("tier") or {}
                per[rid] = {
                    "hits": s["prefix"]["hits"] + s["prefix"]["host_hits"],
                    "stores": tier.get("prefix_stores", 0),
                }
            return per

        def dir_entries():
            return (router.stats()["prefix_directory"] or {}).get("entries", 0)

        # let the warm prompts' own prefix advertisements settle so the
        # seed request's entry is measured against a quiet baseline
        time.sleep(args.push_interval * 2 + 0.5)
        entries0 = dir_entries()
        base = fleet_prefix_stats()

        # -- seed: ONE cold prefill of the shared prompt, fleet-wide ------
        ask(0)
        _wait(
            lambda: dir_entries() > entries0,
            30,
            "the seeded prefix reaching the router directory",
        )
        seeded = fleet_prefix_stats()
        seed_stores = {
            rid: seeded[rid]["stores"] - base[rid]["stores"] for rid in seeded
        }
        holders = [rid for rid, n in seed_stores.items() if n > 0]

        # -- flood: every request must reuse the seeded prefill -----------
        flood_n = 12
        for i in range(1, flood_n + 1):
            ask(i)
        flooded = fleet_prefix_stats()
        flood_hits = sum(
            flooded[rid]["hits"] - seeded[rid]["hits"] for rid in flooded
        )
        flood_stores = sum(
            flooded[rid]["stores"] - seeded[rid]["stores"] for rid in flooded
        )
        rstats = router.stats()
        dir_hits = (rstats["prefix_directory"] or {}).get("hits", 0)

        # -- kill the holder: entries drop, traffic re-routes -------------
        victim = holders[0] if holders else sorted(infos)[0]
        entries_before_kill = dir_entries()
        procs[victim].send_signal(signal.SIGKILL)
        procs[victim].wait(timeout=30)
        _wait(
            lambda: router.stats()["replicas"][victim]["dead"],
            60,
            f"router noticing prefix holder {victim} died",
        )
        entries_after_kill = dir_entries()
        refill_n = 6
        ask(100)  # re-seeds the prefix on a survivor (zero drops: ask()
        # raises on any error). Wait for the survivor's advertisement so
        # the remaining traffic routes by directory, not by luck.
        _wait(
            lambda: dir_entries() > entries_after_kill,
            30,
            "a survivor advertising the re-seeded prefix",
        )
        for i in range(101, 100 + refill_n):
            ask(i)
        refilled = fleet_prefix_stats()
        refill_stores = sum(
            refilled[rid]["stores"] - flooded[rid]["stores"]
            for rid in refilled
        )
        return {
            "replicas": n_replicas,
            "shared_prefix_tokens": len(shared),
            "holder": victim,
            "seed_stores": sum(seed_stores.values()),
            "flood": {
                "requests": flood_n,
                "prefix_hits": flood_hits,
                "cold_stores": flood_stores,
                "directory_hits": dir_hits,
            },
            "kill": {
                "directory_entries_before": entries_before_kill,
                "directory_entries_after": entries_after_kill,
                "rerouted_requests": refill_n,
                "reroute_stores": refill_stores,
            },
        }
    finally:
        mgr.stop()
        router.stop()
        sim.stop()
        for p in procs.values():
            try:
                p.kill()
                p.wait(timeout=10)
            except OSError:
                pass


def run_arm(args, model_cfg, n_replicas, with_chaos) -> dict:
    from opendiloco_tpu import obs
    from opendiloco_tpu.obs import reqtrace

    obs.reset()  # counters cover this arm only
    sim, pub, router, mgr, procs, infos = spawn_fleet(
        model_cfg, args, n_replicas
    )
    for rid in infos:
        infos[rid]["_model"] = model_cfg.to_dict()
    chaos = None
    try:
        _warm(infos, model_cfg.vocab_size)
        monitor = StalenessMonitor(infos, args.max_stale_rounds).start()
        clients = ClientPool(
            router.port, args.clients_per_replica * n_replicas,
            model_cfg, args.max_new,
        ).start()
        t0 = time.perf_counter()
        if with_chaos:
            time.sleep(args.duration * 0.25)  # steady-state first
            chaos = run_chaos_leg(args, procs, infos, mgr, router, monitor)
        deadline = t0 + args.duration
        while time.perf_counter() < deadline:
            time.sleep(0.2)
        clients.stop()
        elapsed = time.perf_counter() - t0
        monitor.stop()

        rstats = router.stats()
        pstats = pub.stats()
        tr = obs.tracer()
        # tracer counter keys are (name, ((label, value), ...)) tuples;
        # fold label sets together per counter name
        counters: dict = {}
        if tr is not None:
            for (cname, _labels), v in tr.counters().items():
                counters[cname] = counters.get(cname, 0) + v
        arm = {
            "replicas": n_replicas,
            "clients": clients.n,
            "duration_s": round(elapsed, 3),
            "requests_per_s": round(clients.completed / elapsed, 3),
            "completed": clients.completed,
            "submitted": clients.submitted,
            "dropped": clients.submitted - clients.completed
            - len(clients.errors),
            "client_errors": clients.errors[:5],
            "latency_ms": {
                "p50": clients.percentile_ms(50),
                "p99": clients.percentile_ms(99),
            },
            "router": {
                "redispatches": rstats["redispatches"],
                "deaths": rstats["deaths"],
                "dispatched": {
                    rid: b["dispatched"]
                    for rid, b in rstats["replicas"].items()
                },
                "affinity_hits": sum(
                    v
                    for k, v in counters.items()
                    if k.startswith("fleet_router_affinity_hits")
                ),
            },
            "staleness": {
                "bound": args.max_stale_rounds,
                "max_seen": monitor.max_seen,
                "violations": monitor.violations[:5],
            },
            "delta_push": _delta_accounting(pstats),
            "trainer_epochs": sim.epoch,
        }
        rt = reqtrace.ring()
        if rt is not None:
            # the router runs in THIS process, so its ring holds one
            # trace per dispatched request — including requests whose
            # first replica was SIGKILLed (same id, redispatches >= 1)
            traces = rt.traces()
            arm["reqtrace"] = {
                "completed": len(traces),
                "evicted": rt.evicted,
                "statuses": rt.report()["statuses"],
                "redispatched_traces": sum(
                    1 for t in traces
                    if (t.get("attrs") or {}).get("redispatches", 0) > 0
                ),
                "dangling_inflight": rt.inflight_ids(),
            }
        if chaos is not None:
            chaos["dead_peer_watchdog_tripped"] = any(
                k.startswith("anomaly_dead_peer") for k in counters
            )
            arm["chaos"] = chaos
        return arm
    finally:
        mgr.stop()
        router.stop()
        sim.stop()
        for p in procs.values():
            try:
                p.kill()
                p.wait(timeout=10)
            except OSError:
                pass


def _delta_accounting(pstats) -> dict:
    per = {}
    worst = 0.0
    for rid, ch in pstats["replicas"].items():
        if ch["delta_frames"]:
            ratio = (
                ch["delta_bytes"]
                / ch["delta_frames"]
                / pstats["fp16_snapshot_bytes"]
            )
            worst = max(worst, ratio)
        else:
            ratio = None
        per[rid] = {
            "delta_bytes": ch["delta_bytes"],
            "delta_frames": ch["delta_frames"],
            "keyframe_bytes": ch["keyframe_bytes"],
            "keyframe_frames": ch["keyframe_frames"],
            "delta_ratio_per_epoch": None
            if ratio is None
            else round(ratio, 5),
        }
    return {
        "fp16_snapshot_bytes": pstats["fp16_snapshot_bytes"],
        "codec": pstats["codec"],
        "keyframe_codec": pstats["keyframe_codec"],
        "per_replica": per,
        "max_delta_ratio_per_epoch": round(worst, 5),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--selftest", action="store_true",
                    help="tiny CI run: 1/2 replicas, artifact under $TMPDIR")
    ap.add_argument("--replicas", default="1,4,8",
                    help="comma-separated fleet sizes to sweep")
    ap.add_argument("--duration", type=float, default=30.0,
                    help="seconds of sustained load per arm")
    ap.add_argument("--clients-per-replica", type=int, default=2)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--max-new", type=int, default=8)
    ap.add_argument("--epoch-interval", type=float, default=1.0,
                    help="seconds per simulated outer epoch")
    ap.add_argument("--push-interval", type=float, default=0.25)
    ap.add_argument("--codec", default="blockwise4bit")
    ap.add_argument("--fragments", type=int, default=4)
    ap.add_argument("--keyframe-every", type=int, default=8)
    ap.add_argument("--max-stale-rounds", type=int, default=2)
    ap.add_argument("--down-s", type=float, default=2.0,
                    help="seconds the SIGKILLed replica stays down")
    args = ap.parse_args()

    out_path = _OUT
    if args.selftest:
        args.replicas = "1,2"
        args.duration = min(args.duration, 10.0)
        out_path = os.path.join(
            os.environ.get("TMPDIR", "/tmp"), "SERVE_FLEET_BENCH.selftest.json"
        )
    sizes = [int(x) for x in args.replicas.split(",") if x.strip()]

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("ODTP_OBS", "fleet-bench")  # chaos plane armed
    # big completed ring: post-kill traffic must not evict the SIGKILL
    # victims' traces before the gates inspect them
    os.environ.setdefault("ODTP_REQTRACE_CAP", "8192")

    from opendiloco_tpu.models.llama import LlamaConfig

    model_cfg = LlamaConfig(
        vocab_size=256,
        hidden_size=args.hidden,
        intermediate_size=args.hidden * 2,
        num_hidden_layers=args.layers,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=256,
    )

    arms = {}
    for n in sizes:
        print(f"=== arm: {n} replica(s) ===")
        arms[str(n)] = run_arm(args, model_cfg, n, with_chaos=False)
        print(
            f"    {arms[str(n)]['requests_per_s']} req/s, "
            f"p99 {arms[str(n)]['latency_ms']['p99']} ms, "
            f"dropped {arms[str(n)]['dropped']}"
        )

    # chaos is its own arm so scaling numbers don't absorb the downtime
    chaos_arm = None
    chaos_n = max(max(sizes), 2)
    print(f"=== chaos arm: {chaos_n} replicas + SIGKILL ===")
    chaos_arm = run_arm(args, model_cfg, chaos_n, with_chaos=True)
    print(
        f"    {chaos_arm['requests_per_s']} req/s through the kill, "
        f"dropped {chaos_arm['dropped']}, "
        f"downtime {chaos_arm['chaos']['downtime_s']}s"
    )

    prefix_n = 2 if args.selftest else 3
    print(f"=== prefix-directory leg: {prefix_n} replicas ===")
    prefix_arm = run_prefix_leg(args, model_cfg, prefix_n)
    print(
        f"    seed_stores={prefix_arm['seed_stores']} "
        f"flood_hits={prefix_arm['flood']['prefix_hits']}/"
        f"{prefix_arm['flood']['requests']} "
        f"cold_stores={prefix_arm['flood']['cold_stores']} "
        f"reroute_stores={prefix_arm['kill']['reroute_stores']}"
    )

    base = arms[str(sizes[0])]["requests_per_s"] / sizes[0]
    scaling = {
        str(n): round(arms[str(n)]["requests_per_s"] / base, 3) if base else None
        for n in sizes
    }
    doc = {
        "schema": 1,
        "selftest": bool(args.selftest),
        "host": {"node": os.uname().nodename, "cpus": os.cpu_count()},
        "updated": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "model": {
            "hidden": model_cfg.hidden_size,
            "layers": model_cfg.num_hidden_layers,
            "vocab": model_cfg.vocab_size,
            "params": int(model_cfg.num_params()),
        },
        "fleet": {
            "codec": args.codec,
            "fragments": args.fragments,
            "keyframe_every": args.keyframe_every,
            "push_interval_s": args.push_interval,
            "epoch_interval_s": args.epoch_interval,
            "max_stale_rounds": args.max_stale_rounds,
        },
        "arms": arms,
        "chaos_arm": chaos_arm,
        "prefix_directory_arm": prefix_arm,
        "scaling_speedup": scaling,
    }
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
    print(f"wrote {out_path}")
    print("scaling:", json.dumps(scaling))

    # -- gates ---------------------------------------------------------------
    # every arm (clean + chaos): zero drops/errors, staleness within bound
    # (the chaos arm's monitor is paused across the kill/rejoin window — the
    # stale flag flipping there is designed behavior, not a violation), and
    # delta pushes <= 1/4 of the fp16 snapshot equivalent per epoch.
    for n, arm in {**arms, "chaos": chaos_arm}.items():
        if arm["dropped"] != 0:
            raise SystemExit(
                f"arm {n}: {arm['dropped']} requests vanished — acceptance is 0"
            )
        if arm["client_errors"]:
            raise SystemExit(f"arm {n}: client errors {arm['client_errors']}")
        if arm["staleness"]["violations"]:
            raise SystemExit(
                f"arm {n}: staleness bound exceeded: "
                f"{arm['staleness']['violations']}"
            )
        ratio = arm["delta_push"]["max_delta_ratio_per_epoch"]
        if ratio > 0.25:
            raise SystemExit(
                f"arm {n}: delta push {ratio} of an fp16 snapshot per epoch "
                "— acceptance is <= 0.25"
            )
        rq = arm.get("reqtrace")
        if rq:
            if rq["dangling_inflight"]:
                raise SystemExit(
                    f"arm {n}: request traces never terminated: "
                    f"{rq['dangling_inflight'][:5]} — every dispatch "
                    "(served, shed, or interrupted by SIGKILL) must finish "
                    "its trace"
                )
            if arm["router"]["redispatches"] > 0 and not rq[
                    "redispatched_traces"]:
                raise SystemExit(
                    f"arm {n}: router redispatched "
                    f"{arm['router']['redispatches']} request(s) but no "
                    "trace records a redispatch — a killed request's "
                    "history was lost across mark-dead -> re-dispatch"
                )
    chaos = chaos_arm["chaos"]
    if not chaos["rejoined"]:
        raise SystemExit("chaos arm: SIGKILLed replica never rejoined")
    if not chaos["dead_peer_watchdog_tripped"]:
        raise SystemExit("chaos arm: dead-peer watchdog never named the victim")
    # prefix-directory leg: the shared prompt was prefilled exactly once
    # fleet-wide, every flood request reused it via the directory, and the
    # holder's death dropped its entries and re-routed traffic (ask()
    # raised on any dropped/errored request, so reaching here = 0 drops)
    pfx = prefix_arm
    if pfx["seed_stores"] != 1:
        raise SystemExit(
            f"prefix leg: shared prompt cold-prefilled {pfx['seed_stores']} "
            "time(s) at seed — acceptance is exactly once fleet-wide"
        )
    if pfx["flood"]["cold_stores"] != 0:
        raise SystemExit(
            f"prefix leg: {pfx['flood']['cold_stores']} flood request(s) "
            "re-prefilled the shared prompt — every one must reuse the "
            "seeded prefill"
        )
    if pfx["flood"]["prefix_hits"] < pfx["flood"]["requests"]:
        raise SystemExit(
            f"prefix leg: only {pfx['flood']['prefix_hits']} of "
            f"{pfx['flood']['requests']} flood requests hit the cached "
            "prefix"
        )
    if pfx["flood"]["directory_hits"] < pfx["flood"]["requests"]:
        raise SystemExit(
            f"prefix leg: router directory routed only "
            f"{pfx['flood']['directory_hits']} of "
            f"{pfx['flood']['requests']} flood requests to the holder"
        )
    if pfx["kill"]["directory_entries_after"] >= pfx["kill"][
            "directory_entries_before"]:
        raise SystemExit(
            "prefix leg: the SIGKILLed holder's directory entries were "
            "not invalidated"
        )
    if pfx["kill"]["reroute_stores"] != 1:
        raise SystemExit(
            f"prefix leg: post-kill traffic re-prefilled the shared "
            f"prompt {pfx['kill']['reroute_stores']} time(s) on the "
            "survivors — acceptance is exactly once"
        )
    if not args.selftest and len(sizes) > 1:
        # ~linear scaling, honestly bounded by the host: N replicas cannot
        # beat the core count on a CPU rig, so the expectation is
        # min(N, cpus) and the artifact records both.
        top = sizes[-1]
        expect = min(top, os.cpu_count() or 1)
        if scaling[str(top)] < 0.5 * expect:
            raise SystemExit(
                f"requests/s at {top} replicas is {scaling[str(top)]}x the "
                f"1-replica arm — acceptance is >= {0.5 * expect}x "
                f"(~linear up to {os.cpu_count()} cores)"
            )
    print("all gates passed")


if __name__ == "__main__":
    main()
