"""Continuous-batching scheduler: request queue + the decode loop.

One daemon thread owns the engine and runs the classic continuous-
batching cycle — retire finished sequences (slots free immediately),
admit queued prompts into free slots (prefill joins them to the running
batch), take one decode step for every live slot, and between decode
steps give the engine a chance to hot-swap weights. Requests are queued
by any thread via :meth:`ContinuousBatcher.submit` and signal completion
through a per-request event; nothing is ever dropped by the scheduler —
a request either completes, is rejected at submit time (prompt too long
/ queue full), or is failed explicitly when the server is torn down
mid-flight.
"""
from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Optional, Sequence

import numpy as np

from opendiloco_tpu import obs
from opendiloco_tpu.obs import reqtrace
from opendiloco_tpu.ops.attention import ring_live_rows
from opendiloco_tpu.serve.engine import ServeEngine
from opendiloco_tpu.serve.kvcache import (
    HostKVTier,
    SlotAllocator,
    common_prefix_len,
    pick_bucket,
    prefix_grid_lengths,
    prefix_key,
)

# a reused prefix must be worth the copy: below this many shared tokens
# the batcher prefills cold (the suffix pass would cover ~the whole
# prompt anyway)
MIN_PREFIX_TOKENS = 4

# slot evictions started per scheduler iteration: bounds how much page-out
# work one pass can stack between decode steps, so a long queue drains the
# batch gradually instead of stalling a whole step on D2H traffic
EVICT_PER_PASS = 2


@dataclasses.dataclass
class Request:
    prompt: list
    max_new_tokens: int
    eos_id: Optional[int] = None
    id: int = 0
    # admission control: lower tier = more important (0 interactive);
    # t_deadline is absolute time.monotonic() — a queued request past it
    # is doomed (its client gave up) and is shed instead of decoded
    priority: int = 0
    t_deadline: Optional[float] = None
    t_submit: float = 0.0
    t_first: Optional[float] = None
    t_done: Optional[float] = None
    tokens: list = dataclasses.field(default_factory=list)
    error: Optional[str] = None
    epoch: Optional[int] = None  # weights epoch that finished the request
    cancelled: bool = False
    # request-trace id in this process's reqtrace ring (None = untraced)
    trace: Optional[str] = None
    _done: threading.Event = dataclasses.field(default_factory=threading.Event)

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def cancel(self) -> None:
        """Ask the loop to retire this request (client went away). The
        slot frees on the next scheduler iteration — decoding stops
        instead of running the remaining tokens into a dead socket."""
        self.cancelled = True

    def finish(self, error: Optional[str] = None) -> None:
        self.error = error
        self.t_done = time.perf_counter()
        self._done.set()

    @property
    def latency_s(self) -> Optional[float]:
        return None if self.t_done is None else self.t_done - self.t_submit

    @property
    def ttft_s(self) -> Optional[float]:
        return None if self.t_first is None else self.t_first - self.t_submit


@dataclasses.dataclass
class _Slot:
    req: Request
    cache_len: int  # tokens in the ring page (absolute position of next write)
    last_token: int
    # decode steps since this tenancy began (admit or tier restore): the
    # eviction policy's coldness signal AND its thrash guard
    resident_steps: int = 0


@dataclasses.dataclass
class _Paused:
    """A live request whose ring page lives in the host tier: everything
    needed to resume decode exactly where it stopped, minus the K/V
    (which :class:`HostKVTier` holds keyed by ``req.id``)."""

    req: Request
    cache_len: int
    last_token: int


class ContinuousBatcher:
    def __init__(
        self,
        engine: ServeEngine,
        *,
        max_queue: int = 1024,
        swap_every_steps: int = 16,
        gauge_every_steps: int = 32,
        prefix_cache: bool = False,
        kv_tier: Optional[HostKVTier] = None,
        tier_quantum_steps: int = 8,
        tier_min_resident_steps: int = 2,
    ):
        self.engine = engine
        self.max_queue = int(max_queue)
        self.swap_every_steps = max(1, int(swap_every_steps))
        self.gauge_every_steps = max(1, int(gauge_every_steps))
        self.prefix_cache = bool(prefix_cache)
        # host-memory cold tier (None = today's all-resident behavior,
        # bit-identical). quantum = steps a RESUMED/long-resident slot is
        # guaranteed before a paused peer may displace it (round-robin
        # time-slicing period); min_resident = floor before a QUEUED
        # request may displace anyone (TTFT pressure evicts sooner, but
        # never a slot that has not decoded at all)
        self.kv_tier = kv_tier
        self.tier_quantum_steps = max(1, int(tier_quantum_steps))
        self.tier_min_resident_steps = max(1, int(tier_min_resident_steps))
        self.spec_decode = engine.spec_k > 0
        self._kernel_probed = False
        self.slots = SlotAllocator(engine.num_slots)
        self._active: dict[int, _Slot] = {}  # slot id -> state
        self._queue: collections.deque[Request] = collections.deque()
        self._cond = threading.Condition()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._next_id = 0
        self.decode_steps = 0
        self._t_step_end: Optional[float] = None
        # stats (mutated only by the loop thread; read racily for gauges)
        self.completed = 0
        self.rejected = 0
        self.failed = 0
        self.cancelled = 0
        self.shed = 0  # deadline-doomed requests dropped unserved
        self.total_new_tokens = 0
        # EWMA of completed-request latency: the wait estimate behind
        # Retry-After hints and the router's admission floor
        self._lat_ewma: Optional[float] = None
        self._latencies: collections.deque = collections.deque(maxlen=4096)
        self._ttfts: collections.deque = collections.deque(maxlen=4096)
        self.staleness_hist: collections.Counter = collections.Counter()
        self._rate_mark = (time.perf_counter(), 0)
        self.loop_error: Optional[str] = None
        # speculative-decode accounting (loop thread only)
        self.spec_proposed = 0
        self.spec_accepted = 0
        # shared-prefix reuse accounting (live-slot ring copies + host
        # tier restores; host_prefix_hits is the tier subset)
        self.prefix_hits = 0
        self.prefix_tokens_saved = 0
        self.host_prefix_hits = 0
        # KV-tier state (loop thread only): paused requests FIFO by pause
        # time, page-outs whose D2H copy is still in flight, and prefix
        # snapshots waiting to be encoded into the tier
        self._paused: "collections.OrderedDict[int, _Paused]" = (
            collections.OrderedDict()
        )
        self._pending_evict: list = []
        self._pending_prefix: list = []
        self.evictions = 0
        self.resumes = 0
        self.paused_peak = 0

    # -- client API --------------------------------------------------------

    def submit(
        self,
        prompt: Sequence[int],
        max_new_tokens: int = 16,
        eos_id: Optional[int] = None,
        priority: int = 0,
        deadline_ms: Optional[float] = None,
        trace: Optional[dict] = None,
    ) -> Request:
        """Queue a prompt; returns a Request whose ``wait()`` unblocks when
        generation completes (or it was rejected — check ``error``).

        ``deadline_ms`` is the remaining client budget: the scheduler
        orders the queue by (priority, deadline) and sheds a request
        whose deadline expires before it reaches a slot — the doomed
        never delay the in-SLO.

        ``trace`` is an optional request-trace context (schema
        TRACE_CTX_KEY shape) adopted into this process's reqtrace ring;
        every lifecycle stage the request passes — queue wait, prefill,
        decode steps, swaps, terminal — is recorded under it."""
        req = Request(
            prompt=[int(t) for t in prompt],
            max_new_tokens=int(max_new_tokens),
            eos_id=eos_id,
            priority=int(priority),
            t_deadline=(
                None
                if deadline_ms is None
                else time.monotonic() + float(deadline_ms) / 1e3
            ),
            t_submit=time.perf_counter(),
        )
        rt = reqtrace.ring()
        if rt is not None and trace is not None:
            req.trace = rt.adopt(
                trace, priority=req.priority, deadline_ms=deadline_ms
            )
        if req.t_deadline is not None and float(deadline_ms) <= 0:
            self.shed += 1
            obs.count("serve_shed", reason="deadline")
            req.finish("deadline exceeded")
            self._trace_terminal(req, "shed", "shed", reason="deadline")
            return req
        if not req.prompt:
            self.rejected += 1
            req.finish("empty prompt")
            self._trace_terminal(req, "retire", "failed", error=req.error)
            return req
        if not self.engine.prompt_fits(len(req.prompt)):
            self.rejected += 1
            req.finish(
                f"prompt length {len(req.prompt)} exceeds max prefill bucket"
            )
            self._trace_terminal(req, "retire", "failed", error=req.error)
            return req
        if req.max_new_tokens < 1:
            self.rejected += 1
            req.finish("max_new_tokens must be >= 1")
            self._trace_terminal(req, "retire", "failed", error=req.error)
            return req
        with self._cond:
            if self._stop.is_set():
                self.rejected += 1
                req.finish("server stopped")
                self._trace_terminal(req, "shed", "shed", reason="stopped")
                return req
            if len(self._queue) >= self.max_queue:
                self.rejected += 1
                req.finish("queue full")
                self._trace_terminal(req, "shed", "shed", reason="queue_full")
                return req
            req.id = self._next_id
            self._next_id += 1
            self._queue.append(req)
            self._cond.notify()
        return req

    @staticmethod
    def _trace_terminal(
        req: Request, stage: str, status: str, **attrs
    ) -> None:
        """Close ``req``'s trace with a zero-width terminal stage event."""
        if req.trace is None:
            return
        rt = reqtrace.ring()
        if rt is None:
            return
        rt.event(req.trace, stage, **attrs)
        rt.finish(req.trace, status, tokens=len(req.tokens), **attrs)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ContinuousBatcher":
        self._thread = threading.Thread(
            target=self._run, name="odtp-serve-batcher", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
        # fail whatever is still in flight so no client blocks forever
        with self._cond:
            pending = list(self._queue)
            self._queue.clear()
        for req in pending:
            self.failed += 1
            req.finish("server stopped")
            self._trace_terminal(req, "retire", "failed", error=req.error)
        for st in self._active.values():
            self.failed += 1
            st.req.finish("server stopped")
            self._trace_terminal(st.req, "retire", "failed", error=st.req.error)
        self._active.clear()
        self._fail_cold("server stopped")

    def _fail_cold(self, error: str) -> None:
        """Fail every tier-resident request (paused or mid-page-out) so no
        client blocks forever on teardown/loop death."""
        for st, _pk, _pv, _t0 in self._pending_evict:
            self.failed += 1
            st.req.finish(error)
            self._trace_terminal(st.req, "retire", "failed", error=error)
        self._pending_evict.clear()
        for p in self._paused.values():
            if self.kv_tier is not None:
                self.kv_tier.drop_paused(p.req.id)
            self.failed += 1
            p.req.finish(error)
            self._trace_terminal(p.req, "retire", "failed", error=error)
        self._paused.clear()

    def drain(self, timeout: float = 60.0) -> bool:
        """Block until queue, batch, and cold tier are empty (bench
        teardown)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._cond:
                if (
                    not self._queue
                    and not self._active
                    and not self._paused
                    and not self._pending_evict
                ):
                    return True
            time.sleep(0.01)
        return False

    # -- the decode loop ---------------------------------------------------

    def _run(self) -> None:
        try:
            t_carry = None
            while not self._stop.is_set():
                # consecutive decode spans TILE: each starts where the
                # previous iteration's accounting ended, so everything an
                # inflight request sat through this iteration — sweeps,
                # queue checks, a co-tenant's admission prefill, retires,
                # gauges — is attributed to its decode residency and a
                # trace's stage sums reconcile with its e2e latency
                it0 = t_carry if t_carry is not None else time.perf_counter()
                self._sweep_cancelled()
                # page-outs started LAST iteration finalize here: their
                # D2H copies overlapped the decode step in between, so
                # the np materialization below is (near-)free
                self._finish_pageouts()
                admitted = self._admit()
                stepped = self._decode(it0)
                if stepped:
                    self.decode_steps += 1
                    if self.decode_steps % self.swap_every_steps == 0:
                        self._maybe_swap()
                    if self.decode_steps % self.gauge_every_steps == 0:
                        self._publish_gauges()
                t_carry = self._t_step_end if stepped else None
                if not admitted and not stepped:
                    # idle: still honor the staleness bound, then sleep
                    self._maybe_swap()
                    with self._cond:
                        if not self._queue and not self._stop.is_set():
                            self._cond.wait(timeout=0.05)
        except Exception as e:  # noqa: BLE001 — fail loudly, never hang clients
            self.loop_error = f"{type(e).__name__}: {e}"
            for slot, st in list(self._active.items()):
                self._retire(st, error=self.loop_error)
                self.slots.free(slot)
            self._active.clear()
            self._fail_cold(self.loop_error)
            with self._cond:
                pending = list(self._queue)
                self._queue.clear()
            for req in pending:
                self.failed += 1
                req.finish(self.loop_error)
                self._trace_terminal(req, "retire", "failed", error=req.error)

    def _maybe_swap(self) -> None:
        """Hot-swap check; a swap that actually happened is a pause every
        in-flight request sat through, so its duration is recorded as a
        ``swap`` span on every traced active request."""
        t0 = time.perf_counter()
        swapped = self.engine.maybe_swap()
        t1 = time.perf_counter()
        if not swapped:
            return
        if self.kv_tier is not None:
            # prefix K/V was computed under the old weights: entries at a
            # stale epoch must never serve (or be advertised) again
            self.kv_tier.purge_stale(self.engine.weights_epoch)
        rt = reqtrace.ring()
        if rt is None:
            return
        for st in self._active.values():
            if st.req.trace is not None:
                rt.span(
                    st.req.trace, "swap", t0, t1,
                    epoch=self.engine.weights_epoch,
                )

    def _sweep_cancelled(self) -> None:
        """Retire cancelled and deadline-expired requests: queued ones
        finish immediately, active ones free their slot before the next
        decode step (a client past its deadline is gone — decoding its
        remaining tokens only starves the in-SLO batch)."""
        now = time.monotonic()

        def expired(req: Request) -> bool:
            return req.t_deadline is not None and now > req.t_deadline

        with self._cond:
            if any(r.cancelled or expired(r) for r in self._queue):
                keep: collections.deque = collections.deque()
                for req in self._queue:
                    if req.cancelled:
                        self.cancelled += 1
                        req.finish("cancelled")
                        obs.count("serve_cancelled")
                        self._trace_terminal(req, "retire", "cancelled")
                    elif expired(req):
                        self.shed += 1
                        req.finish("deadline exceeded")
                        obs.count("serve_shed", reason="deadline")
                        self._trace_terminal(
                            req, "shed", "shed", reason="deadline"
                        )
                    else:
                        keep.append(req)
                self._queue = keep
        gone = [
            s
            for s, st in self._active.items()
            if st.req.cancelled or expired(st.req)
        ]
        for slot in gone:
            st = self._active.pop(slot)
            self.slots.free(slot)
            st.req.epoch = self.engine.weights_epoch
            if st.req.cancelled:
                self.cancelled += 1
                st.req.finish("cancelled")
                obs.count("serve_cancelled")
                self._trace_terminal(st.req, "retire", "cancelled")
            else:
                self.shed += 1
                st.req.finish("deadline exceeded")
                obs.count("serve_shed", reason="deadline")
                self._trace_terminal(st.req, "shed", "shed", reason="deadline")
        # paused (tier-resident) requests: same sweep, plus the tier page
        # is dropped — a dead client's cold state never pins host budget
        cold_gone = [
            rid
            for rid, p in self._paused.items()
            if p.req.cancelled or expired(p.req)
        ]
        for rid in cold_gone:
            p = self._paused.pop(rid)
            if self.kv_tier is not None:
                self.kv_tier.drop_paused(rid)
            if p.req.cancelled:
                self.cancelled += 1
                p.req.finish("cancelled")
                obs.count("serve_cancelled")
                self._trace_terminal(p.req, "retire", "cancelled")
            else:
                self.shed += 1
                p.req.finish("deadline exceeded")
                obs.count("serve_shed", reason="deadline")
                self._trace_terminal(p.req, "shed", "shed", reason="deadline")

    def _find_prefix(self, prompt: list) -> tuple[Optional[int], int]:
        """Longest usable shared prompt prefix among the live slots.

        A source qualifies while its ring has not wrapped (rows < plen
        still hold the prefix K/V) — ``tail_width`` of headroom keeps the
        next spec tail from wrapping before the copy lands. The reused
        length is capped one short of the prompt so the suffix pass always
        has at least the final token to run (its logits seed decode)."""
        best_src, best = None, 0
        for slot, st in self._active.items():
            if (
                st.cache_len + self.engine.tail_width
                > self.engine.max_context
            ):
                continue
            p = common_prefix_len(prompt, st.req.prompt)
            p = min(p, len(prompt) - 1)
            if p > best:
                best_src, best = slot, p
        if best >= MIN_PREFIX_TOKENS:
            return best_src, best
        return None, 0

    def _pop_next(self) -> Optional[Request]:
        """Most urgent queued request: lowest priority tier first, then
        earliest deadline (deadline-free requests after deadlined ones of
        the same tier), then submit order. Linear scan — the queue is
        bounded and admit runs once per freed slot."""
        with self._cond:
            if not self._queue:
                return None
            best = min(
                self._queue,
                key=lambda r: (
                    r.priority,
                    r.t_deadline if r.t_deadline is not None else float("inf"),
                    r.id,
                ),
            )
            self._queue.remove(best)
            return best

    def _admit(self) -> bool:
        """Fill free slots, and under tiering MAKE slots when demand
        exists: resume the oldest paused request first (it already paid
        its TTFT — FIFO keeps completion latency bounded), then admit
        queued prompts; with the batch full, a queued request may
        displace the longest-resident slot (min_resident floor) and a
        paused one may displace a slot that has held its quantum —
        round-robin time-slicing over more sequences than the device
        ring holds."""
        admitted = False
        evictions = 0
        while True:
            if self.slots.num_free:
                if self._paused:
                    self._resume_one(self.slots.alloc())
                    admitted = True
                    continue
                req = self._pop_next()
                if req is None:
                    break
                self._admit_into(self.slots.alloc(), req)
                admitted = True
                continue
            if self.kv_tier is None or evictions >= EVICT_PER_PASS:
                break
            req = self._pop_next()
            if req is not None:
                # TTFT pressure: a never-started request is worth an
                # early eviction (the displaced sequence keeps its state
                # in the tier and rotates back in)
                if self._evict_one(self.tier_min_resident_steps):
                    evictions += 1
                    self._admit_into(self.slots.alloc(), req)
                    admitted = True
                    continue
                with self._cond:
                    self._queue.append(req)  # nothing evictable yet
                break
            if self._paused:
                # pure rotation: oldest paused displaces the slot that
                # has held the batch longest, once per quantum
                if self._evict_one(self.tier_quantum_steps):
                    evictions += 1
                    self._resume_one(self.slots.alloc())
                    admitted = True
                    continue
            break
        return admitted

    def _admit_into(self, slot: int, req: Request) -> None:
        rt = reqtrace.ring()
        t_slot = time.perf_counter()
        src, plen, host = None, 0, None
        if self.prefix_cache:
            src, plen = self._find_prefix(req.prompt)
            if src is None and self.kv_tier is not None:
                host, plen = self._host_prefix_lookup(req.prompt)
        if src is not None:
            tok, _ = self.engine.admit(
                slot, req.prompt, prefix_src=src, prefix_len=plen
            )
            self.prefix_hits += 1
            self.prefix_tokens_saved += plen
            obs.count("serve_prefix_hits")
            obs.count("serve_prefix_tokens_saved", plen)
        elif host is not None:
            tok, _ = self.engine.admit(slot, req.prompt, host_prefix=host)
            self.prefix_hits += 1
            self.host_prefix_hits += 1
            self.prefix_tokens_saved += plen
            obs.count("serve_prefix_hits")
            obs.count("serve_host_prefix_hits")
            obs.count("serve_prefix_tokens_saved", plen)
        else:
            tok, _ = self.engine.admit(slot, req.prompt)
            self._maybe_store_prefix(slot, req.prompt)
        req.t_first = time.perf_counter()
        if rt is not None and req.trace is not None:
            rt.span(
                req.trace, "queue", req.t_submit, t_slot, slot=slot
            )
            rt.span(
                req.trace, "prefill", t_slot, req.t_first,
                tokens=len(req.prompt),
                bucket=pick_bucket(len(req.prompt), self.engine.prefill_buckets),
                prefix_reused=plen,
            )
        req.tokens.append(tok)
        st = _Slot(req=req, cache_len=len(req.prompt), last_token=tok)
        if self._finished(st):
            self._retire(st)
            self.slots.free(slot)
        else:
            self._active[slot] = st

    # -- KV tiering (evict / restore / host prefix store) --------------------

    def _evict_one(self, min_resident: int) -> bool:
        """Page the coldest evictable slot out to the host tier and free
        it. Coldest = most decode steps since its tenancy began (every
        live slot decodes every step, so residency age IS the LRU order
        by last page-in); ``min_resident`` is the thrash guard. The D2H
        copy is only STARTED here — :meth:`_finish_pageouts` encodes it
        into the tier next iteration, after the transfer overlapped a
        decode step."""
        # in-flight page-outs land in the tier next iteration: count them
        # against the pin budget now or a 2-evict pass can overflow it
        if (
            self.kv_tier.paused_count + len(self._pending_evict)
            >= self.kv_tier.host_slots
        ):
            return False
        best_slot = None
        for slot, st in self._active.items():
            if st.resident_steps < min_resident:
                continue
            if best_slot is None or (
                st.resident_steps > self._active[best_slot].resident_steps
            ):
                best_slot = slot
        if best_slot is None:
            return False
        st = self._active.pop(best_slot)
        t0 = time.perf_counter()
        rows = ring_live_rows(st.cache_len, self.engine.max_context)
        pk, pv = self.engine.fetch_slot_pages(best_slot, rows)
        self._pending_evict.append((st, pk, pv, t0))
        self.slots.free(best_slot)
        self.evictions += 1
        obs.count("serve_tier_evictions")
        return True

    def _finish_pageouts(self) -> None:
        if not self._pending_evict:
            self._finish_prefix_stores()
            return
        pending, self._pending_evict = self._pending_evict, []
        rt = reqtrace.ring()
        for st, pk, pv, t0 in pending:
            k, v = np.asarray(pk), np.asarray(pv)
            self.kv_tier.put_paused(st.req.id, k, v)
            self._paused[st.req.id] = _Paused(
                req=st.req, cache_len=st.cache_len, last_token=st.last_token
            )
            t1 = time.perf_counter()
            self.engine.stage_seconds["page_out"] += t1 - t0
            obs.count("serve_page_out_bytes", k.nbytes + v.nbytes)
            if rt is not None and st.req.trace is not None:
                rt.span(
                    st.req.trace, "page_out", t0, t1,
                    tokens=st.cache_len, bytes=k.nbytes + v.nbytes,
                )
        self.paused_peak = max(self.paused_peak, len(self._paused))
        self._finish_prefix_stores()

    def _resume_one(self, slot: int) -> None:
        """Page the oldest paused request back in and rejoin the batch
        exactly where it stopped (tokens, cache_len, last_token are the
        request's own; the ring rows come back from the tier)."""
        rid, p = self._paused.popitem(last=False)
        t0 = time.perf_counter()
        k, v = self.kv_tier.pop_paused(rid)
        self.engine.install_slot_pages(slot, k, v)
        t1 = time.perf_counter()
        self._active[slot] = _Slot(
            req=p.req, cache_len=p.cache_len, last_token=p.last_token
        )
        self.resumes += 1
        obs.count("serve_tier_resumes")
        obs.count("serve_page_in_bytes", k.nbytes + v.nbytes)
        rt = reqtrace.ring()
        if rt is not None and p.req.trace is not None:
            rt.span(
                p.req.trace, "page_in", t0, t1,
                tokens=p.cache_len, bytes=k.nbytes + v.nbytes,
            )

    def _host_prefix_lookup(self, prompt: list):
        """Longest grid-length prompt prefix resident in the host tier at
        the CURRENT weights epoch (stale-epoch entries never serve)."""
        epoch = self.engine.weights_epoch
        for glen in prefix_grid_lengths(len(prompt)):
            got = self.kv_tier.get_prefix(prefix_key(prompt, glen), glen, epoch)
            if got is not None:
                return (got[0], got[1], glen), glen
        return None, 0

    def _maybe_store_prefix(self, slot: int, prompt: list) -> None:
        """After a cold prefill, snapshot the prompt's longest grid-length
        prefix into the tier (async D2H; encoded next iteration). This is
        what makes prefix reuse survive slot churn and what the fleet
        directory advertises."""
        if self.kv_tier is None or not self.prefix_cache:
            return
        grid = prefix_grid_lengths(len(prompt))
        if not grid:
            return
        glen = grid[0]
        key = prefix_key(prompt, glen)
        epoch = self.engine.weights_epoch
        if self.kv_tier.has_prefix(key, glen, epoch):
            return
        t0 = time.perf_counter()
        pk, pv = self.engine.fetch_slot_pages(slot, glen)
        self._pending_prefix.append((key, glen, epoch, pk, pv, t0))

    def _finish_prefix_stores(self) -> None:
        if not self._pending_prefix:
            return
        pending, self._pending_prefix = self._pending_prefix, []
        for key, glen, epoch, pk, pv, t0 in pending:
            if epoch != self.engine.weights_epoch:
                continue  # weights swapped since the snapshot: stale, drop
            k, v = np.asarray(pk), np.asarray(pv)
            self.kv_tier.put_prefix(key, glen, epoch, k, v)
            self.engine.stage_seconds["page_out"] += time.perf_counter() - t0
            obs.count("serve_page_out_bytes", k.nbytes + v.nbytes)

    def resident_prefixes(self) -> list:
        """``[[key, glen], ...]`` the fleet health channel advertises —
        epoch-valid host-tier prefix entries (read racily off-thread;
        the tier's dict snapshot is safe under the GIL)."""
        if self.kv_tier is None:
            return []
        return self.kv_tier.resident_prefixes(self.engine.weights_epoch)

    def _decode(self, t0: Optional[float] = None) -> bool:
        if not self._active:
            return False
        if self.spec_decode:
            return self._decode_spec(t0)
        S = self.engine.num_slots
        # the decode span covers the WHOLE step — batch assembly, the
        # engine call, and token emit — so per-step scheduler time is
        # attributed to the requests it served, and a trace's stage sums
        # reconcile with its end-to-end latency
        if t0 is None:
            t0 = time.perf_counter()
        tokens = np.zeros((S,), np.int32)
        lens = np.zeros((S,), np.int32)
        for slot, st in self._active.items():
            tokens[slot] = st.last_token
            lens[slot] = st.cache_len
        next_tokens, _ = self.engine.decode_step(tokens, lens)
        self.staleness_hist[self.engine.staleness()] += 1
        obs.count("serve_tokens_generated", len(self._active))
        batch = len(self._active)
        done_slots = []
        for slot, st in self._active.items():
            tok = int(next_tokens[slot])
            st.req.tokens.append(tok)
            st.cache_len += 1
            st.last_token = tok
            st.resident_steps += 1
            self.total_new_tokens += 1
            if self._finished(st):
                done_slots.append(slot)
        # the next iteration's window starts HERE, so span recording,
        # retires, and swap/gauge checks below are attributed to the
        # step that pays for them
        t1 = self._t_step_end = time.perf_counter()
        rt = reqtrace.ring()
        if rt is not None:
            for st in self._active.values():
                if st.req.trace is not None:
                    # a just-admitted slot's window starts where its own
                    # prefill ended, never before (no self double-count)
                    rt.span(
                        st.req.trace, "decode", max(t0, st.req.t_first), t1,
                        batch=batch, tokens=1,
                        kernel=self.engine.decode_kernel,
                    )
        for slot in done_slots:
            self.slots.free(slot)
            self._retire(self._active.pop(slot))
        return True

    def _decode_spec(self, t0: Optional[float] = None) -> bool:
        """One speculative round: every live slot consumes its accepted
        prefix + the corrected token, so a single engine call advances a
        slot by 1..k+1 tokens — token-for-token what k+1 plain decode
        steps would have produced (engine.spec_step docstring)."""
        S = self.engine.num_slots
        # span covers the whole round (assembly + engine + emit) — see
        # the plain _decode comment
        if t0 is None:
            t0 = time.perf_counter()
        tokens = np.zeros((S,), np.int32)
        lens = np.zeros((S,), np.int32)
        for slot, st in self._active.items():
            tokens[slot] = st.last_token
            lens[slot] = st.cache_len
        g, m = self.engine.spec_step(tokens, lens)
        self.staleness_hist[self.engine.staleness()] += 1
        proposed = self.engine.spec_k * len(self._active)
        accepted = sum(int(m[slot]) for slot in self._active)
        self.spec_proposed += proposed
        self.spec_accepted += accepted
        obs.count("serve_spec_proposed", proposed)
        obs.count("serve_spec_accepted", accepted)
        batch = len(self._active)
        done_slots = []
        emitted = 0
        emitted_by_slot = {}
        for slot, st in self._active.items():
            slot_emitted = 0
            st.resident_steps += 1
            for tok in g[slot, : int(m[slot]) + 1].tolist():
                st.req.tokens.append(int(tok))
                st.cache_len += 1
                st.last_token = int(tok)
                self.total_new_tokens += 1
                emitted += 1
                slot_emitted += 1
                if self._finished(st):
                    done_slots.append(slot)
                    break
            emitted_by_slot[slot] = slot_emitted
        t1 = self._t_step_end = time.perf_counter()
        rt = reqtrace.ring()
        if rt is not None:
            for slot, st in self._active.items():
                if st.req.trace is not None:
                    rt.span(
                        st.req.trace, "decode", max(t0, st.req.t_first), t1,
                        batch=batch, tokens=emitted_by_slot[slot],
                        proposed=self.engine.spec_k, accepted=int(m[slot]),
                        kernel=self.engine.decode_kernel,
                    )
        obs.count("serve_tokens_generated", emitted)
        for slot in done_slots:
            self.slots.free(slot)
            self._retire(self._active.pop(slot))
        return True

    def _finished(self, st: _Slot) -> bool:
        req = st.req
        if len(req.tokens) >= req.max_new_tokens:
            return True
        return req.eos_id is not None and st.last_token == req.eos_id

    def _retire(self, st: _Slot, error: Optional[str] = None) -> None:
        req = st.req
        if req.eos_id is not None and req.tokens and req.tokens[-1] == req.eos_id:
            req.tokens.pop()  # eos terminates, is not part of the text
        req.epoch = self.engine.weights_epoch
        req.finish(error)
        self._trace_terminal(
            req,
            "retire",
            "done" if error is None else "failed",
            epoch=req.epoch,
            **({} if error is None else {"error": error}),
        )
        if error is None:
            self.completed += 1
            self._latencies.append(req.latency_s)
            ewma = self._lat_ewma
            self._lat_ewma = (
                req.latency_s
                if ewma is None
                else 0.8 * ewma + 0.2 * req.latency_s
            )
            if req.ttft_s is not None:
                self._ttfts.append(req.ttft_s)
            obs.count("serve_requests_completed")
        else:
            self.failed += 1

    def estimate_wait_s(self) -> float:
        """Rough time a new request spends queued: queue length over slot
        parallelism, paced by the completed-latency EWMA. Feeds the 503
        Retry-After hint and the router's admission estimate — a hint,
        not a promise."""
        ewma = self._lat_ewma if self._lat_ewma is not None else 0.25
        with self._cond:
            depth = len(self._queue)
        return (depth / max(1, self.slots.num_slots)) * ewma

    # -- metrics -----------------------------------------------------------

    def _publish_gauges(self) -> None:
        if not self._kernel_probed:
            # one-time per-kernel isolation probe on the live shapes (the
            # path and shapes are fixed per process, so once is enough);
            # attribution only — never take down the serving loop
            self._kernel_probed = True
            try:
                self.engine.kernel_probe()
            except Exception:
                obs.count("serve_kernel_probe_errors")
        lat = np.asarray(self._latencies, np.float64)
        if lat.size:
            obs.gauge("serve_p50_ms", float(np.percentile(lat, 50)) * 1e3)
            obs.gauge("serve_p99_ms", float(np.percentile(lat, 99)) * 1e3)
        now = time.perf_counter()
        t0, n0 = self._rate_mark
        if now > t0:
            obs.gauge(
                "serve_tokens_per_s", (self.total_new_tokens - n0) / (now - t0)
            )
        self._rate_mark = (now, self.total_new_tokens)
        obs.gauge(
            "serve_batch_occupancy", self.slots.num_active / self.slots.num_slots
        )
        staleness = self.engine.staleness()
        obs.gauge("serve_snapshot_staleness", staleness)
        wd = obs.anomaly.watchdog()
        if wd is not None:
            # a breach here means maybe_swap() could NOT restore the bound
            # (e.g. the trainer stalled and no fresh snapshot exists): the
            # watchdog records it, serving continues on the stale snapshot
            wd.serve_staleness(
                staleness,
                self.engine.max_stale_rounds,
                exemplars=self._slo_exemplars(),
            )
        if self.spec_proposed:
            obs.gauge(
                "serve_spec_acceptance", self.spec_accepted / self.spec_proposed
            )
        if self.kv_tier is not None:
            obs.gauge("serve_tier_occupancy", self.kv_tier.occupancy())
            obs.gauge("serve_tier_paused", len(self._paused))
            obs.gauge("serve_tier_prefix_entries", self.kv_tier.prefix_count)
            obs.gauge("serve_tier_stored_bytes", self.kv_tier.stored_bytes())
        with self._cond:
            obs.gauge("serve_queue_depth", len(self._queue))

    @staticmethod
    def _slo_exemplars(n: int = 3) -> list:
        """Trace ids of the slowest recently completed requests in this
        process's reqtrace ring — the evidence attached to staleness /
        SLO-breach watchdog trips and fleet health rows so a breach
        names the requests that caused it."""
        rt = reqtrace.ring()
        if rt is None:
            return []
        return [ex["id"] for ex in rt.exemplars(n)]

    def health(self) -> dict:
        """Compact load vector for the fleet health plane (push replies,
        overseer roll-ups, autoscaler): cheap enough to compute on every
        push-channel reply."""
        lat = np.asarray(self._latencies, np.float64)
        with self._cond:
            depth = len(self._queue)
        out = {
            "queue_depth": depth,
            "occupancy": round(
                self.slots.num_active / self.slots.num_slots, 4
            ),
            "p99_ms": (
                round(float(np.percentile(lat, 99)) * 1e3, 3)
                if lat.size
                else None
            ),
            "wait_estimate_s": round(self.estimate_wait_s(), 4),
            "completed": self.completed,
            "shed": self.shed,
        }
        if self.kv_tier is not None:
            out["tier_occupancy"] = round(self.kv_tier.occupancy(), 4)
            out["tier_paused"] = len(self._paused)
        exemplars = self._slo_exemplars()
        if exemplars:
            out["slo_exemplars"] = exemplars
        return out

    def stats(self) -> dict:
        """Point-in-time summary for the bench / health endpoint."""
        lat = np.asarray(self._latencies, np.float64) * 1e3
        ttft = np.asarray(self._ttfts, np.float64) * 1e3

        def pct(a, q):
            return float(np.percentile(a, q)) if a.size else None

        return {
            "completed": self.completed,
            "rejected": self.rejected,
            "failed": self.failed,
            "cancelled": self.cancelled,
            "shed": self.shed,
            "queued": len(self._queue),
            "active": self.slots.num_active,
            "decode_steps": self.decode_steps,
            "new_tokens": self.total_new_tokens,
            "latency_ms": {
                "p50": pct(lat, 50),
                "p90": pct(lat, 90),
                "p99": pct(lat, 99),
                "mean": float(lat.mean()) if lat.size else None,
            },
            "ttft_ms": {"p50": pct(ttft, 50), "p99": pct(ttft, 99)},
            "weight_swaps": self.engine.swap_count,
            "weights_epoch": self.engine.weights_epoch,
            "staleness": self.engine.staleness(),
            # int keys in numeric order: json.dump(sort_keys=True) sorts
            # dict items BEFORE stringifying, so the artifact reads
            # 0, 1, 2, ... 10 instead of the lexicographic "0", "1", "10"
            "staleness_hist": {
                int(k): v for k, v in sorted(self.staleness_hist.items())
            },
            "stages_s": {
                k: round(v, 6) for k, v in self.engine.stage_seconds.items()
            },
            "spec": {
                "proposed": self.spec_proposed,
                "accepted": self.spec_accepted,
                "acceptance_rate": (
                    self.spec_accepted / self.spec_proposed
                    if self.spec_proposed
                    else None
                ),
            },
            "prefix": {
                "hits": self.prefix_hits,
                "host_hits": self.host_prefix_hits,
                "tokens_saved": self.prefix_tokens_saved,
            },
            "tier": (
                {
                    **self.kv_tier.stats(),
                    "evictions": self.evictions,
                    "resumes": self.resumes,
                    "paused": len(self._paused),
                    "paused_peak": self.paused_peak,
                }
                if self.kv_tier is not None
                else None
            ),
            "loop_error": self.loop_error,
        }
