"""Slot-paged ring KV cache bookkeeping for the serve plane.

The device arrays live in ``models.llama.init_kv_cache`` ([L, S, T, Nkv,
Dh]: one fixed ring page per batch slot); this module owns the host-side
bookkeeping — which slots are free, which compile-size bucket a prompt
pads to — so the engine's jitted ops see only dense arrays and traced
scalars.

:class:`HostKVTier` is the cold tier behind KV tiering (``ODTP_KV_TIER``):
a host-memory store for slot pages evicted D2H between decode steps,
optionally quantized with the outer plane's ``blockwise4bit`` codec, plus
a prefix-cache namespace (prompt-prefix K/V keyed by content hash +
weights epoch) that outlives slot churn and feeds the fleet's
prefix-cache directory.
"""
from __future__ import annotations

import collections
import dataclasses
import hashlib
from typing import Optional, Sequence

import numpy as np

from opendiloco_tpu.diloco.compression import get_codec


class SlotAllocator:
    """Free-list over the cache's S batch slots.

    Continuous batching needs nothing fancier: a finished sequence frees
    its slot between decode steps and the next queued prompt claims it
    immediately; the page is reused in place (stale entries are masked
    until the new tenant's writes reach them — see llama.cache_insert).
    """

    def __init__(self, num_slots: int):
        if num_slots < 1:
            raise ValueError(f"need at least one slot, got {num_slots}")
        self.num_slots = num_slots
        # pop() takes from the tail, so keep ascending order reversed:
        # slot 0 is handed out first (stable slot ids make tests readable)
        self._free = list(range(num_slots))[::-1]

    def alloc(self) -> Optional[int]:
        """Claim a slot, or None when the batch is full."""
        return self._free.pop() if self._free else None

    def free(self, slot: int) -> None:
        if not 0 <= slot < self.num_slots:
            raise ValueError(f"slot {slot} out of range")
        if slot in self._free:
            raise ValueError(f"slot {slot} double-freed")
        self._free.append(slot)

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_active(self) -> int:
        return self.num_slots - len(self._free)


def accept_counts(draft: np.ndarray, verified: np.ndarray) -> np.ndarray:
    """Speculative accept/reject bookkeeping (host side, exact).

    draft [S, k] are the proposed tokens; verified [S, k+1] are the
    full-depth greedy tokens, where verified[:, j] is the model's true
    next token AFTER tail position j. Proposal j is accepted iff every
    proposal before it was and ``draft[:, j] == verified[:, j]`` — the
    longest agreeing prefix. Returns m [S] int32 in [0, k]: the slot
    emits tokens ``verified[:, :m+1]`` (m accepted drafts plus the one
    corrected/bonus token), and the ring keeps exactly tail entries
    0..m — rejected tokens are never inserted, which IS the rollback."""
    draft = np.asarray(draft)
    verified = np.asarray(verified)
    S, k = draft.shape
    if verified.shape != (S, k + 1):
        raise ValueError(
            f"verified shape {verified.shape} != {(S, k + 1)}"
        )
    agree = draft == verified[:, :k]
    # index of the first disagreement == count of accepted proposals
    return np.where(
        agree.all(axis=1), np.int32(k), np.argmin(agree, axis=1).astype(np.int32)
    )


def common_prefix_len(a: Sequence[int], b: Sequence[int]) -> int:
    """Length of the shared leading run of two prompts (prefix-cache
    detection). Pure host bookkeeping; O(min len)."""
    n = min(len(a), len(b))
    for i in range(n):
        if a[i] != b[i]:
            return i
    return n


def pick_bucket(n: int, buckets: Sequence[int]) -> Optional[int]:
    """Smallest prefill compile bucket that fits an n-token prompt, or
    None when the prompt exceeds every bucket (the scheduler rejects it
    rather than compiling an unbounded family of prefill programs)."""
    for b in sorted(buckets):
        if n <= b:
            return b
    return None


# -- prefix hashing (fleet prefix-cache directory) ----------------------------

# prefix store/advertise granularity: prompt prefixes hash at these exact
# lengths, so a replica's advertisement and the router's lookup agree on
# the key without shipping token lists over the health channel
PREFIX_GRID = (16, 32, 64, 128, 256, 512, 1024, 2048)


def prefix_key(prompt: Sequence[int], glen: int) -> str:
    """Stable cross-process content hash of ``prompt[:glen]`` — the
    prefix-directory key. sha1 over the int32 token bytes, truncated: 16
    hex chars is plenty for a directory that holds thousands of entries,
    and keeps advertisement frames small."""
    raw = np.asarray(list(prompt[:glen]), np.int32).tobytes()
    return hashlib.sha1(raw).hexdigest()[:16]


def prefix_grid_lengths(n: int) -> list:
    """Grid lengths usable for an n-token prompt, longest first. Capped
    at n-1: the suffix pass must keep at least the final prompt token to
    run (its logits seed decode) — same cap as live-slot prefix reuse."""
    return [g for g in sorted(PREFIX_GRID, reverse=True) if g <= n - 1]


# -- host-memory cold tier -----------------------------------------------------


@dataclasses.dataclass
class _TierEntry:
    payload_k: bytes
    payload_v: bytes
    meta_k: dict
    meta_v: dict
    shape: tuple  # [L, rows, Kh, Dh] of ONE page (k and v are same shape)
    raw_bytes: int  # uncompressed f32 bytes both pages would occupy
    epoch: int = 0  # weights epoch (prefix entries only; -1 = any)


class HostKVTier:
    """Host-memory cold KV tier: evicted slot pages + a prefix cache.

    Two namespaces share one ``host_slots`` page budget:

    - **paused pages** (``put_paused``/``pop_paused``, keyed by request
      id): a live-but-cold sequence's ring page, evicted D2H so its batch
      slot can serve someone else and paged back H2D on resume. Pinned —
      the zero-drop guarantee means a paused sequence's state is never
      discarded; when pinned pages fill the budget the scheduler simply
      stops evicting.
    - **prefix entries** (``put_prefix``/``get_prefix``, keyed by
      ``(prefix_key, glen)``): prompt-prefix K/V stored at prefill time,
      tagged with the weights epoch that produced it. LRU-dropped under
      budget pressure and invalidated when the engine hot-swaps weights
      (stale-epoch entries never serve — cached prefix K/V must match the
      resident weights, the same consistency rule the ring cache keeps by
      NOT surviving a swap... inverted: the ring keeps old K/V with a
      staleness bound, the prefix store simply refuses to cross epochs).

    Pages are stored codec-encoded (``ODTP_KV_TIER_CODEC``): ``none`` is
    a bit-exact f32 round trip of the bf16/f32 cache values, ``blockwise4bit``
    reuses the outer plane's 4-bit codec for ~8x smaller resident bytes at
    a bounded, test-pinned restore error. All methods are called from the
    scheduler loop thread only (same single-owner discipline as the
    engine); byte/page counters are read racily by gauges, which is fine.
    """

    def __init__(self, *, host_slots: int = 32, codec: str = "none"):
        if host_slots < 1:
            raise ValueError(f"need at least one host slot, got {host_slots}")
        self.host_slots = int(host_slots)
        self.codec_name = str(codec)
        self.codec = get_codec(self.codec_name)
        self._paused: dict[int, _TierEntry] = {}
        # insertion order IS recency order (move_to_end on hit)
        self._prefix: collections.OrderedDict[tuple, _TierEntry] = (
            collections.OrderedDict()
        )
        # transfer accounting (raw f32-equivalent bytes moved per direction
        # plus codec-resident bytes, for the tier gauges / bench artifact)
        self.pages_out = 0
        self.pages_in = 0
        self.bytes_out = 0
        self.bytes_in = 0
        self.prefix_stores = 0
        self.prefix_hits = 0
        self.prefix_dropped = 0
        self.prefix_stale_purged = 0

    # -- encode/decode -------------------------------------------------------

    def _encode(self, k: np.ndarray, v: np.ndarray, epoch: int) -> _TierEntry:
        kf = np.ascontiguousarray(k, np.float32)
        vf = np.ascontiguousarray(v, np.float32)
        pk, mk = self.codec.encode(kf.reshape(-1))
        pv, mv = self.codec.encode(vf.reshape(-1))
        return _TierEntry(
            payload_k=bytes(pk),
            payload_v=bytes(pv),
            meta_k=mk,
            meta_v=mv,
            shape=tuple(k.shape),
            raw_bytes=kf.nbytes + vf.nbytes,
            epoch=int(epoch),
        )

    def _decode(self, e: _TierEntry) -> tuple[np.ndarray, np.ndarray]:
        n = int(np.prod(e.shape))
        k = np.asarray(
            self.codec.decode(e.payload_k, (n,), e.meta_k), np.float32
        ).reshape(e.shape)
        v = np.asarray(
            self.codec.decode(e.payload_v, (n,), e.meta_v), np.float32
        ).reshape(e.shape)
        return k, v

    # -- paused pages (pinned) ----------------------------------------------

    def can_pin(self) -> bool:
        """Room to accept one more paused page? Prefix entries do not
        block a pin — they are droppable and ``put_paused`` reclaims them
        LRU-first; only pinned pages are immovable budget."""
        return len(self._paused) < self.host_slots

    def put_paused(self, req_id: int, k: np.ndarray, v: np.ndarray) -> None:
        if req_id in self._paused:
            raise ValueError(f"request {req_id} already paused in the tier")
        if not self.can_pin():
            raise RuntimeError(
                f"host tier full ({self.host_slots} pinned pages)"
            )
        e = self._encode(k, v, epoch=-1)
        # pinned pages preempt droppable prefix entries under budget
        while len(self._paused) + len(self._prefix) >= self.host_slots and (
            self._prefix
        ):
            self._prefix.popitem(last=False)
            self.prefix_dropped += 1
        self._paused[req_id] = e
        self.pages_out += 1
        self.bytes_out += e.raw_bytes

    def pop_paused(self, req_id: int) -> tuple[np.ndarray, np.ndarray]:
        e = self._paused.pop(req_id)
        self.pages_in += 1
        self.bytes_in += e.raw_bytes
        return self._decode(e)

    def drop_paused(self, req_id: int) -> bool:
        """Discard a paused page without restoring it (request cancelled
        or expired while cold)."""
        return self._paused.pop(req_id, None) is not None

    # -- prefix namespace ----------------------------------------------------

    def has_prefix(self, key: str, glen: int, epoch: int) -> bool:
        e = self._prefix.get((key, int(glen)))
        return e is not None and e.epoch == int(epoch)

    def put_prefix(
        self, key: str, glen: int, epoch: int, k: np.ndarray, v: np.ndarray
    ) -> bool:
        """Store a prompt prefix's pages; returns False when the budget is
        all pinned (nothing droppable) and the entry was declined."""
        while len(self._paused) + len(self._prefix) >= self.host_slots:
            if not self._prefix:
                return False
            self._prefix.popitem(last=False)
            self.prefix_dropped += 1
        self._prefix[(key, int(glen))] = self._encode(k, v, epoch)
        self._prefix.move_to_end((key, int(glen)))
        self.prefix_stores += 1
        return True

    def get_prefix(
        self, key: str, glen: int, epoch: int
    ) -> Optional[tuple[np.ndarray, np.ndarray]]:
        kk = (key, int(glen))
        e = self._prefix.get(kk)
        if e is None:
            return None
        if e.epoch != int(epoch):
            # weight-swap staleness: the stored K/V was produced by older
            # weights; serving it would silently mix epochs
            del self._prefix[kk]
            self.prefix_stale_purged += 1
            return None
        self._prefix.move_to_end(kk)
        self.prefix_hits += 1
        self.pages_in += 1
        self.bytes_in += e.raw_bytes
        return self._decode(e)

    def purge_stale(self, epoch: int) -> int:
        """Drop every prefix entry not produced by ``epoch`` (called after
        a weight hot-swap). Paused pages are untouched: their K/V pairs
        with the sequence's own history, exactly like a live slot's ring
        page surviving a swap."""
        stale = [
            kk for kk, e in self._prefix.items() if e.epoch != int(epoch)
        ]
        for kk in stale:
            del self._prefix[kk]
        self.prefix_stale_purged += len(stale)
        return len(stale)

    def resident_prefixes(self, epoch: int) -> list:
        """``[[key, glen], ...]`` of epoch-valid prefix entries — the
        fleet advertisement payload (rides replica health frames; old
        peers ignore the extra field)."""
        return [
            [key, glen]
            for (key, glen), e in self._prefix.items()
            if e.epoch == int(epoch)
        ]

    # -- introspection -------------------------------------------------------

    @property
    def paused_count(self) -> int:
        return len(self._paused)

    @property
    def prefix_count(self) -> int:
        return len(self._prefix)

    def occupancy(self) -> float:
        return (len(self._paused) + len(self._prefix)) / self.host_slots

    def stored_bytes(self) -> int:
        return sum(
            len(e.payload_k) + len(e.payload_v)
            for e in list(self._paused.values()) + list(self._prefix.values())
        )

    def stats(self) -> dict:
        return {
            "codec": self.codec_name,
            "host_slots": self.host_slots,
            "paused": len(self._paused),
            "prefix_entries": len(self._prefix),
            "occupancy": round(self.occupancy(), 4),
            "pages_out": self.pages_out,
            "pages_in": self.pages_in,
            "bytes_out": self.bytes_out,
            "bytes_in": self.bytes_in,
            "stored_bytes": self.stored_bytes(),
            "prefix_stores": self.prefix_stores,
            "prefix_hits": self.prefix_hits,
            "prefix_dropped": self.prefix_dropped,
            "prefix_stale_purged": self.prefix_stale_purged,
        }
