"""Slot-paged ring KV cache bookkeeping for the serve plane.

The device arrays live in ``models.llama.init_kv_cache`` ([L, S, T, Nkv,
Dh]: one fixed ring page per batch slot); this module owns the host-side
bookkeeping — which slots are free, which compile-size bucket a prompt
pads to — so the engine's jitted ops see only dense arrays and traced
scalars.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


class SlotAllocator:
    """Free-list over the cache's S batch slots.

    Continuous batching needs nothing fancier: a finished sequence frees
    its slot between decode steps and the next queued prompt claims it
    immediately; the page is reused in place (stale entries are masked
    until the new tenant's writes reach them — see llama.cache_insert).
    """

    def __init__(self, num_slots: int):
        if num_slots < 1:
            raise ValueError(f"need at least one slot, got {num_slots}")
        self.num_slots = num_slots
        # pop() takes from the tail, so keep ascending order reversed:
        # slot 0 is handed out first (stable slot ids make tests readable)
        self._free = list(range(num_slots))[::-1]

    def alloc(self) -> Optional[int]:
        """Claim a slot, or None when the batch is full."""
        return self._free.pop() if self._free else None

    def free(self, slot: int) -> None:
        if not 0 <= slot < self.num_slots:
            raise ValueError(f"slot {slot} out of range")
        if slot in self._free:
            raise ValueError(f"slot {slot} double-freed")
        self._free.append(slot)

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_active(self) -> int:
        return self.num_slots - len(self._free)


def accept_counts(draft: np.ndarray, verified: np.ndarray) -> np.ndarray:
    """Speculative accept/reject bookkeeping (host side, exact).

    draft [S, k] are the proposed tokens; verified [S, k+1] are the
    full-depth greedy tokens, where verified[:, j] is the model's true
    next token AFTER tail position j. Proposal j is accepted iff every
    proposal before it was and ``draft[:, j] == verified[:, j]`` — the
    longest agreeing prefix. Returns m [S] int32 in [0, k]: the slot
    emits tokens ``verified[:, :m+1]`` (m accepted drafts plus the one
    corrected/bonus token), and the ring keeps exactly tail entries
    0..m — rejected tokens are never inserted, which IS the rollback."""
    draft = np.asarray(draft)
    verified = np.asarray(verified)
    S, k = draft.shape
    if verified.shape != (S, k + 1):
        raise ValueError(
            f"verified shape {verified.shape} != {(S, k + 1)}"
        )
    agree = draft == verified[:, :k]
    # index of the first disagreement == count of accepted proposals
    return np.where(
        agree.all(axis=1), np.int32(k), np.argmin(agree, axis=1).astype(np.int32)
    )


def common_prefix_len(a: Sequence[int], b: Sequence[int]) -> int:
    """Length of the shared leading run of two prompts (prefix-cache
    detection). Pure host bookkeeping; O(min len)."""
    n = min(len(a), len(b))
    for i in range(n):
        if a[i] != b[i]:
            return i
    return n


def pick_bucket(n: int, buckets: Sequence[int]) -> Optional[int]:
    """Smallest prefill compile bucket that fits an n-token prompt, or
    None when the prompt exceeds every bucket (the scheduler rejects it
    rather than compiling an unbounded family of prefill programs)."""
    for b in sorted(buckets):
        if n <= b:
            return b
    return None
