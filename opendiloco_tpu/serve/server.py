"""Socket front-end for the serve plane: HTTP + JSONL on one port.

Same minimal-socket idiom as ``obs/prom.py`` — a daemon accept loop, one
handler thread per connection, no framework. Both protocols carry the
same JSON request shape::

    {"prompt": [1, 2, 3], "max_new_tokens": 16, "eos_id": null}

- HTTP: ``POST /generate`` with that JSON body; ``GET /healthz`` and
  ``GET /stats`` return scheduler/engine status. Metrics are NOT here —
  they ride the existing obs Prometheus endpoint (one registry per
  process, see obs/prom.py).
- JSONL: any connection whose first bytes are not an HTTP verb is
  treated as a newline-delimited JSON stream; each line gets a response
  line (pipelined in order). An optional ``"id"`` field is echoed back.

Port collisions (e.g. serve.port accidentally equal to
``ODTP_OBS_PROM_PORT``) downgrade to an ephemeral port with a warning
instead of crashing the training process — the bound port is always
``ServeServer.port``.
"""
from __future__ import annotations

import json
import logging
import select
import socket
import threading
import time
from typing import Callable, Optional, Union

from opendiloco_tpu.obs import reqtrace
from opendiloco_tpu.serve.scheduler import ContinuousBatcher

log = logging.getLogger(__name__)

_HTTP_VERBS = (b"GET ", b"POST", b"PUT ", b"HEAD", b"DELE", b"OPTI", b"PATC")

# scheduler rejects that are the server's load, not the request's fault:
# answered as structured 503 + Retry-After so clients back off cleanly
_OVERLOAD_ERRORS = ("queue full", "deadline exceeded")


def bind_with_fallback(
    host: str, port: int, what: str, retry_s: float = 0.0
) -> socket.socket:
    """Bind (host, port), falling back to an ephemeral port when the
    requested one is taken — a shared-process serving plane must never
    take down training over a port clash.

    ``retry_s`` keeps retrying the EXPLICIT port with bounded backoff
    before falling back: a replica respawned at its old address races the
    dying process's listener teardown, and an ephemeral fallback there
    would strand the router/manager dialing the address they know."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    deadline = time.monotonic() + max(0.0, retry_s)
    pause = 0.05
    while True:
        try:
            sock.bind((host, port))
            return sock
        except OSError as e:
            if port == 0:
                sock.close()
                raise
            if time.monotonic() + pause <= deadline:
                time.sleep(pause)
                pause = min(pause * 2, 0.5)
                continue
            log.warning(
                "%s port %d unavailable (%s); falling back to an "
                "ephemeral port",
                what,
                port,
                e,
            )
            sock.bind((host, 0))
            return sock


class ServeServer:
    def __init__(
        self,
        batcher: ContinuousBatcher,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        request_timeout: float = 300.0,
        identity: Optional[Union[dict, Callable[[], dict]]] = None,
        bind_retry_s: float = 0.0,
    ):
        self.batcher = batcher
        self.request_timeout = float(request_timeout)
        self.rejected_total = 0  # structured 503 rejects served
        # who this serving process is (worker/replica id, staleness, ...):
        # a dict, or a callable re-evaluated per request so dynamic fields
        # like staleness stay live. Folded into /healthz and /stats so a
        # fleet router (or odtp_top) can tell replicas apart.
        self._identity = identity
        self._sock = bind_with_fallback(host, port, "serve", bind_retry_s)
        self._sock.listen(32)
        self.host = host
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._accept_loop, name="odtp-serve-http", daemon=True
        )
        self._thread.start()

    # -- accept / dispatch -------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._handle, args=(conn,), daemon=True
            ).start()

    def _handle(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(self.request_timeout)
            head = conn.recv(4096)
            if not head:
                return
            if head[:4].ljust(4) in _HTTP_VERBS or head[:5] == b"PATCH":
                self._handle_http(conn, head)
            else:
                self._handle_jsonl(conn, head)
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # -- identity ------------------------------------------------------------

    def identity(self) -> dict:
        ident = self._identity
        if ident is None:
            return {}
        return dict(ident() if callable(ident) else ident)

    # -- one generation ----------------------------------------------------

    @staticmethod
    def _disconnected(conn: socket.socket) -> bool:
        """True when the peer closed the connection (EOF is readable)."""
        try:
            readable, _, _ = select.select([conn], [], [], 0)
            if not readable:
                return False
            return conn.recv(1, socket.MSG_PEEK) == b""
        except (OSError, ValueError):
            return True

    def _retry_after_s(self) -> float:
        """Backpressure hint for structured 503 rejects: the scheduler's
        current queue-drain estimate, clamped to something a client can
        reasonably sleep on."""
        return round(min(30.0, max(0.1, self.batcher.estimate_wait_s())), 3)

    def _generate(
        self, payload: dict, conn: Optional[socket.socket] = None
    ) -> Optional[dict]:
        deadline_ms = payload.get("deadline_ms")
        # trace context: adopt one propagated from the router, else mint
        # at this edge (standalone serve plane). Absent field = old peer
        # or untraced request — both identical, nothing to version-check.
        trace_ctx = None
        rt = reqtrace.ring()
        if rt is not None:
            trace_ctx = reqtrace.ctx_of(payload)
            if trace_ctx is None:
                trace_ctx = rt.mint(at="server", req_id=payload.get("id"))
        req = self.batcher.submit(
            payload.get("prompt") or [],
            max_new_tokens=int(payload.get("max_new_tokens", 16)),
            eos_id=payload.get("eos_id"),
            priority=int(payload.get("priority", 0)),
            deadline_ms=None if deadline_ms is None else float(deadline_ms),
            trace=trace_ctx,
        )
        # wait in slices, watching the client socket: a disconnect
        # mid-generation retires the slot immediately instead of decoding
        # the remaining tokens into a dead socket (None = nobody to answer)
        deadline = time.monotonic() + self.request_timeout
        while not req.wait(0.05):
            if conn is not None and self._disconnected(conn):
                req.cancel()
                return None
            if time.monotonic() >= deadline:
                req.cancel()
                return {"error": "timeout", "id": payload.get("id")}
        out = {
            "tokens": req.tokens,
            "epoch": req.epoch,
            "latency_ms": None
            if req.latency_s is None
            else round(req.latency_s * 1e3, 3),
        }
        if req.error is not None:
            out["error"] = req.error
            if req.error in _OVERLOAD_ERRORS:
                # structured backpressure: the client learns when to come
                # back instead of watching its connection error out
                out["retry_after_s"] = self._retry_after_s()
                self.rejected_total += 1
        if payload.get("id") is not None:
            out["id"] = payload["id"]
        return out

    # -- HTTP --------------------------------------------------------------

    def _handle_http(self, conn: socket.socket, head: bytes) -> None:
        while b"\r\n\r\n" not in head and len(head) < 65536:
            chunk = conn.recv(4096)
            if not chunk:
                break
            head += chunk
        header, _, body = head.partition(b"\r\n\r\n")
        lines = header.split(b"\r\n")
        method, path = (lines[0].split(b" ") + [b"", b""])[:2]
        clen = 0
        for ln in lines[1:]:
            if ln.lower().startswith(b"content-length:"):
                clen = int(ln.split(b":", 1)[1].strip() or 0)
        while len(body) < clen:
            chunk = conn.recv(65536)
            if not chunk:
                break
            body += chunk

        if method == b"POST" and path.startswith(b"/generate"):
            try:
                payload = json.loads(body.decode() or "{}")
            except (ValueError, UnicodeDecodeError):
                self._respond(conn, 400, {"error": "malformed JSON body"})
                return
            out = self._generate(payload, conn)
            if out is not None:
                if out.get("error") in _OVERLOAD_ERRORS:
                    self._respond(
                        conn,
                        503,
                        out,
                        headers={"Retry-After": str(out["retry_after_s"])},
                    )
                else:
                    self._respond(conn, 400 if "error" in out else 200, out)
        elif method == b"GET" and path.startswith(b"/healthz"):
            self._respond(
                conn,
                200,
                {
                    "ok": self.batcher.loop_error is None,
                    "weights_epoch": self.batcher.engine.weights_epoch,
                    "staleness": self.batcher.engine.staleness(),
                    "free_slots": self.batcher.slots.num_free,
                    # cold-tier load rides health so pollers (router
                    # probe, odtp_top) see paging pressure without /stats
                    **(
                        {
                            "tier_occupancy": round(
                                self.batcher.kv_tier.occupancy(), 4
                            ),
                            "tier_paused": self.batcher.kv_tier.paused_count,
                        }
                        if self.batcher.kv_tier is not None
                        else {}
                    ),
                    **self.identity(),
                },
            )
        elif method == b"GET" and path.startswith(b"/stats"):
            stats = self.batcher.stats()
            stats["rejected_total"] = self.rejected_total
            ident = self.identity()
            if ident:
                stats["identity"] = ident
            self._respond(conn, 200, stats)
        else:
            self._respond(conn, 404, {"error": "unknown route"})

    def _respond(
        self,
        conn: socket.socket,
        status: int,
        obj: dict,
        headers: Optional[dict] = None,
    ) -> None:
        body = (json.dumps(obj) + "\n").encode()
        reason = {
            200: "OK",
            400: "Bad Request",
            404: "Not Found",
            503: "Service Unavailable",
        }.get(status, "Error")
        extra = "".join(f"{k}: {v}\r\n" for k, v in (headers or {}).items())
        head = (
            f"HTTP/1.0 {status} {reason}\r\n"
            "Content-Type: application/json\r\n"
            f"{extra}"
            f"Content-Length: {len(body)}\r\n\r\n"
        ).encode()
        conn.sendall(head + body)

    # -- JSONL -------------------------------------------------------------

    def _handle_jsonl(self, conn: socket.socket, buf: bytes) -> None:
        while True:
            while b"\n" in buf:
                line, _, buf = buf.partition(b"\n")
                line = line.strip()
                if not line:
                    continue
                try:
                    payload = json.loads(line.decode())
                except (ValueError, UnicodeDecodeError):
                    out = {"error": "malformed JSON line"}
                else:
                    out = self._generate(payload, conn)
                    if out is None:  # client disconnected mid-generation
                        return
                conn.sendall((json.dumps(out) + "\n").encode())
            chunk = conn.recv(65536)
            if not chunk:
                return
            buf += chunk

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        self._thread.join(timeout=2.0)
