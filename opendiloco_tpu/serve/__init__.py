"""Serving plane: continuous-batching inference off the live master weights.

The north-star system trains with DiLoCo while "serving heavy traffic"
from the same deployment; this package is that leg. A jitted engine runs
prefill + incremental decode over a slot-paged ring KV cache
(models/llama.py decode mode), a scheduler thread admits/retires
requests between decode steps (continuous batching), and weights
hot-swap from the outer plane's master snapshots — DiLoCo-fresh serving
(arXiv 2311.08105) with a ``max_stale_rounds`` bound, no request dropped
across a swap.

Wiring: ``build_serving(serve_cfg, model_cfg, params, diloco_opt)``
returns a started :class:`ServingPlane`; ``train.py`` calls it when
``config.serve.enabled`` so training and serving share one process (and
one obs registry / Prometheus endpoint).
"""
from __future__ import annotations

import dataclasses
import os
from typing import Optional

import jax.numpy as jnp

from opendiloco_tpu.serve.engine import ServeEngine  # noqa: F401
from opendiloco_tpu.serve.kvcache import (  # noqa: F401
    HostKVTier,
    SlotAllocator,
    pick_bucket,
)
from opendiloco_tpu.serve.scheduler import ContinuousBatcher, Request  # noqa: F401
from opendiloco_tpu.serve.server import ServeServer  # noqa: F401

__all__ = [
    "ContinuousBatcher",
    "HostKVTier",
    "Request",
    "ServeEngine",
    "ServeServer",
    "ServingPlane",
    "SlotAllocator",
    "build_serving",
    "pick_bucket",
]


@dataclasses.dataclass
class ServingPlane:
    """The three live pieces, with one-call teardown (train.py finally)."""

    engine: ServeEngine
    batcher: ContinuousBatcher
    server: Optional[ServeServer]

    @property
    def port(self) -> Optional[int]:
        return None if self.server is None else self.server.port

    def stop(self) -> None:
        if self.server is not None:
            self.server.stop()
        self.batcher.stop()


def build_serving(
    serve_cfg,
    model_cfg,
    params,
    diloco_opt=None,
    *,
    compute_dtype=jnp.bfloat16,
    start_server: bool = True,
) -> ServingPlane:
    """Assemble engine + batcher (+ socket front-end) from a
    ``config.ServeConfig``. ``diloco_opt`` supplies the hot-swap source
    (``master_snapshot_wire`` / ``epoch``); None serves static weights."""
    import jax

    # host roundtrip decouples the engine from the trainer's mesh: live
    # train-state leaves may be sharded/committed, and the engine's jits
    # run single-device with their own fresh buffers
    params = jax.device_get(params)
    snapshot_fn = epoch_fn = None
    epoch = 0
    if diloco_opt is not None:
        snapshot_fn = diloco_opt.master_snapshot_wire
        epoch_fn = lambda: diloco_opt.epoch
        epoch = diloco_opt.epoch
    # fast-decode knob overrides (experiments without a config edit)
    env_k = os.environ.get("ODTP_SPEC_K")
    spec_k = int(env_k) if env_k else serve_cfg.spec_decode_k
    env_wf = os.environ.get("ODTP_DECODE_WEIGHT_FORMAT")
    weight_format = env_wf if env_wf else serve_cfg.weight_format
    env_dk = os.environ.get("ODTP_DECODE_KERNEL")
    decode_kernel = env_dk if env_dk else serve_cfg.decode_kernel
    engine = ServeEngine(
        model_cfg,
        params,
        num_slots=serve_cfg.max_batch,
        max_context=serve_cfg.max_context,
        prefill_buckets=serve_cfg.prefill_buckets,
        compute_dtype=compute_dtype,
        epoch=epoch,
        snapshot_fn=snapshot_fn,
        epoch_fn=epoch_fn,
        max_stale_rounds=serve_cfg.max_stale_rounds,
        spec_k=spec_k,
        draft_layers=serve_cfg.draft_layers,
        weight_format=weight_format,
        decode_kernel=decode_kernel,
    )
    env_tier = os.environ.get("ODTP_KV_TIER")
    kv_tier_on = bool(int(env_tier)) if env_tier else serve_cfg.kv_tier
    kv_tier = None
    if kv_tier_on:
        kv_tier = HostKVTier(
            host_slots=int(
                os.environ.get("ODTP_KV_HOST_SLOTS")
                or serve_cfg.kv_host_slots
            ),
            codec=(
                os.environ.get("ODTP_KV_TIER_CODEC")
                or serve_cfg.kv_tier_codec
            ),
        )
    batcher = ContinuousBatcher(
        engine,
        max_queue=serve_cfg.max_queue,
        swap_every_steps=serve_cfg.swap_every_steps,
        prefix_cache=serve_cfg.prefix_cache,
        kv_tier=kv_tier,
    ).start()
    server = None
    if start_server:
        server = ServeServer(
            batcher, host=serve_cfg.host, port=serve_cfg.port
        )
    return ServingPlane(engine=engine, batcher=batcher, server=server)
