"""Jitted inference engine: prefill, batched incremental decode, weight
hot-swap.

The engine owns its OWN device copy of the weights plus the slot-paged
ring KV cache, and exposes exactly three device operations to the
scheduler loop — ``admit`` (prefill a prompt into a free slot),
``decode_step`` (one token for every live slot), and ``maybe_swap``
(adopt a newer master snapshot from the outer plane). All three are
called from a single scheduler thread; the engine is deliberately not
thread-safe so the jits can donate the cache buffers without a lock.

Hot-swap pulls codec-encoded snapshots (``DiLoCoOptimizer.
master_snapshot_wire``, the fp16 ``ODTP_STATE_CODEC`` path) and rebinds
``self.params`` between decode steps. The KV cache is untouched by
design: cached K/V stays consistent with the weights that produced it,
which is the standard serving trade for not re-prefilling every live
request on each outer round — and the staleness knob bounds how far the
weights may lag (DiLoCo-fresh serving, arXiv 2311.08105).
"""
from __future__ import annotations

import time
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from opendiloco_tpu import obs
from opendiloco_tpu.diloco.compression import get_codec
from opendiloco_tpu.models.llama import (
    LlamaConfig,
    cache_insert,
    decode_forward,
    init_kv_cache,
    prefill_forward,
)
from opendiloco_tpu.serve.kvcache import pick_bucket


@jax.jit
def _fresh_copy(leaves):
    # fresh f32 buffers: the caller may pass live train-state leaves that
    # the next train_step donates (same add-zero idiom as the outer plane)
    return [x.astype(jnp.float32) + jnp.zeros((), jnp.float32) for x in leaves]


# snapshot_fn contract: () -> (epoch, blobs, codec_name) with blobs[i] =
# (payload, meta, shape) per master leaf in params-flatten order — exactly
# what DiLoCoOptimizer.master_snapshot_wire returns.
SnapshotFn = Callable[[], tuple]


class ServeEngine:
    def __init__(
        self,
        cfg: LlamaConfig,
        params,
        *,
        num_slots: int = 8,
        max_context: int = 512,
        prefill_buckets: Sequence[int] = (32, 128, 512),
        compute_dtype=jnp.bfloat16,
        epoch: int = 0,
        snapshot_fn: Optional[SnapshotFn] = None,
        epoch_fn: Optional[Callable[[], int]] = None,
        max_stale_rounds: int = 0,
    ):
        self.cfg = cfg
        self.num_slots = int(num_slots)
        self.max_context = int(max_context)
        self.compute_dtype = compute_dtype
        self.prefill_buckets = sorted(
            min(int(b), self.max_context) for b in prefill_buckets
        )
        self.snapshot_fn = snapshot_fn
        self.epoch_fn = epoch_fn
        self.max_stale_rounds = int(max_stale_rounds)

        leaves, self._treedef = jax.tree.flatten(params)
        self._shapes = [tuple(x.shape) for x in leaves]
        self.params = jax.tree.unflatten(self._treedef, _fresh_copy(leaves))
        self.weights_epoch = int(epoch)
        self.swap_count = 0
        self.swap_seconds = 0.0

        cache = init_kv_cache(cfg, self.num_slots, self.max_context, compute_dtype)
        self.cache_k, self.cache_v = cache["k"], cache["v"]

        cd = compute_dtype

        def _prefill(p, ids, length):
            logits, ks, vs = prefill_forward(p, ids, length, cfg, compute_dtype=cd)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), logits, ks, vs

        def _insert(ck, cv, ks, vs, slot):
            return cache_insert(ck, cv, ks, vs, slot)

        def _decode(p, tokens, lens, ck, cv):
            logits, ck, cv = decode_forward(
                p, tokens, lens, ck, cv, cfg, compute_dtype=cd
            )
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), logits, ck, cv

        # one compile per prompt bucket; insert/decode compile once
        self._prefill = jax.jit(_prefill)
        self._insert = jax.jit(_insert, donate_argnums=(0, 1))
        self._decode = jax.jit(_decode, donate_argnums=(3, 4))

    # -- admission ---------------------------------------------------------

    def admit(self, slot: int, prompt: Sequence[int]) -> tuple[int, np.ndarray]:
        """Prefill ``prompt`` into ``slot`` and return (first greedy token,
        last-position logits [V] f32). The prompt must fit a compile
        bucket (scheduler-enforced via ``prompt_fits``)."""
        n = len(prompt)
        bucket = pick_bucket(n, self.prefill_buckets)
        if bucket is None:
            raise ValueError(
                f"prompt length {n} exceeds max bucket "
                f"{self.prefill_buckets[-1]}"
            )
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :n] = np.asarray(prompt, np.int32)
        tok, logits, ks, vs = self._prefill(
            self.params, jnp.asarray(ids), jnp.int32(n)
        )
        self.cache_k, self.cache_v = self._insert(
            self.cache_k, self.cache_v, ks, vs, jnp.int32(slot)
        )
        return int(tok[0]), np.asarray(logits[0])

    def prompt_fits(self, n: int) -> bool:
        return pick_bucket(n, self.prefill_buckets) is not None

    # -- decode ------------------------------------------------------------

    def decode_step(
        self, tokens: np.ndarray, lens: np.ndarray
    ) -> tuple[np.ndarray, jax.Array]:
        """One greedy token per slot. ``tokens``/``lens`` are dense [S]
        host arrays (inactive slots pass 0s; their ring writes land in
        masked positions and are overwritten on the slot's next tenancy).
        Returns (next tokens [S] np.int32, logits [S, V] on device)."""
        tok, logits, self.cache_k, self.cache_v = self._decode(
            self.params,
            jnp.asarray(tokens, jnp.int32),
            jnp.asarray(lens, jnp.int32),
            self.cache_k,
            self.cache_v,
        )
        return np.asarray(tok), logits

    # -- weight hot-swap ---------------------------------------------------

    def staleness(self) -> int:
        """Outer rounds the serving weights lag the trainer's masters."""
        if self.epoch_fn is None:
            return 0
        return max(0, int(self.epoch_fn()) - self.weights_epoch)

    def maybe_swap(self) -> bool:
        """Adopt the trainer's current master snapshot when staleness
        exceeds ``max_stale_rounds``. Called between decode steps, so no
        request is ever mid-forward across a rebind; the KV cache is not
        touched (pinned by tests/test_serve.py)."""
        if self.snapshot_fn is None:
            return False
        if self.staleness() <= self.max_stale_rounds:
            return False
        t0 = time.perf_counter()
        epoch, blobs, codec_name = self.snapshot_fn()
        if epoch <= self.weights_epoch:
            return False  # raced an in-flight round; keep current weights
        self.install_wire(epoch, blobs, codec_name)
        dt = time.perf_counter() - t0
        self.swap_seconds += dt
        obs.count("serve_weight_swaps")
        obs.gauge("serve_last_swap_ms", dt * 1e3)
        return True

    def install_wire(self, epoch: int, blobs, codec_name: str) -> None:
        """Decode a codec-encoded master snapshot and rebind the weights."""
        codec = get_codec(codec_name)
        if len(blobs) != len(self._shapes):
            raise ValueError(
                f"snapshot has {len(blobs)} leaves, engine expects "
                f"{len(self._shapes)}"
            )
        leaves = []
        for (payload, meta, shape), want in zip(blobs, self._shapes):
            if tuple(shape) != want:
                raise ValueError(f"snapshot leaf shape {shape} != {want}")
            size = int(np.prod(shape)) if shape else 1
            a = np.asarray(
                codec.decode(payload, (size,), meta), np.float32
            ).reshape(shape)
            leaves.append(jax.device_put(a))
        self.params = jax.tree.unflatten(self._treedef, leaves)
        self.weights_epoch = int(epoch)
        self.swap_count += 1

    def install_params(self, epoch: int, params) -> None:
        """Direct (uncompressed) rebind — tests and static-weight mode."""
        leaves = jax.tree.leaves(params)
        self.params = jax.tree.unflatten(self._treedef, _fresh_copy(leaves))
        self.weights_epoch = int(epoch)
        self.swap_count += 1
