"""Jitted inference engine: prefill, batched incremental decode, weight
hot-swap.

The engine owns its OWN device copy of the weights plus the slot-paged
ring KV cache, and exposes a handful of device operations to the
scheduler loop — ``admit`` (prefill a prompt into a free slot, optionally
continuing from a reused prefix), ``decode_step`` (one token for every
live slot), ``spec_step`` (self-speculative draft + verify, several
tokens per live slot), and ``maybe_swap`` (adopt a newer master snapshot
from the outer plane). All are called from a single scheduler thread;
the engine is deliberately not thread-safe so the jits can donate the
cache buffers without a lock.

Fast-decode legs (each individually off by default, and off-path
bit-identical to the plain engine):

- ``spec_k > 0``: self-speculative decode. A draft over the first
  ``draft_layers`` of the SAME weights proposes k greedy tokens per slot;
  one batched full-depth verify pass accepts the longest agreeing prefix
  plus the corrected token (Leviathan et al., arXiv 2211.17192 — greedy
  case). Outputs are token-identical to the one-token loop by
  construction: every emitted token is the full model's greedy argmax
  given exactly the tokens before it.
- ``weight_format="w4"``: the stacked decoder matmul weights stay
  blockwise-4bit packed at rest (PR 8 codec geometry, per layer) and
  dequantize per block inside the jit'd forwards; norms, embeddings and
  the lm head stay fp32. ~4x fewer weight bytes touched per decode step,
  and ``install_wire`` of a blockwise4bit snapshot re-slices the wire
  payload directly into the resident layout when block and layer grids
  align (no dequant/requantize round trip).
- prefix reuse (scheduler-driven): ``admit(..., prefix_src, prefix_len)``
  ring-copies a live slot's prefix K/V and prefills only the suffix.

Hot-swap pulls codec-encoded snapshots (``DiLoCoOptimizer.
master_snapshot_wire``, the fp16 ``ODTP_STATE_CODEC`` path) and rebinds
``self.params`` between decode steps. The KV cache is untouched by
design: cached K/V stays consistent with the weights that produced it,
which is the standard serving trade for not re-prefilling every live
request on each outer round — and the staleness knob bounds how far the
weights may lag (DiLoCo-fresh serving, arXiv 2311.08105).
"""
from __future__ import annotations

import time
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from opendiloco_tpu import obs
from opendiloco_tpu.diloco.compression import (
    get_codec,
    pack_blockwise4_stacked,
    split_blockwise4_stacked,
)
from opendiloco_tpu.models.llama import (
    LlamaConfig,
    PackedW4,
    cache_insert,
    decode_forward,
    dequant_w4,
    draft_propose,
    init_kv_cache,
    prefill_forward,
    prefix_copy,
    spec_cache_insert,
    suffix_insert,
    verify_forward,
)
from opendiloco_tpu.ops.attention import decode_attention, spec_tail_attention
from opendiloco_tpu.ops.decode_kernels import (
    paged_decode_attention,
    resolve_decode_kernel,
    spec_tail_attention_fused,
    w4_matmul,
)
from opendiloco_tpu.serve.kvcache import accept_counts, pick_bucket


@jax.jit
def _fresh_copy(leaves):
    # fresh f32 buffers: the caller may pass live train-state leaves that
    # the next train_step donates (same add-zero idiom as the outer plane)
    return [x.astype(jnp.float32) + jnp.zeros((), jnp.float32) for x in leaves]


# snapshot_fn contract: () -> (epoch, blobs, codec_name) with blobs[i] =
# (payload, meta, shape) per master leaf in params-flatten order — exactly
# what DiLoCoOptimizer.master_snapshot_wire returns.
SnapshotFn = Callable[[], tuple]

_STAGES = (
    "prefill", "draft", "verify", "insert", "decode", "swap",
    "page_out", "page_in",
)


class ServeEngine:
    def __init__(
        self,
        cfg: LlamaConfig,
        params,
        *,
        num_slots: int = 8,
        max_context: int = 512,
        prefill_buckets: Sequence[int] = (32, 128, 512),
        compute_dtype=jnp.bfloat16,
        epoch: int = 0,
        snapshot_fn: Optional[SnapshotFn] = None,
        epoch_fn: Optional[Callable[[], int]] = None,
        max_stale_rounds: int = 0,
        spec_k: int = 0,
        draft_layers: int = 0,
        weight_format: str = "fp32",
        decode_kernel: Optional[str] = None,
    ):
        self.cfg = cfg
        self.num_slots = int(num_slots)
        self.max_context = int(max_context)
        self.compute_dtype = compute_dtype
        self.prefill_buckets = sorted(
            min(int(b), self.max_context) for b in prefill_buckets
        )
        self.snapshot_fn = snapshot_fn
        self.epoch_fn = epoch_fn
        self.max_stale_rounds = int(max_stale_rounds)

        self.weight_format = str(weight_format)
        if self.weight_format not in ("fp32", "w4"):
            raise ValueError(f"unknown weight_format {weight_format!r}")
        # "auto"/None resolves to pallas only on TPU backends; tests force
        # "pallas" explicitly and the kernels run interpreted off-TPU
        self.decode_kernel = resolve_decode_kernel(decode_kernel)
        self.spec_k = int(spec_k)
        if self.spec_k < 0:
            raise ValueError(f"spec_k must be >= 0, got {spec_k}")
        if self.spec_k:
            L = cfg.num_hidden_layers
            ld = int(draft_layers) or max(1, L // 2)
            if not 1 <= ld < L:
                raise ValueError(
                    f"draft_layers {ld} outside [1, {L}) for spec decode"
                )
            if self.spec_k + 1 > self.max_context:
                raise ValueError(
                    f"spec_k {self.spec_k} + 1 exceeds max_context "
                    f"{self.max_context}"
                )
            self.draft_layers = ld
        else:
            self.draft_layers = 0
        # widest unverified tail a slot may carry: current token + k drafts.
        # The scheduler uses it to bound ring headroom for prefix reuse.
        self.tail_width = self.spec_k + 1

        leaves, self._treedef = jax.tree.flatten(params)
        kp, _ = jax.tree_util.tree_flatten_with_path(params)
        self._paths = [
            tuple(getattr(k, "key", str(k)) for k in path) for path, _ in kp
        ]
        self._shapes = [tuple(x.shape) for x in leaves]
        # w4-packable set: the stacked decoder matmuls ([L, in, out] leaves
        # under "layers"); norms ([L, D]), embeddings and lm head stay fp32
        self._packable = [
            p[0] == "layers" and len(s) == 3
            for p, s in zip(self._paths, self._shapes)
        ]
        self.params = self._assemble(leaves)
        self.weights_epoch = int(epoch)
        self.swap_count = 0
        self.swap_seconds = 0.0
        # wall-clock per decode stage (loop-thread only, mirrored to obs
        # spans when a tracer is armed; the bench reads this directly)
        self.stage_seconds = {k: 0.0 for k in _STAGES}

        cache = init_kv_cache(cfg, self.num_slots, self.max_context, compute_dtype)
        self.cache_k, self.cache_v = cache["k"], cache["v"]

        cd = compute_dtype
        dkn = self.decode_kernel

        def _prefill(p, ids, length):
            logits, ks, vs = prefill_forward(
                p, ids, length, cfg, compute_dtype=cd, decode_kernel=dkn
            )
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), logits, ks, vs

        def _insert(ck, cv, ks, vs, slot):
            return cache_insert(ck, cv, ks, vs, slot)

        def _decode(p, tokens, lens, ck, cv):
            logits, ck, cv = decode_forward(
                p, tokens, lens, ck, cv, cfg, compute_dtype=cd,
                decode_kernel=dkn,
            )
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), logits, ck, cv

        # one compile per prompt bucket; insert/decode compile once
        self._prefill = jax.jit(_prefill)
        self._insert = jax.jit(_insert, donate_argnums=(0, 1))
        self._decode = jax.jit(_decode, donate_argnums=(3, 4))

        # speculative-decode jits (compiled only when spec_step runs)
        kk, ld = self.spec_k, self.draft_layers

        def _draft(p, tokens, lens, ck, cv):
            return draft_propose(
                p, tokens, lens, ck, cv, cfg,
                k_steps=kk, draft_layers=ld, compute_dtype=cd,
                decode_kernel=dkn,
            )

        def _verify(p, tail, lens, ck, cv):
            logits, tks, tvs = verify_forward(
                p, tail, lens, ck, cv, cfg, compute_dtype=cd,
                decode_kernel=dkn,
            )
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), tks, tvs

        def _spec_insert(ck, cv, tks, tvs, lens, accept):
            return spec_cache_insert(ck, cv, tks, tvs, lens, accept)

        self._draft = jax.jit(_draft)
        self._verify = jax.jit(_verify)
        self._spec_insert = jax.jit(_spec_insert, donate_argnums=(0, 1))
        # host hook: tests swap in adversarial proposers; returns [S, k] np
        self.propose_fn: Callable[[np.ndarray, np.ndarray], np.ndarray] = (
            self._propose_draft
        )

        # shared-prefix reuse jits (compiled only when the batcher asks)
        def _pcopy(ck, cv, src, dst, plen):
            return prefix_copy(ck, cv, src, dst, plen)

        def _suffix(p, ck, cv, slot, tail, plen):
            # continued prefill = the verify primitive over the one slot's
            # gathered page: tail tokens at positions plen..plen+B-1
            page_k = jnp.take(ck, slot, axis=1)[:, None]  # [L, 1, T, Kh, Dh]
            page_v = jnp.take(cv, slot, axis=1)[:, None]
            logits, tks, tvs = verify_forward(
                p, tail, plen[None], page_k, page_v, cfg, compute_dtype=cd,
                decode_kernel=dkn,
            )
            return logits[0], tks[:, 0], tvs[:, 0]

        def _suffix_ins(ck, cv, ks, vs, slot, start, count):
            return suffix_insert(ck, cv, ks, vs, slot, start, count)

        self._prefix_copy = jax.jit(_pcopy, donate_argnums=(0, 1))
        self._suffix = jax.jit(_suffix)
        self._suffix_insert = jax.jit(_suffix_ins, donate_argnums=(0, 1))

        # KV-tier page transfers (compiled only when tiering is on): one
        # slot's ring pages gathered for D2H eviction / scattered back on
        # H2D restore. ``rows`` is static — padded to the prefill-bucket
        # grid by :meth:`page_rows` so the compile family stays bounded.
        def _fetch_pages(ck, cv, slot, rows):
            pk = jax.lax.dynamic_slice_in_dim(
                jnp.take(ck, slot, axis=1), 0, rows, axis=1
            )
            pv = jax.lax.dynamic_slice_in_dim(
                jnp.take(cv, slot, axis=1), 0, rows, axis=1
            )
            return pk, pv

        def _install_pages(ck, cv, pk, pv, slot):
            zero = jnp.int32(0)
            start = (zero, jnp.asarray(slot, jnp.int32), zero, zero, zero)
            ck = jax.lax.dynamic_update_slice(
                ck, pk[:, None].astype(ck.dtype), start
            )
            cv = jax.lax.dynamic_update_slice(
                cv, pv[:, None].astype(cv.dtype), start
            )
            return ck, cv

        self._fetch_pages = jax.jit(_fetch_pages, static_argnums=(3,))
        self._install_pages = jax.jit(_install_pages, donate_argnums=(0, 1))

    # -- weight residency ---------------------------------------------------

    def _assemble(self, leaves):
        """Rebuild the params tree from flat leaves (original flatten
        order). ``weight_format=w4`` packs the stacked matmul leaves into
        :class:`PackedW4` nodes (or adopts pre-packed ones from the
        install_wire fast path); everything else lands as f32 buffers."""
        if self.weight_format != "w4":
            return jax.tree.unflatten(self._treedef, _fresh_copy(leaves))
        out = []
        for leaf, packable, shape in zip(leaves, self._packable, self._shapes):
            if isinstance(leaf, PackedW4):
                out.append(leaf)
            elif packable:
                q, s = pack_blockwise4_stacked(
                    np.asarray(jax.device_get(leaf), np.float32)
                )
                out.append(
                    PackedW4(jnp.asarray(q), jnp.asarray(s), tuple(shape[1:]))
                )
            else:
                out.append(jnp.asarray(jax.device_get(leaf), jnp.float32))
        return jax.tree.unflatten(self._treedef, out)

    # -- admission ---------------------------------------------------------

    def admit(
        self,
        slot: int,
        prompt: Sequence[int],
        *,
        prefix_src: Optional[int] = None,
        prefix_len: int = 0,
        host_prefix: Optional[tuple] = None,
    ) -> tuple[int, np.ndarray]:
        """Prefill ``prompt`` into ``slot`` and return (first greedy token,
        last-position logits [V] f32). The prompt must fit a compile
        bucket (scheduler-enforced via ``prompt_fits``).

        With ``prefix_src``/``prefix_len`` the first ``prefix_len`` tokens
        are NOT recomputed: their K/V rows are ring-copied from the live
        source slot (bitwise what a cold prefill writes — causal attention
        makes prefix K/V independent of anything after it) and only the
        suffix runs through the model. ``host_prefix=(k, v, plen)`` is the
        cold-tier variant: the prefix K/V pages come from the host prefix
        store (H2D install) instead of a live slot's ring."""
        n = len(prompt)
        bucket = pick_bucket(n, self.prefill_buckets)
        if bucket is None:
            raise ValueError(
                f"prompt length {n} exceeds max bucket "
                f"{self.prefill_buckets[-1]}"
            )
        t0 = time.perf_counter()
        if host_prefix is not None and 0 < host_prefix[2] < n:
            hk, hv, plen = host_prefix
            self.cache_k, self.cache_v = self._install_pages(
                self.cache_k, self.cache_v,
                jnp.asarray(hk, self.compute_dtype),
                jnp.asarray(hv, self.compute_dtype),
                jnp.int32(slot),
            )
            tok, logits = self._run_suffix(slot, prompt, int(plen))
        elif prefix_src is not None and 0 < prefix_len < n:
            tok, logits = self._admit_suffix(slot, prompt, prefix_src, prefix_len)
        else:
            ids = np.zeros((1, bucket), np.int32)
            ids[0, :n] = np.asarray(prompt, np.int32)
            tokd, logitsd, ks, vs = self._prefill(
                self.params, jnp.asarray(ids), jnp.int32(n)
            )
            self.cache_k, self.cache_v = self._insert(
                self.cache_k, self.cache_v, ks, vs, jnp.int32(slot)
            )
            tok, logits = int(tokd[0]), np.asarray(logitsd[0])
        dt = time.perf_counter() - t0
        self.stage_seconds["prefill"] += dt
        tr = obs.tracer()
        if tr is not None:
            tr.add_span("serve_prefill", t0, t0 + dt, tokens=n)
        return tok, logits

    def _admit_suffix(
        self, slot: int, prompt: Sequence[int], src: int, plen: int
    ) -> tuple[int, np.ndarray]:
        self.cache_k, self.cache_v = self._prefix_copy(
            self.cache_k, self.cache_v,
            jnp.int32(src), jnp.int32(slot), jnp.int32(plen),
        )
        return self._run_suffix(slot, prompt, plen)

    def _run_suffix(
        self, slot: int, prompt: Sequence[int], plen: int
    ) -> tuple[int, np.ndarray]:
        """Continued prefill over ``slot`` whose ring already holds the
        first ``plen`` rows (live-slot copy or tier install)."""
        suffix = np.asarray(prompt[plen:], np.int32)
        ns = int(suffix.size)
        sb = pick_bucket(ns, self.prefill_buckets)
        tail = np.zeros((1, sb), np.int32)
        tail[0, :ns] = suffix
        logits, tks, tvs = self._suffix(
            self.params, self.cache_k, self.cache_v,
            jnp.int32(slot), jnp.asarray(tail), jnp.int32(plen),
        )
        self.cache_k, self.cache_v = self._suffix_insert(
            self.cache_k, self.cache_v, tks, tvs,
            jnp.int32(slot), jnp.int32(plen), jnp.int32(ns),
        )
        row = np.asarray(logits[ns - 1])
        return int(row.argmax()), row

    def prompt_fits(self, n: int) -> bool:
        return pick_bucket(n, self.prefill_buckets) is not None

    # -- KV-tier page transfers ---------------------------------------------

    def page_rows(self, rows: int) -> int:
        """Static transfer row count for ``rows`` live ring rows: padded
        up the prefill-bucket grid (bounded compile family; padding rows
        carry a previous tenant's masked entries, which restore rewrites
        verbatim — harmless by the same lens-mask invariant, see
        ``ops.attention.ring_live_rows``)."""
        if not 0 < rows <= self.max_context:
            raise ValueError(
                f"rows {rows} outside (0, {self.max_context}]"
            )
        return pick_bucket(rows, self.prefill_buckets) or self.max_context

    def fetch_slot_pages(self, slot: int, rows: int) -> tuple:
        """Start an async D2H gather of ``slot``'s leading ``rows`` ring
        rows. Returns device arrays ([L, rows', Nkv, Dh] each, rows'
        bucket-padded) with a host copy already in flight — the caller
        materializes them with ``np.asarray`` on a LATER scheduler
        iteration so the transfer overlaps the next decode step instead
        of blocking the loop. The gather is by value: the slot can be
        re-tenanted immediately."""
        t0 = time.perf_counter()
        pk, pv = self._fetch_pages(
            self.cache_k, self.cache_v, jnp.int32(slot), self.page_rows(rows)
        )
        for a in (pk, pv):
            try:
                a.copy_to_host_async()
            except (AttributeError, RuntimeError):
                pass  # backend without async D2H: np.asarray still works
        self.stage_seconds["page_out"] += time.perf_counter() - t0
        return pk, pv

    def install_slot_pages(self, slot: int, k: np.ndarray, v: np.ndarray) -> None:
        """Page a slot's ring rows back H2D (tier restore): rows [0, R)
        of ``slot`` are rewritten from the host arrays. Dispatch is
        async — the next decode step queues behind it on-stream, so the
        scheduler thread never blocks on the transfer."""
        t0 = time.perf_counter()
        self.cache_k, self.cache_v = self._install_pages(
            self.cache_k, self.cache_v,
            jnp.asarray(k, self.compute_dtype),
            jnp.asarray(v, self.compute_dtype),
            jnp.int32(slot),
        )
        self.stage_seconds["page_in"] += time.perf_counter() - t0

    # -- decode ------------------------------------------------------------

    def decode_step(
        self, tokens: np.ndarray, lens: np.ndarray
    ) -> tuple[np.ndarray, jax.Array]:
        """One greedy token per slot. ``tokens``/``lens`` are dense [S]
        host arrays (inactive slots pass 0s; their ring writes land in
        masked positions and are overwritten on the slot's next tenancy).
        Returns (next tokens [S] np.int32, logits [S, V] on device)."""
        t0 = time.perf_counter()
        tok, logits, self.cache_k, self.cache_v = self._decode(
            self.params,
            jnp.asarray(tokens, jnp.int32),
            jnp.asarray(lens, jnp.int32),
            self.cache_k,
            self.cache_v,
        )
        tok = np.asarray(tok)
        self.stage_seconds["decode"] += time.perf_counter() - t0
        obs.count(f"serve_decode_kernel_{self.decode_kernel}")
        return tok, logits

    def _propose_draft(self, tokens: np.ndarray, lens: np.ndarray) -> np.ndarray:
        return np.asarray(
            self._draft(
                self.params,
                jnp.asarray(tokens, jnp.int32),
                jnp.asarray(lens, jnp.int32),
                self.cache_k,
                self.cache_v,
            )
        )

    def spec_step(
        self, tokens: np.ndarray, lens: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray]:
        """One self-speculative round over all S slots: draft k proposals,
        verify the [current, d_1..d_k] tail full-depth, keep the longest
        agreeing prefix. Returns (g [S, k+1] np.int32, m [S] np.int32):
        slot s emits ``g[s, :m[s]+1]`` — its next m[s]+1 greedy tokens,
        token-identical to m[s]+1 plain decode_steps — and its cache now
        holds the tail rows 0..m[s] (rejected proposals were never
        inserted; that IS the rollback)."""
        if not self.spec_k:
            raise RuntimeError("spec_step requires spec_k > 0")
        t0 = time.perf_counter()
        props = np.asarray(self.propose_fn(tokens, lens), np.int32)  # [S, k]
        t1 = time.perf_counter()
        tail = np.concatenate(
            [np.asarray(tokens, np.int32)[:, None], props], axis=1
        )
        g, tks, tvs = self._verify(
            self.params,
            jnp.asarray(tail),
            jnp.asarray(lens, jnp.int32),
            self.cache_k,
            self.cache_v,
        )
        g = np.asarray(g)  # [S, k+1]
        t2 = time.perf_counter()
        m = accept_counts(props, g)
        self.cache_k, self.cache_v = self._spec_insert(
            self.cache_k, self.cache_v, tks, tvs,
            jnp.asarray(lens, jnp.int32), jnp.asarray(m),
        )
        t3 = time.perf_counter()
        self.stage_seconds["draft"] += t1 - t0
        self.stage_seconds["verify"] += t2 - t1
        self.stage_seconds["insert"] += t3 - t2
        obs.count(f"serve_decode_kernel_{self.decode_kernel}")
        tr = obs.tracer()
        if tr is not None:
            tr.add_span("serve_draft", t0, t1, k=self.spec_k)
            tr.add_span("serve_verify", t1, t2)
            tr.add_span("serve_spec_insert", t2, t3)
        return g, m

    # -- kernel attribution -------------------------------------------------

    def kernel_probe(self, iters: int = 3) -> dict:
        """Time the decode-path kernels in isolation on the engine's live
        shapes and publish per-kernel gauges (serve_decode_attn_us,
        serve_verify_attn_us, serve_w4_matmul_us) so DECODE_BENCH
        attribution shows where the kernel time went, per dispatch path.

        Best-of-``iters`` steady-state timings on the resolved path
        (``self.decode_kernel``); the w4 gauge only appears under
        ``weight_format=w4`` (there is no dequant-matmul otherwise)."""
        cfg, cd = self.cfg, self.compute_dtype
        S, T = self.num_slots, self.max_context
        Nh, Nkv, Dh = cfg.num_attention_heads, cfg.kv_heads, cfg.head_dim
        key = jax.random.PRNGKey(0)
        q1 = jax.random.normal(key, (S, Nh, Dh), cd)
        ck, cv = self.cache_k[0], self.cache_v[0]  # live layer-0 ring pages
        lens = jnp.full((S,), T // 2, jnp.int32)
        kq = self.tail_width
        qt = jax.random.normal(key, (S, kq, Nh, Dh), cd)
        tk = jax.random.normal(key, (S, kq, Nkv, Dh), cd)
        pallas = self.decode_kernel == "pallas"

        def _attn(q1, ck, cv, lens):
            if pallas:
                return paged_decode_attention(q1, ck, cv, lens)
            return decode_attention(q1, ck, cv, lens)

        def _vattn(qt, ck, cv, tk, lens):
            if pallas:
                return spec_tail_attention_fused(qt, ck, cv, tk, tk, lens)
            return spec_tail_attention(qt, ck, cv, tk, tk, lens)

        def _best(fn, *argv):
            f = jax.jit(fn)
            f(*argv).block_until_ready()  # compile outside the timing
            best = float("inf")
            for _ in range(max(1, int(iters))):
                t0 = time.perf_counter()
                f(*argv).block_until_ready()
                best = min(best, time.perf_counter() - t0)
            return best * 1e6

        out = {
            "decode_attn_us": _best(_attn, q1, ck, cv, lens),
            "verify_attn_us": _best(_vattn, qt, ck, cv, tk, lens),
        }
        packed = next(
            (
                w
                for w in jax.tree.leaves(
                    self.params, is_leaf=lambda x: isinstance(x, PackedW4)
                )
                if isinstance(w, PackedW4) and len(w.shape) == 2
            ),
            None,
        )
        if packed is not None:
            x = jax.random.normal(key, (S, packed.shape[0]), cd)
            if pallas:
                def _wmm(x, q, s):
                    return w4_matmul(x, q, s, packed.shape, cd)
            else:
                def _wmm(x, q, s):
                    return x @ dequant_w4(q, s, packed.shape, cd)
            # stacked leaf: layer 0's slice is what one scan step sees
            out["w4_matmul_us"] = _best(_wmm, x, packed.q[0], packed.s[0])
        for name, us in out.items():
            obs.gauge(f"serve_{name}", us)
        obs.gauge(
            "serve_decode_kernel_pallas", 1.0 if pallas else 0.0
        )
        return out

    # -- weight hot-swap ---------------------------------------------------

    def staleness(self) -> int:
        """Outer rounds the serving weights lag the trainer's masters."""
        if self.epoch_fn is None:
            return 0
        return max(0, int(self.epoch_fn()) - self.weights_epoch)

    def maybe_swap(self) -> bool:
        """Adopt the trainer's current master snapshot when staleness
        exceeds ``max_stale_rounds``. Called between decode steps, so no
        request is ever mid-forward across a rebind; the KV cache is not
        touched (pinned by tests/test_serve.py)."""
        if self.snapshot_fn is None:
            return False
        if self.staleness() <= self.max_stale_rounds:
            return False
        t0 = time.perf_counter()
        epoch, blobs, codec_name = self.snapshot_fn()
        if epoch <= self.weights_epoch:
            return False  # raced an in-flight round; keep current weights
        self.install_wire(epoch, blobs, codec_name)
        dt = time.perf_counter() - t0
        self.swap_seconds += dt
        self.stage_seconds["swap"] += dt
        obs.count("serve_weight_swaps")
        obs.gauge("serve_last_swap_ms", dt * 1e3)
        return True

    def install_wire(self, epoch: int, blobs, codec_name: str) -> None:
        """Decode a codec-encoded master snapshot and rebind the weights.

        With ``weight_format=w4`` and a ``blockwise4bit`` snapshot the
        packed leaves are re-sliced straight from the wire payload when
        the codec's whole-leaf block grid lands on layer boundaries —
        cheaper than decoding, AND exact where a dequantize/requantize
        round trip is not bit-stable."""
        codec = get_codec(codec_name)
        if len(blobs) != len(self._shapes):
            raise ValueError(
                f"snapshot has {len(blobs)} leaves, engine expects "
                f"{len(self._shapes)}"
            )
        fast = self.weight_format == "w4" and codec_name == "blockwise4bit"
        leaves = []
        for (payload, meta, shape), want, packable in zip(
            blobs, self._shapes, self._packable
        ):
            if tuple(shape) != want:
                raise ValueError(f"snapshot leaf shape {shape} != {want}")
            size = int(np.prod(shape)) if shape else 1
            if fast and packable:
                res = split_blockwise4_stacked(
                    payload, meta, int(shape[0]), size // int(shape[0])
                )
                if res is not None:
                    q, s = res
                    leaves.append(
                        PackedW4(
                            jnp.asarray(q), jnp.asarray(s), tuple(shape[1:])
                        )
                    )
                    continue
            a = np.asarray(
                codec.decode(payload, (size,), meta), np.float32
            ).reshape(shape)
            leaves.append(a)
        self.params = self._assemble(leaves)
        self.weights_epoch = int(epoch)
        self.swap_count += 1

    def install_params(self, epoch: int, params) -> None:
        """Direct (uncompressed) rebind — tests and static-weight mode."""
        self.params = self._assemble(jax.tree.leaves(params))
        self.weights_epoch = int(epoch)
        self.swap_count += 1
