"""Ring attention: causal attention over a sequence-parallel mesh axis.

Long-context capability the reference lacks entirely (SURVEY.md §5.7: no
SP/CP anywhere; seq_length is a scalar config, train_fsdp.py:111). On TPU it
is first-class: the sequence dim shards over the "sp" mesh axis, each device
holds one contiguous chunk of q/k/v, and K/V chunks rotate around the ring
via ``jax.lax.ppermute`` while flash-style online-softmax statistics
(m, l, acc) accumulate in float32. Peak memory per device is
O(T/sp * T/sp) per rotation step, never the full [T, T].

GQA is computed grouped: Q is viewed as [B, T, Hkv, G, D] and contracted
against the narrow K/V directly -- K/V are never materialized at q-head
width.

The backward pass is a hand-written VJP (not autodiff through the scan):
the forward saves only (q, k, v, out, lse); the backward re-rotates K/V
around the ring a second time with dK/dV accumulators travelling along, so
no rotation activations are kept live and each chunk's gradient lands back
on its owner after a full revolution. This is the standard flash-attention
backward recurrence (dS = P * (dP - rowsum(dO*O))) in ring form.

Causality falls out of global position masks: a K/V chunk from a later ring
position contributes nothing (its probabilities underflow to exp(-inf)=0),
chunks from earlier positions contribute fully, and the diagonal chunk is
triangle-masked.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from opendiloco_tpu.ops.pallas_util import (
    axis_size as _axis_size,
    pcast_varying as _pcast_varying,
    shard_map as _shard_map,
)

_NEG_INF = float(-1e30)

# mesh registry: the trainer configures this so model code can stay
# mesh-agnostic (set by InnerTrainer when attn_impl == "ring")
_RING_MESH = None
_RING_AXIS = "sp"


def configure_ring(mesh, axis: str = "sp") -> None:
    global _RING_MESH, _RING_AXIS
    _RING_MESH = mesh
    _RING_AXIS = axis


def _grouped(q: jax.Array, hkv: int) -> jax.Array:
    """[B, T, Hq, D] -> [B, T, Hkv, G, D] view for grouped-query attention."""
    b, t, hq, d = q.shape
    return q.reshape(b, t, hkv, hq // hkv, d)


def _scores(qg: jax.Array, k: jax.Array, q_pos, k_pos, *, causal) -> jax.Array:
    """Masked attention logits [B, Hkv, G, Tq, Tk] (float32).

    Matmul operands stay in the input dtype (bf16 in production -- f32
    inputs run the v5e MXU at a fraction of bf16 rate, same discipline as
    the flash kernel); accumulation is f32 via preferred_element_type.
    """
    d = qg.shape[-1]
    s = jnp.einsum(
        "bqhgd,bkhd->bhgqk",
        qg,
        k,
        preferred_element_type=jnp.float32,
    ) * (d**-0.5)
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None, None, None], s, _NEG_INF)
    return s


def _block_attn(qg, k, v, q_pos, k_pos, m, l, acc, *, causal):
    """One online-softmax accumulation step (grouped heads).

    qg: [B, Tq, Hkv, G, D]; k/v: [B, Tk, Hkv, D]; positions are global.
    m/l: [B, Hkv, G, Tq, 1]; acc: [B, Hkv, G, Tq, D] (all float32).
    """
    s = _scores(qg, k, q_pos, k_pos, causal=causal)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc * corr + jnp.einsum(
        "bhgqk,bkhd->bhgqd",
        p.astype(v.dtype),
        v,
        preferred_element_type=jnp.float32,
    )
    return m_new, l_new, acc_new


def _ring_vma(axis_name: str, ref) -> frozenset:
    """Varying-manual-axes set for ring internals: the ring axis plus any
    OUTER manual axes ``ref`` already varies over. Standalone sp meshes get
    {sp} exactly as before; nested inside the pp pipeline's partial-manual
    region the inputs are also pp-varying, and fresh scan carriers /
    kernel outputs must carry the full type from step 0 or the scan's
    carry types mismatch."""
    try:
        typeof = getattr(jax, "typeof", None)  # newer-jax only
        extra = (
            getattr(typeof(ref), "vma", frozenset()) if typeof else frozenset()
        ) or frozenset()
    except Exception:  # pragma: no cover - tracing-context quirks
        extra = frozenset()
    return frozenset(extra) | {axis_name}


def _ring_forward(q, k, v, axis_name, causal):
    """-> (out [B, Tl, Hq, D], lse [B, Hkv, G, Tq, 1] float32)."""
    b, tl, hq, d = q.shape
    hkv = k.shape[2]
    qg = _grouped(q, hkv)

    idx = jax.lax.axis_index(axis_name)
    n = _axis_size(axis_name)
    q_pos = idx * tl + jnp.arange(tl, dtype=jnp.int32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, i):
        k_cur, v_cur, m, l, acc = carry
        src = (idx - i) % n  # whose chunk we hold at this rotation
        k_pos = src * tl + jnp.arange(tl, dtype=jnp.int32)
        m, l, acc = _block_attn(
            qg, k_cur, v_cur, q_pos, k_pos, m, l, acc, causal=causal
        )
        # rotate for the next step (result intentionally unused on the
        # final iteration -- K/V are simply back at their owners)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, m, l, acc), None

    g = hq // hkv
    m0 = jnp.full((b, hkv, g, tl, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, hkv, g, tl, 1), jnp.float32)
    acc0 = jnp.zeros((b, hkv, g, tl, d), jnp.float32)
    # stats become device-varying after the first accumulation step; the scan
    # carry must have that type from the start (including any outer manual
    # axes when nested in the pp pipeline)
    m0, l0, acc0 = _pcast_varying((m0, l0, acc0), _ring_vma(axis_name, q))
    (_, _, m, l, acc), _ = jax.lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(n), length=n
    )

    l_safe = jnp.where(l == 0, 1.0, l)
    lse = m + jnp.log(l_safe)
    out = acc / l_safe  # [B, Hkv, G, Tq, D]
    out = out.transpose(0, 3, 1, 2, 4).reshape(b, tl, hq, d).astype(q.dtype)
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "sp",
    causal: bool = True,
) -> jax.Array:
    """Must run inside shard_map with the sequence dim sharded on axis_name.

    q/k/v: local chunks [B, T_local, Hq|Hkv, D] -> out [B, T_local, Hq, D].
    """
    out, _ = _ring_forward(q, k, v, axis_name, causal)
    return out


def _ring_fwd(q, k, v, axis_name, causal):
    out, lse = _ring_forward(q, k, v, axis_name, causal)
    # tag residuals so selective remat ("dots") saves them -- otherwise the
    # backward pass replays the whole ring forward, ppermutes included
    out = checkpoint_name(out, "attn_out")
    lse = checkpoint_name(lse, "attn_lse")
    return out, (q, k, v, out, lse)


def _ring_bwd(axis_name, causal, res, dout):
    """Flash backward in ring form: dK/dV accumulators rotate WITH their K/V
    chunks, so after a full revolution each chunk's gradient is home."""
    q, k, v, out, lse = res
    b, tl, hq, d = q.shape
    hkv = k.shape[2]
    scale = d**-0.5

    qg = _grouped(q, hkv)
    dog = _grouped(dout, hkv)
    # D_i = rowsum(dO * O): [B, Hkv, G, Tq, 1] -- elementwise, keep f32
    D = jnp.sum(
        _grouped(dout.astype(jnp.float32), hkv)
        * _grouped(out.astype(jnp.float32), hkv),
        axis=-1,
    ).transpose(0, 2, 3, 1)[..., None]

    idx = jax.lax.axis_index(axis_name)
    n = _axis_size(axis_name)
    q_pos = idx * tl + jnp.arange(tl, dtype=jnp.int32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, i):
        k_cur, v_cur, dk_cur, dv_cur, dq = carry
        src = (idx - i) % n
        k_pos = src * tl + jnp.arange(tl, dtype=jnp.int32)
        s = _scores(qg, k_cur, q_pos, k_pos, causal=causal)
        p = jnp.exp(s - lse)  # masked entries underflow to exactly 0
        dv_cur = dv_cur + jnp.einsum(
            "bhgqk,bqhgd->bkhd",
            p.astype(dout.dtype),
            dog,
            preferred_element_type=jnp.float32,
        )
        dp = jnp.einsum(
            "bqhgd,bkhd->bhgqk",
            dog,
            v_cur,
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - D)
        dq = dq + scale * jnp.einsum(
            "bhgqk,bkhd->bqhgd",
            ds.astype(k_cur.dtype),
            k_cur,
            preferred_element_type=jnp.float32,
        )
        dk_cur = dk_cur + scale * jnp.einsum(
            "bhgqk,bqhgd->bkhd",
            ds.astype(qg.dtype),
            qg,
            preferred_element_type=jnp.float32,
        )
        rotated = [
            jax.lax.ppermute(x, axis_name, perm)
            for x in (k_cur, v_cur, dk_cur, dv_cur)
        ]
        return (*rotated, dq), None

    dk0 = jnp.zeros((b, tl, hkv, d), jnp.float32)
    dv0 = jnp.zeros_like(dk0)
    dq0 = jnp.zeros((b, tl, hkv, hq // hkv, d), jnp.float32)
    dk0, dv0, dq0 = _pcast_varying((dk0, dv0, dq0), _ring_vma(axis_name, q))
    (_, _, dk, dv, dq), _ = jax.lax.scan(
        step, (k, v, dk0, dv0, dq0), jnp.arange(n), length=n
    )
    # n rotations = full revolution: dk/dv are back at their owners
    dq = dq.reshape(b, tl, hq, d).astype(q.dtype)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


ring_attention.defvjp(_ring_fwd, _ring_bwd)


# ---------------------------------------------------------------------------
# flash-chunk ring: per-rotation block math runs the Pallas flash kernels
# ---------------------------------------------------------------------------
#
# Same ring schedule as above, but each rotation step processes its K/V chunk
# with the flash-attention Pallas kernels (ops/flash_attention.py) instead of
# XLA einsums: the [Tl, Tl] score matrix never reaches HBM and the per-chunk
# softmax runs fused in VMEM. Per-chunk (out, lse) pairs merge with the
# standard log-sum-exp recurrence, which is exactly the online-softmax merge
# the einsum path carries, so results are identical up to rounding. The
# rotation schedule is causal-aware: step 0 is the diagonal chunk (causal
# flash), later steps run the unmasked kernel only when the held chunk is
# from an earlier ring position (lax.cond skips future chunks).


def _ring_flash_forward(q, k, v, axis_name, block):
    """q [B,Tl,Hq,D], k/v [B,Tl,Hkv,D] -> (out [B,Tl,Hq,D], lse [B,Hq,1,Tl])."""
    from opendiloco_tpu.ops.flash_attention import _fwd

    qT, kT, vT = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    vma = _ring_vma(axis_name, q)

    idx = jax.lax.axis_index(axis_name)
    n = _axis_size(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    # step 0: own (diagonal) chunk, standard causal flash -- guarantees a
    # finite lse for every query row before any merge
    o, lse = _fwd(qT, kT, vT, block_q=block, block_k=block, causal=True, vma=vma)
    o = o.astype(jnp.float32)

    def step(carry, i):
        k_c, v_c, o, lse = carry
        k_c = jax.lax.ppermute(k_c, axis_name, perm)
        v_c = jax.lax.ppermute(v_c, axis_name, perm)
        src = (idx - i) % n  # ring position of the chunk we now hold

        def live(ops):
            kk, vv = ops
            oi, lsei = _fwd(
                qT, kk, vv, block_q=block, block_k=block, causal=False, vma=vma
            )
            return oi.astype(jnp.float32), lsei

        def dead(ops):
            # future chunk: contributes nothing (lse=-inf merges to a no-op)
            return jnp.zeros_like(o), jnp.full_like(lse, _NEG_INF)

        oi, lsei = jax.lax.cond(src < idx, live, dead, (k_c, v_c))
        lse_new = jnp.logaddexp(lse, lsei)
        # weights are [B,Hq,1,Tl]; swap to [B,Hq,Tl,1] to scale the outputs
        w = jnp.swapaxes(jnp.exp(lse - lse_new), -1, -2)
        wi = jnp.swapaxes(jnp.exp(lsei - lse_new), -1, -2)
        o = o * w + oi * wi
        return (k_c, v_c, o, lse_new), None

    (_, _, o, lse), _ = jax.lax.scan(step, (kT, vT, o, lse), jnp.arange(1, n))
    out = o.transpose(0, 2, 1, 3).astype(q.dtype)
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def ring_flash_attention(q, k, v, axis_name, block):
    """Causal ring attention with Pallas flash per-chunk kernels.

    Must run inside shard_map with the sequence dim sharded on axis_name;
    Tl must tile by ``block`` (the caller gates on this).
    """
    out, _ = _ring_flash_forward(q, k, v, axis_name, block)
    return out


def _ring_flash_fwd(q, k, v, axis_name, block):
    out, lse = _ring_flash_forward(q, k, v, axis_name, block)
    out = checkpoint_name(out, "attn_out")
    lse = checkpoint_name(lse, "attn_lse")
    return out, (q, k, v, out, lse)


def _ring_flash_bwd(axis_name, block, res, dout):
    """Flash backward per chunk with the global lse; dK/dV accumulators
    (f32) rotate with their chunks, one extra rotation brings them home."""
    from opendiloco_tpu.ops.flash_attention import _bwd_impl, _delta

    q, k, v, out, lse = res
    qT, kT, vT, oT, doT = (
        x.transpose(0, 2, 1, 3) for x in (q, k, v, out, dout)
    )
    delta = _delta(doT, oT)

    idx = jax.lax.axis_index(axis_name)
    n = _axis_size(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    kwargs = dict(
        block_q=block,
        block_k=block,
        grad_dtype=jnp.float32,
        vma=_ring_vma(axis_name, q),
    )
    dq, dk, dv = _bwd_impl(qT, kT, vT, doT, lse, delta, causal=True, **kwargs)

    def step(carry, i):
        k_c, v_c, dk, dv, dq = carry
        k_c, v_c, dk, dv = (
            jax.lax.ppermute(x, axis_name, perm) for x in (k_c, v_c, dk, dv)
        )
        src = (idx - i) % n

        def live(ops):
            kk, vv = ops
            return _bwd_impl(qT, kk, vv, doT, lse, delta, causal=False, **kwargs)

        def dead(ops):
            return jnp.zeros_like(dq), jnp.zeros_like(dk), jnp.zeros_like(dv)

        dqi, dki, dvi = jax.lax.cond(src < idx, live, dead, (k_c, v_c))
        return (k_c, v_c, dk + dki, dv + dvi, dq + dqi), None

    (_, _, dk, dv, dq), _ = jax.lax.scan(
        step, (kT, vT, dk, dv, dq), jnp.arange(1, n)
    )
    # n-1 in-scan rotations + this one = full revolution: grads are home
    dk = jax.lax.ppermute(dk, axis_name, perm)
    dv = jax.lax.ppermute(dv, axis_name, perm)
    dq = dq.transpose(0, 2, 1, 3).astype(q.dtype)
    dk = dk.transpose(0, 2, 1, 3).astype(k.dtype)
    dv = dv.transpose(0, 2, 1, 3).astype(v.dtype)
    return dq, dk, dv


ring_flash_attention.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def _flash_chunk_block(mesh, axis: str, q, causal: bool, local: bool = False) -> int:
    """Block size for the flash-chunk ring path, or 0 for the einsum path.

    Flash chunks need: causal attention, a TPU mesh (or the
    OPENDILOCO_TPU_RING_FLASH=1 override for interpret-mode tests), a local
    chunk length that tiles by 128, and a lane-aligned head dim. ``local``:
    q is already the per-device chunk (direct-call path inside an
    already-manual region) rather than the global-view array.
    """
    if not causal:
        return 0
    env = os.environ.get("OPENDILOCO_TPU_RING_FLASH", "").strip().lower()
    if env in ("0", "false", "no", "off"):
        return 0
    if env not in ("1", "true", "yes", "on"):
        # unset (or unrecognized): the Pallas path is TPU-only
        dev = mesh.devices.flat[0]
        if "tpu" not in getattr(dev, "device_kind", "").lower():
            return 0
    from opendiloco_tpu.ops.pallas_util import pick_block as _pick_block

    n = mesh.shape[axis]
    tl = q.shape[1] // n if not local else q.shape[1]
    if q.shape[-1] % 8:
        return 0
    return _pick_block(tl, 1024)


def ring_attention_auto(
    q: jax.Array, k: jax.Array, v: jax.Array, *, mesh=None, axis: Optional[str] = None
) -> jax.Array:
    """Wrap ring_attention in a shard_map over the mesh's sp axis.

    Callable from inside the (jit-compiled) model forward: batch/head dims
    stay auto-sharded, only the sequence axis is manual. Pass the mesh
    explicitly (the trainer threads its plan.mesh through forward); the
    module registry is only a fallback for direct/experimental callers.
    """
    mesh = mesh if mesh is not None else _RING_MESH
    axis = axis or _RING_AXIS
    if mesh is None:
        raise RuntimeError(
            "ring attention needs a mesh: pass mesh= or call configure_ring(mesh)"
        )
    P = jax.sharding.PartitionSpec
    spec = P(None, axis, None, None)
    # block-size/device decisions read the CONCRETE mesh; the shard_map
    # itself must use the tracing context's mesh when we are already inside
    # another partial-manual region (the pp pipeline): there the context is
    # an AbstractMesh with the outer axes Manual, and a concrete mesh would
    # be rejected. Nesting over a disjoint manual axis set is supported --
    # this is what composes sp ring attention with pipeline stages.
    inside_manual = False
    try:
        ctx = jax.sharding.get_abstract_mesh()
        types = dict(
            zip(getattr(ctx, "axis_names", ()), getattr(ctx, "axis_types", ()))
        )
        inside_manual = types.get(axis) == jax.sharding.AxisType.Manual
    except Exception:  # pragma: no cover - older jax without abstract mesh
        pass
    if not inside_manual and not hasattr(jax.sharding, "get_abstract_mesh"):
        # pre-AbstractMesh jax can't introspect the tracing context, but
        # there the esm fallback binds regions FULL-manual — so the ring
        # axis having a bound frame means we are already inside one and a
        # nested shard_map would re-bind outer axes (rejected)
        try:
            jax.core.axis_frame(axis)
            inside_manual = True
        except Exception:
            pass
    block = _flash_chunk_block(mesh, axis, q, causal=True, local=inside_manual)
    if block:
        body = lambda q, k, v: ring_flash_attention(q, k, v, axis, block)
    else:
        # positional args: custom_vjp nondiff_argnums are position-based
        body = lambda q, k, v: ring_attention(q, k, v, axis, True)
    if inside_manual:
        # already inside a manual region over the ring axis (the sp+pp
        # pipeline binds both axes manual): q/k/v are the local chunks,
        # so run the ring body directly — a nested shard_map here would
        # lower in the forward but has no jvp lowering (Shardy rejects
        # re-binding the outer axis; GSPMD check-fails)
        return body(q, k, v)
    fn = _shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        axis_names={axis},
    )
    return fn(q, k, v)
