"""Ring attention: causal attention over a sequence-parallel mesh axis.

Long-context capability the reference lacks entirely (SURVEY.md §5.7: no
SP/CP anywhere; seq_length is a scalar config, train_fsdp.py:111). On TPU it
is first-class: the sequence dim shards over the "sp" mesh axis, each device
holds one contiguous chunk of q/k/v, and K/V chunks rotate around the ring
via ``jax.lax.ppermute`` while flash-style online-softmax statistics
(m, l, acc) accumulate in float32. Peak memory per device is O(T/sp * T/sp)
per rotation step, never the full [T, T].

Causality falls out of global position masks: a K/V chunk from a later ring
position contributes nothing (its probabilities underflow to exp(-inf)=0),
chunks from earlier positions contribute fully, and the diagonal chunk is
triangle-masked.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = float(-1e30)

# mesh registry: the trainer configures this so model code can stay
# mesh-agnostic (set by InnerTrainer when attn_impl == "ring")
_RING_MESH = None
_RING_AXIS = "sp"


def configure_ring(mesh, axis: str = "sp") -> None:
    global _RING_MESH, _RING_AXIS
    _RING_MESH = mesh
    _RING_AXIS = axis


def _block_attn(q, k, v, q_pos, k_pos, m, l, acc, *, causal):
    """One online-softmax accumulation step.

    q: [B, Tq, H, D]; k/v: [B, Tk, H, D]; positions are global indices.
    m/l: [B, H, Tq, 1]; acc: [B, H, Tq, D] (all float32).
    """
    d = q.shape[-1]
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * (d**-0.5)
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc * corr + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v.astype(jnp.float32), preferred_element_type=jnp.float32
    )
    return m_new, l_new, acc_new


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "sp",
    causal: bool = True,
) -> jax.Array:
    """Must run inside shard_map with the sequence dim sharded on axis_name.

    q/k/v: local chunks [B, T_local, H, D] -> out [B, T_local, H, D].
    """
    b, tl, hq, d = q.shape
    hkv = k.shape[2]
    if hkv != hq:
        rep = hq // hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)

    idx = jax.lax.axis_index(axis_name)
    n = jax.lax.axis_size(axis_name)
    qf = q.astype(jnp.float32)
    q_pos = idx * tl + jnp.arange(tl, dtype=jnp.int32)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, i):
        k_cur, v_cur, m, l, acc = carry
        src = (idx - i) % n  # whose chunk we hold at this rotation
        k_pos = src * tl + jnp.arange(tl, dtype=jnp.int32)
        m, l, acc = _block_attn(
            qf, k_cur.astype(jnp.float32), v_cur, q_pos, k_pos, m, l, acc,
            causal=causal,
        )
        # rotate for the next step (skipped result on the last iteration)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, m, l, acc), None

    m0 = jnp.full((b, hq, tl, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, hq, tl, 1), jnp.float32)
    acc0 = jnp.zeros((b, hq, tl, d), jnp.float32)
    # stats become device-varying after the first accumulation step; the scan
    # carry must have that type from the start
    m0, l0, acc0 = jax.lax.pcast((m0, l0, acc0), axis_name, to="varying")
    (k, v, m, l, acc), _ = jax.lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(n), length=n
    )

    l_safe = jnp.where(l == 0, 1.0, l)
    out = (acc / l_safe).astype(q.dtype)  # [B, H, Tl, D]
    return out.transpose(0, 2, 1, 3)


def ring_attention_auto(
    q: jax.Array, k: jax.Array, v: jax.Array, *, mesh=None, axis: Optional[str] = None
) -> jax.Array:
    """Wrap ring_attention in a shard_map over the mesh's sp axis.

    Callable from inside the (jit-compiled) model forward: batch/head dims
    stay auto-sharded, only the sequence axis is manual. Pass the mesh
    explicitly (the trainer threads its plan.mesh through forward); the
    module registry is only a fallback for direct/experimental callers.
    """
    mesh = mesh if mesh is not None else _RING_MESH
    axis = axis or _RING_AXIS
    if mesh is None:
        raise RuntimeError(
            "ring attention needs a mesh: pass mesh= or call configure_ring(mesh)"
        )
    P = jax.sharding.PartitionSpec
    spec = P(None, axis, None, None)
    fn = jax.shard_map(
        functools.partial(ring_attention, axis_name=axis, causal=True),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        axis_names={axis},
    )
    return fn(q, k, v)
