"""Pallas TPU serving kernels: ragged paged decode attention, fused W4
dequant-matmul, fused speculative verify.

PR 11's decode speedups were algorithmic (speculation, 4-bit residency,
prefix reuse); the ops underneath stayed stock XLA: ``decode_attention``
dense-masks the whole ring page per slot, ``spec_tail_attention``
materializes full repeat-KV score tensors, and ``PackedW4`` leaves
dequantize to full f32 weight matrices at every matmul site. These
kernels move the decode hot path onto the MXU the way the inner loop's
flash kernel did (PagedAttention-style cache-aware decode, arXiv
2309.06180), token-bit-exact against the XLA paths:

- :func:`paged_decode_attention` reads the slot-paged ring KV cache
  ``[S, T, Kh, D]`` directly. The per-slot ``lens`` vector rides the
  grid as a scalar-prefetch operand, so each slot's dead ring blocks are
  skipped (``pl.when``) AND their DMAs elided (the BlockSpec index map
  clamps to the last live block, an unchanged index reuses the resident
  tile — same trick as the flash kernel's causal skip). GQA is handled
  by block geometry: grid position (slot, kv-head) loads exactly that kv
  head's ``rep`` query rows, never a ``_repeat_kv`` materialization.
  Online softmax in f32 matches ``decode_attention`` row-for-row.
- :func:`w4_matmul` fuses the blockwise-4-bit dequant into the matmul:
  packed nibbles dequantize in-registers per ``[block_k, N]`` tile with
  bit-for-bit the ``native._dequant4_numpy`` element order and per-4096-
  block f16-scale math (pinned by an identity-matmul probe in tests),
  instead of materializing the full f32 weight in HBM first. Nibble
  interleave is resolved by splitting the output into even/odd column
  planes (one [2, M, N/2] kernel output, re-interleaved by the caller's
  reshape) so the kernel never needs an in-VMEM relayout.
- :func:`spec_tail_attention_fused` implements ``spec_tail_attention``'s
  exact ring-wrap eviction mask over cache AND in-register tail K/V in
  one online-softmax pass — the ring blocks stream first (dead blocks
  skipped via ``lens`` like the decode kernel), the tail block runs
  last, and no concat-mask score tensor is ever built.

Dispatch: ``ODTP_DECODE_KERNEL=auto|pallas|xla`` (``ServeConfig.
decode_kernel``). ``auto`` — the default — selects Pallas only when the
backend is TPU; off-TPU it always resolves to the XLA paths, so CPU rigs
keep today's exact code. Forcing ``pallas`` off-TPU runs the kernels in
Pallas interpret mode (slow, but semantically the kernel) — that is how
the parity tests pin token-bit-exactness on a CPU rig. Shapes a kernel
cannot tile (head_dim not a multiple of 8, odd N) fall back to the XLA
path per call, mirroring ``flash_attention``'s fallback contract.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from opendiloco_tpu.ops.attention import decode_attention, spec_tail_attention
from opendiloco_tpu.ops.pallas_util import (
    NEG_INF,
    compiler_params,
    out_vma,
    sds,
    pick_block,
)

W4_BLOCK = 4096  # diloco.compression._BLOCK (pinned by tests)

DECODE_KERNELS = ("auto", "pallas", "xla")


def resolve_decode_kernel(spec: str | None = None) -> str:
    """Resolve a dispatch spec to the concrete path ("pallas" | "xla").

    ``spec`` is ``ServeConfig.decode_kernel`` or the ``ODTP_DECODE_KERNEL``
    env knob (unset/empty = "auto"). ``auto`` NEVER selects Pallas off-TPU:
    the CPU rig keeps the stock XLA decode path bit-for-bit."""
    spec = spec or os.environ.get("ODTP_DECODE_KERNEL") or "auto"
    if spec not in DECODE_KERNELS:
        raise ValueError(
            f"unknown decode kernel {spec!r}; expected one of {DECODE_KERNELS}"
        )
    if spec == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "xla"
    return spec


def _interpret(interpret: bool | None) -> bool:
    """A forced Pallas path off-TPU runs interpreted — slow, but it is the
    kernel's own dataflow, which is what the CPU parity tests pin."""
    if interpret is None:
        return jax.default_backend() != "tpu"
    return bool(interpret)


def _ring_block(t: int, block_t: int | None) -> int:
    """Ring-page tile size: explicit arg > ``ODTP_DECODE_BLOCK_T`` > the
    shared block heuristic > the whole page (always tiles)."""
    if block_t:
        return block_t if t % block_t == 0 else t
    env = os.environ.get("ODTP_DECODE_BLOCK_T")
    if env:
        b = int(env)
        if b > 0 and t % b == 0:
            return b
    return pick_block(t, 256) or t


# ---------------------------------------------------------------------------
# (a) ragged paged decode attention
# ---------------------------------------------------------------------------


def _decode_attn_kernel(
    lens_ref, q_ref, k_ref, v_ref, o_ref, *rest,
    scale, block_t, t, num_t, with_stats,
):
    if with_stats:
        stats_ref, m_scr, l_scr, acc_scr, cnt_scr = rest
    else:
        (m_scr, l_scr, acc_scr), cnt_scr = rest, None
    rep, d = q_ref.shape
    si, ti = pl.program_id(0), pl.program_id(2)

    @pl.when(ti == 0)
    def _init():
        m_scr[:] = jnp.full((rep, 1), NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros((rep, 1), jnp.float32)
        acc_scr[:] = jnp.zeros((rep, d), jnp.float32)
        if with_stats:
            cnt_scr[:] = jnp.zeros((1, 1), jnp.int32)

    lens_s = lens_ref[si]
    # valid cache entries are idx <= lens (whole ring once lens >= t), so
    # blocks past min(lens, t-1) hold no live rows for this slot
    last_live = jnp.minimum(lens_s, t - 1) // block_t

    @pl.when(ti <= last_live)
    def _step():
        q = q_ref[:]
        k_blk = k_ref[:]
        v_blk = v_ref[:]
        s = scale * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [rep, block_t]
        idx = ti * block_t + jax.lax.broadcasted_iota(
            jnp.int32, (rep, block_t), 1
        )
        valid = (idx <= lens_s) | (lens_s >= t)
        s = jnp.where(valid, s, NEG_INF)
        m_prev, l_prev, acc = m_scr[:], l_scr[:], acc_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        m_scr[:] = m_new
        l_scr[:] = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc * corr + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if with_stats:
            cnt_scr[0, 0] += 1

    @pl.when(ti == num_t - 1)
    def _finish():
        l = l_scr[:]
        l_safe = jnp.where(l == 0, 1.0, l)
        o_ref[:] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        if with_stats:
            stats_ref[0, 0] = cnt_scr[0, 0]


def paged_decode_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    lens: jax.Array,
    *,
    block_t: int | None = None,
    interpret: bool | None = None,
    return_stats: bool = False,
):
    """Drop-in :func:`~opendiloco_tpu.ops.attention.decode_attention`:
    q [S, H, D] over ring pages k/v [S, T, Kh, D] with per-slot ``lens``.

    ``return_stats`` additionally returns the measured per-(slot, kv-head)
    count of ring blocks the kernel actually processed — the dead-block
    skip evidence banked by scripts/decode_kernel_bench.py."""
    s_, t, nkv, d = k.shape
    h = q.shape[1]
    if d % 8 != 0 or h % nkv != 0:
        out = decode_attention(q, k, v, lens)
        return (out, None) if return_stats else out
    rep = h // nkv
    bt = _ring_block(t, block_t)
    num_t = t // bt
    interp = _interpret(interpret)

    # Mosaic requires the last two dims of every block to be (8, 128)-
    # aligned OR equal to the array's own dims. rep and the kv-head axis
    # are tiny and never 8-aligned, so they must BE array dims: view the
    # cache as [S, Kh, T, D] ([bt, d] tiles) and q as [S, Kh, rep, D]
    # ([rep, d] tiles, rep == its array dim). Kernel ref shapes are
    # identical to the untransposed layout — only the DMA geometry moves.
    kt_ = k.transpose(0, 2, 1, 3)
    vt_ = v.transpose(0, 2, 1, 3)
    q4 = q.reshape(s_, nkv, rep, d)

    def kv_map(si, hi, ti, lens_ref):
        # clamp dead blocks to the last live one: unchanged index = no DMA
        last = jnp.minimum(lens_ref[si], t - 1) // bt
        return (si, hi, jnp.minimum(ti, last), 0)

    def q_map(si, hi, ti, lr):
        return (si, hi, 0, 0)

    out_specs = [pl.BlockSpec((None, None, rep, d), q_map)]
    out_shape = [sds((s_, nkv, rep, d), q.dtype, vma=out_vma(q))]
    scratch = [
        pltpu.VMEM((rep, 1), jnp.float32),
        pltpu.VMEM((rep, 1), jnp.float32),
        pltpu.VMEM((rep, d), jnp.float32),
    ]
    if return_stats:
        out_specs.append(
            pl.BlockSpec((None, None, 1, 1), lambda si, hi, ti, lr: (si, hi, 0, 0))
        )
        out_shape.append(sds((s_, nkv, 1, 1), jnp.int32))
        scratch.append(pltpu.VMEM((1, 1), jnp.int32))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(s_, nkv, num_t),
        in_specs=[
            pl.BlockSpec((None, None, rep, d), q_map),
            pl.BlockSpec((None, None, bt, d), kv_map),
            pl.BlockSpec((None, None, bt, d), kv_map),
        ],
        out_specs=out_specs,
        scratch_shapes=scratch,
    )
    res = pl.pallas_call(
        functools.partial(
            _decode_attn_kernel,
            scale=d**-0.5, block_t=bt, t=t, num_t=num_t,
            with_stats=return_stats,
        ),
        grid_spec=grid_spec,
        out_shape=out_shape,
        compiler_params=compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interp,
    )(lens.astype(jnp.int32), q4, kt_, vt_)
    out = res[0].reshape(s_, h, d)
    if return_stats:
        return out, res[1].reshape(s_, nkv)
    return out


# ---------------------------------------------------------------------------
# (c) fused speculative verify (ring + in-register tail, one pass)
# ---------------------------------------------------------------------------


def _spec_tail_kernel(
    lens_ref, q_ref, k_ref, v_ref, tk_ref, tv_ref, o_ref, *rest,
    scale, q_start, block_t, t, num_t, rep, with_stats,
):
    if with_stats:
        stats_ref, m_scr, l_scr, acc_scr, cnt_scr = rest
    else:
        (m_scr, l_scr, acc_scr), cnt_scr = rest, None
    kq, _, d = q_ref.shape
    kt = tk_ref.shape[0]
    si, ti = pl.program_id(0), pl.program_id(2)

    @pl.when(ti == 0)
    def _init():
        m_scr[:] = jnp.full((rep, kq, 1), NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros((rep, kq, 1), jnp.float32)
        acc_scr[:] = jnp.zeros((rep, kq, d), jnp.float32)
        if with_stats:
            cnt_scr[:] = jnp.zeros((1, 1), jnp.int32)

    lens_s = lens_ref[si]
    # pre-tail ring liveness is idx < lens (strict: the tail's own K/V is
    # in-register, not the ring) — or the whole ring once lens >= t
    last_ring = jnp.where(
        lens_s >= t, num_t - 1, jnp.maximum(lens_s - 1, 0) // block_t
    )
    ring_on = (lens_s >= t) | ((lens_s > 0) & (ti <= last_ring))

    @pl.when((ti < num_t) & ring_on)
    def _ring_step():
        k_blk = k_ref[:]  # [block_t, d]
        v_blk = v_ref[:]
        idx = ti * block_t + jax.lax.broadcasted_iota(
            jnp.int32, (kq, block_t), 1
        )
        qi = jax.lax.broadcasted_iota(jnp.int32, (kq, block_t), 0)
        j = q_start + qi
        base = (idx < lens_s) | (lens_s >= t)
        # disp = the i whose tail ring write ((lens+i) % T) lands on this
        # slot; query j has evicted it when that write precedes j and wraps
        disp = jnp.mod(idx - lens_s, t)
        evicted = (disp <= j) & ((lens_s + disp) >= t)
        valid = base & ~evicted  # [kq, block_t], same for every q head
        for r in range(rep):
            q_r = q_ref[:, r, :]  # [kq, d]
            s = scale * jax.lax.dot_general(
                q_r, k_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            s = jnp.where(valid, s, NEG_INF)
            m_prev, l_prev, acc = m_scr[r], l_scr[r], acc_scr[r]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
            p = jnp.exp(s - m_new)
            corr = jnp.exp(m_prev - m_new)
            m_scr[r] = m_new
            l_scr[r] = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
            acc_scr[r] = acc * corr + jax.lax.dot_general(
                p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        if with_stats:
            cnt_scr[0, 0] += 1

    @pl.when(ti == num_t)
    def _tail_step():
        tk_blk = tk_ref[:]  # [kt, d]
        tv_blk = tv_ref[:]
        qi = q_start + jax.lax.broadcasted_iota(jnp.int32, (kq, kt), 0)
        ki = jax.lax.broadcasted_iota(jnp.int32, (kq, kt), 1)
        valid = ki <= qi  # causal within the tail
        for r in range(rep):
            q_r = q_ref[:, r, :]
            s = scale * jax.lax.dot_general(
                q_r, tk_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            s = jnp.where(valid, s, NEG_INF)
            m_prev, l_prev, acc = m_scr[r], l_scr[r], acc_scr[r]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
            p = jnp.exp(s - m_new)
            corr = jnp.exp(m_prev - m_new)
            l_new = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
            acc = acc * corr + jax.lax.dot_general(
                p.astype(tv_blk.dtype), tv_blk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            # the tail always holds at least the query's own position, so
            # l_new > 0; the guard mirrors the flash kernel's finish
            l_safe = jnp.where(l_new == 0, 1.0, l_new)
            o_ref[:, r, :] = (acc / l_safe).astype(o_ref.dtype)
        if with_stats:
            stats_ref[0, 0] = cnt_scr[0, 0]


def spec_tail_attention_fused(
    q: jax.Array,
    cache_k: jax.Array,
    cache_v: jax.Array,
    tail_k: jax.Array,
    tail_v: jax.Array,
    lens: jax.Array,
    *,
    q_start: int = 0,
    block_t: int | None = None,
    interpret: bool | None = None,
    return_stats: bool = False,
):
    """Drop-in :func:`~opendiloco_tpu.ops.attention.spec_tail_attention`:
    q [S, Kq, H, D] over ring pages plus tail K/V [S, Kt, Kh, D], one
    online-softmax pass, exact ring-wrap eviction semantics."""
    s_, t, nkv, d = cache_k.shape
    kq, h = q.shape[1], q.shape[2]
    kt = tail_k.shape[1]
    if d % 8 != 0 or h % nkv != 0:
        out = spec_tail_attention(
            q, cache_k, cache_v, tail_k, tail_v, lens, q_start=q_start
        )
        return (out, None) if return_stats else out
    rep = h // nkv
    bt = _ring_block(t, block_t)
    num_t = t // bt
    interp = _interpret(interpret)

    # same Mosaic tiling story as paged_decode_attention: kv-head and rep
    # axes are tiny, so they must be array dims of their own — caches and
    # tail as [S, Kh, T|Kt, D], q as [S, Kq, Kh, rep, D]. Kernel refs keep
    # the exact shapes the untransposed layout produced.
    ckt = cache_k.transpose(0, 2, 1, 3)
    cvt = cache_v.transpose(0, 2, 1, 3)
    tkt = tail_k.transpose(0, 2, 1, 3)
    tvt = tail_v.transpose(0, 2, 1, 3)
    q5 = q.reshape(s_, kq, nkv, rep, d)

    def kv_map(si, hi, ti, lens_ref):
        last = jnp.where(
            lens_ref[si] >= t, num_t - 1,
            jnp.maximum(lens_ref[si] - 1, 0) // bt,
        )
        return (si, hi, jnp.minimum(ti, last), 0)

    def q_map(si, hi, ti, lr):
        return (si, 0, hi, 0, 0)

    def tail_map(si, hi, ti, lr):
        return (si, hi, 0, 0)

    out_specs = [pl.BlockSpec((None, kq, None, rep, d), q_map)]
    out_shape = [sds((s_, kq, nkv, rep, d), q.dtype, vma=out_vma(q))]
    scratch = [
        pltpu.VMEM((rep, kq, 1), jnp.float32),
        pltpu.VMEM((rep, kq, 1), jnp.float32),
        pltpu.VMEM((rep, kq, d), jnp.float32),
    ]
    if return_stats:
        out_specs.append(
            pl.BlockSpec((None, None, 1, 1), lambda si, hi, ti, lr: (si, hi, 0, 0))
        )
        out_shape.append(sds((s_, nkv, 1, 1), jnp.int32))
        scratch.append(pltpu.VMEM((1, 1), jnp.int32))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(s_, nkv, num_t + 1),  # ring blocks, then the tail block
        in_specs=[
            pl.BlockSpec((None, kq, None, rep, d), q_map),
            pl.BlockSpec((None, None, bt, d), kv_map),
            pl.BlockSpec((None, None, bt, d), kv_map),
            pl.BlockSpec((None, None, kt, d), tail_map),
            pl.BlockSpec((None, None, kt, d), tail_map),
        ],
        out_specs=out_specs,
        scratch_shapes=scratch,
    )
    res = pl.pallas_call(
        functools.partial(
            _spec_tail_kernel,
            scale=d**-0.5, q_start=int(q_start), block_t=bt, t=t,
            num_t=num_t, rep=rep, with_stats=return_stats,
        ),
        grid_spec=grid_spec,
        out_shape=out_shape,
        compiler_params=compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interp,
    )(lens.astype(jnp.int32), q5, ckt, cvt, tkt, tvt)
    out = res[0].reshape(s_, kq, h, d)
    if return_stats:
        return out, res[1].reshape(s_, nkv)
    return out


# ---------------------------------------------------------------------------
# (b) fused W4 dequant-matmul
# ---------------------------------------------------------------------------


def w4_matmul_supported(shape) -> bool:
    """Shapes the fused kernel tiles: a 2-D weight with an even column
    count (nibble pairs pack along rows). Others keep the XLA dequant."""
    return len(shape) == 2 and int(shape[1]) % 2 == 0 and int(shape[1]) > 0


def _w4_kernel(
    x_ref, qb_ref, sarr_ref, hoff_ref, oe_ref, oo_ref, ae_scr, ao_scr,
    *, num_k, n_sel, n_half,
):
    ki = pl.program_id(1)
    bk = qb_ref.shape[0]

    @pl.when(ki == 0)
    def _init():
        ae_scr[:] = jnp.zeros_like(ae_scr)
        ao_scr[:] = jnp.zeros_like(ao_scr)

    # [bk, N/2] packed bytes, widened to i32 — Mosaic has no u8 bitwise
    # ops, and the values (0..255) are exact in any wider int
    b = qb_ref[:].astype(jnp.int32)
    # element 2j of a row is the LOW nibble of byte j (the
    # native._dequant4_numpy order), value = (nibble - 8) * fp16(scale)/7
    lo = (b & 0x0F).astype(jnp.float32) - 8.0
    hi = (b >> 4).astype(jnp.float32) - 8.0
    # scale of columns (2j, 2j+1) in row k: flat block (off_k + 2j) //
    # 4096 == (hoff_k + j) // 2048 — a pair never straddles a boundary
    # (offsets are even), so even/odd planes share one scale field
    jidx = jax.lax.broadcasted_iota(jnp.int32, (bk, n_half), 1)
    nj = (hoff_ref[:] + jidx) // (W4_BLOCK // 2)
    scale = jnp.zeros((bk, n_half), jnp.float32)
    for j in range(n_sel):
        scale = jnp.where(nj == j, sarr_ref[:, j][:, None], scale)
    x = x_ref[:]
    we = (lo * scale).astype(x.dtype)
    wo = (hi * scale).astype(x.dtype)
    ae_scr[:] += jax.lax.dot_general(
        x, we, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    ao_scr[:] += jax.lax.dot_general(
        x, wo, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )

    @pl.when(ki == num_k - 1)
    def _finish():
        oe_ref[:] = ae_scr[:].astype(oe_ref.dtype)
        oo_ref[:] = ao_scr[:].astype(oo_ref.dtype)


def w4_matmul(
    x: jax.Array,
    q: jax.Array,
    s: jax.Array,
    shape,
    dtype,
    *,
    block_k: int | None = None,
    block_m: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """``x [M, K] @ dequant(q, s, (K, N))`` without materializing the f32
    weight: nibbles dequantize in-registers per [block_k, N] tile.

    ``q`` is the per-layer packed stream ([K*N/2] uint8, row-major nibble
    pairs) and ``s`` the [ceil(K*N/4096)] uint16 fp16-bit scales — the
    PackedW4 leaf layout. The per-row scale candidates (each row of W
    touches at most a couple of 4096-element flat blocks) are gathered
    outside the kernel into a [K, n_sel] f32 side table, so the kernel
    selects scales with a static chain of lane-wise wheres — no gather,
    no relayout. With x = I the output is bit-for-bit ``dequant_w4``
    (tests pin this), so the fused path inherits the codec's exactness."""
    K, N = (int(v) for v in shape)
    M = x.shape[0]
    if not w4_matmul_supported(shape):
        raise ValueError(f"w4_matmul cannot tile weight shape {shape}")
    nb = s.shape[0]
    n_half = N // 2
    half_block = W4_BLOCK // 2
    bk = block_k or pick_block(K, 256) or K
    if K % bk:
        bk = K
    bm = block_m or pick_block(M, 256) or M
    if M % bm:
        bm = M
    num_k, num_m = K // bk, M // bm
    # host-side prep (tiny): per-row flat-block offsets + scale candidates
    rows = jnp.arange(K, dtype=jnp.int32)
    base = (rows * N) // W4_BLOCK
    hoff = ((rows * N) % W4_BLOCK) // 2  # [K] half-offsets (pairs)
    n_sel = (half_block - 1 + n_half - 1) // half_block + 1
    sf = jax.lax.bitcast_convert_type(s, jnp.float16).astype(jnp.float32)
    sf = sf / jnp.float32(7.0)
    cand = jnp.clip(
        base[:, None] + jnp.arange(n_sel, dtype=jnp.int32)[None], 0, nb - 1
    )
    sarr = sf[cand]  # [K, n_sel]
    qb = q[: K * n_half].reshape(K, n_half)
    x2 = x.astype(dtype)

    oe, oo = pl.pallas_call(
        functools.partial(
            _w4_kernel, num_k=num_k, n_sel=n_sel, n_half=n_half
        ),
        grid=(num_m, num_k),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda mi, ki: (mi, ki)),
            pl.BlockSpec((bk, n_half), lambda mi, ki: (ki, 0)),
            pl.BlockSpec((bk, n_sel), lambda mi, ki: (ki, 0)),
            pl.BlockSpec((bk, 1), lambda mi, ki: (ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bm, n_half), lambda mi, ki: (mi, 0)),
            pl.BlockSpec((bm, n_half), lambda mi, ki: (mi, 0)),
        ],
        out_shape=[
            sds((M, n_half), dtype, vma=out_vma(x)),
            sds((M, n_half), dtype, vma=out_vma(x)),
        ],
        scratch_shapes=[
            pltpu.VMEM((bm, n_half), jnp.float32),
            pltpu.VMEM((bm, n_half), jnp.float32),
        ],
        compiler_params=compiler_params(
            dimension_semantics=("parallel", "arbitrary")
        ),
        interpret=_interpret(interpret),
    )(x2, qb, sarr, hoff[:, None])
    # re-interleave the even/odd column planes: [M, N/2, 2] -> [M, N]
    return jnp.stack([oe, oo], axis=-1).reshape(M, N)
