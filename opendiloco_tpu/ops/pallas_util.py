"""Shared Pallas TPU tiling helpers.

The house kernels (ops/flash_attention.py training attention, the
ops/decode_kernels.py serving kernels) share the same plumbing: a block
picker that snaps tile sizes to the TPU lane grid and falls back to XLA
when nothing divides, a varying-manual-axes derivation so kernel outputs
type correctly inside shard_map manual regions, and a compiler-params
shim across the jax versions in play (``pltpu.CompilerParams`` was
``TPUCompilerParams`` before jax 0.5). Keeping them here means one set
of heuristics for every kernel instead of per-file copies.
"""

from __future__ import annotations

import jax
from jax.experimental.pallas import tpu as pltpu

# finite stand-in for -inf inside kernels: exp(NEG_INF - m) underflows to
# an exact 0.0 for any live m, so masked lanes never perturb the softmax
# (same invariant jnp.finfo(f32).min gives the XLA paths)
NEG_INF = float(-1e30)


def pick_block(t: int, preferred: int = 512) -> int:
    """Largest of (preferred, 512, 256, 128) that divides ``t``, capped at
    ``preferred``; 0 when nothing divides (caller falls back to XLA)."""
    for b in (preferred, 512, 256, 128):
        if b <= preferred and t % b == 0:
            return b
    return 0


def out_vma(x, vma=None):
    """Varying-manual-axes annotation for kernel ``out_shape``s.

    Required when a kernel runs inside a shard_map manual region (ring
    attention chunks, the sharded flash entry): the outputs must carry
    the same manual axes as the operands or the kernel types wrong. An
    explicit ``vma`` wins; otherwise it is derived from ``x``."""
    if vma is None:
        typeof = getattr(jax, "typeof", None)  # newer-jax only, like vma
        if typeof is not None:
            vma = getattr(typeof(x), "vma", None) or None
    return vma


def compiler_params(*, dimension_semantics=None, **kwargs):
    """``pltpu.CompilerParams`` across jax versions (older releases spell
    it ``TPUCompilerParams``). Extra kwargs (``vmem_limit_bytes``, ...)
    pass through to whichever class this release has."""
    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    if dimension_semantics is not None:
        kwargs["dimension_semantics"] = dimension_semantics
    return cls(**kwargs)


def pcast_varying(xs, vma):
    """``jax.lax.pcast(..., to="varying")`` where available; earlier jax
    has no varying-manual-axes typing, so the cast is the identity."""
    fn = getattr(jax.lax, "pcast", None)
    if fn is None or not vma:
        return xs
    return fn(xs, tuple(sorted(vma)), to="varying")


def axis_size(axis_name) -> int:
    """Static size of a named mesh axis, across jax versions
    (``jax.lax.axis_size`` is newer jax; before that ``jax.core.
    axis_frame`` resolves the bound — to a frame or the size itself)."""
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    frame = jax.core.axis_frame(axis_name)
    return getattr(frame, "size", frame)


def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
              check_vma=None):
    """``jax.shard_map`` across jax versions. Older releases ship it as
    ``jax.experimental.shard_map.shard_map``, where partial-manual is
    spelled ``auto=`` (the complement of ``axis_names``) and the vma
    checker is ``check_rep`` — which has no replication rules for the
    custom calls our kernels lower to, so the old path always disables
    it (the cross-shard semantics at every call site are explicit
    psums/permutes; the check buys nothing, per the fused_xent note)."""
    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        kw = {}
        if axis_names is not None:
            kw["axis_names"] = axis_names
        if check_vma is not None:
            kw["check_vma"] = check_vma
        return fn(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as esm

    # no auto= on the old path: its eager impl raises NotImplementedError
    # outright. Full-manual is equivalent here — axes outside the specs
    # replicate into the region, the same gather auto partitioning emits
    # (and none of our bodies run collectives over them).
    return esm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


try:  # vma= on out_shape structs only exists on newer jax
    jax.ShapeDtypeStruct((), "float32", vma=None)
    _SDS_HAS_VMA = True
except TypeError:
    _SDS_HAS_VMA = False


def sds(shape, dtype, vma=None):
    """``jax.ShapeDtypeStruct`` for kernel ``out_shape``s, attaching the
    vma annotation only when this jax release understands it (older
    releases predate varying-manual-axes and reject the kwarg)."""
    if _SDS_HAS_VMA:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)
