"""Attention kernels for TPU.

The reference selects between torch SDPA and FlashAttention-2 CUDA kernels via
``attn_implementation`` (open_diloco/train_fsdp.py:107,173; README.md:41-47).
Here the equivalent menu is:

- ``xla``: plain jnp attention; XLA fuses it well on TPU and keeps the
  matmuls on the MXU. Softmax accumulates in float32.
- ``pallas``: a Pallas flash-attention kernel (ops/flash_attention.py) that
  tiles over the sequence and never materializes the [T, T] score matrix.
- ``ring``: ring attention over a sequence-parallel mesh axis
  (ops/ring_attention.py) for long-context training; each device holds a
  sequence shard and K/V blocks rotate around the ring via ppermute.

All entry points share one signature over [batch, seq, heads, head_dim]
arrays with grouped-query support (num_q_heads % num_kv_heads == 0).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _repeat_kv(k: jax.Array, num_q_heads: int) -> jax.Array:
    """Broadcast KV heads up to the query head count (GQA)."""
    b, t, nkv, d = k.shape
    if nkv == num_q_heads:
        return k
    assert num_q_heads % nkv == 0, (num_q_heads, nkv)
    rep = num_q_heads // nkv
    return jnp.broadcast_to(k[:, :, :, None, :], (b, t, nkv, rep, d)).reshape(
        b, t, num_q_heads, d
    )


def xla_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
) -> jax.Array:
    """Reference jnp attention: [B, T, H, D] -> [B, T, H, D].

    Scores/softmax in float32 regardless of input dtype; output in q.dtype.
    """
    b, tq, h, d = q.shape
    k = _repeat_kv(k, h)
    v = _repeat_kv(v, h)
    tk = k.shape[1]
    scale = d**-0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    scores = scores * scale
    if causal:
        q_pos = jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
        # when tq < tk (e.g. decode), align the query block to the suffix
        mask = q_pos + (tk - tq) >= k_pos
        scores = jnp.where(mask[None, None], scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def decode_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    lens: jax.Array,
) -> jax.Array:
    """Single-token decode attention over a slot-paged ring KV cache.

    q [S, H, D] is the current token per slot; k/v [S, T, Kh, D] are the
    cache pages; lens [S] int32 is each slot's token count BEFORE this
    step (== the current token's absolute position; its K/V has already
    been written at ring index ``lens % T``). Valid cache entries are
    indices <= lens until the sequence outgrows the page, after which the
    whole ring is live (sliding-window attention over the last T tokens).

    Math matches :func:`xla_attention` row-for-row — f32 scores/softmax,
    probabilities cast back to q.dtype — so incremental decode reproduces
    the training-mode forward (pinned by tests/test_serve.py).
    """
    s, t, nkv, d = k.shape
    h = q.shape[1]
    k = _repeat_kv(k, h)
    v = _repeat_kv(v, h)
    scale = d**-0.5
    scores = jnp.einsum("shd,sthd->sht", q, k, preferred_element_type=jnp.float32)
    scores = scores * scale
    idx = jax.lax.broadcasted_iota(jnp.int32, (s, t), 1)
    valid = (idx <= lens[:, None]) | (lens[:, None] >= t)
    scores = jnp.where(valid[:, None, :], scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("sht,sthd->shd", probs, v)


@functools.partial(jax.jit, static_argnames=("impl", "causal"))
def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    impl: str = "xla",
    causal: bool = True,
) -> jax.Array:
    if impl == "xla":
        return xla_attention(q, k, v, causal=causal)
    if impl == "pallas":
        from opendiloco_tpu.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal)
    if impl == "ring":
        raise ValueError(
            "ring attention needs a mesh context; call "
            "opendiloco_tpu.ops.ring_attention.ring_attention inside shard_map"
        )
    raise ValueError(f"unknown attention impl {impl!r}")
