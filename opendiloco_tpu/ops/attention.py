"""Attention kernels for TPU.

The reference selects between torch SDPA and FlashAttention-2 CUDA kernels via
``attn_implementation`` (open_diloco/train_fsdp.py:107,173; README.md:41-47).
Here the equivalent menu is:

- ``xla``: plain jnp attention; XLA fuses it well on TPU and keeps the
  matmuls on the MXU. Softmax accumulates in float32.
- ``pallas``: a Pallas flash-attention kernel (ops/flash_attention.py) that
  tiles over the sequence and never materializes the [T, T] score matrix.
- ``ring``: ring attention over a sequence-parallel mesh axis
  (ops/ring_attention.py) for long-context training; each device holds a
  sequence shard and K/V blocks rotate around the ring via ppermute.

All entry points share one signature over [batch, seq, heads, head_dim]
arrays with grouped-query support (num_q_heads % num_kv_heads == 0).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _repeat_kv(k: jax.Array, num_q_heads: int) -> jax.Array:
    """Broadcast KV heads up to the query head count (GQA)."""
    b, t, nkv, d = k.shape
    if nkv == num_q_heads:
        return k
    assert num_q_heads % nkv == 0, (num_q_heads, nkv)
    rep = num_q_heads // nkv
    return jnp.broadcast_to(k[:, :, :, None, :], (b, t, nkv, rep, d)).reshape(
        b, t, num_q_heads, d
    )


def xla_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
) -> jax.Array:
    """Reference jnp attention: [B, T, H, D] -> [B, T, H, D].

    Scores/softmax in float32 regardless of input dtype; output in q.dtype.
    """
    b, tq, h, d = q.shape
    k = _repeat_kv(k, h)
    v = _repeat_kv(v, h)
    tk = k.shape[1]
    scale = d**-0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    scores = scores * scale
    if causal:
        q_pos = jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
        # when tq < tk (e.g. decode), align the query block to the suffix
        mask = q_pos + (tk - tq) >= k_pos
        scores = jnp.where(mask[None, None], scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def ring_live_rows(cache_len: int, t: int) -> int:
    """Physically live ring rows for a sequence of ``cache_len`` cached
    tokens in a T-row page — the KV-tier page-transfer contract.

    This is the host-side mirror of the lens masks below: before the page
    wraps, rows [0, cache_len) hold the sequence (``idx <= lens`` exposes
    exactly them plus the current step's write); once ``cache_len >= t``
    the whole ring is live at positions ``pos % t`` (the ``lens >= t``
    branch). A tier eviction therefore pages out exactly these rows and a
    restore writes them back at row 0 — ring layout is preserved in both
    regimes, so the decode/spec-tail masks (and the Pallas kernel's
    dead-block clamp, which derives from the same ``lens``) are already
    exact over a restored page: rows beyond the restored count belong to
    a previous tenant and stay masked until the sequence's own writes
    reach them, the same invariant slot reuse has always relied on."""
    if cache_len < 0:
        raise ValueError(f"cache_len must be >= 0, got {cache_len}")
    return min(int(cache_len), int(t))


def decode_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    lens: jax.Array,
) -> jax.Array:
    """Single-token decode attention over a slot-paged ring KV cache.

    q [S, H, D] is the current token per slot; k/v [S, T, Kh, D] are the
    cache pages; lens [S] int32 is each slot's token count BEFORE this
    step (== the current token's absolute position; its K/V has already
    been written at ring index ``lens % T``). Valid cache entries are
    indices <= lens until the sequence outgrows the page, after which the
    whole ring is live (sliding-window attention over the last T tokens).
    The same mask covers tier-restored slots: a page-in rewrites exactly
    :func:`ring_live_rows` rows at row 0, so validity is still fully
    determined by ``lens``.

    Math matches :func:`xla_attention` row-for-row — f32 scores/softmax,
    probabilities cast back to q.dtype — so incremental decode reproduces
    the training-mode forward (pinned by tests/test_serve.py).
    """
    s, t, nkv, d = k.shape
    h = q.shape[1]
    k = _repeat_kv(k, h)
    v = _repeat_kv(v, h)
    scale = d**-0.5
    scores = jnp.einsum("shd,sthd->sht", q, k, preferred_element_type=jnp.float32)
    scores = scores * scale
    idx = jax.lax.broadcasted_iota(jnp.int32, (s, t), 1)
    valid = (idx <= lens[:, None]) | (lens[:, None] >= t)
    scores = jnp.where(valid[:, None, :], scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("sht,sthd->shd", probs, v)


def spec_tail_attention(
    q: jax.Array,
    cache_k: jax.Array,
    cache_v: jax.Array,
    tail_k: jax.Array,
    tail_v: jax.Array,
    lens: jax.Array,
    *,
    q_start: int = 0,
) -> jax.Array:
    """Multi-token tail attention over a ring KV cache plus in-register
    tail K/V — the verify/draft primitive for speculative decode.

    q [S, Kq, H, D] are unverified tail tokens per slot at absolute
    positions ``lens + q_start + i``; cache_{k,v} [S, T, Kh, D] hold the
    ring pages as of BEFORE the tail (positions <= lens - 1); tail_{k,v}
    [S, K, Kh, D] are the tail's own K/V, kept out of the ring until
    acceptance. ``q_start`` offsets the queries within the tail (the
    draft proposes one token at a time against a growing tail buffer;
    the verify pass runs the whole tail at q_start=0).

    The masking reproduces the sequential one-token loop exactly,
    including ring wrap: tail query i attends tail tokens <= i plus the
    ring entries the sequential path would still hold at its step — a
    ring slot is dropped for query i when the write of tail token j <= i
    would have overwritten it (that is, when ``(lens + j) % T`` lands on
    it with ``lens + j >= T``), which is precisely the sliding-window
    eviction the per-step ring write performs. Softmax terms for masked
    entries are exact zeros, so extra masked slots never perturb the
    live reductions (same invariant the prefill bucket-padding relies
    on).
    """
    s, t, nkv, d = cache_k.shape
    kq = q.shape[1]
    kt = tail_k.shape[1]
    h = q.shape[2]
    ck = _repeat_kv_slots(cache_k, h)
    cv = _repeat_kv_slots(cache_v, h)
    tk = _repeat_kv_slots(tail_k, h)
    tv = _repeat_kv_slots(tail_v, h)
    scale = d**-0.5

    # ring scores [S, H, Kq, T]
    ring_scores = jnp.einsum(
        "sqhd,sthd->shqt", q, ck, preferred_element_type=jnp.float32
    ) * scale
    idx = jax.lax.broadcasted_iota(jnp.int32, (s, t), 1)
    lens_ = lens[:, None].astype(jnp.int32)
    base = (idx < lens_) | (lens_ >= t)  # live pre-tail entries
    # disp = the i whose tail ring write lands on this slot ((lens+i) % T)
    disp = jnp.mod(idx - lens_, t)
    j = q_start + jnp.arange(kq, dtype=jnp.int32)[None, :, None]  # [1, Kq, 1]
    evicted = (disp[:, None, :] <= j) & (
        (lens_[:, None, :] + disp[:, None, :]) >= t
    )
    ring_valid = base[:, None, :] & ~evicted  # [S, Kq, T]
    neg = jnp.finfo(jnp.float32).min
    ring_scores = jnp.where(ring_valid[:, None], ring_scores, neg)

    # tail scores [S, H, Kq, Kt], causal within the tail
    tail_scores = jnp.einsum(
        "sqhd,skhd->shqk", q, tk, preferred_element_type=jnp.float32
    ) * scale
    qi = q_start + jax.lax.broadcasted_iota(jnp.int32, (kq, kt), 0)
    ki = jax.lax.broadcasted_iota(jnp.int32, (kq, kt), 1)
    tail_scores = jnp.where((ki <= qi)[None, None], tail_scores, neg)

    scores = jnp.concatenate([ring_scores, tail_scores], axis=-1)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("shqt,sthd->sqhd", probs[..., :t], cv)
    out = out + jnp.einsum("shqk,skhd->sqhd", probs[..., t:], tv)
    return out


def _repeat_kv_slots(k: jax.Array, num_q_heads: int) -> jax.Array:
    """GQA broadcast for slot-major [S, T, Kh, D] cache layouts."""
    s, t, nkv, d = k.shape
    if nkv == num_q_heads:
        return k
    assert num_q_heads % nkv == 0, (num_q_heads, nkv)
    rep = num_q_heads // nkv
    return jnp.broadcast_to(k[:, :, :, None, :], (s, t, nkv, rep, d)).reshape(
        s, t, num_q_heads, d
    )


@functools.partial(jax.jit, static_argnames=("impl", "causal"))
def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    impl: str = "xla",
    causal: bool = True,
) -> jax.Array:
    if impl == "xla":
        return xla_attention(q, k, v, causal=causal)
    if impl == "pallas":
        from opendiloco_tpu.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal)
    if impl == "ring":
        raise ValueError(
            "ring attention needs a mesh context; call "
            "opendiloco_tpu.ops.ring_attention.ring_attention inside shard_map"
        )
    raise ValueError(f"unknown attention impl {impl!r}")
