"""Fused lm-head + cross-entropy Pallas kernel.

The single largest HBM cost of the small-model train step is materializing
float32 logits [tokens, vocab] (e.g. 2 GB for 16k tokens x 32k vocab) just to
reduce them to one scalar. This kernel streams vocab tiles of the head
matmul through VMEM with an online log-sum-exp, so the full logits never
touch HBM; the backward pass recomputes tiles and accumulates dh and dW the
same way (FlashAttention-style recompute, applied to the classifier).

Opt-in via TrainerConfig.fused_loss; numerically equivalent to the
logits-materializing path (interpret-mode parity tests).

Shapes: h [N, D] tokens, w [D, V] head, labels [N] int32 (IGNORE=-100).
Returns per-token nll [N] float32 (0 where ignored); mean-reduction happens
in the caller.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from opendiloco_tpu.ops.pallas_util import (
    compiler_params as _compiler_params,
    shard_map as _shard_map,
)

IGNORE = -100


def _pick(n: int, pref: int) -> int:
    for b in (pref, pref // 2, pref // 4, 128):
        if b >= 128 and n % b == 0:
            return b
    return 0


# per-kernel VMEM budget. The default scoped window (~16 MB) fits the
# d=768 kernels but every staged tile scales with d, and at 1b's d=2048
# the dh kernel died allocating its output tile on the VMEM stack —
# caught by the deviceless AOT compile (AOT_ROOFLINE, round 5) before
# any hardware run could. v5e has 128 MB of VMEM; claim most of it (all
# three pallas_calls pass vmem_limit_bytes) and only shrink blocks when
# the estimate below still doesn't fit, so the MXU keeps wide tiles.
_VMEM_BUDGET = 100 * 1024 * 1024


def _vmem_caps(d: int) -> tuple[int, int]:
    """(token-block cap, vocab-block cap) for hidden size ``d``.

    Sized against the dw kernel, the hungriest of the three: double-
    buffered (bn, d) + (d, bv) bf16 operand tiles, f32 (d, bv) scratch
    accumulator + output tile, and f32 (bn, bv) score/dlog tiles. Caps
    halve (powers of two only, so ``min(block, cap)`` keeps divisibility
    into n/v) until that estimate fits _VMEM_BUDGET. d=768 (150m) and
    d=2048 (1b) both keep the full 1024/2048 blocks (~39 MB / ~75 MB);
    d=4096 drops the vocab block to 1024."""

    def dw_bytes(bn: int, bv: int) -> int:
        return 2 * bn * d * 2 + 2 * d * bv * 2 + 2 * d * bv * 4 + 2 * bn * bv * 4

    bn, bv = 1024, 2048
    while bv > 512 and dw_bytes(bn, bv) > _VMEM_BUDGET:
        bv //= 2
    while bn > 128 and dw_bytes(bn, bv) > _VMEM_BUDGET:
        bn //= 2
    return bn, bv


def _mask_pad(s, j: int, block_v: int, true_v: int):
    """-inf out vocab-pad columns (tile j of a padded head)."""
    gcols = j * block_v + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(gcols < true_v, s, -1e30)


# ---------------------------------------------------------------------------
# forward: grid (token_blocks, vocab_tiles); scratch carries online stats
# ---------------------------------------------------------------------------


def _fwd_kernel(
    h_ref, w_ref, lbl_ref, nll_ref, lse_ref, m_s, l_s, tgt_s, *, block_v, true_v
):
    j = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(j == 0)
    def _():
        m_s[:] = jnp.full_like(m_s, -1e30)
        l_s[:] = jnp.zeros_like(l_s)
        tgt_s[:] = jnp.zeros_like(tgt_s)

    # bf16 matmul inputs, f32 accumulation (f32 inputs run the MXU at ~1/8
    # rate on v5e)
    s = jax.lax.dot_general(
        h_ref[:],
        w_ref[:],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [block_n, block_v]
    if true_v % block_v:  # vocab padded up to tile size
        s = _mask_pad(s, j, block_v, true_v)

    m_prev = m_s[:]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    l_s[:] = l_s[:] * jnp.exp(m_prev - m_new) + jnp.sum(
        jnp.exp(s - m_new), axis=1, keepdims=True
    )
    m_s[:] = m_new

    # gather the target logit if it falls inside this vocab tile
    lbl = lbl_ref[:].reshape(-1, 1)  # [block_n, 1]
    local = lbl - j * block_v
    cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    hit = cols == local  # at most one column matches
    tgt_s[:] = tgt_s[:] + jnp.sum(jnp.where(hit, s, 0.0), axis=1, keepdims=True)

    @pl.when(j == nv - 1)
    def _():
        lse = m_s[:] + jnp.log(l_s[:])
        mask = (lbl != IGNORE).astype(jnp.float32)
        nll_ref[:] = ((lse - tgt_s[:]) * mask).reshape(nll_ref.shape)
        lse_ref[:] = lse.reshape(lse_ref.shape)


def _fwd(h, w, labels, block_n, block_v, true_v):
    # per-token vectors travel as [1, N] rows with (1, block_n) blocks: 1-D
    # operands get a global XLA tiling tied to one block size, which breaks
    # when forward and backward kernels pick different token blocks.
    # (The SPMD wrapper's shard_map runs with check_vma=False, so no vma
    # annotations are needed on the out_shapes here.)
    n, d = h.shape
    v = w.shape[1]
    grid = (n // block_n, v // block_v)
    nll, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, block_v=block_v, true_v=true_v),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_n, d), lambda i, j: (i, 0)),
            pl.BlockSpec((d, block_v), lambda i, j: (0, j)),
            pl.BlockSpec((1, block_n), lambda i, j: (0, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_n), lambda i, j: (0, i)),
            pl.BlockSpec((1, block_n), lambda i, j: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, n), jnp.float32),
            jax.ShapeDtypeStruct((1, n), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_n, 1), jnp.float32),
            pltpu.VMEM((block_n, 1), jnp.float32),
            pltpu.VMEM((block_n, 1), jnp.float32),
        ],
        compiler_params=_compiler_params(
            vmem_limit_bytes=_VMEM_BUDGET,
        ),
    )(h, w, labels.reshape(1, n))
    return nll.reshape(n), lse.reshape(n)


# ---------------------------------------------------------------------------
# backward: two kernels with transposed grids -- dh accumulates over vocab
# tiles (scratch, vocab innermost), dw accumulates over token blocks
# (scratch, tokens innermost); each recomputes its dlog tile from lse
# ---------------------------------------------------------------------------


def _recompute_dlog(h_ref, w_ref, lbl_ref, lse_ref, g_ref, j, *, block_v, true_v):
    """Rebuild the softmax-xent gradient tile dlog = g * (p - onehot)
    (bf16, [block_n, block_v]) from the forward residual lse."""
    hb = h_ref[:]
    wb = w_ref[:]
    s = jax.lax.dot_general(
        hb, wb, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    if true_v % block_v:  # padded vocab: pad columns contribute p = 0
        s = _mask_pad(s, j, block_v, true_v)
    p = jnp.exp(s - lse_ref[:].reshape(-1, 1))

    lbl = lbl_ref[:].reshape(-1, 1)
    local = lbl - j * block_v
    cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    onehot = (cols == local).astype(jnp.float32)

    g = g_ref[:].reshape(-1, 1)  # upstream per-token grad, 0 where ignored
    return (g * (p - onehot)).astype(hb.dtype)


def _dh_kernel(
    h_ref, w_ref, lbl_ref, lse_ref, g_ref, dh_ref, dh_s, *, block_v, true_v
):
    # grid (token_blocks, vocab_tiles): vocab innermost, dh accumulates in
    # scratch over the consecutive j steps and flushes once per token block
    j = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(j == 0)
    def _():
        dh_s[:] = jnp.zeros_like(dh_s)

    dlog = _recompute_dlog(
        h_ref, w_ref, lbl_ref, lse_ref, g_ref, j, block_v=block_v, true_v=true_v
    )
    dh_s[:] = dh_s[:] + jax.lax.dot_general(
        dlog, w_ref[:], (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )

    @pl.when(j == nv - 1)
    def _():
        dh_ref[:] = dh_s[:].astype(dh_ref.dtype)


def _dw_kernel(
    h_ref, w_ref, lbl_ref, lse_ref, g_ref, dw_ref, dw_s, *, block_v, true_v
):
    # grid (vocab_tiles, token_blocks): tokens innermost, dw accumulates in
    # scratch over the consecutive i steps and flushes once per vocab tile.
    # (A single kernel accumulating dw into its output across token blocks
    # would revisit each dw tile on NON-consecutive grid steps, which Pallas
    # output-revisiting does not support -- the write-back clobbers.)
    j = pl.program_id(0)
    i = pl.program_id(1)
    ni = pl.num_programs(1)

    @pl.when(i == 0)
    def _():
        dw_s[:] = jnp.zeros_like(dw_s)

    dlog = _recompute_dlog(
        h_ref, w_ref, lbl_ref, lse_ref, g_ref, j, block_v=block_v, true_v=true_v
    )
    dw_s[:] = dw_s[:] + jax.lax.dot_general(
        h_ref[:], dlog, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )

    @pl.when(i == ni - 1)
    def _():
        dw_ref[:] = dw_s[:].astype(dw_ref.dtype)


def _bwd_impl(h, w, labels, lse, g, block_n, block_v, true_v):
    n, d = h.shape
    v = w.shape[1]
    ni, nv = n // block_n, v // block_v
    args = (h, w, labels.reshape(1, n), lse.reshape(1, n), g.reshape(1, n))
    vec_spec_i = pl.BlockSpec((1, block_n), lambda i, j: (0, i))
    vec_spec_j = pl.BlockSpec((1, block_n), lambda j, i: (0, i))
    dh = pl.pallas_call(
        functools.partial(_dh_kernel, block_v=block_v, true_v=true_v),
        grid=(ni, nv),
        in_specs=[
            pl.BlockSpec((block_n, d), lambda i, j: (i, 0)),
            pl.BlockSpec((d, block_v), lambda i, j: (0, j)),
            vec_spec_i,
            vec_spec_i,
            vec_spec_i,
        ],
        # dh in the input dtype (cast happens in-kernel); an f32 output
        # would double its VMEM block for no benefit
        out_specs=pl.BlockSpec((block_n, d), lambda i, j: (i, 0)),
        # both grads vary like the (batch-sharded) rows: dw is each
        # shard's partial sum; shard_map's transpose of the replicated-w
        # in_spec psums the partials outside the kernel
        out_shape=jax.ShapeDtypeStruct((n, d), h.dtype),
        scratch_shapes=[pltpu.VMEM((block_n, d), jnp.float32)],
        compiler_params=_compiler_params(
            vmem_limit_bytes=_VMEM_BUDGET,
        ),
    )(*args)
    dw = pl.pallas_call(
        functools.partial(_dw_kernel, block_v=block_v, true_v=true_v),
        grid=(nv, ni),
        in_specs=[
            pl.BlockSpec((block_n, d), lambda j, i: (i, 0)),
            pl.BlockSpec((d, block_v), lambda j, i: (0, j)),
            vec_spec_j,
            vec_spec_j,
            vec_spec_j,
        ],
        out_specs=pl.BlockSpec((d, block_v), lambda j, i: (0, j)),
        out_shape=jax.ShapeDtypeStruct((d, v), jnp.float32),
        scratch_shapes=[pltpu.VMEM((d, block_v), jnp.float32)],
        compiler_params=_compiler_params(
            vmem_limit_bytes=_VMEM_BUDGET,
        ),
    )(*args)
    return dh, dw


# ---------------------------------------------------------------------------
# public entry with custom vjp
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _fused_nll(h, w, labels, block_n, block_v, true_v):
    nll, _ = _fwd(h, w, labels, block_n, block_v, true_v)
    return nll


def _fused_fwd(h, w, labels, block_n, block_v, true_v):
    nll, lse = _fwd(h, w, labels, block_n, block_v, true_v)
    return nll, (h, w, labels, lse)


def _fused_bwd(block_n, block_v, true_v, res, g):
    h, w, labels, lse = res
    mask = (labels != IGNORE).astype(jnp.float32)
    # the backward kernels carry the f32 accumulator scratch on top of the
    # forward's tiles; halve the token block (empirically chosen at d=768,
    # kept proportionally across sizes — a halved power-of-two cap always
    # divides the forward's pick)
    bn = min(block_n, max(128, _vmem_caps(h.shape[1])[0] // 2))
    dh, dw = _bwd_impl(h, w, labels, lse, g * mask, bn, block_v, true_v)
    return dh.astype(h.dtype), dw.astype(w.dtype), None


_fused_nll.defvjp(_fused_fwd, _fused_bwd)


def _nll_sum_count(
    h: jax.Array, w: jax.Array, labels: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """(sum of nll over non-ignored labels, raw non-ignored count).

    The kernel-dispatch core shared by the mean entry point and the SPMD
    wrapper (which psums sums/counts across batch shards before dividing).
    """
    n, d = h.shape
    v = w.shape[1]
    mask = labels != IGNORE
    count = jnp.sum(mask)
    if d % 128 != 0:
        logits = (h.astype(jnp.float32) @ w.astype(jnp.float32))
        lp = jax.nn.log_softmax(logits, axis=-1)
        safe = jnp.where(mask, labels, 0)
        nll = -jnp.take_along_axis(lp, safe[:, None], axis=1)[:, 0] * mask
        return jnp.sum(nll), count
    bn_cap, bv_cap = _vmem_caps(d)
    block_n = _pick(n, bn_cap)
    if block_n == 0:
        # token count doesn't tile (e.g. the causal shift gives B*(T-1));
        # pad rows up to the next 128 multiple with IGNORE labels -- they
        # contribute 0 to nll (masked) and 0 to dh/dw (upstream grad is
        # masked before the kernel)
        n_pad = -(-n // 128) * 128
        h = jnp.pad(h, ((0, n_pad - n), (0, 0)))
        labels = jnp.pad(labels, (0, n_pad - n), constant_values=IGNORE)
        n = n_pad
        block_n = _pick(n, bn_cap)  # nonzero: n is a multiple of 128
    block_v = _pick(v, bv_cap)
    if block_v < 512:
        # pad the head to the smallest wide tile (least dead columns);
        # padded logits are masked to -inf in the kernels (a small pad
        # copy beats 128-wide MXU tiles)
        block_v = min(
            (b for b in (512, 1024, 2048) if b <= bv_cap),
            key=lambda b: -(-v // b) * b,
        )
        v_pad = -(-v // block_v) * block_v
        w_in = jnp.pad(w, ((0, 0), (0, v_pad - v)))
        nll = _fused_nll(h, w_in, labels, block_n, block_v, v)
    else:
        nll = _fused_nll(h, w, labels, block_n, block_v, v)
    return jnp.sum(nll), count


def fused_linear_cross_entropy(
    h: jax.Array, w: jax.Array, labels: jax.Array
) -> jax.Array:
    """Mean nll over non-ignored labels; h [N, D], w [D, V], labels [N].

    Vocabs that don't tile (e.g. Llama's 32000) are zero-padded up to the
    next block_v multiple and masked in-kernel, so the MXU always sees wide
    tiles instead of degrading to 128; token counts that don't tile (the
    causal shift gives B*(T-1) rows) are row-padded with IGNORE labels.
    Falls back to the materializing path only when hidden % 128 != 0.
    """
    s, c = _nll_sum_count(h, w, labels)
    return s / jnp.maximum(c, 1)


def fused_linear_cross_entropy_sharded(
    h: jax.Array,
    w: jax.Array,
    labels: jax.Array,
    *,
    mesh,
    batch_axes: tuple = (),
    tp_axis=None,
) -> jax.Array:
    """SPMD entry for multi-device meshes.

    Mosaic kernels cannot be automatically partitioned (XLA raises at
    compile when a pallas operand has a sharded dim — found by the
    deviceless multichip AOT compile, round 5). The rows of ``h``/
    ``labels`` are sharded over the batch axes, so the kernel runs inside
    a shard_map manual over them: each shard computes its local (nll sum,
    count) and the mean is taken after a psum. ``w`` has no spec entry —
    a tp-sharded head is replicated into the region (the softmax needs
    the full vocab; this is the same gather the auto partitioner emits
    for the unfused path). tp joins the manual set only so that gather is
    explicit rather than an illegal sharded operand."""
    if mesh is None or getattr(mesh, "size", 1) <= 1 or not batch_axes:
        return fused_linear_cross_entropy(h, w, labels)
    P = jax.sharding.PartitionSpec

    def body(hh, ww, ll):
        s, c = _nll_sum_count(hh, ww, ll)
        # psum over the batch shards only: over tp the operands were
        # replicated, so (s, c) are already invariant there. The replicated
        # ww in_spec's TRANSPOSE is a psum, which is exactly the
        # cross-shard aggregation the partial dw needs.
        s = jax.lax.psum(s, tuple(batch_axes))
        c = jax.lax.psum(c, tuple(batch_axes))
        return s / jnp.maximum(c, 1)

    fn = _shard_map(
        body,
        mesh=mesh,
        in_specs=(P(tuple(batch_axes), None), P(), P(tuple(batch_axes))),
        out_specs=P(),
        # ALL mesh axes manual — a partially-manual pallas call still hits
        # the auto partitioner for the remaining axes and XLA refuses; a
        # tp-sharded head replicates into the region (the softmax needs
        # the full vocab; same gather the auto partitioner emits)
        axis_names=set(mesh.axis_names),
        # the vma checker rejects kernel-internal constants mixing with
        # varying refs in interpret mode (fresh jnp.full vs varying block);
        # the cross-shard semantics here are explicit psums, so the check
        # buys nothing
        check_vma=False,
    )
    return fn(h, w, labels)
