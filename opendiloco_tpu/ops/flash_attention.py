"""Pallas TPU flash attention (forward + backward), causal, GQA-aware.

TPU-native replacement for the reference's optional FlashAttention-2 CUDA
kernels (README.md:41-47, train_fsdp.py:107). FlashAttention-2-style online
softmax: never materializes the [T, T] score matrix; scores and softmax
statistics accumulate in float32 on the MXU/VPU while q/k/v stream through
VMEM tiles.

Layout: grid (batch, q-head, q-block, k-block) with the k-block dimension
sequential ("arbitrary") -- K/V stream through VMEM one [block_k, d] tile
per step while the online-softmax state (m, l, acc) persists in VMEM
scratch across k-steps. Per-step VMEM is O(block_q*d + block_k*d),
independent of T, so sequence length is bounded by HBM, not VMEM. GQA is
handled in the BlockSpec index maps (q-head h reads kv-head h // rep) --
KV is never materialized at q-head width.

Causal blocks above the diagonal are skipped with pl.when, and their
BlockSpec index maps clamp to the last needed tile so the revisited block
index elides the DMA too -- a skipped step costs neither compute nor HBM
traffic, only a grid step.

Backward follows the standard FA2 recompute scheme: delta = rowsum(dO * O),
one kernel for dq (streaming k blocks), one for dk/dv (streaming q blocks,
accumulating over the rep q-heads of each kv head).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from opendiloco_tpu.ops.pallas_util import (
    NEG_INF as _NEG_INF,
    compiler_params as _compiler_params,
    out_vma as _out_vma,
    sds as _sds,
    pick_block as _pick_block,
    shard_map as _shard_map,
)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
    *, scale: float, causal: bool, num_k: int
):
    # q_ref/o_ref: [block_q, d]; k_ref/v_ref: [block_k, d] (one tile per step)
    block_q, d = q_ref.shape
    block_k = k_ref.shape[0]
    qi, ki = pl.program_id(2), pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros((block_q, 1), jnp.float32)
        acc_scr[:] = jnp.zeros((block_q, d), jnp.float32)

    # causal: tiles fully above the diagonal contribute nothing
    diag_ok = (ki * block_k) <= (qi * block_q + block_q - 1)

    @pl.when(jnp.logical_or(not causal, diag_ok))
    def _step():
        # matmul inputs stay in bf16 (f32 inputs run the MXU at ~1/8 rate on
        # v5e); accumulation and softmax statistics are f32
        q = q_ref[:]
        k_blk = k_ref[:]
        v_blk = v_ref[:]
        s = scale * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [block_q, block_k]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_prev, l_prev, acc = m_scr[:], l_scr[:], acc_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        m_scr[:] = m_new
        l_scr[:] = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc * corr + jax.lax.dot_general(
            p.astype(v_blk.dtype),
            v_blk,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == num_k - 1)
    def _finish():
        l = l_scr[:]
        l_safe = jnp.where(l == 0, 1.0, l)
        o_ref[:] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse_ref[:] = (m_scr[:] + jnp.log(l_safe)).reshape(1, block_q)


def _fwd(q, k, v, *, block_q: int, block_k: int, causal: bool, vma=None):
    """q: [B, Hq, T, D]; k/v: [B, Hkv, T, D] -> (out [B, Hq, T, D], lse [B, Hq, 1, T]).

    ``vma``: varying-manual-axes annotation for the outputs, required when
    called inside a shard_map manual region (the ring-attention chunks).
    When unset it is derived from q so the kernel types correctly in ANY
    manual region (e.g. flash_attention_sharded's batch/tp shard_map).
    """
    vma = _out_vma(q, vma)
    b, hq, t, d = q.shape
    hkv = k.shape[1]
    rep = hq // hkv
    scale = d**-0.5
    num_k = t // block_k

    if causal:
        # clamp skipped above-diagonal steps to the last needed tile: an
        # unchanged block index re-uses the resident copy (no DMA)
        def kv_map(bi, hi, qi, ki):
            last = (qi * block_q + block_q - 1) // block_k
            return (bi, hi // rep, jnp.minimum(ki, last), 0)
    else:
        def kv_map(bi, hi, qi, ki):
            return (bi, hi // rep, ki, 0)

    grid = (b, hq, t // block_q, num_k)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal, num_k=num_k),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (None, None, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)
            ),
            pl.BlockSpec((None, None, block_k, d), kv_map),
            pl.BlockSpec((None, None, block_k, d), kv_map),
        ],
        out_specs=[
            pl.BlockSpec(
                (None, None, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)
            ),
            pl.BlockSpec(
                (None, None, 1, block_q), lambda bi, hi, qi, ki: (bi, hi, 0, qi)
            ),
        ],
        out_shape=[
            _sds(q.shape, q.dtype, vma=vma),
            _sds((b, hq, 1, t), jnp.float32, vma=vma),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_scr,
    *, scale, causal, num_k
):
    # q/do/dq: [block_q, d]; k/v: [block_k, d] per step; lse/delta: [1, block_q]
    block_q, d = q_ref.shape
    block_k = k_ref.shape[0]
    qi, ki = pl.program_id(2), pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros((block_q, d), jnp.float32)

    diag_ok = (ki * block_k) <= (qi * block_q + block_q - 1)

    @pl.when(jnp.logical_or(not causal, diag_ok))
    def _step():
        q = q_ref[:]
        do = do_ref[:]
        lse = lse_ref[:].reshape(block_q, 1)
        delta = delta_ref[:].reshape(block_q, 1)
        k_blk = k_ref[:]
        v_blk = v_ref[:]
        s = scale * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = (p * (dp - delta)).astype(k_blk.dtype)
        dq_scr[:] = dq_scr[:] + scale * jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(ki == num_k - 1)
    def _finish():
        dq_ref[:] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_scr, dv_scr, *, scale, causal, rep, num_q
):
    # grid point: (batch, kv-head, k-block, rep*q-block). q/do: [1, block_q, d]
    # per step; k/v/dk/dv: [block_k, d]; lse/delta: [1, block_q]
    block_k, d = k_ref.shape
    block_q = q_ref.shape[1]
    ki, step = pl.program_id(2), pl.program_id(3)
    qj = step % num_q  # q-block index within a head

    @pl.when(step == 0)
    def _init():
        dk_scr[:] = jnp.zeros((block_k, d), jnp.float32)
        dv_scr[:] = jnp.zeros((block_k, d), jnp.float32)

    # causal: only q blocks at or after this k block contribute
    diag_ok = (qj * block_q + block_q - 1) >= (ki * block_k)

    @pl.when(jnp.logical_or(not causal, diag_ok))
    def _step():
        k_blk = k_ref[:]
        v_blk = v_ref[:]
        q_blk = q_ref[0]
        do_blk = do_ref[0]
        lse_blk = lse_ref[:].reshape(block_q, 1)
        delta_blk = delta_ref[:].reshape(block_q, 1)
        s = scale * jax.lax.dot_general(
            q_blk, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if causal:
            q_pos = qj * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse_blk)
        pb = p.astype(do_blk.dtype)
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            pb, do_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do_blk, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = (p * (dp - delta_blk)).astype(q_blk.dtype)
        dk_scr[:] = dk_scr[:] + scale * jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(step == rep * num_q - 1)
    def _finish():
        dk_ref[:] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[:] = dv_scr[:].astype(dv_ref.dtype)


def _delta(dout, out):
    """delta = rowsum(dO * O), f32: [B, Hq, T, D] -> [B, Hq, 1, T]."""
    b, hq, t, _ = out.shape
    return jnp.sum(
        dout.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    ).reshape(b, hq, 1, t)


def _bwd(block_q, block_k, causal, res, dout):
    q, k, v, out, lse = res
    return _bwd_impl(
        q, k, v, dout, lse, _delta(dout, out),
        block_q=block_q, block_k=block_k, causal=causal,
    )


def _bwd_impl(
    q, k, v, dout, lse, delta, *, block_q, block_k, causal, grad_dtype=None,
    vma=None,
):
    """Backward kernels with delta precomputed. ``grad_dtype`` overrides the
    output dtype and ``vma`` annotates varying manual axes (both used by the
    ring-attention chunk path, which accumulates f32 inside shard_map);
    an unset vma is derived from q (see _fwd)."""
    vma = _out_vma(q, vma)
    b, hq, t, d = q.shape
    hkv = k.shape[1]
    rep = hq // hkv
    scale = d**-0.5
    num_k = t // block_k
    num_q = t // block_q

    if causal:
        def kv_map(bi, hi, qi, ki):
            last = (qi * block_q + block_q - 1) // block_k
            return (bi, hi // rep, jnp.minimum(ki, last), 0)
    else:
        def kv_map(bi, hi, qi, ki):
            return (bi, hi // rep, ki, 0)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal, num_k=num_k),
        grid=(b, hq, num_q, num_k),
        in_specs=[
            pl.BlockSpec(
                (None, None, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)
            ),
            pl.BlockSpec((None, None, block_k, d), kv_map),
            pl.BlockSpec((None, None, block_k, d), kv_map),
            pl.BlockSpec(
                (None, None, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)
            ),
            pl.BlockSpec(
                (None, None, 1, block_q), lambda bi, hi, qi, ki: (bi, hi, 0, qi)
            ),
            pl.BlockSpec(
                (None, None, 1, block_q), lambda bi, hi, qi, ki: (bi, hi, 0, qi)
            ),
        ],
        out_specs=pl.BlockSpec(
            (None, None, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)
        ),
        out_shape=_sds(q.shape, grad_dtype or q.dtype, vma=vma),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
    )(q, k, v, dout, lse, delta)

    # dk/dv: group q by kv head: [b, hkv, rep, t, d]; the sequential grid
    # dim walks (rep, q-block) in row-major order, streaming one q tile per
    # step while dk/dv accumulate in scratch
    q_g = q.reshape(b, hkv, rep, t, d)
    do_g = dout.reshape(b, hkv, rep, t, d)
    lse_g = lse.reshape(b, hkv, rep, 1, t)
    delta_g = delta.reshape(b, hkv, rep, 1, t)

    def _qj(ki, st):
        qj = st % num_q
        if causal:  # clamp skipped below-diagonal q tiles (DMA elision)
            qj = jnp.maximum(qj, (ki * block_k) // block_q)
        return qj

    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, causal=causal, rep=rep, num_q=num_q
        ),
        grid=(b, hkv, num_k, rep * num_q),
        in_specs=[
            pl.BlockSpec(
                (None, None, 1, block_q, d),
                lambda bi, hi, ki, st: (bi, hi, st // num_q, _qj(ki, st), 0),
            ),
            pl.BlockSpec(
                (None, None, block_k, d), lambda bi, hi, ki, st: (bi, hi, ki, 0)
            ),
            pl.BlockSpec(
                (None, None, block_k, d), lambda bi, hi, ki, st: (bi, hi, ki, 0)
            ),
            pl.BlockSpec(
                (None, None, 1, block_q, d),
                lambda bi, hi, ki, st: (bi, hi, st // num_q, _qj(ki, st), 0),
            ),
            pl.BlockSpec(
                (None, None, 1, 1, block_q),
                lambda bi, hi, ki, st: (bi, hi, st // num_q, 0, _qj(ki, st)),
            ),
            pl.BlockSpec(
                (None, None, 1, 1, block_q),
                lambda bi, hi, ki, st: (bi, hi, st // num_q, 0, _qj(ki, st)),
            ),
        ],
        out_specs=[
            pl.BlockSpec(
                (None, None, block_k, d), lambda bi, hi, ki, st: (bi, hi, ki, 0)
            ),
            pl.BlockSpec(
                (None, None, block_k, d), lambda bi, hi, ki, st: (bi, hi, ki, 0)
            ),
        ],
        out_shape=[
            _sds(k.shape, grad_dtype or k.dtype, vma=vma),
            _sds(v.shape, grad_dtype or v.dtype, vma=vma),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
    )(q_g, k, v, do_g, lse_g, delta_g)

    return dq, dk, dv


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, block_q, block_k, causal):
    out, _ = _fwd(q, k, v, block_q=block_q, block_k=block_k, causal=causal)
    return out


def _flash_fwd(q, k, v, block_q, block_k, causal):
    out, lse = _fwd(q, k, v, block_q=block_q, block_k=block_k, causal=causal)
    # tag the kernel outputs so selective remat policies (llama._maybe_remat
    # "dots") can save them -- without these names the backward pass reruns
    # the whole forward kernel just to rebuild its residuals
    out = checkpoint_name(out, "attn_out")
    lse = checkpoint_name(lse, "attn_lse")
    return out, (q, k, v, out, lse)


_flash.defvjp(_flash_fwd, _bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    block_q: int = 1024,
    block_k: int = 1024,
) -> jax.Array:
    """[B, T, H, D] attention via the Pallas kernel; falls back to XLA for
    shapes the kernel doesn't tile (T not a multiple of 128).

    Blocks default large (1024x1024, on-chip-swept): per-grid-step fixed cost
    dominates at small tiles on TPU, and VMEM per step is only O(block*d) +
    the [bq, bk] f32 score tile, so these fit VMEM comfortably."""
    b, t, hq, d = q.shape
    env = os.environ.get("OPENDILOCO_TPU_FLASH_BLOCKS")  # tuning: "bq,bk"
    if env:
        try:
            eq, ek = (int(x) for x in env.split(","))
        except ValueError:
            raise ValueError(
                f"OPENDILOCO_TPU_FLASH_BLOCKS={env!r}: expected 'block_q,block_k'"
            ) from None
        if eq % 128 or ek % 128:
            raise ValueError(
                f"OPENDILOCO_TPU_FLASH_BLOCKS={env!r}: blocks must be "
                "multiples of 128 (TPU lane tiling)"
            )
        block_q, block_k = eq, ek
    block_q = _pick_block(t, block_q)
    block_k = _pick_block(t, block_k)
    if block_q == 0 or block_k == 0 or d % 8 != 0:
        from opendiloco_tpu.ops.attention import xla_attention

        return xla_attention(q, k, v, causal=causal)
    # kernel layout is [B, H, T, D]
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out = _flash(qt, kt, vt, block_q, block_k, causal)
    return out.transpose(0, 2, 1, 3)


def flash_attention_sharded(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh,
    batch_axes: tuple = (),
    tp_axis=None,
    causal: bool = True,
) -> jax.Array:
    """SPMD entry for multi-device meshes.

    Mosaic kernels cannot be automatically partitioned — XLA raises at
    compile the moment a pallas operand has a sharded dimension (found by
    the deviceless multichip AOT compile, round 5; a single-chip mesh
    never hits it). Attention is independent per (batch row, head), so
    the fix is a shard_map manual over exactly the axes the activations
    are sharded on: the batch axes always, and tp on the head dims when
    it divides BOTH q and kv head counts (shards then keep whole GQA
    groups, so the kernel's local group arithmetic is unchanged). A
    non-dividing tp head dim is instead replicated into the region (tp
    is in the manual set with no spec entry = all-gather), which is the
    same gather the auto partitioner would emit.

    Do NOT call inside another manual region (the pp pipeline): nested
    shard_map has no jvp lowering — there the pipeline's in_specs gather
    the batch, operands arrive replicated, and the plain kernel compiles.
    """
    if mesh is None or getattr(mesh, "size", 1) <= 1:
        return flash_attention(q, k, v, causal=causal)
    P = jax.sharding.PartitionSpec
    hq, hkv = q.shape[2], k.shape[2]
    head = None
    if tp_axis is not None and mesh.shape[tp_axis] > 1:
        n_tp = mesh.shape[tp_axis]
        if hq % n_tp == 0 and hkv % n_tp == 0:
            head = tp_axis
    spec = P(tuple(batch_axes) or None, None, head, None)
    fn = _shard_map(
        lambda a, b, c: flash_attention(a, b, c, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        # ALL mesh axes manual: a partially-manual pallas call still goes
        # through the auto partitioner for the remaining axes and XLA
        # refuses; axes outside the spec replicate into the region (the
        # same gather auto partitioning would emit)
        axis_names=set(mesh.axis_names),
    )
    return fn(q, k, v)
