"""Pallas TPU flash attention (forward + backward), causal, GQA-aware.

TPU-native replacement for the reference's optional FlashAttention-2 CUDA
kernels (README.md:41-47, train_fsdp.py:107). FlashAttention-2-style online
softmax: never materializes the [T, T] score matrix; scores and softmax
statistics accumulate in float32 on the MXU/VPU while q/k/v stream through
VMEM tiles.

Layout: kernels run per (batch, q-head, q-block) grid point with the full
K/V for that head resident in VMEM (fine up to ~8k seq; longer sequences use
ring attention over the sp mesh axis, ops/ring_attention.py). GQA is handled
in the BlockSpec index maps (q-head h reads kv-head h // rep) -- KV is never
materialized at q-head width in the forward pass.

Backward follows the standard FA2 recompute scheme: delta = rowsum(dO * O),
one kernel for dq (loop over k blocks), one for dk/dv (loop over q blocks,
accumulating over the rep q-heads of each kv head).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = float(-1e30)


def _pick_block(t: int, preferred: int = 512) -> int:
    for b in (preferred, 256, 128):
        if t % b == 0:
            return b
    return 0  # caller falls back to XLA attention


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int, scale: float, causal: bool):
    # q_ref: [block_q, d]; k_ref/v_ref: [t, d]; lse_ref: [1, block_q]
    block_q, d = q_ref.shape
    t = k_ref.shape[0]
    qi = pl.program_id(2)
    q = q_ref[:].astype(jnp.float32) * scale

    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def body(ki, carry):
        m_prev, l_prev, acc = carry
        k_blk = k_ref[pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [block_q, block_k]
        if causal:
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
        acc = acc * corr + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc

    num_k = t // block_k if not causal else (qi * block_q + block_q) // block_k
    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, num_k, body, (m0, l0, acc0))

    l_safe = jnp.where(l == 0, 1.0, l)
    o_ref[:] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[:] = (m + jnp.log(l_safe)).reshape(1, block_q)


def _fwd(q, k, v, *, block_q: int, block_k: int, causal: bool):
    """q: [B, Hq, T, D]; k/v: [B, Hkv, T, D] -> (out [B, Hq, T, D], lse [B, Hq, 1, T])."""
    b, hq, t, d = q.shape
    hkv = k.shape[1]
    rep = hq // hkv
    scale = d**-0.5

    grid = (b, hq, t // block_q)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, block_k=block_k, scale=scale, causal=causal),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, None, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, t, d), lambda bi, hi, qi: (bi, hi // rep, 0, 0)),
            pl.BlockSpec((None, None, t, d), lambda bi, hi, qi: (bi, hi // rep, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, None, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, 1, block_q), lambda bi, hi, qi: (bi, hi, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((b, hq, 1, t), jnp.float32),
        ],
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *, block_k, scale, causal):
    # q/do/dq: [block_q, d]; k/v: [t, d]; lse/delta: [1, block_q]
    block_q, d = q_ref.shape
    t = k_ref.shape[0]
    qi = pl.program_id(2)
    q = q_ref[:].astype(jnp.float32)
    do = do_ref[:].astype(jnp.float32)
    lse = lse_ref[:].reshape(block_q, 1)
    delta = delta_ref[:].reshape(block_q, 1)
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def body(ki, dq):
        k_blk = k_ref[pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = scale * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if causal:
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        return dq + scale * jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    num_k = t // block_k if not causal else (qi * block_q + block_q) // block_k
    dq = jax.lax.fori_loop(0, num_k, body, jnp.zeros((block_q, d), jnp.float32))
    dq_ref[:] = dq.astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, *, block_q, scale, causal, rep
):
    # grid point: (batch, kv-head, k-block). q/do: [rep, t, d];
    # k/v/dk/dv: [block_k, d]; lse/delta: [rep, t]
    block_k, d = k_ref.shape
    t = q_ref.shape[1]
    ki = pl.program_id(2)
    k_blk = k_ref[:].astype(jnp.float32)
    v_blk = v_ref[:].astype(jnp.float32)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)

    def head_body(r, carry):
        def body(qj, carry2):
            dk, dv = carry2
            q_blk = q_ref[r, pl.ds(qj * block_q, block_q), :].astype(jnp.float32)
            do_blk = do_ref[r, pl.ds(qj * block_q, block_q), :].astype(jnp.float32)
            lse_blk = lse_ref[r, pl.ds(qj * block_q, block_q)].reshape(block_q, 1)
            delta_blk = delta_ref[r, pl.ds(qj * block_q, block_q)].reshape(block_q, 1)
            s = scale * jax.lax.dot_general(
                q_blk, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )
            if causal:
                q_pos = qj * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0
                )
                s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
            p = jnp.exp(s - lse_blk)
            dv = dv + jax.lax.dot_general(
                p, do_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
            )
            dp = jax.lax.dot_general(
                do_blk, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )
            ds = p * (dp - delta_blk)
            dk = dk + scale * jax.lax.dot_general(
                ds, q_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
            )
            return dk, dv

        # causal: only q blocks at or after this k block contribute
        q_start = (ki * block_k) // block_q if causal else 0
        return jax.lax.fori_loop(q_start, t // block_q, body, carry)

    dk0 = jnp.zeros((block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(0, rep, head_body, (dk0, dv0))
    dk_ref[:] = dk.astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _bwd(block_q, block_k, causal, res, dout):
    q, k, v, out, lse = res
    b, hq, t, d = q.shape
    hkv = k.shape[1]
    rep = hq // hkv
    scale = d**-0.5

    delta = jnp.sum(
        dout.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    ).reshape(b, hq, 1, t)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, block_k=block_k, scale=scale, causal=causal),
        grid=(b, hq, t // block_q),
        in_specs=[
            pl.BlockSpec((None, None, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, t, d), lambda bi, hi, qi: (bi, hi // rep, 0, 0)),
            pl.BlockSpec((None, None, t, d), lambda bi, hi, qi: (bi, hi // rep, 0, 0)),
            pl.BlockSpec((None, None, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, 1, block_q), lambda bi, hi, qi: (bi, hi, 0, qi)),
            pl.BlockSpec((None, None, 1, block_q), lambda bi, hi, qi: (bi, hi, 0, qi)),
        ],
        out_specs=pl.BlockSpec((None, None, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
    )(q, k, v, dout, lse, delta)

    # dk/dv: group q by kv head: [b, hkv, rep, t, d]
    q_g = q.reshape(b, hkv, rep, t, d)
    do_g = dout.reshape(b, hkv, rep, t, d)
    lse_g = lse.reshape(b, hkv, rep, t)
    delta_g = delta.reshape(b, hkv, rep, t)

    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, block_q=block_q, scale=scale, causal=causal, rep=rep
        ),
        grid=(b, hkv, t // block_k),
        in_specs=[
            pl.BlockSpec((None, None, rep, t, d), lambda bi, hi, ki: (bi, hi, 0, 0, 0)),
            pl.BlockSpec((None, None, block_k, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((None, None, block_k, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((None, None, rep, t, d), lambda bi, hi, ki: (bi, hi, 0, 0, 0)),
            pl.BlockSpec((None, None, rep, t), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, rep, t), lambda bi, hi, ki: (bi, hi, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, None, block_k, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((None, None, block_k, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
    )(q_g, k, v, do_g, lse_g, delta_g)

    return dq, dk, dv


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, block_q, block_k, causal):
    out, _ = _fwd(q, k, v, block_q=block_q, block_k=block_k, causal=causal)
    return out


def _flash_fwd(q, k, v, block_q, block_k, causal):
    out, lse = _fwd(q, k, v, block_q=block_q, block_k=block_k, causal=causal)
    return out, (q, k, v, out, lse)


_flash.defvjp(_flash_fwd, _bwd)


def flash_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *, causal: bool = True
) -> jax.Array:
    """[B, T, H, D] attention via the Pallas kernel; falls back to XLA for
    shapes the kernel doesn't tile (T not a multiple of 128)."""
    b, t, hq, d = q.shape
    block_q = _pick_block(t)
    block_k = _pick_block(t, 256)
    if block_q == 0 or block_k == 0 or d % 8 != 0:
        from opendiloco_tpu.ops.attention import xla_attention

        return xla_attention(q, k, v, causal=causal)
    # kernel layout is [B, H, T, D]
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out = _flash(qt, kt, vt, block_q, block_k, causal)
    return out.transpose(0, 2, 1, 3)
