"""Unified tracing + metrics plane (spans, counters, gauges, exporters).

Hook-site idiom, mirroring ``chaos.plane()``::

    from opendiloco_tpu import obs
    tr = obs.tracer()          # None when ODTP_OBS is unset (zero-cost)
    if tr is not None:
        t0 = tr.now()
        ...
        tr.add_span("outer/encode", t0, tr.now(), round=key, worker=r)

or, in plain synchronous code::

    with obs.span("outer/rendezvous", round=key):
        ...

See ``obs/trace.py`` for the env knobs and ``obs/export.py`` for the
Chrome-trace / Prometheus / JSONL exporters.
"""
from opendiloco_tpu.obs.trace import (  # noqa: F401
    StageTimes,
    Tracer,
    count,
    enabled,
    gauge,
    span,
    tracer,
)
from opendiloco_tpu.obs import (  # noqa: F401
    anomaly,
    blackbox,
    export,
    mfu,
    overseer,
    reqtrace,
)
from opendiloco_tpu.obs import trace as _trace


def reset() -> None:
    """Drop every cached obs singleton (tests / env changes): tracer,
    flight recorder, request-trace ring, overseer, and watchdogs."""
    anomaly.reset()
    blackbox.reset()
    reqtrace.reset()
    overseer.reset()
    _trace.reset()


__all__ = [
    "StageTimes",
    "Tracer",
    "anomaly",
    "blackbox",
    "count",
    "enabled",
    "export",
    "gauge",
    "mfu",
    "overseer",
    "reqtrace",
    "reset",
    "span",
    "tracer",
]
