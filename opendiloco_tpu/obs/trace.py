"""Zero-dependency tracing + metrics plane for the whole stack.

One process-wide :class:`Tracer` records spans (Chrome ``trace_event``
compatible, monotonic-clock timed), counters and gauges. The plane is
armed by ``ODTP_OBS`` and is zero-cost when unset: the :func:`tracer`
accessor is a single environment-dict lookup plus a cached string
compare returning ``None`` (the same idiom as ``chaos.plane()``), and
every hook site in the data plane is one ``is None`` branch.

Environment knobs (all read lazily, so tests can flip them):

- ``ODTP_OBS``            arm the plane ("1", or a free-form tag)
- ``ODTP_OBS_DIR``        directory for the JSONL event sink; when set,
                          the tracer flushes ``trace-w<rank>-<pid>.jsonl``
                          there at exit (and on explicit ``flush()``)
- ``ODTP_OBS_PROM_PORT``  bind a pull-based Prometheus text endpoint on
                          this port (0 = ephemeral). No port is ever
                          bound while ``ODTP_OBS`` is unset.
- ``ODTP_OBS_EVENTS_CAP`` ring limit for recorded events (default 65536);
                          overflow increments ``tracer().dropped``.
"""
from __future__ import annotations

import atexit
import json
import os
import threading
import time
from typing import Any, Callable, Optional

_ENV = "ODTP_OBS"
_DIR_ENV = "ODTP_OBS_DIR"
_PROM_ENV = "ODTP_OBS_PROM_PORT"
_CAP_ENV = "ODTP_OBS_EVENTS_CAP"
_DEFAULT_CAP = 65536


class _NullSpan:
    """Inert context manager returned by :func:`span` when disabled."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc: object) -> bool:
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tr", "name", "attrs", "t0")

    def __init__(self, tr: "Tracer", name: str, attrs: dict):
        self._tr = tr
        self.name = name
        self.attrs = attrs
        self.t0 = 0.0

    def __enter__(self) -> "_Span":
        tr = self._tr
        stack = tr._stack()
        if stack:
            self.attrs.setdefault("parent", stack[-1])
        stack.append(self.name)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc: object) -> bool:
        t1 = time.perf_counter()
        stack = self._tr._stack()
        if stack and stack[-1] == self.name:
            stack.pop()
        self._tr.add_span(self.name, self.t0, t1, **self.attrs)
        return False


class StageTimes:
    """Thread-safe per-stage wall-clock accumulator for one round.

    Concurrent stages (a pipelined encode overlapping a send) sum past
    wall-clock by design: the totals answer "where did work time go",
    not "how long did the round take".
    """

    __slots__ = ("_lock", "totals")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.totals: dict[str, float] = {}

    def add(self, stage: str, seconds: float) -> None:
        with self._lock:
            self.totals[stage] = self.totals.get(stage, 0.0) + seconds

    def timed(self, stage: str, fn: Callable) -> Callable:
        """Wrap ``fn`` so its wall time accrues to ``stage``."""

        def run(*args: Any, **kwargs: Any) -> Any:
            t0 = time.perf_counter()
            try:
                return fn(*args, **kwargs)
            finally:
                self.add(stage, time.perf_counter() - t0)

        return run


class Tracer:
    """Process-wide span/counter/gauge recorder. Thread-safe."""

    def __init__(self, spec: str):
        self.spec = spec
        self.pid = os.getpid()
        self.origin = time.perf_counter()
        self.origin_wall = time.time()
        self.cap = int(os.environ.get(_CAP_ENV, _DEFAULT_CAP))
        self.events: list[dict] = []
        self.dropped = 0
        self.identity: dict[str, Any] = {}
        self._lock = threading.Lock()
        self._counters: dict[tuple, float] = {}
        self._gauges: dict[tuple, float] = {}
        self._local = threading.local()
        self.prom = None
        port = os.environ.get(_PROM_ENV)
        if port is not None and port != "":
            from opendiloco_tpu.obs import prom as _prom

            self.prom = _prom.start(int(port), self)
        if os.environ.get(_DIR_ENV):
            atexit.register(self.flush)

    # -- identity / time ----------------------------------------------------
    def set_identity(self, **attrs: Any) -> None:
        self.identity.update(attrs)

    def now(self) -> float:
        return time.perf_counter()

    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    # -- spans --------------------------------------------------------------
    def span(self, name: str, **attrs: Any) -> _Span:
        return _Span(self, name, attrs)

    def add_span(self, name: str, t0: float, t1: float, **attrs: Any) -> None:
        """Record a completed interval (perf_counter stamps)."""
        self._record({
            "name": name,
            "ph": "X",
            "ts": (t0 - self.origin) * 1e6,
            "dur": max(0.0, (t1 - t0) * 1e6),
            "tid": threading.get_ident() & 0x7FFFFFFF,
            "args": attrs,
        })

    def instant(self, name: str, **attrs: Any) -> None:
        self._record({
            "name": name,
            "ph": "i",
            "ts": (time.perf_counter() - self.origin) * 1e6,
            "s": "t",
            "tid": threading.get_ident() & 0x7FFFFFFF,
            "args": attrs,
        })

    def _record(self, ev: dict) -> None:
        with self._lock:
            if len(self.events) >= self.cap:
                self.dropped += 1
            else:
                self.events.append(ev)
        # mirror into the flight recorder's ring of the RECENT past --
        # including events the capped main buffer dropped (a long run's
        # tail is exactly what a postmortem needs). Outside self._lock:
        # the recorder has its own lock and must not nest under ours.
        from opendiloco_tpu.obs import blackbox

        bb = blackbox.recorder()
        if bb is not None:
            bb.note_event(ev)

    # -- counters / gauges --------------------------------------------------
    @staticmethod
    def _key(name: str, labels: dict) -> tuple:
        return (name, tuple(sorted(labels.items())))

    def count(self, name: str, n: float = 1, **labels: Any) -> None:
        key = self._key(name, labels)
        with self._lock:
            self._counters[key] = self._counters.get(key, 0.0) + n

    def gauge(self, name: str, value: float, **labels: Any) -> None:
        v = float(value)
        with self._lock:
            self._gauges[self._key(name, labels)] = v
        # gauges double as Chrome ``counter`` events (ph="C") so Perfetto
        # renders loss / tokens_per_s / pseudo_grad_norm as value tracks
        # alongside the spans; labels fold into the track name the same
        # way _flat_metrics renders them
        if labels:
            body = ",".join(f"{k}={lv}" for k, lv in sorted(labels.items()))
            track = f"{name}{{{body}}}"
        else:
            track = name
        self._record({
            "name": track,
            "ph": "C",
            "ts": (time.perf_counter() - self.origin) * 1e6,
            "tid": 0,
            "args": {"value": v},
        })

    def counters(self) -> dict:
        with self._lock:
            return {k: v for k, v in self._counters.items()}

    def gauges(self) -> dict:
        with self._lock:
            return {k: v for k, v in self._gauges.items()}

    def snapshot(self) -> dict:
        """Counters + gauges with the chaos plane folded in first-class."""
        counters = self.counters()
        try:
            from opendiloco_tpu.diloco import chaos

            cp = chaos.plane()
            if cp is not None:
                for kind, n in dict(cp.counters).items():
                    counters[self._key("chaos_faults", {"kind": kind})] = n
        except Exception:
            pass
        return {
            "counters": counters,
            "gauges": self.gauges(),
            "events": len(self.events),
            "dropped": self.dropped,
        }

    # -- sinks --------------------------------------------------------------
    def jsonl_path(self) -> Optional[str]:
        out_dir = os.environ.get(_DIR_ENV)
        if not out_dir:
            return None
        worker = self.identity.get("worker", "x")
        return os.path.join(out_dir, f"trace-w{worker}-{self.pid}.jsonl")

    def flush(self, path: Optional[str] = None) -> Optional[str]:
        """Write all events + a trailing meta record as JSONL."""
        path = path or self.jsonl_path()
        if path is None:
            return None
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        snap = self.snapshot()
        with self._lock:
            events = list(self.events)
        tmp = f"{path}.tmp.{self.pid}"
        with open(tmp, "w") as f:
            for ev in events:
                f.write(json.dumps(_jsonable(ev)) + "\n")
            meta = {
                "name": "meta",
                "ph": "M",
                "origin_wall": self.origin_wall,
                "pid": self.pid,
                "identity": _jsonable(self.identity),
                "counters": _flat_metrics(snap["counters"]),
                "gauges": _flat_metrics(snap["gauges"]),
                "dropped": snap["dropped"],
                "spec": self.spec,
            }
            f.write(json.dumps(meta) + "\n")
        os.replace(tmp, path)
        return path

    def close(self) -> None:
        if self.prom is not None:
            self.prom.stop()
            self.prom = None
        try:
            atexit.unregister(self.flush)
        except Exception:
            pass


def _jsonable(obj: Any) -> Any:
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    try:
        return float(obj)
    except Exception:
        return str(obj)


def _flat_metrics(metrics: dict) -> dict:
    """(name, labels) tuple keys -> 'name{a=b}' flat string keys."""
    out = {}
    for (name, labels), value in sorted(metrics.items(), key=str):
        if labels:
            body = ",".join(f"{k}={v}" for k, v in labels)
            out[f"{name}{{{body}}}"] = value
        else:
            out[name] = value
    return out


# -- process-wide accessor (same idiom as chaos.plane()) --------------------
_tracer: Optional[Tracer] = None
_spec: Optional[str] = None
_lock = threading.Lock()


def tracer() -> Optional[Tracer]:
    """The process tracer, or None when ODTP_OBS is unset (zero-cost)."""
    global _tracer, _spec
    spec = os.environ.get(_ENV) or None
    if spec == _spec:
        return _tracer
    with _lock:
        if spec != _spec:
            old, _tracer = _tracer, (Tracer(spec) if spec else None)
            _spec = spec
            if old is not None:
                old.close()
    return _tracer


def enabled() -> bool:
    return tracer() is not None


def span(name: str, **attrs: Any):
    """Module-level span: inert singleton context when disabled."""
    tr = tracer()
    if tr is None:
        return _NULL_SPAN
    return tr.span(name, **attrs)


def count(name: str, n: float = 1, **labels: Any) -> None:
    tr = tracer()
    if tr is not None:
        tr.count(name, n, **labels)


def gauge(name: str, value: float, **labels: Any) -> None:
    tr = tracer()
    if tr is not None:
        tr.gauge(name, value, **labels)


def reset() -> None:
    """Drop the cached tracer (tests / env changes); stops any endpoint."""
    global _tracer, _spec
    with _lock:
        if _tracer is not None:
            _tracer.close()
        _tracer = None
        _spec = None
