"""Achieved-MFU estimation from the banked roofline numbers.

``AOT_ROOFLINE.json`` (repo root) carries the device peak
(``peak_flops``) and, per model size, XLA's executed-flops cost
analysis (``multichip_rows[*].executed_flops_per_device`` /
``tokens_per_step``). When a row matches the configured model we use
the measured flops/token; otherwise we fall back to the standard
``6 * n_params`` analytic estimate. Everything is computed once at
startup — the per-step cost of the MFU gauge is one multiply.
"""
from __future__ import annotations

import json
import os
from typing import Optional

_DEFAULT_PEAK = 1.97e14  # TPU v5e bf16, matches the banked roofline


def roofline_path() -> Optional[str]:
    override = os.environ.get("ODTP_ROOFLINE")
    if override:
        return override if os.path.exists(override) else None
    here = os.path.dirname(os.path.abspath(__file__))
    for _ in range(4):
        here = os.path.dirname(here)
        cand = os.path.join(here, "AOT_ROOFLINE.json")
        if os.path.exists(cand):
            return cand
    return None


def _model_key(path_model: str) -> str:
    base = os.path.basename(str(path_model).rstrip("/")).lower()
    if base.endswith(".json"):
        base = base[: -len(".json")]
    if base.startswith("config_"):
        base = base[len("config_"):]
    return base


def flops_per_token(
    path_model: str, n_params: Optional[int] = None
) -> "tuple[Optional[float], float, str]":
    """-> (total model flops per token or None, per-device peak, source)."""
    peak = _DEFAULT_PEAK
    path = roofline_path()
    rows: list[dict] = []
    if path is not None:
        try:
            with open(path) as f:
                roofline = json.load(f)
            peak = float(roofline.get("peak_flops", _DEFAULT_PEAK))
            rows = roofline.get("multichip_rows") or []
        except (OSError, ValueError):
            rows = []
    key = _model_key(path_model)
    best: Optional[dict] = None
    for row in rows:
        if row.get("model") != key:
            continue
        if not row.get("executed_flops_per_device"):
            continue
        if not row.get("tokens_per_step"):
            continue
        # prefer the largest-scale measurement of this model
        if best is None or row.get("chips", 0) > best.get("chips", 0):
            best = row
    if best is not None:
        per_token = (
            float(best["executed_flops_per_device"])
            * float(best.get("chips", 1))
            / float(best["tokens_per_step"])
        )
        return per_token, peak, "roofline"
    if n_params:
        return 6.0 * float(n_params), peak, "analytic_6n"
    return None, peak, "unavailable"


def mfu(
    tokens_per_second: float,
    model_flops_per_token: float,
    n_devices: int,
    peak_flops_per_device: float = _DEFAULT_PEAK,
) -> float:
    """Model FLOPs utilization in [0, ~1] across ``n_devices`` chips."""
    achieved = model_flops_per_token * tokens_per_second
    return achieved / (peak_flops_per_device * max(1, n_devices))
