"""Exporters for the obs plane: Chrome trace_event, Prometheus text, JSONL.

The Chrome output is the JSON Object Format of the ``trace_event`` spec
(a ``traceEvents`` list plus metadata) and loads directly in Perfetto /
``chrome://tracing``. The Prometheus output is version 0.0.4 text
exposition (``# TYPE`` comments, ``name{label="v"} value`` samples).
"""
from __future__ import annotations

import json
import re
from typing import Any, Iterable, Optional

from opendiloco_tpu.obs.trace import Tracer, _flat_metrics

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_OK = re.compile(r"[^a-zA-Z0-9_]")


# -- Chrome trace_event -----------------------------------------------------
def clock_shifts(
    workers: "list[tuple[Any, list[dict], dict]]",
) -> "tuple[float, list[float]]":
    """Cross-process clock alignment: ``(t0, shifts_us)``.

    Each worker's events carry microsecond timestamps relative to its own
    monotonic origin; its meta record pins that origin to the wall clock
    (``origin_wall``). Shifting worker *i* by ``shifts_us[i]`` puts every
    event on one shared timeline whose zero is the earliest origin ``t0``.
    Both the Chrome merge below and ``scripts/odtp_postmortem.py`` order
    cross-worker events with exactly this arithmetic.
    """
    origins = [m.get("origin_wall", 0.0) for _, _, m in workers]
    t0 = min(origins) if origins else 0.0
    shifts = [
        (m.get("origin_wall", t0) - t0) * 1e6 for _, _, m in workers
    ]
    return t0, shifts


def chrome_trace(
    workers: "list[tuple[Any, list[dict], dict]]",
) -> dict:
    """Merge per-worker event lists into one Chrome trace object.

    ``workers`` is ``[(worker_id, events, meta), ...]`` where ``meta``
    is the trailing JSONL meta record (needs ``origin_wall`` to align
    monotonic clocks across processes). Each worker becomes one Chrome
    ``pid`` row, named ``worker <id>``.
    """
    _, shifts = clock_shifts(workers)
    trace_events: list[dict] = []
    for pid, (worker, events, meta) in enumerate(workers):
        shift_us = shifts[pid]
        trace_events.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": f"worker {worker}"},
        })
        for ev in events:
            out = {
                "name": ev.get("name", "?"),
                "ph": ev.get("ph", "X"),
                "ts": float(ev.get("ts", 0.0)) + shift_us,
                "pid": pid,
                "tid": int(ev.get("tid", 0)),
                "args": ev.get("args", {}),
            }
            if out["ph"] == "X":
                out["dur"] = float(ev.get("dur", 0.0))
            elif out["ph"] == "i":
                out["s"] = ev.get("s", "t")
            elif out["ph"] == "C":
                # gauge counter track (see Tracer.gauge): Perfetto keys the
                # track on (pid, name) and plots args["value"] over time
                out["args"] = {"value": float(
                    (ev.get("args") or {}).get("value", 0.0))}
            trace_events.append(out)
    return {
        "traceEvents": trace_events,
        "displayTimeUnit": "ms",
        "otherData": {"producer": "opendiloco_tpu.obs"},
    }


def tracer_chrome_trace(tr: Tracer) -> dict:
    """Single-process convenience wrapper around :func:`chrome_trace`."""
    with tr._lock:
        events = list(tr.events)
    meta = {"origin_wall": tr.origin_wall}
    worker = tr.identity.get("worker", tr.pid)
    return chrome_trace([(worker, events, meta)])


def write_chrome_trace(path: str, trace: dict) -> None:
    with open(path, "w") as f:
        json.dump(trace, f)
        f.write("\n")


# -- JSONL ------------------------------------------------------------------
def load_jsonl(path: str) -> "tuple[list[dict], dict]":
    """Read one worker trace file -> (events, meta record)."""
    events: list[dict] = []
    meta: dict = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if rec.get("ph") == "M" and rec.get("name") == "meta":
                meta = rec
            else:
                events.append(rec)
    return events, meta


# -- Prometheus text exposition ---------------------------------------------
def _metric_name(name: str) -> str:
    name = _NAME_OK.sub("_", str(name))
    if not name or not re.match(r"[a-zA-Z_:]", name[0]):
        name = "_" + name
    return f"odtp_{name}"


def _label_pairs(labels: Iterable) -> str:
    parts = []
    for k, v in labels:
        key = _LABEL_OK.sub("_", str(k))
        val = str(v).replace("\\", r"\\").replace('"', r"\"")
        val = val.replace("\n", r"\n")
        parts.append(f'{key}="{val}"')
    return "{" + ",".join(parts) + "}" if parts else ""


# curated help strings for metrics whose meaning isn't obvious from the
# name; everything else gets the generic family line
_HELP = {
    "odtp_link_bps": "EWMA goodput toward labelled peer, bytes/second "
    "(adaptive outer transport, diloco/linkstate.py)",
    "odtp_link_rtt_ms": "EWMA round-trip time toward labelled peer, ms "
    "(adaptive outer transport)",
    "odtp_outer_rounds_adaptive": "outer rounds run with adaptive "
    "(link-proportional) butterfly partitioning",
    "odtp_bulk_stripe_hedges": "lagging bulk stripes re-dispatched over an "
    "idle connection (straggler hedging)",
}


def _render_family(
    out: list, metrics: dict, kind: str
) -> None:
    by_name: dict[str, list] = {}
    for (name, labels), value in metrics.items():
        by_name.setdefault(_metric_name(name), []).append((labels, value))
    for name in sorted(by_name):
        help_txt = _HELP.get(name, f"opendiloco_tpu obs {kind}")
        out.append(f"# HELP {name} {help_txt}")
        out.append(f"# TYPE {name} {kind}")
        for labels, value in sorted(by_name[name], key=str):
            out.append(f"{name}{_label_pairs(labels)} {float(value)}")


def prometheus_text(tr: Optional[Tracer]) -> str:
    """Render the tracer snapshot as Prometheus 0.0.4 text exposition."""
    if tr is None:
        return ""
    snap = tr.snapshot()
    out: list[str] = []
    _render_family(out, snap["counters"], "counter")
    _render_family(out, snap["gauges"], "gauge")
    out.append("# HELP odtp_obs_events_total obs events recorded")
    out.append("# TYPE odtp_obs_events_total counter")
    out.append(f"odtp_obs_events_total {float(snap['events'])}")
    out.append("# HELP odtp_obs_events_dropped_total obs events dropped")
    out.append("# TYPE odtp_obs_events_dropped_total counter")
    out.append(f"odtp_obs_events_dropped_total {float(snap['dropped'])}")
    return "\n".join(out) + "\n"


__all__ = [
    "chrome_trace",
    "clock_shifts",
    "tracer_chrome_trace",
    "write_chrome_trace",
    "load_jsonl",
    "prometheus_text",
    "_flat_metrics",
]
