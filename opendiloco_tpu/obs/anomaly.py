"""Anomaly watchdogs: detect trouble, record it, never kill the run.

Four detectors, each sourced from telemetry that already exists:

- **straggler** — any worker whose round wall time exceeds
  ``ODTP_WATCHDOG_STRAGGLER_X`` x the galaxy median, or whose inner
  tokens/s falls below 1/X of it (both ride the overseer roll-ups; the
  throughput signal is the one that LOCALIZES a slow host, since a
  barrier-synchronized round spreads its delay over everyone);
- **divergence** — own pseudo-grad-norm or loss is a
  ``ODTP_WATCHDOG_DIVERGE_Z``-sigma outlier vs the galaxy;
- **stall** — no outer-round progress for ``ODTP_WATCHDOG_STALL_S``
  seconds (0 = off), checked by one low-frequency daemon thread;
- **dead peer** — an elastic round is missing a worker that completed
  earlier rounds (the overseer saw it in a group before);
- **stale worker** — under async bounded-staleness gossip
  (``ODTP_ASYNC_STALENESS`` > 0), a worker's epoch lags the galaxy's
  front-runner by more than the window: it can no longer be matched, so
  its progress stops mixing into the galaxy;
- **serve staleness breach** — the serving plane's adopted snapshot is
  older than its own ``max_stale_rounds`` bound;
- **SLO breach** — a serving replica's measured request p99 crossed the
  fleet's declared SLO; the trip carries exemplar request-trace IDs
  (obs/reqtrace.py) naming the offending requests.

Every trip emits an ``odtp_anomaly_<kind>`` counter, an
``anomaly/<kind>`` instant span, and a flight-recorder dump — and
nothing else: watchdogs observe, operators decide. Trips are
cooldown-limited per (kind, subject) so a persistent condition counts
once per window instead of flooding.

Armed by ``ODTP_OBS`` like the rest of the obs plane; :func:`watchdog`
is the same zero-cost accessor idiom as ``chaos.plane()``.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Optional

from opendiloco_tpu.utils.logger import get_text_logger

log = get_text_logger(__name__)

_ENV = "ODTP_OBS"
_STALL_ENV = "ODTP_WATCHDOG_STALL_S"
_STRAGGLER_ENV = "ODTP_WATCHDOG_STRAGGLER_X"
_DIVERGE_ENV = "ODTP_WATCHDOG_DIVERGE_Z"
_ASYNC_WINDOW_ENV = "ODTP_ASYNC_STALENESS"
_DEFAULT_STALL_S = 0.0
_DEFAULT_STRAGGLER_X = 3.0
_DEFAULT_DIVERGE_Z = 6.0

# one trip per (kind, subject) per cooldown window; counters still
# increment per trip, so persistent conditions show a growing count
_COOLDOWN_S = 30.0

# straggler comparisons only consider roll-ups measured within this many
# seconds of the freshest one: a gossip matrix keeps a departed worker's
# last vector forever, and a stale vector reflects a different load
# regime (compile warm-up, different galaxy population) than the rows
# it would be compared against. Wide enough that a slow host that only
# joins every few elastic rounds still lands in the window
_STRAGGLER_FRESH_S = 60.0


def _median(vals: list) -> float:
    vals = sorted(vals)
    n = len(vals)
    mid = n // 2
    return vals[mid] if n % 2 else 0.5 * (vals[mid - 1] + vals[mid])


class Watchdog:
    """Stateful detectors over round-health rows + the overseer matrix."""

    def __init__(self, spec: str):
        self.spec = spec
        self.stall_s = float(os.environ.get(_STALL_ENV, _DEFAULT_STALL_S))
        self.straggler_x = float(
            os.environ.get(_STRAGGLER_ENV, _DEFAULT_STRAGGLER_X))
        self.diverge_z = float(os.environ.get(_DIVERGE_ENV, _DEFAULT_DIVERGE_Z))
        # async gossip's bounded-staleness window: a worker whose epoch
        # lag exceeds it can no longer be matched, which is worth an
        # anomaly even though training proceeds without it
        self.async_window = int(os.environ.get(_ASYNC_WINDOW_ENV, "0") or 0)
        self._lock = threading.Lock()
        self._last_progress: Optional[float] = None
        self._last_trip: dict[tuple, float] = {}
        self._grouped: set = set()  # peers seen completing a round with us
        self._stall_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- trip plumbing --------------------------------------------------------
    def _trip(self, kind: str, subject: str = "", **attrs: Any) -> bool:
        """Record one anomaly (counter + instant + blackbox dump), unless
        the same (kind, subject) tripped within the cooldown window."""
        now = time.monotonic()
        with self._lock:
            key = (kind, subject)
            if now - self._last_trip.get(key, -_COOLDOWN_S) < _COOLDOWN_S:
                return False
            self._last_trip[key] = now
        log.warning("watchdog: %s %s %s", kind, subject, attrs)
        from opendiloco_tpu.obs import trace

        tr = trace.tracer()
        if tr is not None:
            labels = {"peer": subject} if subject else {}
            tr.count(f"anomaly_{kind}", **labels)
            tr.instant(f"anomaly/{kind}", subject=subject, **attrs)
        try:
            from opendiloco_tpu.obs import blackbox

            bb = blackbox.recorder()
            if bb is not None:
                bb.note_anomaly({
                    "wall": round(time.time(), 3), "kind": kind,
                    "subject": subject, **attrs,
                })
        except Exception:
            pass
        return True

    # -- detectors ------------------------------------------------------------
    def on_round(self, health: dict, matrix: dict,
                 own_id: Optional[str] = None,
                 members: Optional[list] = None) -> None:
        """Run the per-round detectors. ``matrix`` is the overseer's
        current galaxy view; ``members`` the group that just completed."""
        self.note_progress()
        self._check_straggler(matrix)
        self._check_divergence(health, matrix, own_id)
        self._check_dead_peers(health, members)
        self._check_stale_worker(matrix)

    def _check_straggler(self, matrix: dict) -> None:
        """Two signals, same threshold factor. Round wall time catches a
        worker whose rounds genuinely diverge from the galaxy's (retry
        loops, elastic regroups). Tokens/s catches the classic slow host:
        a barrier-synchronized round absorbs a straggler's delay into
        EVERYONE's round time, so only per-worker inner throughput
        localizes who the galaxy is waiting on. Both signals skip stale
        roll-ups (departed workers' frozen vectors) and first-round ones
        (compile warm-up dominates the timings)."""
        if self.straggler_x <= 0.0:
            return
        fresh_ts = max(
            (float(v["ts"]) for v in matrix.values()
             if isinstance(v.get("ts"), (int, float))), default=0.0)
        warm = {
            pid: v for pid, v in matrix.items()
            if isinstance(v.get("ts"), (int, float))
            and fresh_ts - float(v["ts"]) <= _STRAGGLER_FRESH_S
            and isinstance(v.get("rounds"), (int, float))
            and v["rounds"] >= 2
        }
        times = {
            pid: float(v["stages"]["round_s"])
            for pid, v in warm.items()
            if isinstance(v.get("stages"), dict)
            and v["stages"].get("round_s")
        }
        if len(times) >= 3:  # a median of two is just the other worker
            med = _median(list(times.values()))
            if med > 0.0:
                for pid, t in times.items():
                    if t > self.straggler_x * med:
                        self._trip(
                            "straggler", subject=pid,
                            round_s=round(t, 3),
                            galaxy_median_s=round(med, 3),
                            factor=round(t / med, 2),
                        )
        tps = {
            pid: float(v["tokens_per_s"]) for pid, v in warm.items()
            if isinstance(v.get("tokens_per_s"), (int, float))
            and v["tokens_per_s"] > 0
        }
        if len(tps) >= 3:
            med = _median(list(tps.values()))
            if med > 0.0:
                for pid, t in tps.items():
                    if t * self.straggler_x < med:
                        self._trip(
                            "straggler", subject=pid,
                            tokens_per_s=round(t, 1),
                            galaxy_median_tokens_per_s=round(med, 1),
                            factor=round(med / t, 2),
                        )

    def _check_stale_worker(self, matrix: dict) -> None:
        """Async bounded-staleness gossip only (window > 0): a worker
        whose epoch lags the galaxy's front-runner by MORE than the
        staleness window has fallen out of matchable range — nobody will
        mix with it until it catches up (or desync-onboards), so its
        local progress stops reaching the galaxy. Epochs ride the same
        overseer roll-ups odtp_top renders; stale vectors are skipped the
        same way the straggler detector skips them."""
        if self.async_window <= 0:
            return
        fresh_ts = max(
            (float(v["ts"]) for v in matrix.values()
             if isinstance(v.get("ts"), (int, float))), default=0.0)
        epochs = {
            pid: int(v["epoch"]) for pid, v in matrix.items()
            if isinstance(v.get("epoch"), (int, float))
            and isinstance(v.get("ts"), (int, float))
            and fresh_ts - float(v["ts"]) <= _STRAGGLER_FRESH_S
        }
        if len(epochs) < 2:
            return
        front = max(epochs.values())
        for pid, e in epochs.items():
            lag = front - e
            if lag > self.async_window:
                self._trip(
                    "stale_worker", subject=pid,
                    epoch=e, galaxy_front_epoch=front,
                    lag=lag, window=self.async_window,
                )

    def _check_divergence(self, health: dict, matrix: dict,
                          own_id: Optional[str]) -> None:
        if self.diverge_z <= 0.0 or own_id is None:
            return
        for field in ("pg_norm", "loss"):
            vals = {
                pid: float(v[field]) for pid, v in matrix.items()
                if isinstance(v.get(field), (int, float))
            }
            own = vals.get(own_id)
            if own is None or len(vals) < 4:
                continue
            others = [v for pid, v in vals.items() if pid != own_id]
            mean = sum(others) / len(others)
            var = sum((v - mean) ** 2 for v in others) / len(others)
            std = var ** 0.5
            if std <= 0.0:
                continue
            z = abs(own - mean) / std
            if z > self.diverge_z:
                self._trip(
                    "divergence", subject=str(field),
                    value=round(own, 6), galaxy_mean=round(mean, 6),
                    z=round(z, 2), round=health.get("round"),
                )

    def _check_dead_peers(self, health: dict,
                         members: Optional[list]) -> None:
        if not members:
            return
        current = set(members)
        with self._lock:
            missing = (self._grouped - current) if health.get("elastic") \
                else set()
            self._grouped |= current
        for pid in sorted(missing):
            if self._trip("dead_peer", subject=str(pid),
                          round=health.get("round")):
                with self._lock:
                    # once reported, a peer must complete a round with us
                    # again before it can be declared dead a second time
                    self._grouped.discard(pid)

    def serve_staleness(
        self, staleness: float, bound: float, exemplars: Any = ()
    ) -> None:
        """Serving-plane hook: adopted-snapshot staleness vs its bound.
        ``exemplars`` names recent request-trace IDs served while stale,
        so the anomaly record points at reviewable evidence."""
        if bound > 0 and staleness > bound:
            self._trip(
                "serve_staleness", staleness=round(float(staleness), 3),
                bound=float(bound), exemplars=list(exemplars),
            )

    def slo_breach(
        self,
        p99_ms: float,
        bound_ms: float,
        subject: str = "",
        exemplars: Any = (),
    ) -> bool:
        """Serving-fleet hook: measured request p99 crossed the declared
        SLO. ``exemplars`` carries the offending trace IDs (reqtrace
        ring exemplars) so every breach — and the scale-up it triggers —
        is explainable from recorded evidence."""
        if bound_ms > 0 and p99_ms > bound_ms:
            return self._trip(
                "slo_breach", subject=subject,
                p99_ms=round(float(p99_ms), 3), bound_ms=float(bound_ms),
                exemplars=list(exemplars),
            )
        return False

    def fleet_replica_dead(self, replica_id: str) -> bool:
        """Fleet-router hook: a serving replica stopped answering. Same
        anomaly kind as a departed training peer — the subject prefix
        tells the two planes apart in the counters."""
        return self._trip("dead_peer", subject=f"replica:{replica_id}")

    # -- stall deadline -------------------------------------------------------
    def note_progress(self, epoch: Optional[int] = None) -> None:
        """Any sign of outer progress resets the stall deadline. Called
        per round by the overseer and per outer step by the optimizer (so
        every backend feeds it, not just TCP)."""
        with self._lock:
            self._last_progress = time.monotonic()
            if (self.stall_s > 0.0 and self._stall_thread is None
                    and not self._stop.is_set()):
                self._stall_thread = threading.Thread(
                    target=self._stall_loop, name="odtp-watchdog-stall",
                    daemon=True,
                )
                self._stall_thread.start()

    def _stall_loop(self) -> None:
        # low-frequency: the deadline is in seconds-to-minutes territory
        period = max(1.0, self.stall_s / 4.0)
        while not self._stop.wait(period):
            with self._lock:
                last = self._last_progress
            if last is None:
                continue
            idle = time.monotonic() - last
            if idle > self.stall_s:
                self._trip("stall", idle_s=round(idle, 1),
                           deadline_s=self.stall_s)
                with self._lock:
                    # re-arm: a continuing stall trips once per deadline,
                    # not once per poll
                    self._last_progress = time.monotonic()

    def close(self) -> None:
        self._stop.set()
        t = self._stall_thread
        if t is not None:
            t.join(timeout=2.0)
            self._stall_thread = None


# -- process-wide accessor (same idiom as chaos.plane()) ----------------------
_watchdog: Optional[Watchdog] = None
_spec: Optional[str] = None
_lock = threading.Lock()


def watchdog() -> Optional[Watchdog]:
    """The process watchdog set, or None when ODTP_OBS is unset."""
    global _watchdog, _spec
    spec = os.environ.get(_ENV) or None
    if spec == _spec:
        return _watchdog
    with _lock:
        if spec != _spec:
            old, _watchdog = _watchdog, (Watchdog(spec) if spec else None)
            _spec = spec
            if old is not None:
                old.close()
    return _watchdog


def reset() -> None:
    """Drop the cached watchdog (tests / env changes); stops the thread."""
    global _watchdog, _spec
    with _lock:
        if _watchdog is not None:
            _watchdog.close()
        _watchdog = None
        _spec = None
