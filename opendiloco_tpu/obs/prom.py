"""Minimal pull-based Prometheus text endpoint.

A daemon thread accepts plain HTTP GETs and answers with the current
tracer snapshot rendered by :func:`export.prometheus_text`. Started
only from ``Tracer.__init__`` when both ``ODTP_OBS`` and
``ODTP_OBS_PROM_PORT`` are set — with the plane disarmed no socket is
ever bound.
"""
from __future__ import annotations

import socket
import threading


class PromServer:
    def __init__(self, port: int, tracer) -> None:
        self._tracer = tracer
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("0.0.0.0", port))
        self._sock.listen(8)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._serve, name="odtp-obs-prom", daemon=True
        )
        self._thread.start()

    def _serve(self) -> None:
        from opendiloco_tpu.obs.export import prometheus_text

        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            try:
                conn.settimeout(2.0)
                try:
                    conn.recv(4096)  # drain the request; any GET is /metrics
                except OSError:
                    pass
                body = prometheus_text(self._tracer).encode()
                head = (
                    "HTTP/1.0 200 OK\r\n"
                    "Content-Type: text/plain; version=0.0.4\r\n"
                    f"Content-Length: {len(body)}\r\n\r\n"
                ).encode()
                conn.sendall(head + body)
            except OSError:
                pass
            finally:
                try:
                    conn.close()
                except OSError:
                    pass

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        self._thread.join(timeout=2.0)


def start(port: int, tracer) -> PromServer:
    return PromServer(port, tracer)
