"""Minimal pull-based Prometheus text endpoint.

A daemon thread accepts plain HTTP GETs and answers with the current
tracer snapshot rendered by :func:`export.prometheus_text`. Started
only from ``Tracer.__init__`` when both ``ODTP_OBS`` and
``ODTP_OBS_PROM_PORT`` are set — with the plane disarmed no socket is
ever bound.

One registry per process: the tracer is process-wide, so trainer metrics
and the serve plane's gauges (serve_p50_ms, serve_tokens_per_s, ...)
come out of the SAME snapshot on the SAME endpoint — the serve plane
calls :func:`get_or_start` rather than binding a second port. A
requested port that is already taken (e.g. serve.port colliding with
``ODTP_OBS_PROM_PORT`` when both are enabled) downgrades to an ephemeral
port with a warning instead of killing the process; the bound port is
always ``PromServer.port``.
"""
from __future__ import annotations

import logging
import socket
import threading

log = logging.getLogger(__name__)


class PromServer:
    def __init__(self, port: int, tracer) -> None:
        self._tracer = tracer
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            self._sock.bind(("0.0.0.0", port))
        except OSError as e:
            if port == 0:
                raise
            log.warning(
                "prometheus port %d unavailable (%s); "
                "falling back to an ephemeral port",
                port,
                e,
            )
            self._sock.bind(("0.0.0.0", 0))
        self._sock.listen(8)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._serve, name="odtp-obs-prom", daemon=True
        )
        self._thread.start()

    def _serve(self) -> None:
        from opendiloco_tpu.obs.export import prometheus_text

        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            try:
                conn.settimeout(2.0)
                try:
                    conn.recv(4096)  # drain the request; any GET is /metrics
                except OSError:
                    pass
                body = prometheus_text(self._tracer).encode()
                head = (
                    "HTTP/1.0 200 OK\r\n"
                    "Content-Type: text/plain; version=0.0.4\r\n"
                    f"Content-Length: {len(body)}\r\n\r\n"
                ).encode()
                conn.sendall(head + body)
            except OSError:
                pass
            finally:
                try:
                    conn.close()
                except OSError:
                    pass

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        self._thread.join(timeout=2.0)


def start(port: int, tracer) -> PromServer:
    return PromServer(port, tracer)


def get_or_start(port: int, tracer) -> PromServer:
    """The process's single metrics endpoint: reuse the tracer's already-
    bound server when there is one (its snapshot covers every subsystem's
    gauges — one registry), else bind now and attach it to the tracer so
    later callers converge on the same instance."""
    if tracer.prom is not None:
        return tracer.prom
    tracer.prom = start(port, tracer)
    return tracer.prom
