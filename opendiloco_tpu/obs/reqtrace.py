"""Per-request distributed tracing ring for the serving path.

Every request entering the serving plane gets a trace context minted at
the edge (router or server); the context rides the existing JSON payload
as the optional ``schema.TRACE_CTX_KEY`` field, and each hop appends
causally-ordered stage spans — admission, candidate choice, re-dispatch,
queue wait, prefill, per-decode-step batches, hot-swap pauses, terminal
retire/shed — into this process-local bounded ring.

Blackbox-style and zero-cost when ``ODTP_OBS`` is unset: the :func:`ring`
accessor is the same cached env-lookup idiom as ``trace.tracer()`` and
every hook site is one ``is None`` branch. Sampling is deterministic
(``ODTP_REQTRACE_SAMPLE``) and decided once at mint time: a request the
edge skipped carries no context, so downstream hops do no work either.

Cross-process assembly happens offline: each process records only the
spans it witnessed, keyed by the shared trace id, and
``scripts/obs_report.py --reqtrace`` (or ``odtp_top --requests``) merges
the per-process views. Span timestamps are milliseconds relative to the
local trace origin; ``wall0`` pins that origin to the wall clock for
cross-process ordering, the same arithmetic as ``export.clock_shifts``.

Environment knobs (all registered in analysis/knobs.py):

- ``ODTP_REQTRACE_CAP``     completed-trace ring bound (default 256)
- ``ODTP_REQTRACE_SAMPLE``  fraction of edge requests traced (default 1.0)
- ``ODTP_REQTRACE_EXPORT``  explicit dump path; defaults to
                            ``ODTP_OBS_DIR/reqtrace-<worker>-<pid>.json``
"""
from __future__ import annotations

import atexit
import json
import os
import threading
import time
from collections import deque
from typing import Any, Optional

from opendiloco_tpu.diloco.schema import (
    REQTRACE_STAGES,
    TRACE_CTX_KEY,
)

_ENV = "ODTP_OBS"
_DIR_ENV = "ODTP_OBS_DIR"
_CAP_ENV = "ODTP_REQTRACE_CAP"
_SAMPLE_ENV = "ODTP_REQTRACE_SAMPLE"
_EXPORT_ENV = "ODTP_REQTRACE_EXPORT"

# per-trace span-list bound: a long generation's decode steps coalesce
# past this (stage seconds keep accruing exactly; only the span list
# stops growing), so one 10k-token request cannot own the ring's memory
MAX_SPANS_PER_TRACE = 128

_DUMP_MIN_INTERVAL_S = 5.0


# -- trace-context payload helpers -------------------------------------------


def ctx_of(payload: Any) -> Optional[dict]:
    """The request's trace context, or None (absent/malformed — old peers
    and untraced requests look identical)."""
    if not isinstance(payload, dict):
        return None
    ctx = payload.get(TRACE_CTX_KEY)
    if isinstance(ctx, dict) and isinstance(ctx.get("id"), str):
        return ctx
    return None


def attach(payload: dict, ctx: Optional[dict]) -> dict:
    """Payload with the trace context attached (copy); identity when
    ``ctx`` is None so untraced requests stay byte-identical on the wire."""
    if ctx is None:
        return payload
    return {**payload, TRACE_CTX_KEY: {"id": ctx["id"], "o": ctx.get("o", "")}}


def _pctl(xs: list, q: float) -> float:
    if not xs:
        return 0.0
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(round(q * (len(xs) - 1))))]


class RequestTraceRing:
    """Bounded per-process request-trace recorder. Thread-safe."""

    def __init__(self, spec: str):
        self.spec = spec
        self.pid = os.getpid()
        self.cap = int(os.environ.get(_CAP_ENV, "256"))
        self.sample = float(os.environ.get(_SAMPLE_ENV, "1.0"))
        self.worker: Any = "x"
        self.origin = time.perf_counter()
        self.origin_wall = time.time()
        self._lock = threading.Lock()
        self._salt = os.urandom(3).hex()
        self._seen = 0  # edge arrivals, sampled or not
        self._seq = 0
        self.inflight: dict[str, dict] = {}
        self.completed: deque = deque()
        self._done_index: dict[str, dict] = {}
        self.minted = 0
        self.adopted = 0
        self.finished = 0
        self.evicted = 0
        self._last_dump = 0.0
        if os.environ.get(_EXPORT_ENV) or os.environ.get(_DIR_ENV):
            atexit.register(self._atexit_dump)

    def set_identity(self, worker: Any) -> None:
        self.worker = worker

    # -- trace lifecycle ----------------------------------------------------
    def mint(self, **attrs: Any) -> Optional[dict]:
        """Mint a trace context for one edge arrival, or None when the
        deterministic sampler skips it. The returned dict is the wire
        context (``{"id", "o"}``) to attach to the request payload."""
        with self._lock:
            self._seen += 1
            if int(self._seen * self.sample) == int((self._seen - 1) * self.sample):
                return None
            self._seq += 1
            tid = f"{self.worker}-{self.pid:x}-{self._salt}-{self._seq:x}"
            self._begin_locked(tid, str(self.worker), attrs)
            self.minted += 1
        return {"id": tid, "o": str(self.worker)}

    def adopt(self, ctx: Optional[dict], **attrs: Any) -> Optional[str]:
        """Begin the local record for a context minted upstream (the
        sampling decision already happened at the edge). Idempotent."""
        if ctx is None or not isinstance(ctx.get("id"), str):
            return None
        tid = ctx["id"]
        with self._lock:
            if tid not in self.inflight and tid not in self._done_index:
                self._begin_locked(tid, str(ctx.get("o", "")), attrs)
                self.adopted += 1
        return tid

    def _begin_locked(self, tid: str, origin: str, attrs: dict) -> None:
        self.inflight[tid] = {
            "id": tid,
            "origin": origin,
            "worker": str(self.worker),
            "pid": self.pid,
            "t0": time.perf_counter(),
            "wall0": time.time(),
            "spans": [],
            "spans_dropped": 0,
            "stages_s": {},
            "attrs": dict(attrs),
            "status": None,
            "e2e_ms": None,
        }

    def _find(self, tid: Optional[str]) -> Optional[dict]:
        if tid is None:
            return None
        tr = self.inflight.get(tid)
        if tr is None:
            tr = self._done_index.get(tid)
        return tr

    def span(
        self, tid: Optional[str], stage: str, t0: float, t1: float, **attrs: Any
    ) -> None:
        """Append one completed stage interval (perf_counter stamps).

        Late spans landing after finish() still accrue (a re-dispatched
        request's first forward may complete its error path after the
        retry already answered) — causal order is by timestamp, not by
        arrival."""
        with self._lock:
            tr = self._find(tid)
            if tr is None:
                return
            dur = max(0.0, t1 - t0)
            tr["stages_s"][stage] = tr["stages_s"].get(stage, 0.0) + dur
            if len(tr["spans"]) >= MAX_SPANS_PER_TRACE:
                tr["spans_dropped"] += 1
                return
            tr["spans"].append({
                "stage": stage,
                "ts": (t0 - tr["t0"]) * 1e3,
                "ms": dur * 1e3,
                "attrs": attrs,
            })

    def event(self, tid: Optional[str], stage: str, **attrs: Any) -> None:
        """Zero-width span (e.g. a re-dispatch marker)."""
        now = time.perf_counter()
        self.span(tid, stage, now, now, **attrs)

    def annotate(self, tid: Optional[str], **attrs: Any) -> None:
        with self._lock:
            tr = self._find(tid)
            if tr is not None:
                tr["attrs"].update(attrs)

    def finish(
        self, tid: Optional[str], status: str = "done", **attrs: Any
    ) -> None:
        """Move the trace to the completed ring with a terminal status
        (done / shed / failed / cancelled). Idempotent."""
        with self._lock:
            tr = self.inflight.pop(tid, None) if tid else None
            if tr is None:
                return
            tr["status"] = status
            tr["e2e_ms"] = (time.perf_counter() - tr["t0"]) * 1e3
            tr["attrs"].update(attrs)
            self.completed.append(tr)
            self._done_index[tid] = tr
            self.finished += 1
            while len(self.completed) > self.cap:
                old = self.completed.popleft()
                self._done_index.pop(old["id"], None)
                self.evicted += 1

    # -- queries ------------------------------------------------------------
    def get(self, tid: str) -> Optional[dict]:
        with self._lock:
            tr = self._find(tid)
            return json.loads(json.dumps(tr, default=str)) if tr else None

    def has(self, tid: str) -> bool:
        with self._lock:
            return self._find(tid) is not None

    def inflight_ids(self) -> list:
        with self._lock:
            return list(self.inflight)

    def exemplars(self, n: int = 3) -> list:
        """The slowest recently-completed traces, worst first — the
        evidence an SLO-breach decision links to."""
        with self._lock:
            done = sorted(
                self.completed, key=lambda t: t["e2e_ms"] or 0.0, reverse=True
            )[: max(0, n)]
            return [
                {"id": t["id"], "e2e_ms": round(t["e2e_ms"], 3),
                 "status": t["status"]}
                for t in done
            ]

    # -- aggregation --------------------------------------------------------
    def report(self) -> dict:
        """Fleet-mergeable per-stage decomposition: per-request stage
        totals' p50/p99 + counts, plus end-to-end latency percentiles."""
        with self._lock:
            done = list(self.completed)
            n_inflight = len(self.inflight)
        stages: dict[str, dict] = {}
        for stage in REQTRACE_STAGES:
            samples = [
                t["stages_s"][stage] * 1e3
                for t in done
                if stage in t["stages_s"]
            ]
            if not samples:
                continue
            stages[stage] = {
                "count": len(samples),
                "p50_ms": round(_pctl(samples, 0.50), 3),
                "p99_ms": round(_pctl(samples, 0.99), 3),
                "total_s": round(sum(samples) / 1e3, 6),
            }
        e2e = [t["e2e_ms"] for t in done if t["e2e_ms"] is not None]
        statuses: dict[str, int] = {}
        for t in done:
            statuses[t["status"]] = statuses.get(t["status"], 0) + 1
        dominant = max(
            stages, key=lambda s: stages[s]["p99_ms"], default=None
        )
        return {
            "worker": str(self.worker),
            "pid": self.pid,
            "completed": len(done),
            "inflight": n_inflight,
            "minted": self.minted,
            "adopted": self.adopted,
            "evicted": self.evicted,
            "statuses": statuses,
            "e2e_ms": {
                "count": len(e2e),
                "p50": round(_pctl(e2e, 0.50), 3),
                "p99": round(_pctl(e2e, 0.99), 3),
            },
            "stages": stages,
            "dominant_stage_p99": dominant,
        }

    def snapshot(self, recent: int = 32) -> dict:
        """Control-frame body: the report plus compact inflight + recent
        trace rows for the odtp_top --requests live view."""
        now = time.perf_counter()
        with self._lock:
            infl = [
                {
                    "id": t["id"],
                    "age_ms": round((now - t["t0"]) * 1e3, 3),
                    "last_stage": (
                        t["spans"][-1]["stage"] if t["spans"] else None
                    ),
                    "stages_ms": {
                        k: round(v * 1e3, 3) for k, v in t["stages_s"].items()
                    },
                }
                for t in self.inflight.values()
            ]
            done = [
                {
                    "id": t["id"],
                    "status": t["status"],
                    "e2e_ms": round(t["e2e_ms"], 3),
                    "stages_ms": {
                        k: round(v * 1e3, 3) for k, v in t["stages_s"].items()
                    },
                    "attrs": t["attrs"],
                }
                for t in list(self.completed)[-max(0, recent):]
            ]
        return {"report": self.report(), "inflight": infl, "recent": done}

    def traces(self) -> list:
        """Full completed traces (spans included) — dump/merge payload."""
        with self._lock:
            return json.loads(json.dumps(list(self.completed), default=str))

    # -- sinks --------------------------------------------------------------
    def dump_path(self) -> Optional[str]:
        explicit = os.environ.get(_EXPORT_ENV) or None
        if explicit:
            return explicit
        out_dir = os.environ.get(_DIR_ENV)
        if not out_dir:
            return None
        return os.path.join(
            out_dir, f"reqtrace-{self.worker}-{self.pid}.json"
        )

    def dump(self, path: Optional[str] = None, reason: str = "") -> Optional[str]:
        path = path or self.dump_path()
        if path is None:
            return None
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        body = {
            "reason": reason,
            "spec": self.spec,
            "worker": str(self.worker),
            "pid": self.pid,
            "origin_wall": self.origin_wall,
            "report": self.report(),
            "traces": self.traces(),
            "inflight": self.snapshot(recent=0)["inflight"],
        }
        tmp = f"{path}.tmp.{self.pid}"
        with open(tmp, "w") as f:
            json.dump(body, f)
            f.write("\n")
        os.replace(tmp, path)
        self._last_dump = time.monotonic()
        return path

    def autodump(self, reason: str = "") -> Optional[str]:
        """Rate-limited dump (blackbox idiom) for periodic hook sites."""
        if time.monotonic() - self._last_dump < _DUMP_MIN_INTERVAL_S:
            return None
        return self.dump(reason=reason)

    def _atexit_dump(self) -> None:
        try:
            self.dump(reason="atexit")
        except Exception:
            pass

    def close(self) -> None:
        try:
            atexit.unregister(self._atexit_dump)
        except Exception:
            pass


# -- process-wide accessor (same idiom as trace.tracer()) --------------------
_ring: Optional[RequestTraceRing] = None
_spec: Optional[str] = None
_lock = threading.Lock()


def ring() -> Optional[RequestTraceRing]:
    """The process request-trace ring, or None when ODTP_OBS is unset
    (zero-cost: one env lookup + cached string compare)."""
    global _ring, _spec
    spec = os.environ.get(_ENV) or None
    if spec == _spec:
        return _ring
    with _lock:
        if spec != _spec:
            old, _ring = _ring, (RequestTraceRing(spec) if spec else None)
            _spec = spec
            if old is not None:
                old.close()
    return _ring


def reset() -> None:
    """Drop the cached ring (tests / env changes)."""
    global _ring, _spec
    with _lock:
        if _ring is not None:
            _ring.close()
        _ring = None
        _spec = None
