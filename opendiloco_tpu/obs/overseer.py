"""Overseer: a converging galaxy health matrix riding existing gossip.

Each worker folds its telemetry into a compact roll-up dict (round id,
stage times, WAN/intra wire bytes, pseudo-grad norm, loss, tokens/s,
serve staleness, link capacity) and piggybacks it on the channels that
already gossip — the rendezvous ``progress`` dict (daemons store and
replay progress verbatim, see rendezvous.PeerInfo) and the post-round
link-vector announce. Every ``register``/``progress`` reply and every
``join_group`` group snapshot therefore hands each worker the latest
roll-up of every peer, so the whole galaxy converges on one health
matrix with **no new connections and no global barrier** — exactly how
link vectors travel (diloco/linkstate.py), and version-gated the same
way via :data:`HEALTH_VEC_VERSION`.

The matrix survives elastic membership and hier aggregator re-election
for free: it is keyed by peer id and refreshed by whatever announces
still happen; a dead worker's row simply stops updating (its ``ts``
ages), which is itself signal (see obs/anomaly.py dead-peer detection).

Zero-cost when ``ODTP_OBS`` is unset: :func:`plane` is the same
env-dict-hit + cached-compare accessor as ``chaos.plane()``; every hook
site in the transport is one ``is None`` branch.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Optional

_ENV = "ODTP_OBS"

HEALTH_VEC_VERSION = 1

# gauges folded into the roll-up, tracer-name -> roll-up field
_GAUGE_FIELDS = (
    ("inner_loss", "loss"),
    ("inner_tokens_per_second", "tokens_per_s"),
    ("inner_steps_per_second", "steps_per_s"),
    ("pseudo_grad_norm", "pg_norm"),
    ("outer_epoch", "epoch"),
    ("serve_snapshot_staleness", "staleness"),
)
# cumulative counters folded in, tracer-name -> roll-up field
_COUNTER_FIELDS = (
    ("wire_tx_bytes", "wire_tx"),
    ("wire_rx_bytes", "wire_rx"),
    ("wire_tx_bytes_wan", "wire_tx_wan"),
    ("wire_rx_bytes_wan", "wire_rx_wan"),
)
# round-health ledger keys carried verbatim (stage StageTimes rows ride
# as their ``*_s`` ledger names)
_HEALTH_FIELDS = (
    "round", "group_size", "expected", "elastic", "retries",
    # gossip pair rounds (diloco/gossip.py): who this worker mixed with
    # last round, and whether the round was a pair round at all; pair_lag
    # is the epoch distance of an async bounded-staleness match
    "gossip", "partner", "pair_lag",
)
_STAGE_SUFFIX = "_s"


class Overseer:
    """Per-process roll-up builder + merged view of every peer's roll-up."""

    def __init__(self, spec: str):
        self.spec = spec
        self._lock = threading.Lock()
        self._matrix: dict[str, dict] = {}
        self._last_health: Optional[dict] = None
        self._rounds = 0

    # -- producing ------------------------------------------------------------
    def rollup(self, **extra: Any) -> dict:
        """This worker's compact health vector (JSON-ready, ~300 bytes).

        Cheap enough to rebuild on every progress announce: a handful of
        dict reads from the tracer plus the cached last round-health row.
        """
        from opendiloco_tpu.obs import trace

        out: dict[str, Any] = {
            "v": HEALTH_VEC_VERSION,
            "ts": round(time.time(), 3),
        }
        tr = trace.tracer()
        if tr is not None:
            if "worker" in tr.identity:
                out["worker"] = tr.identity["worker"]
            gauges = tr.gauges()
            for name, field in _GAUGE_FIELDS:
                v = gauges.get((name, ()))
                if v is not None:
                    out[field] = round(float(v), 6)
            counters = tr.counters()
            for name, field in _COUNTER_FIELDS:
                v = counters.get((name, ()))
                if v:
                    out[field] = int(v)
        with self._lock:
            health = self._last_health
            out["rounds"] = self._rounds
        if health:
            for k in _HEALTH_FIELDS:
                if k in health:
                    out[k] = health[k]
            stages = {
                k: health[k] for k in health
                if k.endswith(_STAGE_SUFFIX) and isinstance(
                    health[k], (int, float))
            }
            if stages:
                out["stages"] = stages
        for k, v in extra.items():
            if v is not None:
                out[k] = v
        return out

    def note_round(self, health: dict, own_id: Optional[str] = None,
                   members: Optional[list] = None) -> None:
        """One completed outer round: refresh own matrix row, feed the
        flight recorder, and run the anomaly watchdogs. Called from the
        transport's round-health ledger append — never from a new channel.
        """
        with self._lock:
            self._last_health = health
            self._rounds += 1
        if own_id is not None:
            self.merge(own_id, self.rollup())
        try:
            from opendiloco_tpu.obs import blackbox

            bb = blackbox.recorder()
            if bb is not None:
                bb.note_health(health)
        except Exception:
            pass
        try:
            from opendiloco_tpu.obs import anomaly

            wd = anomaly.watchdog()
            if wd is not None:
                wd.on_round(health, self.matrix(), own_id=own_id,
                            members=members)
        except Exception:
            pass

    # -- merging --------------------------------------------------------------
    def merge(self, peer_id: str, vec: Any) -> None:
        """Adopt a peer's roll-up if it is well-formed, version-matched,
        and newer than what we hold (announce replies can replay stale
        progress after a daemon failover)."""
        if not peer_id or not isinstance(vec, dict):
            return
        if int(vec.get("v", 0) or 0) != HEALTH_VEC_VERSION:
            return
        ts = float(vec.get("ts", 0.0) or 0.0)
        with self._lock:
            cur = self._matrix.get(peer_id)
            if cur is not None and float(cur.get("ts", 0.0) or 0.0) > ts:
                return
            self._matrix[peer_id] = vec

    def matrix(self) -> dict[str, dict]:
        """peer_id -> latest roll-up, as this worker currently sees it."""
        with self._lock:
            return {pid: dict(v) for pid, v in self._matrix.items()}


# -- process-wide accessor (same idiom as chaos.plane()) ----------------------
_overseer: Optional[Overseer] = None
_spec: Optional[str] = None
_lock = threading.Lock()


def plane() -> Optional[Overseer]:
    """The process overseer, or None when ODTP_OBS is unset (zero-cost)."""
    global _overseer, _spec
    spec = os.environ.get(_ENV) or None
    if spec == _spec:
        return _overseer
    with _lock:
        if spec != _spec:
            _overseer = Overseer(spec) if spec else None
            _spec = spec
    return _overseer


def reset() -> None:
    """Drop the cached overseer (tests / env changes)."""
    global _overseer, _spec
    with _lock:
        _overseer = None
        _spec = None
