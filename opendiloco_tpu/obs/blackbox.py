"""Flight recorder: an always-on, bounded black box per worker.

While the tracer (obs/trace.py) records *everything* up to a cap and
flushes once at exit, the flight recorder keeps only the *recent past*
— a ring of the last spans/instants, the last round-health rows, the
last metric snapshots, injected chaos faults, and watchdog anomalies —
and persists it whenever something interesting happens, so a worker
that dies mid-round (SIGKILL included) leaves a readable black box
behind. Dump triggers:

- fatal signal (SIGTERM/SIGABRT via chained handlers; hard crashes via
  ``faulthandler`` into a sidecar ``.crash`` file) and ``atexit``;
- chaos-plane fault injection (rate-limited by the flush interval);
- a watchdog trip (obs/anomaly.py) — always immediate;
- every round-health row, rate-limited by ``ODTP_OBS_BLACKBOX_FLUSH_S``
  — this continuous autodump is what survives a SIGKILL.

Dumps go atomically (tmp + ``os.replace``) to
``ODTP_OBS_DIR/blackbox-<worker>-<pid>.json`` (pid-suffixed so a worker
restarted under the same rank cannot overwrite its dead predecessor's
evidence); ``scripts/odtp_postmortem.py`` merges them across workers
into one causally-ordered round timeline.

The plane is armed by ``ODTP_OBS`` and zero-cost when unset: the
:func:`recorder` accessor is the same env-dict-hit + cached-compare
idiom as ``chaos.plane()`` / ``obs.tracer()``.

Environment knobs (read lazily at arm time):

- ``ODTP_OBS_BLACKBOX_CAP``      event-ring length (default 512)
- ``ODTP_OBS_BLACKBOX_FLUSH_S``  min seconds between rate-limited
                                 autodumps (default 5.0; 0 = dump on
                                 every trigger)
"""
from __future__ import annotations

import atexit
import faulthandler
import json
import os
import signal
import threading
import time
from collections import deque
from typing import Any, Optional

_ENV = "ODTP_OBS"
_DIR_ENV = "ODTP_OBS_DIR"
_CAP_ENV = "ODTP_OBS_BLACKBOX_CAP"
_FLUSH_ENV = "ODTP_OBS_BLACKBOX_FLUSH_S"
_DEFAULT_CAP = 512
_DEFAULT_FLUSH_S = 5.0

BLACKBOX_VERSION = 1

# signals that normally terminate a worker and can still run Python code
# (SIGKILL can't be caught -- the periodic autodump covers it)
_FATAL_SIGNALS = ("SIGTERM", "SIGABRT", "SIGHUP")


class FlightRecorder:
    """Bounded rings of recent telemetry + atomic dump-on-trouble."""

    def __init__(self, spec: str):
        self.spec = spec
        self.pid = os.getpid()
        self.cap = int(os.environ.get(_CAP_ENV, _DEFAULT_CAP))
        self.flush_s = float(os.environ.get(_FLUSH_ENV, _DEFAULT_FLUSH_S))
        self._lock = threading.Lock()
        self.events: deque = deque(maxlen=self.cap)
        self.health: deque = deque(maxlen=64)
        self.snapshots: deque = deque(maxlen=16)
        self.faults: deque = deque(maxlen=128)
        self.anomalies: deque = deque(maxlen=64)
        self.decisions: deque = deque(maxlen=128)
        self.dumps = 0
        self._last_dump = 0.0
        self._last_reason: Optional[str] = None
        self._installed = False
        self._prev_handlers: dict[int, Any] = {}
        self._crash_file = None

    # -- feeds (all O(1), ring-bounded) ---------------------------------------
    def note_event(self, ev: dict) -> None:
        """Mirror one tracer event (span/instant/counter-track) into the
        ring. Called from Tracer._record, so only when the plane is armed."""
        with self._lock:
            self.events.append(ev)

    def note_health(self, row: dict) -> None:
        """One round-health ledger row; also snapshots metrics and ticks
        the rate-limited autodump (the SIGKILL-survival path)."""
        with self._lock:
            self.health.append(row)
            self.snapshots.append({
                "wall": round(time.time(), 3),
                "round": row.get("round"),
                "metrics": self._flat_metrics(),
            })
        self.autodump("round")

    def note_fault(self, kind: str, site: str, detail: dict) -> None:
        """One chaos-plane injected fault (called from ChaosPlane._record)."""
        with self._lock:
            self.faults.append({
                "wall": round(time.time(), 3), "kind": kind, "site": site,
                **detail,
            })
        self.autodump(f"chaos:{kind}")

    def note_decision(self, rec: dict) -> None:
        """One control-plane decision (autoscaler scale/replace/shed
        policy change): ring-recorded and rate-limit-dumped, so a
        postmortem can line fleet actions up against the health rows
        that drove them."""
        with self._lock:
            self.decisions.append({"wall": round(time.time(), 3), **rec})
        self.autodump(f"decision:{rec.get('action', '?')}")

    def note_anomaly(self, rec: dict) -> None:
        """A watchdog trip: recorded and dumped immediately (no rate limit
        -- trips are already cooldown-limited by the watchdog itself)."""
        with self._lock:
            self.anomalies.append(rec)
        self.dump(reason=f"anomaly:{rec.get('kind', '?')}")

    # -- dumping --------------------------------------------------------------
    def autodump(self, reason: str) -> Optional[str]:
        """Dump unless one already happened within the flush interval."""
        now = time.monotonic()
        with self._lock:
            if self._last_dump and now - self._last_dump < self.flush_s:
                return None
        return self.dump(reason=reason)

    def path(self) -> Optional[str]:
        out_dir = os.environ.get(_DIR_ENV)
        if not out_dir:
            return None
        # pid-suffixed like trace-w<rank>-<pid>.jsonl: a worker restarted
        # under the same rank must not overwrite its dead predecessor's
        # black box -- that file IS the crash evidence
        return os.path.join(
            out_dir, f"blackbox-{self._worker()}-{self.pid}.json"
        )

    def _worker(self) -> Any:
        from opendiloco_tpu.obs import trace

        tr = trace.tracer()
        if tr is not None and "worker" in tr.identity:
            return tr.identity["worker"]
        return self.pid

    def _flat_metrics(self) -> dict:
        from opendiloco_tpu.obs import trace

        tr = trace.tracer()
        if tr is None:
            return {}
        snap = tr.snapshot()
        return {
            "counters": trace._flat_metrics(snap["counters"]),
            "gauges": trace._flat_metrics(snap["gauges"]),
        }

    def dump(self, reason: str = "manual", path: Optional[str] = None
             ) -> Optional[str]:
        """Atomically persist the black box. Returns the path, or None
        when no ``ODTP_OBS_DIR`` is set (the rings still accumulate)."""
        from opendiloco_tpu.obs import trace

        path = path or self.path()
        if path is None:
            return None
        tr = trace.tracer()
        galaxy: dict = {}
        try:
            from opendiloco_tpu.obs import overseer

            ov = overseer.plane()
            if ov is not None:
                galaxy = ov.matrix()
        except Exception:
            pass
        reqtrace_report: dict = {}
        try:
            from opendiloco_tpu.obs import reqtrace as _reqtrace

            rt = _reqtrace.ring()
            if rt is not None:
                reqtrace_report = rt.report()
        except Exception:
            pass
        with self._lock:
            self.dumps += 1
            self._last_dump = time.monotonic()
            self._last_reason = reason
            box = {
                "version": BLACKBOX_VERSION,
                "worker": self._worker(),
                "pid": self.pid,
                "reason": reason,
                "wall": round(time.time(), 3),
                "origin_wall": tr.origin_wall if tr is not None else 0.0,
                "identity": dict(tr.identity) if tr is not None else {},
                "spec": self.spec,
                "dumps": self.dumps,
                "events": list(self.events),
                "health": list(self.health),
                "snapshots": list(self.snapshots),
                "faults": list(self.faults),
                "anomalies": list(self.anomalies),
                "decisions": list(self.decisions),
                "metrics": self._flat_metrics(),
                "galaxy": galaxy,
                "reqtrace": reqtrace_report,
            }
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{self.pid}"
        with open(tmp, "w") as f:
            json.dump(trace._jsonable(box), f)
            f.write("\n")
        os.replace(tmp, path)
        return path

    # -- crash hooks ----------------------------------------------------------
    def install(self) -> None:
        """Idempotently install atexit / fatal-signal / faulthandler hooks.

        Called by long-lived entry points (train.py, serve scheduler) --
        NOT by the accessor, so short-lived tools and tests that arm the
        plane don't take over process signal handling as a side effect.
        """
        with self._lock:
            if self._installed:
                return
            self._installed = True
        atexit.register(self._atexit_dump)
        for name in _FATAL_SIGNALS:
            sig = getattr(signal, name, None)
            if sig is None:
                continue
            try:  # main thread only; embedded uses keep working without
                self._prev_handlers[sig] = signal.signal(sig, self._on_signal)
            except (ValueError, OSError):
                pass
        path = self.path()
        if path is not None:
            # hard crashes (SIGSEGV/SIGFPE/...) can't run Python: route the
            # C-level traceback to a sidecar next to the JSON black box
            try:
                os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
                self._crash_file = open(path + ".crash", "w")
                faulthandler.enable(self._crash_file)
            except Exception:
                self._crash_file = None

    def _atexit_dump(self) -> None:
        try:
            self.dump(reason="atexit")
        except Exception:
            pass

    def _on_signal(self, signum, frame) -> None:
        try:
            self.dump(reason=f"signal:{signum}")
        except Exception:
            pass
        prev = self._prev_handlers.get(signum)
        if callable(prev):
            prev(signum, frame)
        elif prev != signal.SIG_IGN:
            # restore the default disposition and re-deliver so the exit
            # status still reflects the signal
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)

    def close(self) -> None:
        if not self._installed:
            return
        try:
            atexit.unregister(self._atexit_dump)
        except Exception:
            pass
        for sig, prev in self._prev_handlers.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError):
                pass
        self._prev_handlers.clear()
        if self._crash_file is not None:
            try:
                faulthandler.disable()
                self._crash_file.close()
            except Exception:
                pass
            self._crash_file = None
        self._installed = False


# -- process-wide accessor (same idiom as chaos.plane()) ----------------------
_rec: Optional[FlightRecorder] = None
_spec: Optional[str] = None
_lock = threading.Lock()


def recorder() -> Optional[FlightRecorder]:
    """The process flight recorder, or None when ODTP_OBS is unset."""
    global _rec, _spec
    spec = os.environ.get(_ENV) or None
    if spec == _spec:
        return _rec
    with _lock:
        if spec != _spec:
            old, _rec = _rec, (FlightRecorder(spec) if spec else None)
            _spec = spec
            if old is not None:
                old.close()
    return _rec


def install() -> Optional[FlightRecorder]:
    """Arm-and-install convenience for process entry points."""
    bb = recorder()
    if bb is not None:
        bb.install()
    return bb


def reset() -> None:
    """Drop the cached recorder (tests / env changes); restores signals."""
    global _rec, _spec
    with _lock:
        if _rec is not None:
            _rec.close()
        _rec = None
        _spec = None
