"""ctypes bindings for the native outer-loop kernels (native/odtp_kernels.cpp).

Loads ``native/libodtp.so`` when present (``make -C native``), building it on
first use if a compiler is available; otherwise every entry point falls back
to numpy so the framework never hard-requires the native build.

The fused entry points matter most: ``f16_accumulate`` and
``dequant8_accumulate`` turn the butterfly collect step (decode + add over
multi-GB buffers) into a single parallel pass.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_LIB_PATH = os.path.join(_NATIVE_DIR, "libodtp.so")

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _try_build() -> None:
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR, "-s"],
            check=True,
            capture_output=True,
            timeout=120,
        )
    except Exception:
        pass


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, or None (numpy fallback)."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not os.path.exists(_LIB_PATH) and os.environ.get(
        "OPENDILOCO_TPU_NO_NATIVE_BUILD"
    ) not in ("1", "true"):
        _try_build()
    if not os.path.exists(_LIB_PATH):
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    u16p = ctypes.POINTER(ctypes.c_uint16)
    f32p = ctypes.POINTER(ctypes.c_float)
    i8p = ctypes.POINTER(ctypes.c_int8)
    st = ctypes.c_size_t
    lib.odtp_add_f32.argtypes = [f32p, f32p, st]
    lib.odtp_scale_f32.argtypes = [f32p, ctypes.c_float, st]
    lib.odtp_sub_f32.argtypes = [f32p, f32p, f32p, st]
    lib.odtp_f32_to_f16.argtypes = [f32p, u16p, st]
    lib.odtp_f16_to_f32.argtypes = [u16p, f32p, st]
    lib.odtp_f16_accumulate_f32.argtypes = [u16p, f32p, st]
    lib.odtp_quantize_blockwise_i8.argtypes = [f32p, i8p, f32p, st, st]
    lib.odtp_dequantize_blockwise_i8.argtypes = [i8p, f32p, f32p, st, st]
    lib.odtp_dequantize_blockwise_i8_accumulate.argtypes = [i8p, f32p, f32p, st, st]
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.odtp_quantile_assign.argtypes = [f32p, f32p, u8p, st]
    lib.odtp_quantile_edges.argtypes = [f32p, st, f32p]
    lib.odtp_version.restype = ctypes.c_int
    try:  # version-2 kernels (a stale .so without them keeps the v1 surface)
        lib.odtp_quantize_uniform8.argtypes = [f32p, u8p, st, f32p, f32p]
        lib.odtp_dequantize_uniform8.argtypes = [
            u8p, ctypes.c_float, ctypes.c_float, f32p, st,
        ]
        lib.odtp_dequantize_uniform8_accumulate.argtypes = [
            u8p, ctypes.c_float, ctypes.c_float, f32p, st,
        ]
        lib.odtp_lut256_gather.argtypes = [u8p, f32p, f32p, st]
        lib.odtp_lut256_accumulate.argtypes = [u8p, f32p, f32p, st]
    except AttributeError:
        pass
    try:  # version-3 kernels (fused scaled-fp16 paths)
        lib.odtp_absmax_f32.argtypes = [f32p, st]
        lib.odtp_absmax_f32.restype = ctypes.c_float
        lib.odtp_f32_to_f16_scaled.argtypes = [f32p, ctypes.c_float, u16p, st]
        lib.odtp_f16_to_f32_scaled.argtypes = [u16p, ctypes.c_float, f32p, st]
        lib.odtp_f16_accumulate_scaled_f32.argtypes = [
            u16p, ctypes.c_float, f32p, st,
        ]
    except AttributeError:
        pass
    try:  # version-4 kernels (chunk-granular encode prescans)
        lib.odtp_minmax_f32.argtypes = [f32p, st, f32p, f32p]
        lib.odtp_quantize_uniform8_given.argtypes = [
            f32p, u8p, st, ctypes.c_float, ctypes.c_float,
        ]
    except AttributeError:
        pass
    try:  # version-5 kernels (fused outer SGD + sqnorm)
        lib.odtp_outer_sgd_f32.argtypes = [
            f32p, f32p, f32p, ctypes.c_float, ctypes.c_float, ctypes.c_int, st,
        ]
        lib.odtp_sqnorm_f32.argtypes = [f32p, st]
        lib.odtp_sqnorm_f32.restype = ctypes.c_double
    except AttributeError:
        pass
    try:  # version-6 kernels (4-bit blockwise codec)
        lib.odtp_quantize_blockwise4.argtypes = [f32p, u8p, u16p, st, st]
        lib.odtp_dequantize_blockwise4.argtypes = [u8p, u16p, f32p, st, st]
        lib.odtp_dequantize_blockwise4_accumulate.argtypes = [
            u8p, u16p, f32p, st, st,
        ]
    except AttributeError:
        pass
    for fn in (lib.odtp_sendall, lib.odtp_recvall):
        fn.argtypes = [ctypes.c_int, ctypes.c_void_p, st]
        fn.restype = ctypes.c_int
    _lib = lib
    return _lib


def available() -> bool:
    return get_lib() is not None


def _f32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _u16p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16))


def _i8p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int8))


def _check_out(out: np.ndarray, n: int) -> None:
    """Decode destinations must be 1-D contiguous float32 of exactly n
    elements: the C kernels write n floats through a raw pointer (an
    undersized buffer would be heap corruption, not an exception), and the
    numpy fallbacks' reshape(-1) would silently copy (and discard the
    result) for non-contiguous ND views."""
    if out.dtype != np.float32 or out.ndim != 1 or not out.flags.c_contiguous:
        raise ValueError(
            "out must be a contiguous 1-D float32 array, got "
            f"dtype={out.dtype} ndim={out.ndim} contiguous={out.flags.c_contiguous}"
        )
    if out.size != n:
        raise ValueError(f"out holds {out.size} elements, need exactly {n}")


def _check_len(have: int, need: int, what: str) -> None:
    """The C kernels read exactly `need` elements; a short payload (peer
    bug, truncated transfer) must fail loudly, not read out of bounds."""
    if have < need:
        raise ValueError(f"{what}: payload holds {have} elements, need {need}")


# -- public ops (native with numpy fallback) --------------------------------


def add_inplace(dst: np.ndarray, src: np.ndarray) -> None:
    """dst += src over float32 buffers."""
    lib = get_lib()
    if lib is None or dst.dtype != np.float32 or not dst.flags.c_contiguous:
        np.add(dst, src, out=dst)
        return
    src = np.ascontiguousarray(src, np.float32)
    lib.odtp_add_f32(_f32p(dst), _f32p(src), dst.size)


def scale_inplace(dst: np.ndarray, s: float) -> None:
    lib = get_lib()
    if lib is None or dst.dtype != np.float32 or not dst.flags.c_contiguous:
        np.multiply(dst, s, out=dst)
        return
    lib.odtp_scale_f32(_f32p(dst), ctypes.c_float(s), dst.size)


def sub(
    a: np.ndarray, b: np.ndarray, out: Optional[np.ndarray] = None
) -> np.ndarray:
    """a - b -> float32 array (pseudo-gradient). ``out`` reuses a buffer:
    fresh multi-GB allocations every outer round hit kernel page-fault /
    compaction stalls (measured 0.1 GB/s worst case vs ~1 GB/s into an
    existing buffer), so the optimizer passes persistent buffers here."""
    lib = get_lib()
    a = np.ascontiguousarray(a, np.float32)
    b = np.ascontiguousarray(b, np.float32)
    if out is None or out.shape != a.shape or out.dtype != np.float32:
        out = np.empty_like(a)
    if lib is None:
        np.subtract(a, b, out=out)
        return out
    lib.odtp_sub_f32(_f32p(a), _f32p(b), _f32p(out), a.size)
    return out


def f32_to_f16_bytes(a: np.ndarray) -> bytes:
    lib = get_lib()
    a = np.ascontiguousarray(a, np.float32)
    if lib is None:
        return a.astype(np.float16).tobytes()
    out = np.empty(a.size, np.uint16)
    lib.odtp_f32_to_f16(_f32p(a.reshape(-1)), _u16p(out), a.size)
    return out.tobytes()


def f16_bytes_to_f32(
    payload: bytes, n: int, out: Optional[np.ndarray] = None
) -> np.ndarray:
    lib = get_lib()
    src = np.frombuffer(payload, np.uint16)
    _check_len(src.size, n, "f16_bytes_to_f32")
    if out is None:
        out = np.empty(n, np.float32)
    else:
        _check_out(out, n)
    if lib is None:
        out[:] = np.frombuffer(payload, np.float16)[:n]
        return out
    lib.odtp_f16_to_f32(_u16p(src), _f32p(out), n)
    return out


def f16_accumulate(payload: bytes, dst: np.ndarray) -> None:
    """dst += decode_f16(payload) in one fused pass."""
    lib = get_lib()
    _check_len(len(payload) // 2, dst.size, "f16_accumulate")
    if lib is None or dst.dtype != np.float32 or not dst.flags.c_contiguous:
        dst += np.frombuffer(payload, np.float16).astype(np.float32).reshape(dst.shape)
        return
    src = np.frombuffer(payload, np.uint16)
    lib.odtp_f16_accumulate_f32(_u16p(src), _f32p(dst), dst.size)


def absmax(a: np.ndarray) -> float:
    """max(|a|) in one pass with no temporary abs array (NaNs skipped)."""
    lib = get_lib()
    a = np.ascontiguousarray(a, np.float32).reshape(-1)
    if not _has(lib, "odtp_absmax_f32"):
        return float(np.max(np.abs(a))) if a.size else 0.0
    return float(lib.odtp_absmax_f32(_f32p(a), a.size))


def f32_to_f16_scaled_bytes(a: np.ndarray, scale: float) -> bytes:
    """f16(a / scale) fused into one pass (scaled-fp16 encode); bit-equal
    to the fallback's explicit division."""
    lib = get_lib()
    a = np.ascontiguousarray(a, np.float32).reshape(-1)
    if not _has(lib, "odtp_f32_to_f16_scaled"):
        return (a / np.float32(scale)).astype(np.float16).tobytes()
    out = np.empty(a.size, np.uint16)
    lib.odtp_f32_to_f16_scaled(
        _f32p(a), ctypes.c_float(scale), _u16p(out), a.size
    )
    return out.tobytes()


def f16_bytes_to_f32_scaled(
    payload: bytes, scale: float, n: int, out: Optional[np.ndarray] = None
) -> np.ndarray:
    """decode_f16(payload) * scale in one fused pass."""
    lib = get_lib()
    src = np.frombuffer(payload, np.uint16)
    _check_len(src.size, n, "f16_bytes_to_f32_scaled")
    if out is None:
        out = np.empty(n, np.float32)
    else:
        _check_out(out, n)
    if not _has(lib, "odtp_f16_to_f32_scaled"):
        np.multiply(
            np.frombuffer(payload, np.float16)[:n].astype(np.float32),
            np.float32(scale),
            out=out,
        )
        return out
    lib.odtp_f16_to_f32_scaled(_u16p(src), ctypes.c_float(scale), _f32p(out), n)
    return out


def f16_accumulate_scaled(payload: bytes, scale: float, dst: np.ndarray) -> None:
    """dst += decode_f16(payload) * scale in one fused pass."""
    lib = get_lib()
    _check_len(len(payload) // 2, dst.size, "f16_accumulate_scaled")
    if (
        not _has(lib, "odtp_f16_accumulate_scaled_f32")
        or dst.dtype != np.float32
        or not dst.flags.c_contiguous
    ):
        dst += (
            np.frombuffer(payload, np.float16)[: dst.size]
            .astype(np.float32)
            .reshape(dst.shape)
            * np.float32(scale)
        )
        return
    src = np.frombuffer(payload, np.uint16)
    lib.odtp_f16_accumulate_scaled_f32(
        _u16p(src), ctypes.c_float(scale), _f32p(dst), dst.size
    )


def quantize_blockwise(a: np.ndarray, block: int) -> tuple[bytes, bytes]:
    """-> (int8 payload, float32 scales payload)."""
    lib = get_lib()
    a = np.ascontiguousarray(a, np.float32).reshape(-1)
    nblocks = (a.size + block - 1) // block
    if lib is None:
        pad = (-a.size) % block
        padded = np.pad(a, (0, pad)).reshape(-1, block)
        scales = np.max(np.abs(padded), axis=1)
        scales[scales == 0] = 1.0
        q = np.clip(
            np.round(padded / scales[:, None] * 127.0), -127, 127
        ).astype(np.int8)
        return q.reshape(-1)[: a.size].tobytes(), scales.astype(np.float32).tobytes()
    q = np.empty(a.size, np.int8)
    scales = np.empty(nblocks, np.float32)
    lib.odtp_quantize_blockwise_i8(_f32p(a), _i8p(q), _f32p(scales), a.size, block)
    return q.tobytes(), scales.tobytes()


def dequantize_blockwise(
    payload: bytes, scales_payload: bytes, n: int, block: int,
    out: Optional[np.ndarray] = None,
) -> np.ndarray:
    lib = get_lib()
    q = np.frombuffer(payload, np.int8)
    scales = np.frombuffer(scales_payload, np.float32)
    _check_len(q.size, n, "dequantize_blockwise")
    _check_len(scales.size, (n + block - 1) // block, "dequantize_blockwise scales")
    if out is None:
        out = np.empty(n, np.float32)
    else:
        _check_out(out, n)
    if lib is None:
        pad = (-n) % block
        qp = np.pad(q[:n].astype(np.float32), (0, pad)).reshape(-1, block)
        dec = qp * (scales[: qp.shape[0], None] / 127.0)
        out[:] = dec.reshape(-1)[:n]
        return out
    lib.odtp_dequantize_blockwise_i8(_i8p(q), _f32p(scales), _f32p(out), n, block)
    return out


def dequant8_accumulate(payload: bytes, scales_payload: bytes, dst: np.ndarray, block: int) -> None:
    """dst += dequantize_blockwise(payload) in one fused pass."""
    lib = get_lib()
    _check_len(len(payload), dst.size, "dequant8_accumulate")
    _check_len(
        len(scales_payload) // 4,
        (dst.size + block - 1) // block,
        "dequant8_accumulate scales",
    )
    if lib is None or dst.dtype != np.float32 or not dst.flags.c_contiguous:
        dst += dequantize_blockwise(payload, scales_payload, dst.size, block).reshape(
            dst.shape
        )
        return
    q = np.frombuffer(payload, np.int8)
    scales = np.frombuffer(scales_payload, np.float32)
    lib.odtp_dequantize_blockwise_i8_accumulate(
        _i8p(q), _f32p(scales), _f32p(dst), dst.size, block
    )


def quantize_blockwise4(a: np.ndarray, block: int) -> tuple[bytes, bytes]:
    """4-bit blockwise quantize -> (packed nibble payload, fp16 scales
    payload). Element 2i is the low nibble of byte i, element 2i+1 the high
    nibble; an odd tail leaves the final high nibble 0 (NOT quantized zero,
    which would be 8). Quantization runs against the fp16-ROUNDED scale so
    encode and decode use the same value. ``block`` must be even so block
    boundaries land on byte boundaries."""
    if block % 2:
        raise ValueError(f"block must be even for nibble packing, got {block}")
    lib = get_lib()
    a = np.ascontiguousarray(a, np.float32).reshape(-1)
    nblocks = (a.size + block - 1) // block
    if not _has(lib, "odtp_quantize_blockwise4"):
        pad = (-a.size) % block
        padded = np.pad(a, (0, pad)).reshape(-1, block)
        amax = np.max(np.abs(padded), axis=1) if nblocks else np.zeros(0, np.float32)
        s = np.where(amax > 0, amax, np.float32(1.0)).astype(np.float32)
        # clamp into the fp16 normal range, same as the C kernel: an amax
        # above 65504 would round to f16 inf (NaN payload on decode), one
        # below the min normal would flush the whole block
        np.clip(s, np.float32(6.1035156e-05), np.float32(65504.0), out=s)
        s16 = s.astype(np.float16)
        inv = np.float32(7.0) / s16.astype(np.float32)
        q = np.clip(np.round(padded * inv[:, None]), -7, 7)
        nib = (q.reshape(-1)[: a.size] + 8).astype(np.uint8)
        if a.size % 2:
            nib = np.append(nib, np.uint8(0))
        packed = nib[0::2] | (nib[1::2] << 4)
        return packed.tobytes(), s16.view(np.uint16).tobytes()
    packed = np.empty((a.size + 1) // 2, np.uint8)
    scales = np.empty(nblocks, np.uint16)
    lib.odtp_quantize_blockwise4(
        _f32p(a), _u8p(packed), _u16p(scales), a.size, block
    )
    return packed.tobytes(), scales.tobytes()


def _dequant4_numpy(
    packed: np.ndarray, scales: np.ndarray, n: int, block: int
) -> np.ndarray:
    nib = np.empty(2 * packed.size, np.uint8)
    nib[0::2] = packed & 0x0F
    nib[1::2] = packed >> 4
    q = nib[:n].astype(np.float32) - np.float32(8.0)
    s = scales[: (n + block - 1) // block].view(np.float16).astype(
        np.float32
    ) / np.float32(7.0)
    qp = np.pad(q, (0, (-n) % block)).reshape(-1, block)
    return (qp * s[:, None]).reshape(-1)[:n]


def dequantize_blockwise4(
    payload: bytes, scales_payload: bytes, n: int, block: int,
    out: Optional[np.ndarray] = None,
) -> np.ndarray:
    if block % 2:
        raise ValueError(f"block must be even for nibble packing, got {block}")
    lib = get_lib()
    packed = np.frombuffer(payload, np.uint8)
    scales = np.frombuffer(scales_payload, np.uint16)
    _check_len(packed.size, (n + 1) // 2, "dequantize_blockwise4")
    _check_len(scales.size, (n + block - 1) // block, "dequantize_blockwise4 scales")
    if out is None:
        out = np.empty(n, np.float32)
    else:
        _check_out(out, n)
    if not _has(lib, "odtp_dequantize_blockwise4"):
        out[:] = _dequant4_numpy(packed, scales, n, block)
        return out
    lib.odtp_dequantize_blockwise4(_u8p(packed), _u16p(scales), _f32p(out), n, block)
    return out


def dequant4_accumulate(
    payload: bytes, scales_payload: bytes, dst: np.ndarray, block: int
) -> None:
    """dst += dequantize_blockwise4(payload) in one fused pass."""
    lib = get_lib()
    packed = np.frombuffer(payload, np.uint8)
    scales = np.frombuffer(scales_payload, np.uint16)
    _check_len(packed.size, (dst.size + 1) // 2, "dequant4_accumulate")
    _check_len(
        scales.size,
        (dst.size + block - 1) // block,
        "dequant4_accumulate scales",
    )
    if (
        not _has(lib, "odtp_dequantize_blockwise4_accumulate")
        or dst.dtype != np.float32
        or not dst.flags.c_contiguous
    ):
        dst += _dequant4_numpy(packed, scales, dst.size, block).reshape(dst.shape)
        return
    lib.odtp_dequantize_blockwise4_accumulate(
        _u8p(packed), _u16p(scales), _f32p(dst), dst.size, block
    )


def _u8p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _has(lib, name: str) -> bool:
    try:
        return lib is not None and getattr(lib, name) is not None
    except AttributeError:  # stale .so predating the symbol
        return False


def quantize_uniform8(a: np.ndarray) -> tuple[bytes, float, float]:
    """Linear lo/span uint8 quantization -> (payload, lo, span); min/max
    reduction and quantize both native single passes when built.

    NaN caveat (mirrors ``absmax``): the C kernel's min/max reduction skips
    NaNs (finite lo/span, NaN elements clamp arbitrarily), while the numpy
    fallback's ``a.min()/a.max()`` propagate NaN into lo/span and hence the
    whole payload. NaN gradients are already a broken upstream state (the
    fp16 scaler skips the step), so the two paths are only bit-identical on
    finite inputs -- which is what the parity tests assert."""
    a = np.ascontiguousarray(a, np.float32).reshape(-1)
    lib = get_lib()
    if not _has(lib, "odtp_quantize_uniform8"):
        lo = float(a.min()) if a.size else 0.0
        hi = float(a.max()) if a.size else 0.0
        span = (hi - lo) or 1.0
        # same expression ORDER as the C kernel ((x-lo) * (255/span), f32):
        # a different order can differ by 1 ulp at .5 rounding boundaries
        # and flip a bucket, breaking native-vs-fallback bit-equality
        inv = np.float32(255.0) / np.float32(span)
        q = np.clip(
            np.round((a - np.float32(lo)) * inv), 0, 255
        ).astype(np.uint8)
        return q.tobytes(), lo, span
    q = np.empty(a.size, np.uint8)
    lo_out = np.empty(1, np.float32)
    span_out = np.empty(1, np.float32)
    lib.odtp_quantize_uniform8(
        _f32p(a), _u8p(q), a.size, _f32p(lo_out), _f32p(span_out)
    )
    return q.tobytes(), float(lo_out[0]), float(span_out[0])


def minmax_span(a: np.ndarray) -> tuple[float, float]:
    """(lo, span) of ``a`` with the same reduction, arithmetic precision,
    and zero-span fix-up as ``quantize_uniform8``, so a chunked encode fed
    by this prescan is bit-identical to the fused whole-tensor kernel on
    the matching build (native-vs-native, fallback-vs-fallback)."""
    a = np.ascontiguousarray(a, np.float32).reshape(-1)
    lib = get_lib()
    if not _has(lib, "odtp_minmax_f32"):
        lo = float(a.min()) if a.size else 0.0
        hi = float(a.max()) if a.size else 0.0
        span = (hi - lo) or 1.0
        return lo, span
    lo_out = np.empty(1, np.float32)
    hi_out = np.empty(1, np.float32)
    lib.odtp_minmax_f32(_f32p(a), a.size, _f32p(lo_out), _f32p(hi_out))
    # f32 subtraction, exactly as the C kernel computes span
    span = np.float32(hi_out[0]) - np.float32(lo_out[0])
    if not (span > 0):
        span = np.float32(1.0)
    return float(lo_out[0]), float(span)


def quantize_uniform8_given(a: np.ndarray, lo: float, span: float) -> bytes:
    """Quantize ``a`` with a precomputed (lo, span) — the per-chunk half of
    the prescan/quantize split. Expression order matches the fused kernel
    (and the ``quantize_uniform8`` fallback) for bit-parity."""
    a = np.ascontiguousarray(a, np.float32).reshape(-1)
    lib = get_lib()
    if not _has(lib, "odtp_quantize_uniform8_given"):
        inv = np.float32(255.0) / np.float32(span)
        q = np.clip(
            np.round((a - np.float32(lo)) * inv), 0, 255
        ).astype(np.uint8)
        return q.tobytes()
    q = np.empty(a.size, np.uint8)
    lib.odtp_quantize_uniform8_given(
        _f32p(a), _u8p(q), a.size, ctypes.c_float(lo), ctypes.c_float(span)
    )
    return q.tobytes()


def dequantize_uniform8(
    payload: bytes, lo: float, span: float, n: int,
    out: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Single-pass uniform8 decode, optionally straight into ``out``."""
    q = np.frombuffer(payload, np.uint8)
    _check_len(q.size, n, "dequantize_uniform8")
    lib = get_lib()
    if out is None:
        out = np.empty(n, np.float32)
    else:
        _check_out(out, n)
    if not _has(lib, "odtp_dequantize_uniform8"):
        np.multiply(q[:n].astype(np.float32), span / 255.0, out=out)
        out += lo
        return out
    lib.odtp_dequantize_uniform8(
        _u8p(q), ctypes.c_float(lo), ctypes.c_float(span), _f32p(out), n
    )
    return out


def dequant_uniform8_accumulate(
    payload: bytes, lo: float, span: float, dst: np.ndarray
) -> None:
    """dst += uniform8_decode(payload) in one fused pass."""
    lib = get_lib()
    _check_len(len(payload), dst.size, "dequant_uniform8_accumulate")
    if (
        not _has(lib, "odtp_dequantize_uniform8_accumulate")
        or dst.dtype != np.float32
        or not dst.flags.c_contiguous
    ):
        q = np.frombuffer(payload, np.uint8)
        dst += (q.astype(np.float32) * (span / 255.0) + lo).reshape(dst.shape)
        return
    lib.odtp_dequantize_uniform8_accumulate(
        _u8p(np.frombuffer(payload, np.uint8)),
        ctypes.c_float(lo),
        ctypes.c_float(span),
        _f32p(dst),
        dst.size,
    )


def lut256_gather(
    idx_payload: bytes, lut: np.ndarray, n: int,
    out: Optional[np.ndarray] = None,
) -> np.ndarray:
    """out = lut[idx] (quantile codebook decode), optionally into ``out``."""
    idx = np.frombuffer(idx_payload, np.uint8)
    _check_len(idx.size, n, "lut256_gather")
    lut = np.ascontiguousarray(lut, np.float32)
    _check_len(lut.size, 256, "lut256_gather codebook")
    lib = get_lib()
    if out is None:
        out = np.empty(n, np.float32)
    else:
        _check_out(out, n)
    if not _has(lib, "odtp_lut256_gather"):
        np.take(lut, idx[:n], out=out)
        return out
    lib.odtp_lut256_gather(_u8p(idx), _f32p(lut), _f32p(out), n)
    return out


def lut256_accumulate(
    idx_payload: bytes, lut: np.ndarray, dst: np.ndarray
) -> None:
    """dst += lut[idx] in one fused pass."""
    idx = np.frombuffer(idx_payload, np.uint8)
    _check_len(idx.size, dst.size, "lut256_accumulate")
    lut = np.ascontiguousarray(lut, np.float32)
    _check_len(lut.size, 256, "lut256_accumulate codebook")
    lib = get_lib()
    if (
        not _has(lib, "odtp_lut256_accumulate")
        or dst.dtype != np.float32
        or not dst.flags.c_contiguous
    ):
        dst += lut[idx].reshape(dst.shape)
        return
    lib.odtp_lut256_accumulate(_u8p(idx), _f32p(lut), _f32p(dst), dst.size)


def quantile_assign(flat: np.ndarray, inner_edges: np.ndarray) -> np.ndarray:
    """Assign each value to one of 256 buckets split by 255 sorted inner
    edges (searchsorted side='right' semantics)."""
    lib = get_lib()
    flat = np.ascontiguousarray(flat, np.float32)
    inner_edges = np.ascontiguousarray(inner_edges, np.float32)
    if lib is None:
        return np.clip(
            np.searchsorted(inner_edges, flat, side="right"), 0, 255
        ).astype(np.uint8)
    out = np.empty(flat.size, np.uint8)
    lib.odtp_quantile_assign(
        _f32p(flat),
        _f32p(inner_edges),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        flat.size,
    )
    return out


def sock_sendall(sock, buf) -> None:
    """Send an entire contiguous buffer on a connected socket. Native path
    pumps bytes in C with the GIL released; fallback is socket.sendall
    (also zero-copy for memoryview/ndarray)."""
    lib = get_lib()
    if lib is None:
        sock.sendall(buf if isinstance(buf, (bytes, memoryview)) else memoryview(buf))
        return
    a = np.frombuffer(buf, np.uint8)  # zero-copy view, works read-only
    rc = lib.odtp_sendall(sock.fileno(), ctypes.c_void_p(a.ctypes.data), a.size)
    if rc != 0:
        raise OSError(-rc, f"odtp_sendall failed (rc={rc})")


def sock_recvall(sock, buf: np.ndarray) -> None:
    """Receive exactly len(buf) bytes into a writable contiguous buffer."""
    lib = get_lib()
    if lib is None:
        view = memoryview(buf).cast("B")
        got = 0
        while got < len(view):
            r = sock.recv_into(view[got:])
            if r == 0:
                raise ConnectionResetError("peer closed mid-transfer")
            got += r
        return
    a = np.frombuffer(buf, np.uint8)
    rc = lib.odtp_recvall(sock.fileno(), ctypes.c_void_p(a.ctypes.data), a.size)
    if rc == -1:
        raise ConnectionResetError("peer closed mid-transfer")
    if rc != 0:
        raise OSError(-rc, f"odtp_recvall failed (rc={rc})")


def quantile_edges(flat: np.ndarray) -> np.ndarray:
    """257 quantile edges of a strided <=100k sample of ``flat`` (the
    codebook build of the quantile8bit codec), float32."""
    lib = get_lib()
    flat = np.ascontiguousarray(flat, np.float32).reshape(-1)
    if lib is None:
        cap = 100_000
        if flat.size <= cap:
            sample = flat
        else:
            stride = flat.size / cap
            sample = flat[(np.arange(cap) * stride).astype(np.int64)]
        return np.quantile(sample, np.linspace(0, 1, 257)).astype(np.float32)
    out = np.empty(257, np.float32)
    lib.odtp_quantile_edges(_f32p(flat), flat.size, _f32p(out))
    return out


def outer_sgd_step(
    p: np.ndarray,
    g: np.ndarray,
    buf: np.ndarray,
    lr: float,
    momentum: float,
    nesterov: bool,
) -> bool:
    """Fused momentum outer-SGD update of one leaf, all in place:
    ``buf = momentum*buf + g; p -= lr*(g + momentum*buf | buf)``.
    Returns False when the native path can't run (no lib, stale .so, or a
    non-contiguous/non-f32 in-place target) — caller keeps the numpy body.
    ``p`` and ``buf`` must be written through, so unlike the codec wrappers
    there is no ascontiguousarray coercion on them (a coerced copy would
    discard the update)."""
    lib = get_lib()
    if (
        not _has(lib, "odtp_outer_sgd_f32")
        or p.dtype != np.float32
        or buf.dtype != np.float32
        or not p.flags.c_contiguous
        or not buf.flags.c_contiguous
        or g.shape != p.shape
        or buf.shape != p.shape
    ):
        return False
    g = np.ascontiguousarray(g, np.float32)
    lib.odtp_outer_sgd_f32(
        _f32p(p),
        _f32p(g),
        _f32p(buf),
        ctypes.c_float(lr),
        ctypes.c_float(momentum),
        ctypes.c_int(1 if nesterov else 0),
        p.size,
    )
    return True


def sqnorm(a: np.ndarray) -> float:
    """sum(a*a) with a double accumulator (one OMP reduction pass); the
    pseudo_grad_norm gauge's per-leaf term."""
    lib = get_lib()
    a = np.ascontiguousarray(a, np.float32).reshape(-1)
    if not _has(lib, "odtp_sqnorm_f32"):
        v = a.astype(np.float64, copy=False)
        return float(np.dot(v, v))
    return float(lib.odtp_sqnorm_f32(_f32p(a), a.size))
