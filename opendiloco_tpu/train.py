"""Main training driver: the TPU-native ``train_fsdp.py``.

End-to-end Llama pretraining with optional DiLoCo outer loop:

    python -m opendiloco_tpu.train --path-model 150m --fake-data \\
        --per-device-train-batch-size 32 --total-batch-size 512 \\
        --diloco.local-steps 500 --diloco.initial-peers HOST:PORT \\
        --diloco.world-rank 0 --diloco.galaxy-size 8 \\
        --ckpt.path outputs --ckpt.interval 500 --metric-logger-type wandb

Reference call-stack parity (train_fsdp.py:177-516): config -> mesh ->
model -> dataloader -> trainer -> (DiLoCo optimizer | plain inner loop) ->
train loop with metrics, activation probes, peer-drop handling, checkpoint
cadence + resume. What disappears on TPU: torchrun process-per-GPU (one
controller process drives the local mesh), FSDP wrapping (shardings),
GradScaler (bf16), and the post-outer-step NCCL broadcast (the outer update
is written to the sharded pytree directly).
"""

from __future__ import annotations

import math
import os
import time
from typing import Optional

import jax
import numpy as np

from opendiloco_tpu import ckpt as ckpt_lib
from opendiloco_tpu import obs
from opendiloco_tpu.config import Config, DilocoConfig, parse_argv
from opendiloco_tpu.diloco import chaos
from opendiloco_tpu.data.dataloader import get_dataloader
from opendiloco_tpu.diloco.backend import OuterBackend
from opendiloco_tpu.diloco.optimizer import DiLoCoOptimizer, PeerDropError
from opendiloco_tpu.models import hf_io
from opendiloco_tpu.models.llama import init_params
from opendiloco_tpu.parallel.mesh import build_mesh
from opendiloco_tpu.parallel.world import make_world
from opendiloco_tpu.trainer import InnerTrainer, TrainerConfig
from opendiloco_tpu.utils.logger import get_logger, get_text_logger

log = get_text_logger(__name__)


def make_backend(cfg: DilocoConfig) -> OuterBackend:
    if cfg.backend == "tcp":
        from opendiloco_tpu.diloco.tcp import TcpBackend

        return TcpBackend(
            cfg.initial_peers,
            host=cfg.host if cfg.host != "0.0.0.0" else "127.0.0.1",
            port=cfg.port,
            peer_id=f"worker-{cfg.world_rank}",
            compression=cfg.compression,
            matchmaking_time=cfg.matchmaking_time,
            # config True forces adaptive transport on; False defers to the
            # ODTP_LINK_ADAPT env switch (None = backend reads env per round)
            link_adapt=cfg.link_adapt or None,
        )
    raise ValueError(
        f"backend {cfg.backend!r} has no factory (loopback backends are "
        "constructed from a shared LoopbackWorld in-process)"
    )


def train(config: Config, backend: Optional[OuterBackend] = None) -> dict:
    """Returns a summary dict (final step/loss) for programmatic callers."""
    world_rank = config.diloco.world_rank if config.diloco else 0
    os.environ.setdefault("DILOCO_WORLD_RANK", str(world_rank))
    _cp = chaos.plane()
    if _cp is not None:
        # scope rank-targeted faults (straggle_worker, kill_worker) to us
        _cp.set_identity(world_rank)
    _tr = obs.tracer()
    if _tr is not None:
        _tr.set_identity(worker=world_rank)
        # arm the flight recorder's crash hooks (atexit / fatal signals /
        # faulthandler) so this worker leaves a black box behind even when
        # it dies mid-round; identity must be set first so the dump file
        # is blackbox-<rank>-<pid>.json, not blackbox-<pid>-<pid>.json
        obs.blackbox.install()

    if config.multihost:
        # in-worker multi-host slice: every host of the slice runs this
        # driver; jax.distributed wires the hosts into one mesh over ICI/DCN
        jax.distributed.initialize(
            coordinator_address=config.coordinator_address,
            num_processes=config.num_processes,
            process_id=config.process_id,
        )
        log.info(
            "multihost: process %d/%d, %d local / %d global devices",
            jax.process_index(),
            jax.process_count(),
            jax.local_device_count(),
            jax.device_count(),
        )

    model_cfg, params = hf_io.get_model(config.path_model)
    plan = build_mesh(
        config.sharding_strategy,
        dp_size=config.dp_size,
        fsdp_size=config.fsdp_size,
        tp_size=config.tp_size,
        sp_size=config.sp_size,
        pp_size=config.pp_size,
        ep_size=config.ep_size,
    )
    tc = TrainerConfig(
        lr=config.lr,
        weight_decay=config.weight_decay,
        adam_betas=tuple(config.adam_betas),
        warmup_steps=config.warmup_steps,
        total_steps=config.total_steps,
        max_grad_norm=config.max_grad_norm,
        precision=config.precision,
        attn_impl=config.attn_implementation,
        remat=config.remat,
        fused_loss=config.fused_loss,
        scan_unroll=config.scan_unroll,
        allow_sp_activation_sharding=config.allow_sp_activation_sharding,
    )
    trainer = InnerTrainer(model_cfg, tc, plan)

    if config.ckpt.interval:
        ckpt_lib.check_checkpoint_path_access(config.ckpt.path, world_rank)

    # batch/accumulation accounting (train_fsdp.py:189-190)
    dp = plan.data_parallel_size
    global_micro = config.per_device_train_batch_size * dp
    accum = max(1, config.total_batch_size // global_micro)
    if config.total_batch_size % global_micro:
        raise ValueError(
            f"total_batch_size {config.total_batch_size} not divisible by "
            f"per_device_train_batch_size*dp = {global_micro}"
        )

    # under multihost every process loads only its shard of the global batch
    # (the data is already split by process_index; shard_batch assembles the
    # global array from per-process rows)
    nproc = jax.process_count()
    if config.total_batch_size % nproc:
        raise ValueError(
            f"total_batch_size {config.total_batch_size} not divisible by "
            f"process_count {nproc}"
        )
    local_batch_size = config.total_batch_size // nproc
    if local_batch_size % accum:
        raise ValueError(
            f"per-process batch {local_batch_size} not divisible by the "
            f"accumulation factor {accum} (= total_batch_size / "
            f"(per_device_train_batch_size * dp)); adjust batch sizes"
        )
    if config.eval_interval and (global_micro % nproc):
        raise ValueError(
            f"eval batch per_device_train_batch_size*dp = {global_micro} "
            f"not divisible by process_count {nproc}"
        )
    loader = get_dataloader(
        fake_data=config.fake_data,
        fake_data_mode=config.fake_data_mode,
        dataset_name_or_paths=config.dataset_name_or_paths,
        tokenizer_name=config.tokenizer_name,
        seq_length=config.seq_length,
        batch_size=local_batch_size,
        vocab_size=model_cfg.vocab_size,
        world_rank=world_rank,
        galaxy_size=config.diloco.galaxy_size if config.diloco else 1,
        streaming=config.dataset_streaming,
    )

    state = trainer.init_state(jax.random.key(42), params)

    diloco_opt: Optional[DiLoCoOptimizer] = None
    owns_backend = False
    if config.diloco is not None:
        # world-messenger split (reference train_fsdp.py:183,205-212): in a
        # multihost slice only process 0 joins the WAN fabric; the other
        # processes run the same outer loop against mesh collectives
        world = make_world(plan.mesh)
        if backend is None and world.is_messenger:
            backend = make_backend(config.diloco)
            owns_backend = True
        diloco_opt = DiLoCoOptimizer(
            trainer,
            backend,
            config.diloco,
            state,
            batch_size=config.total_batch_size,
            world=world,
        )
        log.info(
            "outer data plane: placement=%s (requested %s)",
            diloco_opt.placement,
            config.diloco.outer_placement,
        )

    # resume (ckpt_utils.py:23-45 + train_fsdp.py:313-344)
    start_step = 0
    resume, resume_dir, resume_step = ckpt_lib.get_resume_info(
        config.ckpt.resume,
        config.ckpt.path,
        diloco_rank=world_rank if config.diloco else None,
    )
    if resume:
        log.info("resuming from %s (step %d)", resume_dir, resume_step)
        state, diloco_state, loader_state, extra = ckpt_lib.load_checkpoint(
            resume_dir, state
        )
        if diloco_opt is not None and diloco_state is not None:
            diloco_opt.load_state_dict(diloco_state)
        if loader_state is not None:
            loader.load_state_dict(loader_state)
        start_step = resume_step
    elif diloco_opt is not None and not config.diloco.skip_load_from_peers:
        updated = diloco_opt.load_state_from_peers(state)
        if updated is not None:
            state = updated
            log.info("loaded state from peers (epoch %d)", diloco_opt.epoch)

    metric_logger = get_logger(
        config.metric_logger_type,
        config.project,
        config.model_dump(),
        resume=bool(resume),
    )

    # in-process serving plane: inference threads share this process (and
    # its obs registry) with the inner loop; weights hot-swap from the
    # DiLoCo master snapshots between decode steps (opendiloco_tpu/serve)
    serving = None
    if config.serve is not None and config.serve.enabled:
        from opendiloco_tpu.serve import build_serving

        serving = build_serving(
            config.serve,
            model_cfg,
            state["params"],
            diloco_opt,
            compute_dtype=tc.compute_dtype,
        )
        log.info(
            "serving plane up on %s:%d (%d slots, ctx %d)",
            config.serve.host,
            serving.port,
            config.serve.max_batch,
            config.serve.max_context,
        )

    # serving fleet: replica engines (subprocesses by default) fed by
    # delta pushes off the masters, behind one router (opendiloco_tpu/fleet)
    fleet_plane = None
    if config.fleet is not None and config.fleet.enabled:
        from opendiloco_tpu.fleet import build_fleet

        fleet_plane = build_fleet(
            config.fleet,
            model_cfg,
            state["params"],
            diloco_opt,
            compute_dtype=tc.compute_dtype,
        )
        log.info(
            "serving fleet up: router %s:%d over %d replicas (codec %s)",
            config.fleet.host,
            fleet_plane.port,
            config.fleet.replicas,
            config.fleet.codec,
        )

    eval_iter = None
    if config.eval_interval:
        eval_loader = get_dataloader(
            fake_data=config.fake_data,
            fake_data_mode=config.fake_data_mode,
            dataset_name_or_paths=config.dataset_name_or_paths,
            tokenizer_name=config.tokenizer_name,
            seq_length=config.seq_length,
            batch_size=global_micro // nproc,
            vocab_size=model_cfg.vocab_size,
            world_rank=world_rank,
            galaxy_size=config.diloco.galaxy_size if config.diloco else 1,
            split="validation",
            streaming=config.dataset_streaming,
        )
        eval_iter = iter(eval_loader)

    tokens_per_step = config.total_batch_size * config.seq_length
    # one-time MFU setup: flops/token from the banked roofline (or 6N
    # fallback); the per-step cost is a single multiply in flush()
    n_params = sum(int(x.size) for x in jax.tree.leaves(state["params"]))
    model_flops_per_token, peak_flops, mfu_source = obs.mfu.flops_per_token(
        config.path_model, n_params
    )
    n_devices = jax.device_count()
    if _tr is not None:
        _tr.set_identity(
            model=config.path_model, mfu_source=mfu_source, n_params=n_params
        )
    summary = {"step": start_step, "loss": float("nan")}
    data_iter = iter(loader)
    prefetcher = None
    if config.prefetch_depth > 0:
        from opendiloco_tpu.data.prefetch import DevicePrefetcher

        prefetcher = DevicePrefetcher(
            data_iter,
            lambda hb: trainer.shard_batch(hb["input_ids"], hb["labels"], accum),
            depth=config.prefetch_depth,
            state_fn=loader.state_dict,
        )
    pending = None  # (real_step, device_metrics, dt, extras) of the prior step
    profiling = False

    def flush(p) -> None:
        """Materialize a step's metrics row. Deferred one step behind the
        dispatch so the float() fetch never stalls the accelerator pipeline."""
        nonlocal summary
        real_step, metrics, dt, extras = p
        loss = float(metrics["loss"])
        row = {
            "Loss": loss,
            "Perplexity": math.exp(min(loss, 30.0)),
            "step": real_step,
            "lr": trainer.current_lr(real_step),
            "effective_step": real_step
            * (config.diloco.galaxy_size if config.diloco else 1),
            "total_samples": real_step * config.total_batch_size,
            "time_taken": dt,
            "tokens_per_second": tokens_per_step / dt,
            "grad_norm": float(metrics["grad_norm"]),
        }
        if model_flops_per_token is not None:
            row["mfu"] = obs.mfu.mfu(
                row["tokens_per_second"],
                model_flops_per_token,
                n_devices,
                peak_flops,
            )
        tr = obs.tracer()
        if tr is not None:
            tr.count("inner_tokens", tokens_per_step)
            tr.gauge("inner_loss", loss)
            tr.gauge("inner_grad_norm", row["grad_norm"])
            tr.gauge("inner_tokens_per_second", row["tokens_per_second"])
            # per-worker inner-step rate: the roll-up field odtp_top's
            # step/s column reads (async skew shows here even when batch
            # shapes differ across the galaxy and tokens/s doesn't divide)
            tr.gauge("inner_steps_per_second", 1.0 / dt if dt > 0 else 0.0)
            tr.gauge("inner_step_s", dt)
            if "mfu" in row:
                tr.gauge("inner_mfu", row["mfu"])
        if diloco_opt is not None:
            row["num_peers"] = diloco_opt.max_num_peers
            row["outer_epoch"] = diloco_opt.epoch
            # round-health fields ride along so the chaos soak can read
            # elastic rescale and aggregator re-election from the rows
            for k in ("outer_step_s", "outer_allreduce_s", "outer_wait_s",
                      "elastic", "expected_peers", "round_retries",
                      "hier_plan", "hier_aggregators"):
                if k in metrics:
                    row[k] = metrics[k]
        row.update(extras)
        metric_logger.log(row)
        if real_step % 10 == 0 or real_step == 1:
            log.info(
                "step %d loss %.4f lr %.2e %.0f tok/s",
                real_step,
                loss,
                row["lr"],
                row["tokens_per_second"],
            )
        summary = {"step": real_step, "loss": loss}

    try:
        for step in range(start_step, config.total_steps):
            if config.profile_dir and step == start_step + config.profile_start:
                jax.profiler.start_trace(config.profile_dir)
                profiling = True
            if profiling and step == start_step + config.profile_start + config.profile_steps:
                jax.profiler.stop_trace()
                profiling = False
                log.info("wrote profiler trace to %s", config.profile_dir)
            t0 = time.perf_counter()
            if prefetcher is not None:
                host_batch, batch = next(prefetcher)
            else:
                host_batch = next(data_iter)
                batch = trainer.shard_batch(
                    host_batch["input_ids"], host_batch["labels"], accum
                )
            data_wait_s = time.perf_counter() - t0  # ~0 when prefetch keeps up
            cp = chaos.plane()
            if cp is not None:
                d = cp.straggle_inner_s()
                if d:  # slow-host emulation, inside the measured step window
                    time.sleep(d)
            if diloco_opt is not None:
                state, metrics = diloco_opt.step(state, batch)
            else:
                state, metrics = trainer.train_step(state, batch)
            if cp is not None:
                x = cp.straggle_inner_x()
                if x > 1.0:
                    # sustained rate skew: stretch THIS step by (x-1) of
                    # its own measured duration, so the worker runs at
                    # exactly 1/x speed whatever the hardware is doing
                    time.sleep((x - 1.0) * (time.perf_counter() - t0))

            # the prior step's results are certainly ready now: flush them
            # while this step runs on device
            if pending is not None:
                flush(pending)
            real_step = step + 1
            dt = time.perf_counter() - t0
            extras: dict = {"data_wait_s": round(data_wait_s, 6)}
            if (
                config.log_activations_steps
                and real_step % config.log_activations_steps == 0
            ):
                extras.update(
                    trainer.probe_norms(state["params"], host_batch["input_ids"])
                )
            if eval_iter is not None and real_step % config.eval_interval == 0:
                eval_losses = []
                for _ in range(config.eval_batches):
                    eb = next(eval_iter)
                    eval_losses.append(
                        trainer.eval_loss(state["params"], eb["input_ids"], eb["labels"])
                    )
                extras["eval_loss"] = float(np.mean(eval_losses))
                extras["eval_perplexity"] = math.exp(min(extras["eval_loss"], 30.0))
                log.info("eval at %d: loss %.4f", real_step, extras["eval_loss"])
            pending = (real_step, metrics, dt, extras)

            if config.ckpt.interval and real_step % config.ckpt.interval == 0:
                flush(pending)
                pending = None
                if diloco_opt is not None:
                    # land any in-flight overlapped outer round so the saved
                    # master reflects every launched all-reduce
                    state = diloco_opt.flush(state)
                ckpt_lib.save_checkpoint(
                    config.ckpt.path,
                    real_step,
                    state,
                    diloco_rank=world_rank if config.diloco else None,
                    diloco_state=diloco_opt.state_dict() if diloco_opt else None,
                    dataloader_state=(
                        prefetcher.state_dict() if prefetcher else loader.state_dict()
                    ),
                    extra={"loss": summary["loss"], "step": real_step},
                )
                ckpt_lib.delete_old_checkpoints(config.ckpt.path, config.ckpt.topk)
        if pending is not None:
            flush(pending)
            pending = None
        if diloco_opt is not None:
            state = diloco_opt.flush(state)
    except PeerDropError:
        log.error("a DiLoCo worker dropped and fail_rank_drop is set; exiting")
        raise
    finally:
        if fleet_plane is not None:
            # pusher threads read master snapshots through diloco_opt;
            # stop them (and the replicas) before the backend goes away
            fleet_plane.stop()
        if serving is not None:
            # before the backend goes away: the batcher thread may be
            # mid-swap pulling a master snapshot through diloco_opt
            serving.stop()
        if diloco_opt is not None:
            # abnormal exits must not leave an outer round holding the
            # backend open (the comm thread is daemonized, but drop it so
            # backend.close() below isn't racing a live reduce)
            diloco_opt.drop_pending()
        if profiling:
            # a window extending past total_steps must still flush the trace;
            # never let a trace-serialization failure mask the real error or
            # skip the remaining cleanup
            try:
                jax.profiler.stop_trace()
                log.info("wrote profiler trace to %s", config.profile_dir)
            except Exception:
                log.exception("failed to flush profiler trace")
        if prefetcher is not None:
            prefetcher.stop()
        loader.stop()
        metric_logger.finish()
        _tr_out = obs.tracer()
        if _tr_out is not None:
            try:
                _tr_out.flush()
            except Exception:
                log.exception("failed to flush obs trace")
            _bb = obs.blackbox.recorder()
            if _bb is not None:
                try:
                    _bb.dump(reason="train_exit")
                except Exception:
                    log.exception("failed to dump flight recorder")
        if owns_backend and backend is not None:
            backend.close()
    return summary


def main() -> None:
    # the axon site hook pins jax_platforms before argv parsing; honor an
    # explicit override (used by CPU-mesh tests and local dry runs)
    platform = os.environ.get("OPENDILOCO_TPU_PLATFORM")
    if platform:
        jax.config.update("jax_platforms", platform)
    config = Config(**parse_argv())
    log.info("starting training: %s", config.model_dump())
    train(config)


if __name__ == "__main__":
    main()
