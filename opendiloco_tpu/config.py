"""Configuration tree and CLI parsing.

Mirrors the reference's pydantic-based config semantics (nested dotted flags
like ``--diloco.local-steps 500`` and ``--no-x`` booleans; reference:
open_diloco/train_fsdp.py:79-129, pydantic_config fork) with a thin,
dependency-free argv parser.
"""

from __future__ import annotations

import sys
from typing import Any, Literal, Optional, Union

from pydantic import BaseModel, field_validator, model_validator
from pydantic import ConfigDict


class CkptConfig(BaseModel):
    """Checkpoint cadence/paths (reference: open_diloco/ckpt_utils.py:16-21)."""

    model_config = ConfigDict(extra="forbid")

    path: str = "outputs"
    interval: Optional[int] = None
    topk: Optional[int] = None
    # resume: True -> auto-discover latest ckpt under `path`; str -> explicit
    # checkpoint directory; None/False -> fresh start.
    resume: Optional[str | bool] = None

    @field_validator("interval", "topk", mode="before")
    @classmethod
    def _no_flag_means_none(cls, v: Any) -> Any:
        # `--no-ckpt.interval` parses to False; treat as "disabled"
        return None if v is False else v


class DilocoConfig(BaseModel):
    """Outer-loop (DiLoCo) configuration.

    Equivalent of the reference's ``HvConfig`` (open_diloco/train_fsdp.py:79-101)
    plus the DiLoCoOptimizer kwargs it forwards
    (open_diloco/hivemind_diloco.py:326-406).
    """

    model_config = ConfigDict(extra="forbid")

    outer_lr: float = 0.7
    outer_momentum: float = 0.9
    outer_nesterov: bool = True
    local_steps: int = 500

    # peer bootstrap / identity
    initial_peers: list[str] = []
    host: str = "0.0.0.0"
    port: int = 0  # 0 -> ephemeral
    world_rank: int = 0
    galaxy_size: int = 1

    # straggler / failure policy (reference: hivemind_diloco.py:285-300)
    all_reduce_strategy: Literal["wait_for_all", "no_wait"] = "wait_for_all"
    timeout_waiting_for_peers: float = 600.0
    averaging_timeout: float = 300.0
    # matchmaking window for outer-round group formation. Must cover the
    # gap between a peer REPORTING its epoch boundary and it actually
    # joining matchmaking -- which includes the device->host boundary
    # param fetch (measured ~35 s for 150m through a slow transport; scale
    # with model size). A large window costs nothing when peers are
    # prompt: the rendezvous closes the round early once every live
    # registered peer has joined (rendezvous.py). 5 s windows made two
    # staggered live 150m workers matchmake SOLO groups every round; the
    # banked paired run (LIVE_DILOCO_TCP.json) used this 60 s default.
    matchmaking_time: float = 60.0
    fail_rank_drop: bool = False  # crash if a peer drops (train_fsdp.py:93)

    # wire compression for the outer all-reduce (utils.py:83-121, plus the
    # sub-8-bit codecs: blockwise4bit = packed nibbles + fp16 block scales,
    # topk = sparse top-|x| at ODTP_TOPK_DENSITY)
    compression: Literal[
        "none", "fp16", "scaled-fp16", "uniform8bit", "quantile8bit",
        "blockwise8bit", "blockwise4bit", "topk",
    ] = "none"

    # error feedback for lossy compression: each round's encode/decode
    # residual (quantization or sparsification error) is accumulated
    # per-leaf and added to the NEXT round's pseudo-gradient before
    # encoding, so dropped signal is carried instead of lost. Residuals
    # checkpoint with the optimizer state and survive elastic dropped
    # rounds. Requires a lossy codec (compression != "none").
    error_feedback: bool = False

    # onboarding (train_fsdp.py:348-349)
    skip_load_from_peers: bool = False

    # communication backend: "loopback" (in-process, tests), "tcp" (DCN)
    backend: Literal["loopback", "tcp"] = "tcp"

    # optional periodic full state averaging (hivemind_diloco.py:634-638)
    average_state_every: int = 0  # 0 = never

    # outer averaging topology:
    #   "allreduce" - every epoch averages over the whole galaxy (reference)
    #   "gossip"    - NoLoCo (arxiv 2506.10911): every worker mixes
    #                 (master, momentum, pseudo_grad) with ONE partner per
    #                 round over a point-to-point push-pull — no global
    #                 barrier, no rendezvous round. Pairings are derived
    #                 locally from a shared epoch-keyed PRNG over the
    #                 gossiped membership (diloco/gossip.py), link-biased
    #                 when link_adapt is on; disagreement mixes away over
    #                 re-pairings. Composes with streaming_fragments
    #                 (fragment k pairs on its own clock), overlap_comm,
    #                 sub-8-bit codecs + per-partner error feedback, and
    #                 device placement.
    outer_mode: Literal["allreduce", "gossip"] = "allreduce"

    # overlap the outer all-reduce with the next inner epoch (Eager Updates
    # for Overlapped Communication in DiLoCo, arxiv 2502.12996):
    #   "none"    - blocking outer step (reference semantics)
    #   "delayed" - inner training continues; the averaged outer update is
    #               applied as a parameter delta when communication lands
    #   "eager"   - additionally applies the update estimated from the LOCAL
    #               pseudo-gradient immediately, corrected on arrival
    overlap_comm: Literal["none", "delayed", "eager"] = "none"

    # Streaming DiLoCo-style fragment sync (arxiv 2501.18512): partition
    # the parameter leaves into N size-balanced fragments and sync ONE
    # fragment per outer boundary (fragment = epoch mod N). Each fragment
    # gets outer updates every N epochs on its own staggered clock; the
    # un-synced leaves keep training locally. Peak per-boundary bandwidth
    # drops ~N-fold. 0/1 = off (reference full-sync semantics).
    streaming_fragments: int = 0

    # streaming x overlap stagger (arxiv 2502.12996 "eager updates"
    # composed with the 2501.18512 fragment schedule): with
    # streaming_fragments=N AND overlap_comm != "none", EVERY fragment
    # syncs each epoch on its own mid-phase clock -- fragment k's
    # all-reduce launches at inner step  min(H, int(k*stagger*H/N)+1)
    # and lands while the inner loop keeps training. 1.0 spreads the
    # launches evenly across the whole inner phase; smaller values
    # front-load them (0.5 packs all launches into the first half,
    # leaving more time to land before the next epoch's slot).
    stream_stagger: float = 1.0

    # where the outer data plane (master weights + Nesterov momentum) lives:
    #   "host"   - numpy master, serial host Nesterov step (reference
    #              hivemind offload_optimizer semantics)
    #   "device" - sharded device arrays; pseudo-gradient and outer apply
    #              are fused, donated jit ops at HBM bandwidth and the
    #              boundary D2H moves wire-width bytes (diloco/outer_device.py)
    #   "auto"   - device on TPU meshes, host elsewhere
    # Device placement is single-process only; multihost meshes fall back
    # to host with a warning.
    outer_placement: Literal["auto", "host", "device"] = "auto"

    # bandwidth-aware adaptive outer transport (diloco/linkstate.py):
    # capacity-proportional butterfly partitioning, BDP-derived
    # striping/chunking, straggler hedging. True forces it on for this
    # worker; False defers to the ODTP_LINK_ADAPT env switch (so a swarm
    # can be flipped without touching configs). Off = bit-identical to the
    # uniform butterfly.
    link_adapt: bool = False

    @model_validator(mode="after")
    def _streaming_constraints(self):
        if self.streaming_fragments > 1:
            if self.average_state_every:
                raise ValueError(
                    "streaming_fragments makes average_state_every "
                    "unnecessary AND destructive: masters cannot drift "
                    "(every fragment update is the same all-reduced "
                    "result on every peer), while a full master reset "
                    "would erase the un-synced fragments' local progress "
                    "without it ever forming a pseudo-gradient"
                )
        if not (0.0 < self.stream_stagger <= 1.0):
            raise ValueError(
                f"stream_stagger must be in (0, 1], got {self.stream_stagger}"
            )
        return self

    # The former _gossip_constraints validator is gone: NoLoCo gossip now
    # composes with overlap_comm, streaming_fragments, sub-8-bit codecs,
    # error feedback (per-partner residuals), and device placement. The
    # master weights ride the STATE codec (fp16 family) on the pair wire;
    # only the pseudo-gradient section uses the configured lossy codec,
    # so sub-fp16 codecs no longer touch master bytes (see MIGRATION.md).

    @model_validator(mode="after")
    def _error_feedback_constraints(self):
        if self.error_feedback and self.compression == "none":
            raise ValueError(
                "error_feedback carries the codec's encode/decode residual; "
                "with compression='none' there is none -- pick a lossy codec"
            )
        return self

    @field_validator("initial_peers", mode="before")
    @classmethod
    def _coerce_peers(cls, v: Any) -> Any:
        # reference coerces scalar -> list (train_fsdp.py:95-101);
        # comma-separated strings list multiple bootstrap peers
        if isinstance(v, str):
            return [x.strip() for x in v.split(",") if x.strip()]
        return v


class ServeConfig(BaseModel):
    """In-process serving plane (opendiloco_tpu/serve): continuous-batching
    inference over the live master weights while training runs."""

    model_config = ConfigDict(extra="forbid")

    enabled: bool = False
    host: str = "127.0.0.1"
    port: int = 0  # 0 -> ephemeral; collisions downgrade to ephemeral
    # continuous-batching geometry
    max_batch: int = 8  # decode slots (concurrent sequences)
    max_context: int = 1024  # per-slot ring KV page; longer sequences slide
    # prefill compile-size buckets (prompts pad up to the smallest fit;
    # prompts beyond the largest bucket are rejected, not truncated)
    prefill_buckets: list[int] = [64, 256, 1024]
    max_queue: int = 1024  # backpressure: submits beyond this are rejected
    # weight hot-swap policy: check every N decode steps; swap when the
    # serving weights lag the trainer's masters by MORE than
    # max_stale_rounds outer rounds (0 = adopt every new round)
    swap_every_steps: int = 16
    max_stale_rounds: int = 0
    # fast decode path (PR 11): each leg defaults OFF and the off path is
    # bit-identical to the plain engine
    # self-speculative decode: draft k tokens per slot per step from the
    # first draft_layers of the same weights, verify full-depth, keep the
    # longest agreeing greedy prefix (token-exact vs the one-token loop);
    # 0 disables
    spec_decode_k: int = 0
    # draft depth; 0 = auto (half the stack, min 1); must stay < num layers
    draft_layers: int = 0
    # replica weight residency: "fp32" (today's layout) or "w4" (stacked
    # matmul weights blockwise-4bit packed at rest, dequantized per block
    # inside the jit'd decode; norms/embeddings/lm head stay fp32)
    weight_format: Literal["fp32", "w4"] = "fp32"
    # decode-path kernel dispatch: "auto" picks the Pallas serving kernels
    # (paged decode attention, fused W4 dequant-matmul, fused speculative
    # verify) on TPU backends and the stock XLA ops elsewhere; "pallas" /
    # "xla" force a path (forced pallas off-TPU runs interpreted — test
    # rigs only). Token-bit-exact either way.
    decode_kernel: Literal["auto", "pallas", "xla"] = "auto"
    # shared-prefix KV reuse: prefill a common prompt prefix once and
    # ring-copy its K/V into joining slots
    prefix_cache: bool = False
    # host-memory cold KV tier: evicted slot pages park D2H between decode
    # steps so the scheduler time-slices more live sequences than the ring
    # holds; off = today's all-resident behavior, bit-identical
    kv_tier: bool = False
    # cold-page codec: "none" stores f32 (evict+restore is bit-exact),
    # "blockwise4bit" quantizes pages 8x smaller (restore error bounded,
    # test-pinned)
    kv_tier_codec: Literal["none", "blockwise4bit"] = "none"
    # host tier budget: paused pages + prefix entries it may hold at once
    kv_host_slots: int = 32

    @field_validator("prefill_buckets", mode="before")
    @classmethod
    def _coerce_buckets(cls, v: Any) -> Any:
        if isinstance(v, str):
            return [int(x) for x in v.split(",") if x.strip()]
        return v

    @model_validator(mode="after")
    def _geometry(self):
        if self.max_batch < 1:
            raise ValueError("serve.max_batch must be >= 1")
        if not self.prefill_buckets:
            raise ValueError("serve.prefill_buckets must be non-empty")
        if min(self.prefill_buckets) < 1:
            raise ValueError("serve.prefill_buckets must be positive")
        if max(self.prefill_buckets) > self.max_context:
            raise ValueError(
                "largest prefill bucket exceeds serve.max_context "
                "(a prompt must fit its slot's KV page)"
            )
        if self.spec_decode_k < 0:
            raise ValueError("serve.spec_decode_k must be >= 0")
        if self.draft_layers < 0:
            raise ValueError("serve.draft_layers must be >= 0")
        if self.spec_decode_k + 1 > self.max_context:
            raise ValueError(
                "serve.spec_decode_k + 1 exceeds serve.max_context "
                "(a speculative tail must fit the ring)"
            )
        if self.kv_host_slots < 1:
            raise ValueError("serve.kv_host_slots must be >= 1")
        return self


class FleetConfig(BaseModel):
    """Serving fleet (opendiloco_tpu/fleet): N replica engines fed by
    delta pushes from the trainer's masters, behind one front-end router."""

    model_config = ConfigDict(extra="forbid")

    enabled: bool = False
    replicas: int = 2
    host: str = "127.0.0.1"
    port: int = 0  # router ingress; 0 -> ephemeral
    # run replicas inside the trainer process (tests/benches) instead of
    # as `python -m opendiloco_tpu.fleet.replica` subprocesses
    inprocess: bool = False
    # delta-push channel: per-fragment master deltas in this codec with
    # per-replica error feedback; a full state-codec keyframe every
    # keyframe_every epochs re-pins bit-exactness and onboards
    # (re)joining replicas without history replay
    codec: Literal["blockwise4bit", "topk"] = "blockwise4bit"
    fragments: int = 4
    keyframe_every: int = 8
    error_feedback: bool = True
    push_interval_s: float = 0.25
    # health bound: a replica whose serving weights lag the trainer by
    # MORE than this many outer rounds reports itself stale and the
    # router stops preferring it
    max_stale_rounds: int = 2
    # per-replica engine geometry (same semantics as ServeConfig)
    max_batch: int = 4
    max_context: int = 256
    prefill_buckets: list[int] = [32, 128]
    max_queue: int = 1024
    prefix_cache: bool = True
    # fleet prefix-cache directory: replicas advertise host-tier resident
    # prefix hashes on their health frames and the router routes matching
    # prompts to a holder, so a fleet-shared system prompt is prefilled
    # once fleet-wide. Turning it on also arms each replica's host KV
    # tier (the advertised entries must outlive slot churn).
    prefix_directory: bool = False
    # SLO-driven autoscaling (fleet/autoscaler.py): a closed control loop
    # that scales replica count against the declared SLO and replaces
    # dead replicas without operator action. `replicas` becomes the
    # initial size; the loop holds it within [min_replicas, max_replicas].
    autoscale: bool = False
    # declared SLO: worst ready-replica client p99 the loop defends
    # (0 disables the latency signal) and the per-replica queue depth
    # above which traffic is considered backlogged
    slo_p99_ms: float = 0.0
    slo_queue_depth: int = 8
    min_replicas: int = 1
    max_replicas: int = 8
    # pre-keyframed standby replicas (push channel attached, router not):
    # scale-up adopts one instantly instead of cold-booting
    warm_spares: int = 0
    # control-loop damping: seconds between scale actions, evaluation
    # cadence, and consecutive breached/clear evaluations required before
    # scaling up/down (hysteresis — up reacts faster than down)
    scale_cooldown_s: float = 5.0
    scale_eval_interval_s: float = 0.5
    scale_up_evals: int = 2
    scale_down_evals: int = 8

    @field_validator("prefill_buckets", mode="before")
    @classmethod
    def _coerce_buckets(cls, v: Any) -> Any:
        if isinstance(v, str):
            return [int(x) for x in v.split(",") if x.strip()]
        return v

    @model_validator(mode="after")
    def _geometry(self):
        if self.replicas < 1:
            raise ValueError("fleet.replicas must be >= 1")
        if self.fragments < 1:
            raise ValueError("fleet.fragments must be >= 1")
        if self.keyframe_every < 1:
            raise ValueError("fleet.keyframe_every must be >= 1")
        if self.max_stale_rounds < 0:
            raise ValueError("fleet.max_stale_rounds must be >= 0")
        if not self.prefill_buckets:
            raise ValueError("fleet.prefill_buckets must be non-empty")
        if max(self.prefill_buckets) > self.max_context:
            raise ValueError(
                "largest fleet prefill bucket exceeds fleet.max_context"
            )
        if self.min_replicas < 1:
            raise ValueError("fleet.min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                "fleet.max_replicas must be >= fleet.min_replicas"
            )
        if self.warm_spares < 0:
            raise ValueError("fleet.warm_spares must be >= 0")
        if self.slo_p99_ms < 0:
            raise ValueError("fleet.slo_p99_ms must be >= 0")
        if self.slo_queue_depth < 1:
            raise ValueError("fleet.slo_queue_depth must be >= 1")
        if self.scale_up_evals < 1 or self.scale_down_evals < 1:
            raise ValueError("fleet.scale_*_evals must be >= 1")
        return self


class Config(BaseModel):
    """Top-level training config (reference: open_diloco/train_fsdp.py:104-129)."""

    model_config = ConfigDict(extra="forbid")

    # model
    # "auto" resolves per-backend at trainer build: the Pallas flash kernel
    # on TPU (measured +20% tokens/sec over XLA attention on v5e), plain XLA
    # attention elsewhere; "ring" (sequence parallel) stays opt-in
    attn_implementation: Literal["auto", "xla", "pallas", "ring"] = "auto"
    path_model: str = "configs/config_150m.json"
    # rematerialization policy: false/"none" (save everything), true/"full"
    # (reference-style per-layer checkpointing), or "dots" (save MXU outputs,
    # recompute elementwise -- near-full memory savings without the extra
    # matmul forward)
    remat: Union[bool, Literal["none", "full", "dots", "dots_all"]] = True
    # fused lm-head+xent Pallas kernel; None = auto (on for TPU dense models,
    # off elsewhere -- the kernel avoids the [tokens, vocab] f32 logits in HBM)
    fused_loss: Optional[bool] = None
    # layer-scan unroll width; None = auto (full unroll on TPU for dense
    # stacks <= 16 layers -- measured +6.8% tok/s on the HBM-bound 150m
    # step -- and 1 elsewhere)
    scan_unroll: Optional[int] = None
    # sp+pp cannot run ring attention; with this opt-in the sp axis shards
    # activations only (full-sequence attention per device). Without it the
    # combination is an error rather than a silent downgrade.
    allow_sp_activation_sharding: bool = False

    # data
    dataset_name_or_paths: str = "allenai/c4"
    dataset_streaming: bool = True
    fake_data: bool = False
    # "random" = uniform tokens (entropy-floor loss, plumbing only);
    # "ramp" = learnable consecutive-token ramps (convergence-oracle
    # stream) so loss-descent assertions on fake data are meaningful
    fake_data_mode: str = "random"
    tokenizer_name: str = "mistralai/Mistral-7B-v0.1"
    seq_length: int = 1024
    num_workers: int = 1  # host dataloading threads
    prefetch_depth: int = 2  # async H2D read-ahead batches (0 disables)

    # optimization (train_fsdp.py:250-260)
    lr: float = 4e-4
    weight_decay: float = 0.1
    adam_betas: tuple[float, float] = (0.9, 0.95)
    warmup_steps: int = 1000
    total_steps: int = 88_000
    max_grad_norm: float = 1.0
    per_device_train_batch_size: int = 32
    total_batch_size: int = 512

    # precision: bf16-mixed = bf16 compute / f32 master params (TPU default;
    # the reference itself recommends bf16 over fp16, README.md:295)
    precision: Literal["bf16-mixed", "fp16-mixed", "fp32"] = "bf16-mixed"

    # in-worker parallelism (utils.py:138-152 equivalents)
    sharding_strategy: Literal[
        "NO_SHARD", "SHARD_GRAD_OP", "FULL_SHARD", "HYBRID_SHARD", "HYBRID_SHARD_ZERO2"
    ] = "NO_SHARD"
    # mesh axis sizes; None -> infer from available devices
    dp_size: Optional[int] = None
    fsdp_size: Optional[int] = None
    tp_size: int = 1
    sp_size: int = 1  # sequence/context parallel (ring attention)
    pp_size: int = 1  # pipeline stages (GPipe schedule over the layer stack)
    ep_size: int = 1  # expert parallel (MoE expert dim over the ep axis)

    # observability
    project: str = "opendiloco_tpu"
    metric_logger_type: Literal["wandb", "dummy", "jsonl"] = "wandb"
    log_activations_steps: Optional[int] = None
    # periodic evaluation on the validation split (train_diloco_torch.py:87-110)
    eval_interval: Optional[int] = None
    eval_batches: int = 16
    # jax.profiler trace of steps [profile_start, profile_start+profile_steps)
    profile_dir: Optional[str] = None
    profile_start: int = 10
    profile_steps: int = 5

    # multi-host inner loop (one TPU slice spanning hosts):
    # jax.distributed.initialize() before any jax use (train_fsdp.py:70-72
    # NCCL-group equivalent). coordinator "host:port"; ranks from env when None
    multihost: bool = False
    coordinator_address: Optional[str] = None
    num_processes: Optional[int] = None
    process_id: Optional[int] = None

    ckpt: CkptConfig = CkptConfig()
    diloco: Optional[DilocoConfig] = None  # None -> plain data-parallel mode
    # in-process serving plane; None or enabled=False -> training only
    serve: Optional[ServeConfig] = None
    # serving fleet (replica galaxy + delta-push sync + router); None or
    # enabled=False -> no fleet
    fleet: Optional[FleetConfig] = None

    @field_validator("adam_betas", mode="before")
    @classmethod
    def _coerce_betas(cls, v: Any) -> Any:
        if isinstance(v, str):
            return tuple(float(x) for x in v.split(","))
        return v


# ---------------------------------------------------------------------------
# argv parsing: nested dotted flags + --no-x booleans
# ---------------------------------------------------------------------------


def _set_nested(tree: dict, dotted: str, value: Any) -> None:
    keys = dotted.split(".")
    node = tree
    for k in keys[:-1]:
        node = node.setdefault(k, {})
        if not isinstance(node, dict):
            raise ValueError(f"flag {dotted!r} conflicts with earlier scalar flag")
    leaf = keys[-1]
    node[leaf] = value  # repeated flags: last one wins


def parse_argv(argv: Optional[list[str]] = None) -> dict:
    """Parse ``--a.b value`` / ``--no-a.b`` style flags into a nested dict.

    Semantics follow the reference's pydantic_config ``parse_argv``
    (train_fsdp.py:525): dashes in key names normalize to underscores,
    ``--no-flag`` sets False, a bare ``--flag`` followed by another flag (or
    end of argv) sets True, repeated flags keep the last value (so test
    harnesses can append overrides), and list-valued fields take
    comma-separated strings.
    """
    if argv is None:
        argv = sys.argv[1:]
    tree: dict = {}
    i = 0
    while i < len(argv):
        tok = argv[i]
        if not tok.startswith("--"):
            raise ValueError(f"unexpected positional argument {tok!r}")
        key = tok[2:]
        value: Any
        if "=" in key:
            key, value = key.split("=", 1)
            i += 1
        elif key.startswith("no-") or key.startswith("no_"):
            key, value = key[3:], False
            i += 1
        elif i + 1 >= len(argv) or argv[i + 1].startswith("--"):
            value = True
            i += 1
        else:
            value = argv[i + 1]
            i += 2
        key = ".".join(part.replace("-", "_") for part in key.split("."))
        _set_nested(tree, key, value)
    return tree
