"""AST pass over jit/donation sites.

With ``donate_argnums`` the XLA runtime may reuse the donated buffer for
the output; the Python array object still exists but its device memory is
gone. Reading it afterwards returns garbage or raises -- under a 500-step
inner phase, usually minutes after the actual bug. Three checks:

  use-after-donate      a caller passes ``x`` (a local or ``self.attr``)
                        at a donated position, then loads the same
                        expression later in the function without rebinding
                        it first. The idiomatic safe shape
                        ``x = f(x, ...)`` rebinds in the same statement.
  jit-captures-self     a function passed to jax.jit whose body references
                        ``self`` without taking it as a parameter: the
                        closure freezes mutable object state at trace time
                        (and silently stops tracking it afterwards).
  unhashable-static     a call site passes a list/dict/set literal at a
                        ``static_argnums``/``static_argnames`` position --
                        jit requires hashable statics and fails at runtime.

The pass is intra-module and name-based: donating callables are resolved
by the bare name they are bound to (``_apply_fused``, ``self._insert``),
which matches how every site in this repo is written.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable, Optional

from opendiloco_tpu.analysis.common import (
    Finding,
    dotted,
    fold_const,
    iter_py_files,
    parse_file,
    suppressed,
)


@dataclasses.dataclass
class _Jitted:
    name: str  # bound name, without any self./module prefix
    donate: tuple[int, ...]
    static_nums: tuple[int, ...]
    static_names: tuple[str, ...]
    line: int


def _tuple_of_ints(node: Optional[ast.AST]) -> tuple[int, ...]:
    v = fold_const(node) if not isinstance(node, (ast.Tuple, ast.List)) else None
    if isinstance(v, int):
        return (v,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            ev = fold_const(e)
            if isinstance(ev, int):
                out.append(ev)
        return tuple(out)
    return ()


def _tuple_of_strs(node: Optional[ast.AST]) -> tuple[str, ...]:
    v = fold_const(node) if not isinstance(node, (ast.Tuple, ast.List)) else None
    if isinstance(v, str):
        return (v,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(
            e.value for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        )
    return ()


def _jit_call_kwargs(call: ast.Call) -> Optional[dict]:
    """kwargs of a jax.jit(...) or functools.partial(jax.jit, ...) call,
    else None when the call isn't a jit wrapper."""
    fn = dotted(call.func)
    if fn in ("jax.jit", "jit"):
        return {kw.arg: kw.value for kw in call.keywords if kw.arg}
    if fn in ("functools.partial", "partial") and call.args:
        inner = dotted(call.args[0])
        if inner in ("jax.jit", "jit"):
            return {kw.arg: kw.value for kw in call.keywords if kw.arg}
    return None


def _jitted_from_call(bound_name: str, call: ast.Call) -> Optional[_Jitted]:
    kw = _jit_call_kwargs(call)
    if kw is None:
        return None
    return _Jitted(
        bound_name,
        _tuple_of_ints(kw.get("donate_argnums")),
        _tuple_of_ints(kw.get("static_argnums")),
        _tuple_of_strs(kw.get("static_argnames")),
        call.lineno,
    )


def _target_key(node: ast.AST) -> Optional[str]:
    """Canonical tracking key for a donated argument expression: a bare
    name ('avg') or a self attribute ('self.cache_k')."""
    if isinstance(node, ast.Name):
        return node.id
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return f"self.{node.attr}"
    return None


def _collect_jitted(tree: ast.Module) -> dict[str, _Jitted]:
    """name -> _Jitted for every decorator / assignment jit site."""
    out: dict[str, _Jitted] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    j = _jitted_from_call(node.name, dec)
                    if j is not None:
                        out[node.name] = j
        elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            j = None
            kw = _jit_call_kwargs(node.value)
            if kw is not None:
                for t in node.targets:
                    key = _target_key(t)
                    if key is not None:
                        j = _jitted_from_call(key.split(".")[-1], node.value)
                        if j is not None:
                            out[j.name] = j
    return out


def _jit_wrapped_defs(tree: ast.Module) -> list[tuple[str, int]]:
    """(wrapped function name, jit site line) for every jax.jit(f, ...) /
    @partial(jax.jit, ...) application, to check self capture."""
    sites: list[tuple[str, int]] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _jit_call_kwargs(node) is not None:
            args = node.args
            if dotted(node.func) in ("functools.partial", "partial"):
                args = args[1:]
            for a in args:
                if isinstance(a, ast.Name):
                    sites.append((a.id, node.lineno))
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call) and _jit_call_kwargs(dec) is not None:
                    sites.append((node.name, dec.lineno))
    return sites


def _funcs(tree: ast.Module):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _check_use_after_donate(
    tree: ast.Module, jitted: dict[str, _Jitted], rel: str, lines: list[str]
) -> list[Finding]:
    findings: list[Finding] = []
    for fn in _funcs(tree):
        # donated expression key -> line of the donating call
        dead: dict[str, int] = {}

        class _V(ast.NodeVisitor):
            def visit_If(self, node: ast.If) -> None:
                # branches are mutually exclusive: each starts from the
                # pre-state; afterwards an expr is dead if either branch
                # donated it (may-analysis)
                self.visit(node.test)
                pre = dict(dead)
                for s in node.body:
                    self.visit(s)
                post_body = dict(dead)
                dead.clear()
                dead.update(pre)
                for s in node.orelse:
                    self.visit(s)
                dead.update(post_body)

            def visit_FunctionDef(self, node) -> None:
                # nested defs are their own scope (each gets its own _V
                # walk from _funcs); only descend into the root function
                if node is fn:
                    self.generic_visit(node)

            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_Call(self, call: ast.Call) -> None:
                self.generic_visit(call)
                name = dotted(call.func)
                short = name.split(".")[-1] if name else None
                j = jitted.get(short or "")
                if j is None:
                    return
                for pos in j.donate:
                    if pos < len(call.args):
                        key = _target_key(call.args[pos])
                        if key is not None:
                            dead[key] = call.lineno

            def visit_Assign(self, node: ast.Assign) -> None:
                # RHS first (donating call / loads), then targets revive
                self.visit(node.value)
                for t in node.targets:
                    for el in ast.walk(t):
                        key = _target_key(el)
                        if key is not None:
                            dead.pop(key, None)

            def visit_AugAssign(self, node: ast.AugAssign) -> None:
                self.visit(node.value)
                self._load(node.target)

            def visit_Name(self, node: ast.Name) -> None:
                if isinstance(node.ctx, ast.Load):
                    self._load(node)

            def visit_Attribute(self, node: ast.Attribute) -> None:
                if isinstance(node.ctx, ast.Load) and _target_key(node):
                    self._load(node)
                else:
                    self.generic_visit(node)

            def _load(self, node: ast.AST) -> None:
                key = _target_key(node)
                if key is None:
                    return
                at = dead.get(key)
                if at is not None and not suppressed(
                    lines, node.lineno, "use-after-donate"
                ):
                    findings.append(
                        Finding(
                            "use-after-donate", rel, node.lineno,
                            f"`{key}` was donated to a jit'd function on "
                            f"line {at} (its device buffer may be reused "
                            "for the output) but is read again here -- "
                            "rebind it from the call's result or drop "
                            "the donation",
                        )
                    )
                    dead.pop(key, None)  # one finding per donation

        _V().visit(fn)
    return findings


def _check_self_capture(
    tree: ast.Module, rel: str, lines: list[str]
) -> list[Finding]:
    findings: list[Finding] = []
    defs = {
        n.name: n
        for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    for name, line in _jit_wrapped_defs(tree):
        fn = defs.get(name)
        if fn is None:
            continue
        params = {a.arg for a in fn.args.args + fn.args.kwonlyargs}
        if "self" in params:
            continue
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and node.id == "self":
                if not suppressed(lines, line, "jit-captures-self"):
                    findings.append(
                        Finding(
                            "jit-captures-self", rel, line,
                            f"jit of `{name}` closes over `self`: object "
                            "state is frozen into the trace and mutations "
                            "after compile are silently ignored -- pass "
                            "the state as an argument",
                        )
                    )
                break
    return findings


def _check_unhashable_static(
    tree: ast.Module, jitted: dict[str, _Jitted], rel: str, lines: list[str]
) -> list[Finding]:
    findings: list[Finding] = []
    unhashable = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp, ast.SetComp)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func)
        short = name.split(".")[-1] if name else None
        j = jitted.get(short or "")
        if j is None:
            continue
        flagged: list[tuple[int, str]] = []
        for pos in j.static_nums:
            if pos < len(node.args) and isinstance(node.args[pos], unhashable):
                flagged.append((node.args[pos].lineno, f"position {pos}"))
        for kw in node.keywords:
            if kw.arg in j.static_names and isinstance(kw.value, unhashable):
                flagged.append((kw.value.lineno, f"`{kw.arg}`"))
        for line, what in flagged:
            if not suppressed(lines, line, "unhashable-static"):
                findings.append(
                    Finding(
                        "unhashable-static", rel, line,
                        f"static argument {what} of `{j.name}` is an "
                        "unhashable literal -- jit static args must be "
                        "hashable (use a tuple / frozen value)",
                    )
                )
    return findings


def check(roots: Iterable[str], relto: Optional[str] = None) -> list[Finding]:
    import os

    findings: list[Finding] = []
    for path in iter_py_files(roots):
        tree, lines = parse_file(path)
        if tree is None:
            continue
        rel = os.path.relpath(path, relto) if relto else path
        jitted = _collect_jitted(tree)
        if jitted:
            findings += _check_use_after_donate(tree, jitted, rel, lines)
            findings += _check_unhashable_static(tree, jitted, rel, lines)
        findings += _check_self_capture(tree, rel, lines)
    return findings
