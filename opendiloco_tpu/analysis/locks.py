"""Static lock-order extraction + acyclicity check.

Each ``threading.Lock()``/``RLock()``/``Condition()`` bound at class or
module level is a named lock node (``bulk.BulkServer._lock``). A
``Condition(existing_lock)`` aliases the lock it wraps -- acquiring the
condition IS acquiring that lock. Edges:

  - syntactic nesting: ``with A:`` containing ``with B:`` adds A -> B
  - one level of interprocedural closure: a ``with A:`` body calling a
    method known to acquire B adds A -> B (methods resolved by bare name
    across the scanned modules; same-name collisions are unioned, which
    over-approximates -- safe direction for a deadlock check)

A cycle in the resulting graph is a potential deadlock: two threads
taking the locks in opposite orders can block forever. The runtime
witness (analysis/lockcheck.py, ``ODTP_LOCKCHECK=1``) checks the same
property against actually-executed acquisition orders.
"""

from __future__ import annotations

import ast
import os
from typing import Iterable, Optional

from opendiloco_tpu.analysis.common import (
    Finding,
    dotted,
    iter_py_files,
    parse_file,
    suppressed,
)

_LOCK_CTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "Lock", "RLock", "Condition",
}

# bare method names too generic to resolve across modules: `d.get(k)` on a
# dict would otherwise alias _BufferPool.get and fabricate edges. Their
# real orderings still surface through syntactic `with` nesting.
_GENERIC_METHODS = frozenset({
    "get", "pop", "add", "put", "release", "append", "update", "setdefault",
    "items", "keys", "values", "clear", "set", "wait", "discard", "remove",
    "acquire", "send", "close", "start", "join", "copy", "extend", "insert",
})


def _lock_ctor(call: ast.AST) -> Optional[str]:
    if isinstance(call, ast.Call) and dotted(call.func) in _LOCK_CTORS:
        return dotted(call.func).split(".")[-1]
    return None


class _Module:
    def __init__(self, path: str, tree: ast.Module, lines: list[str]):
        self.path = path
        self.mod = os.path.splitext(os.path.basename(path))[0]
        self.tree = tree
        self.lines = lines
        # expression key ("self._lock" / "_rate_lock") -> canonical lock id
        self.locks: dict[tuple[Optional[str], str], str] = {}
        self._collect_locks()

    def _collect_locks(self) -> None:
        # module-level locks
        for stmt in self.tree.body:
            self._maybe_lock(stmt, cls=None)
        # class-attribute locks assigned in any method (self.x = Lock())
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ClassDef):
                for sub in ast.walk(node):
                    self._maybe_lock(sub, cls=node.name)

    def _maybe_lock(self, stmt: ast.AST, cls: Optional[str]) -> None:
        if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            return
        value = stmt.value
        if value is None:
            return
        targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
        ctor = _lock_ctor(value)
        if ctor is None:
            return
        alias: Optional[str] = None
        if ctor == "Condition" and value.args:
            # Condition(self.lock): same underlying lock, alias it
            inner = self._expr_key(value.args[0])
            if inner is not None:
                alias = self.locks.get((cls, inner)) or self.locks.get((None, inner))
        for t in targets:
            key = self._expr_key(t)
            if key is None:
                continue
            scope = cls if key.startswith("self.") else None
            lock_id = alias or f"{self.mod}.{cls + '.' if scope else ''}{key.removeprefix('self.')}"
            self.locks[(scope, key)] = lock_id
            if scope is not None:
                # methods of the same class refer to it the same way; also
                # index classless so nested helpers resolve approximately
                self.locks.setdefault((None, key), lock_id)

    @staticmethod
    def _expr_key(node: ast.AST) -> Optional[str]:
        d = dotted(node)
        if d is None:
            return None
        if d.startswith("self."):
            return d
        if "." not in d:
            return d
        return None

    def resolve(self, node: ast.AST, cls: Optional[str]) -> Optional[str]:
        key = self._expr_key(node)
        if key is None:
            return None
        return self.locks.get((cls, key)) or self.locks.get((None, key))


def _walk_withs(
    m: _Module,
    body: list[ast.stmt],
    cls: Optional[str],
    held: tuple[str, ...],
    edges: dict[tuple[str, str], tuple[str, int]],
    acquires: Optional[dict[str, set[str]]],
    calls_under: Optional[dict[str, set[tuple[str, str, int]]]],
    fn_name: str,
) -> None:
    for stmt in body:
        if isinstance(stmt, ast.With):
            new_held = held
            for item in stmt.items:
                lock = m.resolve(item.context_expr, cls)
                if lock is not None:
                    for h in new_held:
                        if h != lock:
                            edges.setdefault((h, lock), (m.path, stmt.lineno))
                    new_held = new_held + (lock,)
                    if acquires is not None:
                        acquires.setdefault(fn_name, set()).add(lock)
            _walk_withs(m, stmt.body, cls, new_held, edges, acquires, calls_under, fn_name)
            continue
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _walk_withs(m, stmt.body, cls, (), edges, acquires, calls_under, stmt.name)
            continue
        if isinstance(stmt, ast.ClassDef):
            _walk_withs(m, stmt.body, stmt.name, (), edges, acquires, calls_under, fn_name)
            continue
        # record method calls made while holding locks (one-level closure)
        if held and calls_under is not None:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call):
                    name = dotted(node.func)
                    if name is not None:
                        short = name.split(".")[-1]
                        if short in _GENERIC_METHODS:
                            continue
                        for h in held:
                            calls_under.setdefault(short, set()).add(
                                (h, m.path, node.lineno)
                            )
        # recurse into nested blocks, with-held state preserved
        for field in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, field, None)
            if sub:
                _walk_withs(m, sub, cls, held, edges, acquires, calls_under, fn_name)
        for handler in getattr(stmt, "handlers", []) or []:
            _walk_withs(m, handler.body, cls, held, edges, acquires, calls_under, fn_name)


def _find_cycles(edges: dict[tuple[str, str], tuple[str, int]]) -> list[list[str]]:
    graph: dict[str, set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    cycles: list[list[str]] = []
    color: dict[str, int] = {}
    stack: list[str] = []

    def dfs(v: str) -> None:
        color[v] = 1
        stack.append(v)
        for w in sorted(graph[v]):
            if color.get(w, 0) == 0:
                dfs(w)
            elif color.get(w) == 1:
                cycles.append(stack[stack.index(w):] + [w])
        stack.pop()
        color[v] = 2

    for v in sorted(graph):
        if color.get(v, 0) == 0:
            dfs(v)
    return cycles


def check(roots: Iterable[str], relto: Optional[str] = None) -> list[Finding]:
    modules: list[_Module] = []
    for path in iter_py_files(roots):
        tree, lines = parse_file(path)
        if tree is None:
            continue
        modules.append(_Module(path, tree, lines))

    edges: dict[tuple[str, str], tuple[str, int]] = {}
    acquires: dict[str, set[str]] = {}
    calls_under: dict[str, set[tuple[str, str, int]]] = {}
    for m in modules:
        _walk_withs(m, m.tree.body, None, (), edges, acquires, calls_under, "<module>")

    # one-level interprocedural closure: holding H while calling f, where f
    # is known to acquire L, orders H before L
    for fname, sites in calls_under.items():
        for lock in acquires.get(fname, ()):
            for held, path, line in sites:
                if held != lock:
                    edges.setdefault((held, lock), (path, line))

    findings: list[Finding] = []
    lines_cache: dict[str, list[str]] = {m.path: m.lines for m in modules}
    for cycle in _find_cycles(edges):
        # anchor the finding at the edge closing the cycle
        a, b = cycle[-2], cycle[-1]
        path, line = edges.get((a, b), ("", 0))
        rel = os.path.relpath(path, relto) if (relto and path) else path
        if path and suppressed(lines_cache.get(path, []), line, "lock-order"):
            continue
        findings.append(
            Finding(
                "lock-order", rel or "<graph>", line,
                "lock acquisition cycle " + " -> ".join(cycle)
                + " -- two threads taking these in opposite orders deadlock; "
                "break the cycle or pin a global order",
            )
        )
    return findings
