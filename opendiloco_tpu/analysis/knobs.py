"""The declarative registry of every ``ODTP_*`` environment knob.

This table is the single authority: the knob_check pass fails the build
when code reads a knob missing here (undeclared), when a registered knob
is never read anywhere (dead), or when a read site's literal default
disagrees with the registered default (mismatch). The README knob table
is generated from this registry (``scripts/odtp_lint.py --write-knob-table``),
so docs cannot drift from code either.

``default`` is the exact fallback the code uses when the variable is
unset; ``""`` means unset-is-off/derived (the ``doc_default`` column says
what that behaves like). Keep entries sorted by (subsystem, name).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class Knob:
    name: str
    type: str  # bool | int | float | str | spec | path
    default: str  # canonical code default ("" = unset)
    subsystem: str  # transport | diloco | chaos | obs | serve | fleet | model | bench | analysis
    doc: str  # one line, lands verbatim in the README table
    doc_default: str = ""  # display override when default="" reads poorly


KNOBS: tuple[Knob, ...] = (
    # -- analysis -------------------------------------------------------------
    Knob("ODTP_LOCKCHECK", "bool", "", "analysis",
         "`1` wraps `threading` locks created by this package in the runtime "
         "lock-order witness: per-thread acquisition order is recorded and any "
         "cycle in the global order graph raises immediately instead of "
         "deadlocking. Zero-cost when unset.", doc_default="off"),
    # -- bench ----------------------------------------------------------------
    Knob("ODTP_ASYNC_BENCH_OUT", "path", "", "bench",
         "Output path override for `bench_outer.py --async` "
         "(default `ASYNC_BENCH.json` in the repo root).",
         doc_default="repo artifact"),
    Knob("ODTP_AUTOSCALE_BENCH_OUT", "path", "", "bench",
         "Output path override for `scripts/fleet_autoscale_bench.py` "
         "(default `AUTOSCALE_BENCH.json` in the repo root).",
         doc_default="repo artifact"),
    Knob("ODTP_BOUNDARY_BENCH_OUT", "path", "", "bench",
         "Output path override for `bench_outer.py --boundary` "
         "(default `BOUNDARY_BENCH.json` in the repo root).",
         doc_default="repo artifact"),
    Knob("ODTP_COMPRESS_BENCH_OUT", "path", "", "bench",
         "Output path override for `bench_outer.py --compress`.",
         doc_default="repo artifact"),
    Knob("ODTP_CONV_STEPS", "int", "300", "bench",
         "Inner steps per arm in `scripts/convergence_evidence.py`."),
    Knob("ODTP_DECODE_BENCH_OUT", "path", "", "bench",
         "Output path override for `scripts/serve_bench.py --decode`.",
         doc_default="repo artifact"),
    Knob("ODTP_GOSSIP_BENCH_OUT", "path", "", "bench",
         "Output path override for `bench_outer.py --gossip`.",
         doc_default="repo artifact"),
    Knob("ODTP_HETERO_BENCH_OUT", "path", "", "bench",
         "Output path override for `bench_outer.py --hetero`.",
         doc_default="repo artifact"),
    Knob("ODTP_HIER_BENCH_OUT", "path", "", "bench",
         "Output path override for `bench_outer.py --hier`.",
         doc_default="repo artifact"),
    Knob("ODTP_LIVE_TRAIN_STEPS", "int", "1500", "bench",
         "Step budget for `scripts/live_train.py`."),
    Knob("ODTP_OUTER_BENCH_OUT", "path", "", "bench",
         "Output path override for the `bench_outer.py` all-reduce sweep.",
         doc_default="repo artifact"),
    Knob("ODTP_SERVE_BENCH_OUT", "path", "", "bench",
         "Output path override for `scripts/serve_bench.py`.",
         doc_default="repo artifact"),
    Knob("ODTP_SERVE_FLEET_BENCH_OUT", "path", "", "bench",
         "Output path override for `scripts/serve_fleet_bench.py`.",
         doc_default="repo artifact"),
    Knob("ODTP_STREAM_BENCH_OUT", "path", "", "bench",
         "Output path override for `bench_outer.py --stream`.",
         doc_default="repo artifact"),
    # -- chaos ----------------------------------------------------------------
    Knob("ODTP_CHAOS", "spec", "", "chaos",
         "Seedable fault-injection spec, e.g. "
         "`seed=7;drop_conn=0.05;delay_ms=20..200;kill_worker=r3:w5`. "
         "Unset = plane off, zero cost.", doc_default="off"),
    Knob("ODTP_RETRY_BASE_S", "float", "0.5", "chaos",
         "Base of the bounded exponential backoff between outer-round retries."),
    Knob("ODTP_RETRY_CAP_S", "float", "15", "chaos",
         "Cap of the outer-round retry backoff, seconds."),
    Knob("ODTP_ROUND_RETRIES", "int", "3", "chaos",
         "How many times a failed outer round re-forms before the step "
         "raises (callers may pass a different programmatic default)."),
    # -- diloco ---------------------------------------------------------------
    Knob("ODTP_ASYNC_DECAY", "float", "0.5", "diloco",
         "Geometric discount on an async gossip partner's mixing weight "
         "per epoch of staleness distance (weight = 0.5 * decay^d — "
         "exactly the pair average at distance 0)."),
    Knob("ODTP_ASYNC_PATIENCE_S", "float", "2.0", "diloco",
         "How long an async-gossip worker waits for ANY in-window partner "
         "before stepping alone (self-round policy) — bounds what a fast "
         "worker can lose to a slow galaxy per round."),
    Knob("ODTP_ASYNC_STALENESS", "int", "0", "diloco",
         "Bounded-staleness window (outer epochs) for fully asynchronous "
         "gossip rounds: workers free-run their inner loops and mix with "
         "any partner within this epoch distance. `0` keeps the lockstep "
         "per-(epoch, fragment) pairing."),
    Knob("ODTP_GOSSIP_LINK_BIAS", "float", "1.0", "diloco",
         "Exponent on the normalized pair capacity when gossip draws "
         "partners (linkstate-aware pairing); `0` disables link awareness, "
         "higher prefers fast pairs harder."),
    Knob("ODTP_GOSSIP_LINK_FLOOR", "float", "0.25", "diloco",
         "Minimum relative draw weight for the slowest gossip pair — keeps "
         "every pair reachable under any bias (never starved; NoLoCo "
         "mixing needs connectivity)."),
    Knob("ODTP_GOSSIP_SEED", "int", "0", "diloco",
         "Shared pairing-PRNG seed for gossip outer rounds; must match "
         "galaxy-wide (every worker derives the same pairing locally)."),
    Knob("ODTP_GOSSIP_SELF_ROUND", "str", "nesterov", "diloco",
         "Odd-galaxy self-pair policy: `nesterov` steps on own state "
         "(plain DiLoCo step, no wire), `hold` skips the round entirely."),
    Knob("ODTP_STATE_CODEC", "str", "", "diloco",
         "Codec override for onboarding/serve state payloads (`none` "
         "restores raw fp32; default: configured codec when fp16-family, "
         "else fp16).", doc_default="derived"),
    Knob("ODTP_TOPK_DENSITY", "float", "0.03125", "diloco",
         "Fraction of largest-|x| elements the `topk` codec keeps (1/32 "
         "default ~= 0.25 B/elem on the wire)."),
    # -- fleet ----------------------------------------------------------------
    Knob("ODTP_FLEET_CODEC", "str", "", "fleet",
         "Delta-push codec override for the serving fleet "
         "(`blockwise4bit` or `topk`); keyframes always ride the "
         "onboarding state codec.", doc_default="config"),
    Knob("ODTP_FLEET_KEYFRAME_EVERY", "int", "", "fleet",
         "Full-snapshot keyframe cadence override (outer epochs) for the "
         "fleet delta publisher; keyframes re-pin replica bit-exactness "
         "and onboard (re)joining replicas.", doc_default="config"),
    Knob("ODTP_PREFIX_DIRECTORY", "bool", "", "fleet",
         "`1` arms the fleet prefix-cache directory: replicas advertise "
         "host-tier prefix hashes on health frames and the router routes "
         "matching prompts to a holder (shared system prompt prefilled "
         "once fleet-wide). Arms each replica's KV tier.",
         doc_default="config"),
    Knob("ODTP_FLEET_PUSH_INTERVAL_S", "float", "", "fleet",
         "Seconds between fleet pusher wake-ups per replica (each wake-up "
         "ships pending delta/keyframe frames or a staleness ping).",
         doc_default="config"),
    Knob("ODTP_FLEET_SCALE_COOLDOWN_S", "float", "", "fleet",
         "Minimum seconds between autoscaler scaling actions (replacement "
         "of dead replicas and spare replenishment are never "
         "cooldown-gated).", doc_default="config"),
    Knob("ODTP_FLEET_SLO_P99_MS", "float", "", "fleet",
         "Serving latency SLO for the fleet autoscaler: worst-replica "
         "decode p99 above this (or queue depth above "
         "`fleet.slo_queue_depth`) is a breach that scales the fleet up. "
         "0 disables the latency term.", doc_default="config"),
    Knob("ODTP_FLEET_WARM_SPARES", "int", "", "fleet",
         "Warm-spare pool size: replicas kept pre-keyframed on the push "
         "channel but unregistered with the router, so scale-up is a "
         "promotion (mailbox adoption), not a cold boot.",
         doc_default="config"),
    # -- model ----------------------------------------------------------------
    Knob("ODTP_SCAN_UNROLL", "int", "", "model",
         "Overrides the scan-over-layers unroll factor (experiments and "
         "`scripts/aot_roofline.py`; cost analysis needs the stack unrolled).",
         doc_default="config"),
    # -- obs ------------------------------------------------------------------
    Knob("ODTP_OBS", "bool", "", "obs",
         "`1` arms the tracing/metrics plane (and with it the flight "
         "recorder, galaxy overseer and anomaly watchdogs). Unset = "
         "zero-cost no-op.", doc_default="off"),
    Knob("ODTP_OBS_BLACKBOX_CAP", "int", "512", "obs",
         "Flight-recorder event-ring length (recent spans/instants kept "
         "for the black-box dump)."),
    Knob("ODTP_OBS_BLACKBOX_FLUSH_S", "float", "5.0", "obs",
         "Min seconds between rate-limited black-box autodumps (per round "
         "and per chaos fault); `0` dumps on every trigger. Watchdog trips "
         "always dump immediately."),
    Knob("ODTP_OBS_DIR", "path", "", "obs",
         "Flush a `trace-w<rank>-<pid>.jsonl` event file here at exit, and "
         "`blackbox-<worker>-<pid>.json` flight-recorder dumps on trouble.",
         doc_default="no flush"),
    Knob("ODTP_OBS_EVENTS_CAP", "int", "65536", "obs",
         "Event ring limit; overflow increments a `dropped` counter."),
    Knob("ODTP_OBS_PROM_PORT", "int", "", "obs",
         "Serve Prometheus 0.0.4 text at `:PORT/metrics`.",
         doc_default="no endpoint"),
    Knob("ODTP_REQTRACE_CAP", "int", "256", "obs",
         "Completed request traces kept per process in the reqtrace ring "
         "(oldest evicted); inflight traces are unbounded by this."),
    Knob("ODTP_REQTRACE_EXPORT", "path", "", "obs",
         "Write the reqtrace ring (report + full traces) here at exit; "
         "unset falls back to `ODTP_OBS_DIR/reqtrace-<worker>-<pid>.json` "
         "when a dir is set.", doc_default="no export"),
    Knob("ODTP_REQTRACE_SAMPLE", "float", "1.0", "obs",
         "Fraction of requests traced at the minting edge (deterministic "
         "1-in-N thinning); adopted upstream contexts are always "
         "honored."),
    Knob("ODTP_ROOFLINE", "path", "", "obs",
         "Path override for the banked roofline JSON backing MFU gauges.",
         doc_default="auto-discover"),
    Knob("ODTP_WATCHDOG_DIVERGE_Z", "float", "6.0", "obs",
         "Divergence watchdog: trip when own pseudo-grad norm or loss is "
         "this many sigma from the galaxy's (needs >= 4 reporting workers); "
         "`0` disables."),
    Knob("ODTP_WATCHDOG_STALL_S", "float", "0.0", "obs",
         "Stall watchdog deadline: no outer-round progress for this many "
         "seconds trips `anomaly_stall` + a black-box dump (never kills "
         "the run).", doc_default="off"),
    Knob("ODTP_WATCHDOG_STRAGGLER_X", "float", "3.0", "obs",
         "Straggler watchdog factor: trip on a worker whose round time "
         "exceeds X times the galaxy median, or whose inner tokens/s falls "
         "below 1/X of it; `0` disables."),
    # -- serve ----------------------------------------------------------------
    Knob("ODTP_DECODE_BLOCK_T", "int", "", "serve",
         "Ring-page tile size for the Pallas decode kernels (must divide "
         "the slot context); unset = the shared block heuristic.",
         doc_default="auto"),
    Knob("ODTP_DECODE_KERNEL", "str", "", "serve",
         "Decode-path kernel dispatch: `auto` picks the Pallas serving "
         "kernels (paged decode attention, fused W4 dequant-matmul, fused "
         "speculative verify) on TPU and the stock XLA ops elsewhere; "
         "`pallas`/`xla` force a path. Token-bit-exact either way.",
         doc_default="config"),
    Knob("ODTP_DECODE_WEIGHT_FORMAT", "str", "", "serve",
         "Replica weight residency override for the serve plane: `w4` keeps "
         "stacked matmul weights blockwise-4bit packed at rest (dequantized "
         "per block inside the jit'd decode); `fp32` restores today's layout.",
         doc_default="config"),
    Knob("ODTP_KV_HOST_SLOTS", "int", "", "serve",
         "Host KV-tier budget: paused slot pages + prefix-store entries it "
         "may hold at once (page-outs beyond it are declined and the slot "
         "stays resident).", doc_default="config"),
    Knob("ODTP_KV_TIER", "bool", "", "serve",
         "`1` arms the host-memory cold KV tier: the scheduler pages "
         "evicted slot rings D2H between decode steps and time-slices more "
         "live sequences than the device ring holds. Off = all-resident, "
         "bit-identical.", doc_default="config"),
    Knob("ODTP_KV_TIER_CODEC", "str", "", "serve",
         "Cold-page codec: `none` stores f32 (evict+restore bit-exact), "
         "`blockwise4bit` stores pages 8x smaller with a bounded, "
         "test-pinned restore error.", doc_default="config"),
    Knob("ODTP_SPEC_K", "int", "", "serve",
         "Self-speculative decode override: draft this many tokens per slot "
         "per step and verify full-depth (token-exact vs the one-token "
         "loop); `0` disables.", doc_default="config"),
    # -- transport ------------------------------------------------------------
    Knob("ODTP_BULK_BANDWIDTH_BPS", "float", "0", "transport",
         "Per-process egress cap in bytes/s (token bucket) emulating a "
         "constrained WAN link; 0 = unlimited."),
    Knob("ODTP_BULK_STREAMS", "int", "4", "transport",
         "Parallel TCP streams a large bulk frame stripes over."),
    Knob("ODTP_BULK_STRIPE_MIN", "int", "67108864", "transport",
         "Payload bytes above which a bulk frame stripes (64 MiB)."),
    Knob("ODTP_BULK_STRIPE_WAIT_S", "float", "300", "transport",
         "How long a receiver waits for a stripe's session before failing "
         "the round to the retry path."),
    Knob("ODTP_BULK_THRESHOLD", "int", "1048576", "transport",
         "Payload bytes above which a frame rides the threaded bulk plane "
         "instead of the asyncio RPC path (1 MiB)."),
    Knob("ODTP_EXPECT_PEERS", "int", "0", "transport",
         "Rendezvous group-complete fast path: close matchmaking as soon "
         "as this many peers joined; 0 = wait out the window."),
    Knob("ODTP_HIER", "bool", "", "transport",
         "`1` arms the two-level hierarchical outer round: the planner "
         "clusters peers into sites, elects one aggregator per site, and "
         "only aggregators touch the WAN. Off = flat butterfly.",
         doc_default="off"),
    Knob("ODTP_HIER_AGG", "spec", "", "transport",
         "`|`-separated fnmatch globs over peer ids naming PREFERRED "
         "aggregators (e.g. the site-uplink hosts); sites with no live "
         "match fall back to capacity/peer-id election.",
         doc_default="elected"),
    Knob("ODTP_LINK_ADAPT", "bool", "", "transport",
         "`1` arms bandwidth-aware transport: proportional reduce-scatter "
         "partitioning, BDP-derived striping, straggler hedging. Off = "
         "bit-identical uniform path.", doc_default="off"),
    Knob("ODTP_LINK_ALPHA", "float", "0.4", "transport",
         "EWMA weight of the per-peer link estimator."),
    Knob("ODTP_LINK_HEDGE_FACTOR", "float", "3.0", "transport",
         "A stripe lagging this multiple of its link-derived deadline is "
         "re-dispatched over an idle connection; 0 disables hedging."),
    Knob("ODTP_LINK_HYST", "float", "0.25", "transport",
         "Relative drift before a peer's published link estimate tracks "
         "the live EWMA (plan anti-flap)."),
    Knob("ODTP_LINK_MIN_SHARE", "float", "0.25", "transport",
         "Floor on a worker's reduce-scatter part, as a fraction of the "
         "uniform 1/n share."),
    Knob("ODTP_LINK_PROBE_BYTES", "int", "262144", "transport",
         "Micro-probe payload seeding the link estimator on fresh peers; "
         "0 disables probing."),
    Knob("ODTP_PIPELINE", "bool", "1", "transport",
         "`1` (default) chunk-pipelines the outer all-reduce (codec work "
         "overlaps the socket); `0` restores the serial path."),
    Knob("ODTP_PIPELINE_CHUNK_ELEMS", "int", "", "transport",
         "Pipeline chunk size in raw elements; overrides "
         "`ODTP_PIPELINE_CHUNK_MB`.", doc_default="derived"),
    Knob("ODTP_PIPELINE_CHUNK_MB", "float", "8", "transport",
         "Pipeline chunk size in MB of fp32 elements."),
    Knob("ODTP_RDV_FAILBACK_S", "float", "60.0", "transport",
         "How long a worker keeps trying the native rendezvous daemon "
         "before failing back to worker-hosted rendezvous."),
    Knob("ODTP_SITE_RATIO", "float", "4.0", "transport",
         "Auto-clustering threshold: peers whose pairwise link capacity is "
         "within this factor of the group's fattest link share a site."),
    Knob("ODTP_SITES", "spec", "", "transport",
         "Explicit site assignment: `;`-separated sites, each a "
         "`|`-separated list of fnmatch globs over peer ids (e.g. "
         "`rack-a-*;rack-b-*`). Unset = cluster from the gossiped link "
         "matrix.", doc_default="auto-cluster"),
    Knob("ODTP_WORKER_RENDEZVOUS", "bool", "1", "transport",
         "`0` disables the in-process fallback rendezvous server (require "
         "the external daemon)."),
)

REGISTRY: dict[str, Knob] = {k.name: k for k in KNOBS}

TABLE_BEGIN = "<!-- odtp-knobs:begin (generated by scripts/odtp_lint.py --write-knob-table; do not edit by hand) -->"
TABLE_END = "<!-- odtp-knobs:end -->"


def render_table() -> str:
    """The README knob table, grouped by subsystem, markdown."""
    out = [
        TABLE_BEGIN,
        "",
        "| Knob | Type | Default | Subsystem | What it does |",
        "|---|---|---|---|---|",
    ]
    for k in sorted(KNOBS, key=lambda k: (k.subsystem, k.name)):
        default = k.doc_default or k.default or "unset"
        out.append(
            f"| `{k.name}` | {k.type} | `{default}` | {k.subsystem} | {k.doc} |"
        )
    out += ["", TABLE_END]
    return "\n".join(out)
