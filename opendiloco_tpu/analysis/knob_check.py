"""AST pass: every ``ODTP_*`` env read must resolve to the knob registry.

Read shapes handled:
  - ``os.environ.get("ODTP_X"[, default])`` / ``os.getenv(...)``
  - ``os.environ["ODTP_X"]`` (Load context)
  - indirection through module constants: ``_ENV = "ODTP_X"`` then
    ``os.environ.get(_ENV)``
  - indirection through env-helper functions: a function whose body reads
    ``os.environ.get(<param>, ...)`` becomes a helper; literal calls like
    ``_env_float("ODTP_X", 0.4)`` count as reads with that default.

Failures:
  undeclared-knob        read in code, missing from knobs.KNOBS
  dead-knob              declared, never read under the scanned roots
  knob-default-mismatch  a read site's foldable literal default disagrees
                         with the registered default

Writes (``os.environ["ODTP_X"] = ...``) are validated for declaration
only -- benches set knobs for child processes; they don't carry defaults.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable, Optional

from opendiloco_tpu.analysis.common import (
    UNFOLDABLE,
    Finding,
    dotted,
    fold_const,
    iter_py_files,
    module_constants,
    parse_file,
    suppressed,
)
from opendiloco_tpu.analysis.knobs import REGISTRY

_ENV_GET = {"os.environ.get", "environ.get", "os.getenv", "getenv"}
_ENV_SUB = {"os.environ", "environ"}


@dataclasses.dataclass
class _Read:
    name: str
    path: str
    line: int
    default: object  # folded literal default, UNFOLDABLE, or None (absent)
    is_write: bool = False


def _key_and_default(call: ast.Call, env: dict) -> tuple[object, object]:
    """(knob name, folded default) of an env .get()/getenv call."""
    key = fold_const(call.args[0], env) if call.args else UNFOLDABLE
    default = fold_const(call.args[1], env) if len(call.args) > 1 else None
    return key, default


def _helper_signature(fn: ast.FunctionDef) -> Optional[tuple[int, Optional[int]]]:
    """(key_param_idx, default_param_idx) when ``fn`` is an env-read helper:
    its body contains an env get whose key expression is one of its own
    parameters. The default param is recognized when the helper's fallback
    expression references another parameter (e.g. ``... or default``)."""
    params = [a.arg for a in fn.args.args]
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Call) and dotted(node.func) in _ENV_GET):
            continue
        if not (node.args and isinstance(node.args[0], ast.Name)):
            continue
        key = node.args[0].id
        if key not in params:
            continue
        default_idx: Optional[int] = None
        # fallback via second .get arg, or an enclosing `x or default`
        cands = list(node.args[1:])
        for outer in ast.walk(fn):
            if isinstance(outer, ast.BoolOp) and any(
                n is node for n in ast.walk(outer)
            ):
                cands.extend(outer.values)
        for c in cands:
            if isinstance(c, ast.Name) and c.id in params and c.id != key:
                default_idx = params.index(c.id)
                break
        return params.index(key), default_idx
    return None


def _scan_file(path: str) -> tuple[list[_Read], dict[str, tuple[int, Optional[int]]], list[str]]:
    tree, lines = parse_file(path)
    if tree is None:
        return [], {}, lines
    env = module_constants(tree)
    reads: list[_Read] = []
    helpers: dict[str, tuple[int, Optional[int]]] = {}

    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            sig = _helper_signature(node)
            if sig is not None:
                helpers[node.name] = sig

    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and dotted(node.func) in _ENV_GET:
            key, default = _key_and_default(node, env)
            if isinstance(key, str):
                reads.append(_Read(key, path, node.lineno, default))
            continue
        if (
            isinstance(node, ast.Subscript)
            and dotted(node.value) in _ENV_SUB
        ):
            key = fold_const(node.slice, env)
            if isinstance(key, str):
                reads.append(
                    _Read(
                        key, path, node.lineno, None,
                        is_write=isinstance(node.ctx, (ast.Store, ast.Del)),
                    )
                )
            continue
        # os.environ.setdefault / .pop are writes/erasures, declaration-only
        if isinstance(node, ast.Call) and dotted(node.func) in (
            "os.environ.setdefault", "environ.setdefault",
            "os.environ.pop", "environ.pop",
        ):
            key = fold_const(node.args[0], env) if node.args else UNFOLDABLE
            if isinstance(key, str):
                reads.append(_Read(key, path, node.lineno, None, is_write=True))

    # second sweep: calls into this module's env helpers with literal keys
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)):
            continue
        sig = helpers.get(node.func.id)
        if sig is None:
            continue
        key_idx, default_idx = sig
        if key_idx >= len(node.args):
            continue
        key = fold_const(node.args[key_idx], env)
        if not isinstance(key, str):
            continue
        default = (
            fold_const(node.args[default_idx], env)
            if default_idx is not None and default_idx < len(node.args)
            else None
        )
        reads.append(_Read(key, path, node.lineno, default))

    return reads, helpers, lines


def _defaults_agree(site: object, registered: str) -> bool:
    if site is None or site is UNFOLDABLE:
        return True  # no literal default at this site to compare
    try:
        return float(site) == float(registered)
    except (TypeError, ValueError):
        return str(site) == registered


def check(roots: Iterable[str], relto: Optional[str] = None) -> list[Finding]:
    findings: list[Finding] = []
    seen_reads: dict[str, list[_Read]] = {}
    for path in iter_py_files(roots):
        reads, _, lines = _scan_file(path)
        rel = _rel(path, relto)
        for r in reads:
            if not r.name.startswith("ODTP_"):
                continue
            r.path = rel
            seen_reads.setdefault(r.name, []).append(r)
            knob = REGISTRY.get(r.name)
            if knob is None:
                if not suppressed(lines, r.line, "undeclared-knob"):
                    findings.append(
                        Finding(
                            "undeclared-knob", rel, r.line,
                            f"{r.name} is read here but not declared in "
                            "analysis/knobs.py -- add it to the registry "
                            "(name, type, default, subsystem, doc)",
                        )
                    )
                continue
            if r.is_write:
                continue
            if not _defaults_agree(r.default, knob.default):
                if not suppressed(lines, r.line, "knob-default-mismatch"):
                    findings.append(
                        Finding(
                            "knob-default-mismatch", rel, r.line,
                            f"{r.name} falls back to {r.default!r} here but "
                            f"the registry declares default {knob.default!r}"
                            " -- two sites disagreeing on a default is a"
                            " config fork",
                        )
                    )
    for name, knob in REGISTRY.items():
        sites = seen_reads.get(name, [])
        if not any(not r.is_write for r in sites):
            findings.append(
                Finding(
                    "dead-knob", "opendiloco_tpu/analysis/knobs.py", 0,
                    f"{name} is declared but never read under the scanned "
                    "roots -- delete the registry entry or the feature that "
                    "was supposed to read it",
                )
            )
    return findings


def _rel(path: str, relto: Optional[str]) -> str:
    if relto is None:
        return path
    import os

    try:
        return os.path.relpath(path, relto)
    except ValueError:
        return path
