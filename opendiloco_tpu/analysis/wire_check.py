"""Wire-schema conformance: every encode/decode layout must match
diloco/schema.py.

Checks:
  wire-undeclared-struct  a ``struct.Struct``/``pack``/``unpack``/
                          ``calcsize`` literal format string that is not
                          one of the schema's declared formats -- a layout
                          born outside the schema module
  wire-schema-internal    schema self-consistency (declared header size vs
                          struct.calcsize, hash algo exists, geometry table
                          covers every registered codec)
  wire-chunk-meta         ``wire.chunk_fields`` must stamp exactly the
                          schema's CHUNK_META_FIELDS and ``wire.chunk_span``
                          must read only declared keys
  wire-codec-geometry     codec classes' chunk_align/wire_align_bytes must
                          match schema.CODEC_WIRE_GEOMETRY (runtime import)
  wire-daemon-magic       the C++ rendezvous daemon must frame with the
                          same magic bytes and a 4-byte network-order
                          header length (textual check over the .cpp)

The magic/header constants are also *imported* by wire.py/bulk.py, so
Python-side drift is impossible by construction; the pass exists for the
sites that cannot import (C++), for new code that hardcodes a format, and
for the schema's own arithmetic.
"""

from __future__ import annotations

import ast
import hashlib
import os
import re
import struct as _structmod
from typing import Iterable, Optional

from opendiloco_tpu.analysis.common import (
    Finding,
    dotted,
    iter_py_files,
    parse_file,
    suppressed,
)
from opendiloco_tpu.diloco import schema

_STRUCT_FNS = {
    "struct.Struct", "struct.pack", "struct.unpack", "struct.pack_into",
    "struct.unpack_from", "struct.calcsize",
}

DECLARED_FORMATS = {schema.FRAME_HDR_FMT, schema.SO_TIMEVAL_FMT}


def _check_struct_literals(roots: Iterable[str], relto: Optional[str]) -> list[Finding]:
    findings: list[Finding] = []
    for path in iter_py_files(roots):
        if os.path.abspath(path) == os.path.abspath(schema.__file__):
            continue
        tree, lines = parse_file(path)
        if tree is None:
            continue
        rel = os.path.relpath(path, relto) if relto else path
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and dotted(node.func) in _STRUCT_FNS):
                continue
            if not node.args:
                continue
            fmt = node.args[0]
            if isinstance(fmt, ast.Constant) and isinstance(fmt.value, str):
                if fmt.value in DECLARED_FORMATS:
                    continue
                if suppressed(lines, node.lineno, "wire-undeclared-struct"):
                    continue
                findings.append(
                    Finding(
                        "wire-undeclared-struct", rel, node.lineno,
                        f"struct format {fmt.value!r} is not declared in "
                        "diloco/schema.py -- every wire layout lives there "
                        "once, encode and decode import it",
                    )
                )
            # Name/Attribute formats referencing schema constants are the
            # by-construction-safe spelling; nothing to check
    return findings


def _check_schema_internal() -> list[Finding]:
    findings: list[Finding] = []
    spath = os.path.relpath(schema.__file__)
    if _structmod.calcsize(schema.FRAME_HDR_FMT) != schema.FRAME_HDR_SIZE:
        findings.append(
            Finding(
                "wire-schema-internal", spath, 0,
                f"FRAME_HDR_SIZE={schema.FRAME_HDR_SIZE} but "
                f"calcsize({schema.FRAME_HDR_FMT!r})="
                f"{_structmod.calcsize(schema.FRAME_HDR_FMT)}",
            )
        )
    if schema.FRAME_HDR.size != schema.FRAME_HDR_SIZE:
        findings.append(
            Finding(
                "wire-schema-internal", spath, 0,
                "FRAME_HDR struct disagrees with FRAME_HDR_SIZE",
            )
        )
    if len(schema.MAGIC) != 4:
        findings.append(
            Finding("wire-schema-internal", spath, 0,
                    f"MAGIC must be 4 bytes, got {schema.MAGIC!r}")
        )
    try:
        digest = hashlib.new(schema.PLAN_HASH_ALGO)
        if schema.PLAN_HASH_HEXLEN > digest.digest_size * 2:
            findings.append(
                Finding("wire-schema-internal", spath, 0,
                        "PLAN_HASH_HEXLEN exceeds the digest length")
            )
    except ValueError:
        findings.append(
            Finding("wire-schema-internal", spath, 0,
                    f"unknown PLAN_HASH_ALGO {schema.PLAN_HASH_ALGO!r}")
        )
    return findings


def _dict_literal_keys(fn: ast.FunctionDef) -> Optional[list[str]]:
    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and isinstance(node.value, ast.Dict):
            keys = []
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    keys.append(k.value)
                else:
                    return None
            return keys
    return None


def _meta_get_keys(fn: ast.FunctionDef) -> set[str]:
    """String keys read off ``meta`` via .get()/[] inside the function."""
    keys: set[str] = set()
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "get"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "meta"
            and node.args
            and isinstance(node.args[0], ast.Constant)
        ):
            keys.add(node.args[0].value)
        if (
            isinstance(node, ast.Subscript)
            and isinstance(node.value, ast.Name)
            and node.value.id == "meta"
            and isinstance(node.slice, ast.Constant)
        ):
            keys.add(node.slice.value)
    return keys


def _check_chunk_meta(wire_path: str, relto: Optional[str]) -> list[Finding]:
    findings: list[Finding] = []
    tree, _ = parse_file(wire_path)
    if tree is None:
        return findings
    rel = os.path.relpath(wire_path, relto) if relto else wire_path
    fns = {
        n.name: n for n in ast.walk(tree)
        if isinstance(n, ast.FunctionDef)
    }
    cf = fns.get("chunk_fields")
    if cf is not None:
        keys = _dict_literal_keys(cf)
        if keys is not None and tuple(keys) != schema.CHUNK_META_FIELDS:
            findings.append(
                Finding(
                    "wire-chunk-meta", rel, cf.lineno,
                    f"chunk_fields stamps {tuple(keys)} but schema declares "
                    f"CHUNK_META_FIELDS={schema.CHUNK_META_FIELDS}",
                )
            )
    cs = fns.get("chunk_span")
    if cs is not None:
        extra = _meta_get_keys(cs) - set(schema.CHUNK_META_FIELDS)
        if extra:
            findings.append(
                Finding(
                    "wire-chunk-meta", rel, cs.lineno,
                    f"chunk_span reads undeclared meta keys {sorted(extra)}"
                    " -- declare them in schema.CHUNK_META_FIELDS",
                )
            )
    return findings


def _check_codec_geometry() -> list[Finding]:
    findings: list[Finding] = []
    from opendiloco_tpu.diloco import compression

    spath = "opendiloco_tpu/diloco/schema.py"
    registered: dict[str, type] = {}
    for obj in vars(compression).values():
        if (
            isinstance(obj, type)
            and issubclass(obj, compression.Codec)
            and "name" in vars(obj)
        ):
            registered[obj.name] = obj
    for name, cls in sorted(registered.items()):
        want = schema.CODEC_WIRE_GEOMETRY.get(name)
        got = (cls.chunk_align, cls.wire_align_bytes)
        if want is None:
            findings.append(
                Finding(
                    "wire-codec-geometry", spath, 0,
                    f"codec {name!r} ships without a CODEC_WIRE_GEOMETRY "
                    "entry -- declare its (chunk_align, wire_align_bytes)",
                )
            )
        elif got != want:
            findings.append(
                Finding(
                    "wire-codec-geometry", spath, 0,
                    f"codec {name!r} has (chunk_align, wire_align_bytes)="
                    f"{got} but schema declares {want}",
                )
            )
    for name in schema.CODEC_WIRE_GEOMETRY:
        if name not in registered:
            findings.append(
                Finding(
                    "wire-codec-geometry", spath, 0,
                    f"schema declares geometry for unknown codec {name!r}",
                )
            )
    return findings


def _check_daemon(cpp_path: str, relto: Optional[str]) -> list[Finding]:
    findings: list[Finding] = []
    if not os.path.exists(cpp_path):
        return findings
    rel = os.path.relpath(cpp_path, relto) if relto else cpp_path
    with open(cpp_path, encoding="utf-8", errors="replace") as f:
        src = f.read()
    magic = schema.MAGIC.decode()
    if f'"{magic}"' not in src:
        findings.append(
            Finding(
                "wire-daemon-magic", rel, 0,
                f"rendezvous daemon does not frame with magic {magic!r} "
                "(schema.MAGIC)",
            )
        )
    # header length must travel as a 4-byte network-order u32 (the ">I" of
    # FRAME_HDR_FMT); htonl/ntohl on a uint32_t is the C++ spelling
    if not re.search(r"htonl\s*\(\s*\(?\s*uint32_t\s*\)?", src) or "ntohl" not in src:
        findings.append(
            Finding(
                "wire-daemon-magic", rel, 0,
                "rendezvous daemon must encode/decode the frame header "
                "length with htonl/ntohl(uint32_t) to match schema "
                f"FRAME_HDR_FMT={schema.FRAME_HDR_FMT!r}",
            )
        )
    return findings


def check(
    roots: Iterable[str],
    relto: Optional[str] = None,
    wire_path: Optional[str] = None,
    daemon_cpp: Optional[str] = None,
) -> list[Finding]:
    findings = _check_struct_literals(roots, relto)
    findings += _check_schema_internal()
    findings += _check_codec_geometry()
    if wire_path is None:
        wire_path = os.path.join(
            os.path.dirname(schema.__file__), "wire.py"
        )
    findings += _check_chunk_meta(wire_path, relto)
    if daemon_cpp is None:
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(schema.__file__))))
        daemon_cpp = os.path.join(pkg_root, "native", "odtp_rendezvousd.cpp")
    findings += _check_daemon(daemon_cpp, relto)
    return findings
