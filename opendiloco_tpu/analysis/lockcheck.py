"""Runtime lock-order witness (``ODTP_LOCKCHECK=1``).

The static pass (analysis/locks.py) proves the *written* acquisition
graph acyclic; this witness checks the *executed* one. When armed it
replaces ``threading.Lock``/``RLock``/``Condition`` with factories that
hand locks created **inside opendiloco_tpu/** a thin recording proxy
(foreign callers -- stdlib, jax -- keep the raw primitive untouched).

Each proxy is tagged with its creation site (file:line). Per thread, the
stack of currently-held sites is tracked; on every acquisition an edge
held-site -> new-site enters a process-global order graph. An edge that
closes a cycle raises ``LockOrderViolation`` at acquire time -- turning a
would-be silent deadlock under the chaos soak or the serve scheduler into
an immediate, attributable failure.

Zero-cost contract (same as ``ODTP_OBS``/``ODTP_CHAOS``): when the env
var is unset, ``maybe_install()`` is a single dict lookup at import and
``threading`` is untouched -- no proxy, no indirection, no allocation on
any lock path.
"""

from __future__ import annotations

import os
import threading

_ENV = "ODTP_LOCKCHECK"

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_installed = False
_raw_lock = threading.Lock
_raw_rlock = threading.RLock
_raw_condition = threading.Condition


class LockOrderViolation(AssertionError):
    pass


class _Order:
    """Process-global acquisition-order graph over creation sites."""

    def __init__(self) -> None:
        self.mu = _raw_lock()
        self.edges: dict[str, set[str]] = {}
        self.first_seen: dict[tuple[str, str], str] = {}
        self.tls = threading.local()

    def held(self) -> list:
        st = getattr(self.tls, "stack", None)
        if st is None:
            st = self.tls.stack = []
        return st

    def _reaches(self, src: str, dst: str) -> bool:
        seen = set()
        stack = [src]
        while stack:
            v = stack.pop()
            if v == dst:
                return True
            if v in seen:
                continue
            seen.add(v)
            stack.extend(self.edges.get(v, ()))
        return False

    def note_acquire(self, proxy) -> None:
        st = self.held()
        site = proxy._site
        for held_proxy in st:
            h = held_proxy._site
            if h == site:
                continue  # same creation site (lock maps etc.): no ordering
            with self.mu:
                if site in self.edges.get(h, ()):
                    continue
                if self._reaches(site, h):
                    order = " -> ".join(p._site for p in st) + f" -> {site}"
                    first = self.first_seen.get((site, h), "?")
                    raise LockOrderViolation(
                        f"lock-order inversion: acquiring {site} while "
                        f"holding {h}, but the opposite order was witnessed "
                        f"at {first}. This thread: {order}. Two threads "
                        "interleaving these orders deadlock."
                    )
                self.edges.setdefault(h, set()).add(site)
                self.first_seen[(h, site)] = (
                    f"thread={threading.current_thread().name}"
                )
        st.append(proxy)

    def note_release(self, proxy) -> None:
        st = self.held()
        # release order need not be LIFO; remove the newest matching entry
        for i in range(len(st) - 1, -1, -1):
            if st[i] is proxy:
                del st[i]
                return

    def snapshot(self) -> dict[str, set[str]]:
        with self.mu:
            return {k: set(v) for k, v in self.edges.items()}

    def reset(self) -> None:
        with self.mu:
            self.edges.clear()
            self.first_seen.clear()


order = _Order()


class _LockProxy:
    """Recording wrapper; duck-compatible with the primitive lock
    (acquire/release/locked/context manager), including use as the lock
    behind a ``threading.Condition``."""

    _factory = staticmethod(lambda: _raw_lock())

    def __init__(self, site: str):
        self._inner = self._factory()
        self._site = site
        self._count = 0  # recursion depth (RLock); plain Lock stays 0/1

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            if self._count == 0:
                order.note_acquire(self)
            self._count += 1
        return got

    def release(self) -> None:
        self._count -= 1
        if self._count == 0:
            order.note_release(self)
        self._inner.release()

    def locked(self) -> bool:
        inner = self._inner
        return inner.locked() if hasattr(inner, "locked") else self._count > 0

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {self._site} inner={self._inner!r}>"


class _RLockProxy(_LockProxy):
    _factory = staticmethod(lambda: _raw_rlock())

    # Condition integration: these are looked up via hasattr(); providing
    # them keeps wait() bookkeeping correct for re-entrant holders
    def _release_save(self):
        state = self._inner._release_save()
        count = self._count
        self._count = 0
        order.note_release(self)
        return (state, count)

    def _acquire_restore(self, saved) -> None:
        state, count = saved
        self._inner._acquire_restore(state)
        order.note_acquire(self)
        self._count = count

    def _is_owned(self) -> bool:
        return self._inner._is_owned()


def _caller_site(depth: int = 2) -> tuple[str, bool]:
    import sys

    frame = sys._getframe(depth)
    path = frame.f_code.co_filename
    inside = os.path.abspath(path).startswith(_PKG_ROOT + os.sep)
    short = os.path.relpath(path, os.path.dirname(_PKG_ROOT)) if inside else path
    return f"{short}:{frame.f_lineno}", inside


def _make_lock():
    site, inside = _caller_site()
    return _LockProxy(site) if inside else _raw_lock()


def _make_rlock():
    site, inside = _caller_site()
    return _RLockProxy(site) if inside else _raw_rlock()


def _make_condition(lock=None):
    site, inside = _caller_site()
    if lock is None and inside:
        # a bare Condition() owns its lock; witness it under this site
        lock = _RLockProxy(site)
    return _raw_condition(lock)


def enabled() -> bool:
    return _installed


def maybe_install() -> bool:
    """Arm the witness iff ODTP_LOCKCHECK is set truthy. Called once from
    ``opendiloco_tpu.__init__``; locks created before that import (none in
    this package) would escape witnessing."""
    global _installed
    if _installed:
        return True
    if os.environ.get(_ENV, "").lower() not in ("1", "true", "on"):
        return False
    threading.Lock = _make_lock
    threading.RLock = _make_rlock
    threading.Condition = _make_condition
    _installed = True
    return True


def uninstall() -> None:
    """Restore the raw primitives (tests only)."""
    global _installed
    threading.Lock = _raw_lock
    threading.RLock = _raw_rlock
    threading.Condition = _raw_condition
    order.reset()
    _installed = False
