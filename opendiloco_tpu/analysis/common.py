"""Shared infrastructure for the static passes: findings, inline
suppressions, file iteration and a small constant folder.

A finding names the check that fired, the site, and the invariant broken.
Suppression is per-line and must carry a justification:

    lock = outer.lock  # odtp-lint: disable=lock-order -- release order pinned by test_x

``disable=all`` silences every check on that line. A ``disable=`` with no
justification text after ``--`` does NOT suppress (the comment is the
documentation; an empty one documents nothing).
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Iterable, Iterator, Optional

_SUPPRESS_RE = re.compile(
    r"#\s*odtp-lint:\s*disable=([A-Za-z0-9_,\-]+)\s*--\s*(\S.*)"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    check: str  # kebab-case check id, e.g. "undeclared-knob"
    path: str  # repo-relative when produced by the driver
    line: int  # 1-indexed; 0 = whole-file/tree finding
    message: str

    def render(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{loc}: [{self.check}] {self.message}"


def iter_py_files(roots: Iterable[str]) -> Iterator[str]:
    """Every .py file under the given roots (files pass through as-is),
    sorted for deterministic finding order, __pycache__ skipped."""
    out = []
    for root in roots:
        if os.path.isfile(root):
            out.append(root)
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            out.extend(
                os.path.join(dirpath, f) for f in filenames if f.endswith(".py")
            )
    return iter(sorted(out))


def parse_file(path: str) -> tuple[Optional[ast.Module], list[str]]:
    """(AST, source lines); (None, lines) on syntax errors -- the style
    gate owns those, the invariant passes just skip the file."""
    with open(path, encoding="utf-8") as f:
        src = f.read()
    lines = src.splitlines()
    try:
        return ast.parse(src, filename=path), lines
    except SyntaxError:
        return None, lines


def suppressed(lines: list[str], lineno: int, check: str) -> bool:
    """True when the 1-indexed source line carries a justified
    ``# odtp-lint: disable=`` comment naming this check (or ``all``)."""
    if not 1 <= lineno <= len(lines):
        return False
    m = _SUPPRESS_RE.search(lines[lineno - 1])
    if m is None:
        return False
    named = {c.strip() for c in m.group(1).split(",")}
    return check in named or "all" in named


def filter_suppressed(
    findings: list[Finding], lines_by_path: dict[str, list[str]]
) -> list[Finding]:
    return [
        f
        for f in findings
        if not suppressed(lines_by_path.get(f.path, []), f.line, f.check)
    ]


# -- constant folding ---------------------------------------------------------

_FOLD_CASTS = {"str": str, "int": int, "float": float}


def fold_const(node: Optional[ast.AST], env: Optional[dict] = None):
    """Evaluate a side-effect-free constant expression: literals, module
    constants (via ``env``), +,-,*,/,//,<<,>>, unary +/-, and str/int/float
    casts of foldable values. Returns the value, or the _Unfoldable
    sentinel when the expression isn't statically known."""
    if node is None:
        return UNFOLDABLE
    if isinstance(node, ast.Constant):
        return node.value
    if isinstance(node, ast.Name):
        if env is not None and node.id in env:
            return env[node.id]
        return UNFOLDABLE
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, (ast.UAdd, ast.USub)):
        v = fold_const(node.operand, env)
        if v is UNFOLDABLE or not isinstance(v, (int, float)):
            return UNFOLDABLE
        return -v if isinstance(node.op, ast.USub) else +v
    if isinstance(node, ast.BinOp):
        lhs, rhs = fold_const(node.left, env), fold_const(node.right, env)
        if lhs is UNFOLDABLE or rhs is UNFOLDABLE:
            return UNFOLDABLE
        try:
            if isinstance(node.op, ast.Add):
                return lhs + rhs
            if isinstance(node.op, ast.Sub):
                return lhs - rhs
            if isinstance(node.op, ast.Mult):
                return lhs * rhs
            if isinstance(node.op, ast.Div):
                return lhs / rhs
            if isinstance(node.op, ast.FloorDiv):
                return lhs // rhs
            if isinstance(node.op, ast.LShift):
                return lhs << rhs
            if isinstance(node.op, ast.RShift):
                return lhs >> rhs
        except Exception:
            return UNFOLDABLE
        return UNFOLDABLE
    if (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Name)
        and node.func.id in _FOLD_CASTS
        and len(node.args) == 1
        and not node.keywords
    ):
        v = fold_const(node.args[0], env)
        if v is UNFOLDABLE:
            return UNFOLDABLE
        try:
            return _FOLD_CASTS[node.func.id](v)
        except Exception:
            return UNFOLDABLE
    return UNFOLDABLE


class _Unfoldable:
    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return "<unfoldable>"


UNFOLDABLE = _Unfoldable()


def module_constants(tree: ast.Module) -> dict:
    """Top-level ``NAME = <foldable>`` bindings (str/int/float), the
    pattern behind indirect env reads like ``os.environ.get(_ENV)``."""
    env: dict = {}
    for stmt in tree.body:
        if (
            isinstance(stmt, ast.Assign)
            and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Name)
        ):
            v = fold_const(stmt.value, env)
            if v is not UNFOLDABLE and isinstance(v, (str, int, float)):
                env[stmt.targets[0].id] = v
    return env


def dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None
