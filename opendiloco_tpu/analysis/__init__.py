"""odtp-check: the invariant lint + sanitizer plane.

Four static passes over ``opendiloco_tpu/`` + ``scripts/`` keep the
stack's core invariants machine-checked instead of reviewer-remembered:

    knob_check  -- every ODTP_* env knob read resolves to the declarative
                   registry (knobs.py); undeclared, dead and
                   default-mismatched knobs fail the build, and the README
                   knob table is generated from the registry.
    donation    -- use-after-donate on jit'd donated buffers, jitted
                   closures capturing mutable ``self`` state, unhashable
                   static args.
    locks       -- the static lock-acquisition order graph across the
                   threaded planes must stay acyclic (lockcheck.py is the
                   matching ODTP_LOCKCHECK=1 runtime witness).
    wire_check  -- encode/decode struct layouts, chunk meta keys, the C++
                   daemon's frame header and codec wire geometry must all
                   match the single declaration in diloco/schema.py.

Driver: ``python scripts/odtp_lint.py`` (exit 1 on any finding).
Suppression: append ``# odtp-lint: disable=<check> -- <why>`` to the
flagged line; the justification text is mandatory.
"""

from opendiloco_tpu.analysis.common import Finding, iter_py_files, parse_file

__all__ = ["Finding", "iter_py_files", "parse_file"]
