"""Inner-loop trainer: one jit-compiled train step over a sharded pytree.

This is the TPU-native replacement for the reference's FSDP hot loop
(open_diloco/train_fsdp.py:361-413): forward/backward per micro-batch with
gradient accumulation (``no_sync`` + loop -> a single ``lax.scan`` inside
jit), global-norm clip 1.0, AdamW with cosine/warmup schedule
(train_fsdp.py:250-260), all compiled once per shape. Collectives are
inserted by XLA from the mesh shardings -- there is no hand-written
all-reduce in the step.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from opendiloco_tpu import obs
from opendiloco_tpu.models.llama import (
    LlamaConfig,
    RematPolicy,
    causal_lm_loss,
    forward,
    init_params,
)
from opendiloco_tpu.parallel.mesh import MeshPlan
from opendiloco_tpu.parallel.sharding import optstate_specs, param_specs
from opendiloco_tpu.utils.logger import get_text_logger

log = get_text_logger(__name__)


@dataclasses.dataclass(frozen=True)
class TrainerConfig:
    """The optimization-relevant slice of the top-level Config."""

    lr: float = 4e-4
    weight_decay: float = 0.1
    adam_betas: tuple[float, float] = (0.9, 0.95)
    adam_eps: float = 1e-8
    warmup_steps: int = 1000
    total_steps: int = 88_000
    max_grad_norm: float = 1.0
    precision: str = "bf16-mixed"
    # "auto" resolves at trainer build: pallas on TPU meshes, xla elsewhere
    attn_impl: str = "auto"
    remat: RematPolicy = True
    # fused lm-head + cross-entropy Pallas kernel (ops/fused_xent.py):
    # avoids materializing [tokens, vocab] float32 logits in HBM.
    # None = auto (TPU dense models on, otherwise off)
    fused_loss: Optional[bool] = None
    # layer-scan unroll width. None = auto: FULL unroll on TPU for dense
    # models up to 16 layers (measured +6.8% tok/s at the 150m bench shape
    # -- the HBM-bound step gains cross-layer scheduling/fusion; round-5
    # live window), 1 elsewhere (CPU tests, MoE, deep models where the
    # unrolled program's size would eat HBM -- the 1b looped program is
    # already 8.2G). ODTP_SCAN_UNROLL overrides for experiments.
    scan_unroll: Optional[int] = None
    pp_microbatches: Optional[int] = None  # pipeline microbatches (None = pp size)
    # sp+pp fallback selector. With the DEFAULT (auto) attention, sp+pp
    # composes via ring attention running inside the pipeline's manual
    # region; setting this instead selects the activation-sharding mode
    # (full-sequence attention, the sp axis only shards activations) — a
    # real memory-scaling mode, but never an implicit one. An EXPLICIT
    # attn_impl always wins over this flag (explicit ring composes, and an
    # explicit non-ring impl under sp+pp raises unless this is set).
    allow_sp_activation_sharding: bool = False
    # fp16 dynamic loss scaling (torch GradScaler parity, train_fsdp.py:228,
    # 383-405; bf16 needs none -- the reference itself recommends bf16)
    init_loss_scale: float = 2.0**15
    scale_growth_interval: int = 2000

    @property
    def compute_dtype(self):
        if self.precision == "bf16-mixed":
            return jnp.bfloat16
        if self.precision == "fp16-mixed":
            return jnp.float16
        return jnp.float32

    @property
    def use_loss_scaling(self) -> bool:
        return self.precision == "fp16-mixed"


def make_schedule(tc: TrainerConfig) -> optax.Schedule:
    """Linear warmup then cosine decay to 0 over the remaining steps
    (HF get_cosine_schedule_with_warmup semantics used at train_fsdp.py:256-260)."""
    return optax.join_schedules(
        [
            optax.linear_schedule(0.0, tc.lr, tc.warmup_steps),
            optax.cosine_decay_schedule(tc.lr, max(1, tc.total_steps - tc.warmup_steps)),
        ],
        boundaries=[tc.warmup_steps],
    )


def make_inner_optimizer(tc: TrainerConfig) -> optax.GradientTransformation:
    return optax.chain(
        optax.clip_by_global_norm(tc.max_grad_norm),
        optax.adamw(
            make_schedule(tc),
            b1=tc.adam_betas[0],
            b2=tc.adam_betas[1],
            eps=tc.adam_eps,
            weight_decay=tc.weight_decay,
        ),
    )


def _resolve_perf_defaults(
    tc: TrainerConfig, model_cfg: LlamaConfig, plan: MeshPlan
) -> TrainerConfig:
    """Resolve attn_impl="auto" / fused_loss=None to concrete choices.

    On TPU meshes the Pallas kernels won the on-chip sweep (v5e, llama-150m
    seq 1024: flash attention +20% tokens/sec over XLA attention, fused
    lm-head+xent a further gain on top) and become the defaults; every other
    backend (the CPU test mesh included) keeps the portable XLA paths.
    Explicit user choices pass through untouched.
    """
    if (
        tc.attn_impl != "auto"
        and tc.fused_loss is not None
        and tc.scan_unroll is not None
    ):
        return tc
    dev = plan.mesh.devices.flat[0]
    on_tpu = "tpu" in getattr(dev, "device_kind", "").lower()
    changes: dict = {}
    if tc.attn_impl == "auto":
        if getattr(plan, "sp_axis", None) is not None and not (
            tc.allow_sp_activation_sharding and getattr(plan, "pp_axis", None)
        ):
            # sequence-parallel mesh: flash/xla attention are not
            # sequence-sharded, so XLA would all-gather the full sequence
            # per device, silently defeating the sp axis -- ring attention
            # is the only impl that keeps the shards local. This includes
            # sp+pp (round 5): the pipeline binds both axes manual and the
            # ring body runs DIRECTLY on each stage's local chunks (no
            # nested shard_map -- that construction has no jvp lowering)
            changes["attn_impl"] = "ring"
        else:
            if getattr(plan, "sp_axis", None) is not None:
                # sp+pp with the explicit activation-sharding opt-in: the
                # sp axis shards activations while attention sees the full
                # sequence
                log.warning(
                    "sp+pp with allow_sp_activation_sharding: using "
                    "full-sequence %s attention; the sp axis only shards "
                    "activations",
                    "pallas" if on_tpu else "xla",
                )
            changes["attn_impl"] = "pallas" if on_tpu else "xla"
    if tc.scan_unroll is None:
        # full unroll measured +6.8% tok/s on the HBM-bound 150m step (v5e
        # live window, round 5: 62.0k -> 66.2k at bs24+remat=dots); gated
        # to dense stacks <= 16 layers so deep/MoE models don't trade HBM
        # for program size untested
        changes["scan_unroll"] = (
            model_cfg.num_hidden_layers
            if (
                on_tpu
                and not model_cfg.num_experts
                and model_cfg.num_hidden_layers <= 16
            )
            else 1
        )
    if tc.fused_loss is None:
        # auto-on only where the sweep measured a win: pallas attention on a
        # non-sequence-parallel mesh WITH the layer scan still looped.
        # Under the full unroll (the TPU default for dense <=16-layer
        # stacks) the round-5 chained op timings showed the fused kernel's
        # backward is ~1.6x slower than XLA's unfused path, and end-to-end
        # the unfused step measured faster at every batch (70.2k vs 68.5k
        # tok/s best; PUSH40.json) -- XLA fuses the lm-head matmul into the
        # unrolled graph itself. For looped stacks (1b, MoE, pp) the fused
        # kernel's memory saving (no [B*T, V] logits materialization)
        # still carries the win. Sequence-parallel meshes keep the
        # standard loss: the fused kernel is not sequence-sharded and
        # would gather the full [B*T, d] activations per device. (MoE
        # composes: the router aux rides return_hidden and is added after
        # the fused xent.)
        attn = changes.get("attn_impl", tc.attn_impl)
        unroll = changes.get("scan_unroll", tc.scan_unroll) or 1
        changes["fused_loss"] = (
            on_tpu
            and attn == "pallas"
            and getattr(plan, "sp_axis", None) is None
            and unroll < model_cfg.num_hidden_layers
        )
    return dataclasses.replace(tc, **changes)


class InnerTrainer:
    """Owns the optimizer, shardings, and the compiled train/eval steps.

    state pytree: {"params": f32 pytree, "opt_state": optax state, "step": i32}
    """

    def __init__(self, model_cfg: LlamaConfig, tc: TrainerConfig, plan: MeshPlan):
        # sp+pp composes as of round 5: the pipeline binds BOTH axes manual
        # and ring attention runs directly on the local sequence chunks.
        # --allow-sp-activation-sharding selects the fallback mode instead
        # (full-sequence attention, sp shards activations only); a non-ring
        # attention choice under sp+pp without that opt-in stays an error —
        # it would silently defeat the sp axis ("chosen, not discovered").
        tc = _resolve_perf_defaults(tc, model_cfg, plan)
        if (
            plan.pp_axis
            and getattr(plan, "sp_axis", None)
            and tc.attn_impl != "ring"
            and not tc.allow_sp_activation_sharding
        ):
            raise ValueError(
                f"sp+pp with attn_impl={tc.attn_impl!r} would shard "
                "activations while every device attends over the FULL "
                "sequence. Use the default/ring attention (composes with "
                "the pipeline), or opt into the activation-sharding mode "
                "with --allow-sp-activation-sharding"
            )
        self.model_cfg = model_cfg
        self.tc = tc
        self.plan = plan
        if plan.pp_axis:
            pp_n = plan.mesh.shape[plan.pp_axis]
            if model_cfg.num_hidden_layers % pp_n:
                raise ValueError(
                    f"{model_cfg.num_hidden_layers} layers cannot stage over "
                    f"pp={pp_n} (must divide evenly)"
                )
            if tc.attn_impl == "ring" and not getattr(plan, "sp_axis", None):
                raise ValueError(
                    "ring attention under pp needs a sequence-parallel axis "
                    "to ring over: add sp_size > 1 (the pipeline binds both "
                    "axes manual and the ring runs on each stage's local "
                    "chunks), or use attn_impl xla/pallas"
                )
        if plan.ep_axis:
            ep_n = plan.mesh.shape[plan.ep_axis]
            if model_cfg.num_experts == 0:
                raise ValueError(
                    f"--ep-size {ep_n} with a dense model silently replicates "
                    "work across the ep axis; use an MoE config (num_experts "
                    "> 0) or drop ep_size"
                )
            if model_cfg.num_experts % ep_n:
                raise ValueError(
                    f"{model_cfg.num_experts} experts cannot shard over "
                    f"ep={ep_n} (must divide evenly)"
                )
        self.optimizer = make_inner_optimizer(tc)
        self.schedule = make_schedule(tc)
        # post-dispatch hooks: state -> state transforms run right after
        # each train_step dispatch returns (the step itself is async on
        # device, so hook work overlaps it). The streaming outer scheduler
        # rides this to launch/land mid-phase fragment rounds without the
        # driver loop ever knowing.
        self._post_dispatch_hooks: list = []

        self.p_specs = param_specs(model_cfg, plan, for_params=True)
        params_shapes = jax.eval_shape(
            functools.partial(init_params, cfg=model_cfg), jax.random.key(0)
        )
        opt_shapes = jax.eval_shape(self.optimizer.init, params_shapes)
        self.opt_specs = optstate_specs(
            opt_shapes,
            params_shapes,
            param_specs(model_cfg, plan, for_params=False),
            plan,
        )
        from jax.sharding import PartitionSpec as P

        self.state_specs = {
            "params": self.p_specs,
            "opt_state": self.opt_specs,
            "step": P(),
            "scaler": {"scale": P(), "good_steps": P()},
        }
        self.state_shardings = jax.tree.map(
            plan.sharding, self.state_specs, is_leaf=lambda x: isinstance(x, P)
        )
        self._P = P

        self._train_step = jax.jit(
            self._train_step_impl,
            donate_argnums=(0,),
            in_shardings=(self.state_shardings, plan.sharding(plan.batch_spec(3, accum=True))),
            out_shardings=(self.state_shardings, None),
        )
        self._eval_step = jax.jit(
            self._eval_step_impl,
            in_shardings=(
                self.state_shardings["params"],
                plan.sharding(plan.batch_spec(2)),
            ),
        )
        self._probe_step = jax.jit(
            self._probe_step_impl,
            in_shardings=(
                self.state_shardings["params"],
                plan.sharding(plan.batch_spec(2)),
            ),
        )

    def lower_abstract(self, global_bs: int, seq: int, accum: int = 1):
        """Lower ``_train_step`` from ShapeDtypeStructs only (no arrays
        materialized) — the one recipe the offline cost/memory analyses
        share (scripts/aot_roofline.py, scripts/mfu_sweep.py). Deviceless
        AOT targets work too: the shardings carry the topology's devices."""
        state_sds = jax.tree.map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
            jax.eval_shape(self.init_state, jax.random.key(0)),
            self.state_shardings,
        )
        bsh = self.plan.sharding(self.plan.batch_spec(3, accum=True))
        if global_bs % accum:
            raise ValueError(f"global_bs {global_bs} not divisible by accum {accum}")
        batch_sds = {
            k: jax.ShapeDtypeStruct(
                (accum, global_bs // accum, seq), np.int32, sharding=bsh
            )
            for k in ("input_ids", "labels")
        }
        return self._train_step.lower(state_sds, batch_sds)

    # -- state ------------------------------------------------------------

    def init_state(self, rng: jax.Array, params: Optional[dict] = None) -> dict:
        """Initialize (or adopt) params and optimizer state, sharded per plan."""
        init_fn = functools.partial(init_params, cfg=self.model_cfg)

        if params is None:
            # init UNSHARDED, then reshard: with non-partitionable
            # threefry (this jax's default) a sharded out_shardings
            # changes the RNG lowering and thus the drawn values, so the
            # same seed would yield different weights on different
            # meshes — breaking every cross-mesh equivalence guarantee
            # (and DiLoCo's same-seed multi-worker init contract)
            params = jax.device_put(
                jax.jit(init_fn)(rng), self.state_shardings["params"]
            )
        else:
            params = jax.device_put(params, self.state_shardings["params"])
        opt_state = jax.jit(
            self.optimizer.init, out_shardings=self.state_shardings["opt_state"]
        )(params)
        step = jax.device_put(
            jnp.zeros((), jnp.int32), self.state_shardings["step"]
        )
        # device_put with the replicated sharding: an uncommitted scalar has
        # a different aval than the train-step output and would force a
        # second full compile at step 2
        scaler = jax.device_put(
            {
                "scale": jnp.float32(
                    self.tc.init_loss_scale if self.tc.use_loss_scaling else 1.0
                ),
                "good_steps": jnp.zeros((), jnp.int32),
            },
            self.state_shardings["scaler"],
        )
        return {
            "params": params,
            "opt_state": opt_state,
            "step": step,
            "scaler": scaler,
        }

    def force_step_position(self, state: dict, step: int) -> dict:
        """Teleport the LR-schedule position to ``step``.

        Used when a late joiner adopts the swarm's epoch (reference stubs
        scheduler sync, hivemind_diloco.py:54-58; here we own the stack, so a
        joiner at outer epoch E resumes the cosine schedule at
        E*local_steps instead of re-running warmup). Rewrites ``state["step"]``
        and every integer scalar counter inside the optax state (the adamw
        schedule reads its own ``count``), keeping shardings so the jit cache
        stays warm.
        """
        state = dict(state)
        state["step"] = jax.device_put(
            jnp.asarray(step, jnp.int32), self.state_shardings["step"]
        )

        def fix(leaf, shard):
            if (
                hasattr(leaf, "dtype")
                and getattr(leaf, "ndim", None) == 0
                and jnp.issubdtype(leaf.dtype, jnp.integer)
            ):
                return jax.device_put(jnp.asarray(step, leaf.dtype), shard)
            return leaf

        state["opt_state"] = jax.tree.map(
            fix, state["opt_state"], self.state_shardings["opt_state"]
        )
        return state

    # -- steps ------------------------------------------------------------

    def _fused_lm_loss(self, hidden: jax.Array, head: jax.Array, labels: jax.Array):
        """Shifted fused lm-head+xent over final hidden states (the single
        shift/reshape site for both the plain and pipeline paths). On
        multi-device meshes the SPMD entry runs the kernel manual over the
        batch shards (Mosaic cannot be auto-partitioned); single-device
        meshes take the plain kernel."""
        from opendiloco_tpu.ops.fused_xent import fused_linear_cross_entropy_sharded

        d = hidden.shape[-1]
        return fused_linear_cross_entropy_sharded(
            hidden[:, :-1].reshape(-1, d),
            head,
            labels[:, 1:].reshape(-1),
            mesh=self.plan.mesh,
            batch_axes=self.plan.batch_axes,
            tp_axis=self.plan.tp_axis,
        )

    def _loss_fn(self, params: dict, input_ids: jax.Array, labels: jax.Array):
        """Dispatch on mesh shape only; the moe/fused branching is shared.

        pp meshes stage the decoder stack over the pp axis
        (parallel/pipeline.py) with embed / final norm / head replicated;
        non-pp meshes thread the ring-attention mesh instead. fused_loss
        composes with both (they hand back hidden states), and the MoE
        router aux rides return_moe_aux either way (through the pipeline's
        per-stage accumulators under pp)."""
        if self.plan.pp_axis:
            fwd_kwargs = dict(
                pp_mesh=self.plan.mesh,
                pp_axis=self.plan.pp_axis,
                pp_microbatches=self.tc.pp_microbatches,
                # sp+pp: forward threads the ring axis into the pipeline's
                # manual region (ring runs directly on the local chunks)
                ring_mesh=self.plan.mesh,
                ring_axis=self.plan.sp_axis or "sp",
            )
        else:
            fwd_kwargs = dict(
                ring_mesh=self.plan.mesh,
                ring_axis=self.plan.sp_axis or "sp",
            )
        moe = bool(self.model_cfg.num_experts)
        aux = lambda a: self.model_cfg.router_aux_coef * a
        fwd_kwargs.update(
            batch_axes=self.plan.batch_axes,
            tp_axis=self.plan.tp_axis,
            compute_dtype=self.tc.compute_dtype,
            attn_impl=self.tc.attn_impl,
            remat=self.tc.remat,
            scan_unroll=self.tc.scan_unroll,
        )
        if self.tc.fused_loss:
            out = forward(
                params,
                input_ids,
                self.model_cfg,
                return_hidden=True,
                return_moe_aux=moe,
                **fwd_kwargs,
            )
            if moe:
                hidden, head, moe_aux = out
                return self._fused_lm_loss(hidden, head, labels) + aux(moe_aux)
            hidden, head = out
            return self._fused_lm_loss(hidden, head, labels)
        out = forward(
            params, input_ids, self.model_cfg, return_moe_aux=moe, **fwd_kwargs
        )
        if moe:
            logits, moe_aux = out
            return causal_lm_loss(logits, labels) + aux(moe_aux)
        return causal_lm_loss(out, labels)

    def _train_step_impl(self, state: dict, batch: dict):
        """batch arrays are [accum, global_microbatch, seq]."""
        params = state["params"]
        accum = batch["input_ids"].shape[0]
        scale = state["scaler"]["scale"]

        def scaled_loss(p, ids, labels):
            return self._loss_fn(p, ids, labels) * scale

        grad_fn = jax.value_and_grad(scaled_loss)

        def micro(carry, mb):
            loss_sum, grad_sum = carry
            loss, grads = grad_fn(params, mb["input_ids"], mb["labels"])
            return (
                loss_sum + loss,
                jax.tree.map(jnp.add, grad_sum, grads),
            ), None

        zero_grads = jax.tree.map(jnp.zeros_like, params)
        (loss_sum, grad_sum), _ = jax.lax.scan(micro, (0.0, zero_grads), batch)
        inv = 1.0 / (accum * scale)
        grads = jax.tree.map(lambda g: g * inv, grad_sum)
        loss = loss_sum * inv

        grad_norm = optax.global_norm(grads)
        updates, opt_state = self.optimizer.update(
            grads, state["opt_state"], params
        )
        new_params = optax.apply_updates(params, updates)

        if self.tc.use_loss_scaling:
            # GradScaler semantics (found_inf_grad, utils.py:124-135): on
            # non-finite grads skip the update and halve the scale; grow 2x
            # after scale_growth_interval clean steps
            finite = jnp.isfinite(grad_norm)
            keep = lambda new, old: jax.tree.map(
                lambda a, b: jnp.where(finite, a, b), new, old
            )
            new_params = keep(new_params, params)
            opt_state = keep(opt_state, state["opt_state"])
            good = jnp.where(finite, state["scaler"]["good_steps"] + 1, 0)
            grow = finite & (good >= self.tc.scale_growth_interval)
            new_scale = jnp.where(
                finite, jnp.where(grow, scale * 2.0, scale), scale * 0.5
            )
            scaler = {
                "scale": new_scale,
                "good_steps": jnp.where(grow, 0, good),
            }
            metrics = {
                "loss": loss,
                "grad_norm": grad_norm,
                "found_inf": (~finite).astype(jnp.float32),
                "loss_scale": scale,
            }
        else:
            scaler = state["scaler"]
            metrics = {"loss": loss, "grad_norm": grad_norm}
        return (
            {
                "params": new_params,
                "opt_state": opt_state,
                "step": state["step"] + 1,
                "scaler": scaler,
            },
            metrics,
        )

    def _eval_step_impl(self, params: dict, batch: dict):
        return self._loss_fn(params, batch["input_ids"], batch["labels"])

    def _probe_step_impl(self, params: dict, batch: dict):
        """Activation-norm probes (reference register_metrics_hooks,
        utils.py:43-67): runs a forward with taps, no grads."""
        _, aux = forward(
            params,
            batch["input_ids"],
            self.model_cfg,
            compute_dtype=self.tc.compute_dtype,
            attn_impl=self.tc.attn_impl,
            remat=False,
            return_aux=True,
            ring_mesh=self.plan.mesh,
            ring_axis=self.plan.sp_axis or "sp",
            batch_axes=self.plan.batch_axes,
            tp_axis=self.plan.tp_axis,
        )
        return aux

    # -- host API ---------------------------------------------------------

    def _to_global(self, a, sharding, batch_axis: int):
        """Host array -> global device array. Single-process: the array IS
        the global batch. Multihost: each process passes its LOCAL rows
        (the dataloader shards by process) and the global array is
        assembled from per-process shards."""
        if jax.process_count() == 1:
            return jax.device_put(a, sharding)
        global_shape = list(a.shape)
        global_shape[batch_axis] *= jax.process_count()
        return jax.make_array_from_process_local_data(
            sharding, a, tuple(global_shape)
        )

    def shard_batch(self, input_ids: np.ndarray, labels: np.ndarray, accum: int) -> dict:
        """[local_bs, T] host arrays -> [accum, mb, T] device arrays
        (local_bs = global batch / process_count under multihost)."""
        gbs, seq = input_ids.shape
        assert gbs % accum == 0, (gbs, accum)
        shaped = lambda a: a.reshape(accum, gbs // accum, seq)
        sharding = self.plan.sharding(self.plan.batch_spec(3, accum=True))
        return {
            "input_ids": self._to_global(shaped(input_ids), sharding, 1),
            "labels": self._to_global(shaped(labels), sharding, 1),
        }

    def add_post_dispatch_hook(self, fn) -> None:
        """Register a ``state -> state`` callback fired after every
        ``train_step`` dispatch (on the calling thread, while the step
        itself still runs on device)."""
        self._post_dispatch_hooks.append(fn)

    def train_step(self, state: dict, batch: dict):
        tr = obs.tracer()
        if tr is None:
            state, metrics = self._train_step(state, batch)
        else:
            # dispatch wall only: the jit'd step is async, device time
            # surfaces in the driver's step gap (train.py logs the synced
            # step time)
            t0 = tr.now()
            state, metrics = self._train_step(state, batch)
            tr.add_span("inner/dispatch", t0, tr.now())
            tr.count("inner_steps")
        for hook in self._post_dispatch_hooks:
            state = hook(state)
        return state, metrics

    def eval_loss(self, params: dict, input_ids: np.ndarray, labels: np.ndarray) -> float:
        sharding = self.plan.sharding(self.plan.batch_spec(2))
        batch = {
            "input_ids": self._to_global(input_ids, sharding, 0),
            "labels": self._to_global(labels, sharding, 0),
        }
        return float(self._eval_step(params, batch))

    def probe_norms(self, params: dict, input_ids: np.ndarray) -> dict:
        sharding = self.plan.sharding(self.plan.batch_spec(2))
        batch = {
            "input_ids": self._to_global(input_ids, sharding, 0),
            "labels": self._to_global(np.zeros_like(input_ids), sharding, 0),
        }
        aux = jax.device_get(self._probe_step(params, batch))
        out = {
            f"activation_norm/layers.{i}.self_attn": float(v)
            for i, v in enumerate(aux["attn_out_norm"])
        }
        out["activation_norm/lm_head"] = float(aux["lm_head_norm"])
        return out

    def current_lr(self, step: int) -> float:
        return float(self.schedule(step))
