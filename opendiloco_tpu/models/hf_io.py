"""HF-interop: load/save Llama weights as HF-named safetensors.

Parity target: the reference loads ``LlamaForCausalLM.from_pretrained`` from a
local path or hub id (open_diloco/train_fsdp.py:171-174) and ships a committed
2M-parameter test model (tests/models/llama-2m-fresh). We read/write the same
``model.safetensors`` naming so checkpoints interchange with HF tooling.

Layout differences handled here:
- HF linear weights are [out_features, in_features]; ours are [in, out]
  (we compute ``x @ W``) -> transpose on both directions.
- Our per-layer weights are stacked on a leading layer axis for
  ``lax.scan``; HF keys are per-layer -> stack/unstack.
"""

from __future__ import annotations

import json
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from opendiloco_tpu.models.llama import LlamaConfig, shapes

_PKG_CONFIG_DIR = os.path.join(os.path.dirname(__file__), "configs")

# (our layer-tree key, HF module name, transpose?)
_LAYER_KEYS = [
    ("input_norm", "input_layernorm", False),
    ("post_attn_norm", "post_attention_layernorm", False),
    ("q_proj", "self_attn.q_proj", True),
    ("k_proj", "self_attn.k_proj", True),
    ("v_proj", "self_attn.v_proj", True),
    ("o_proj", "self_attn.o_proj", True),
    ("gate_proj", "mlp.gate_proj", True),
    ("up_proj", "mlp.up_proj", True),
    ("down_proj", "mlp.down_proj", True),
]


def resolve_model_path(path_model: str) -> str:
    """Map a name like 'configs/config_150m.json', a packaged size name
    ('150m'), or a directory path to a concrete config path/dir."""
    if os.path.isdir(path_model) or os.path.isfile(path_model):
        return path_model
    short = path_model.removeprefix("configs/").removesuffix(".json")
    short = short.removeprefix("config_")
    candidate = os.path.join(_PKG_CONFIG_DIR, f"config_{short}.json")
    if os.path.isfile(candidate):
        return candidate
    raise FileNotFoundError(f"cannot resolve model path {path_model!r}")


def load_config(path_model: str) -> LlamaConfig:
    path = resolve_model_path(path_model)
    if os.path.isdir(path):
        path = os.path.join(path, "config.json")
    return LlamaConfig.from_json(path)


def _reject_moe(cfg: LlamaConfig, op: str) -> None:
    if cfg.num_experts:
        raise ValueError(
            f"cannot {op} MoE weights as HF llama safetensors (the llama "
            "architecture has no routed experts); use the framework "
            "checkpointer (opendiloco_tpu.ckpt) for MoE models"
        )


def load_params(model_dir: str, cfg: Optional[LlamaConfig] = None) -> dict:
    """Read an HF llama ``model.safetensors`` into our stacked pytree."""
    from safetensors import safe_open

    if cfg is None:
        cfg = load_config(model_dir)
    _reject_moe(cfg, "load")
    st_path = os.path.join(model_dir, "model.safetensors")
    tensors: dict[str, np.ndarray] = {}
    with safe_open(st_path, framework="numpy") as f:
        for key in f.keys():
            tensors[key] = f.get_tensor(key)

    def get(name: str, transpose: bool) -> np.ndarray:
        t = tensors[name].astype(np.float32)
        return t.T if transpose else t

    L = cfg.num_hidden_layers
    layers = {}
    for ours, hf, tr in _LAYER_KEYS:
        layers[ours] = jnp.asarray(
            np.stack(
                [get(f"model.layers.{i}.{hf}.weight", tr) for i in range(L)], axis=0
            )
        )
    params = {
        "embed_tokens": jnp.asarray(get("model.embed_tokens.weight", False)),
        "layers": layers,
        "final_norm": jnp.asarray(get("model.norm.weight", False)),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = jnp.asarray(get("lm_head.weight", True))
    chex_shapes = shapes(cfg)
    got = jax.tree.map(lambda x: x.shape, params)
    want = jax.tree.map(lambda s: s.shape, chex_shapes)
    if got != want:
        raise ValueError(f"weight shapes mismatch config: {got} vs {want}")
    return params


def save_params(params: dict, cfg: LlamaConfig, model_dir: str) -> None:
    """Write our pytree as an HF-named ``model.safetensors`` + config.json."""
    from safetensors.numpy import save_file

    _reject_moe(cfg, "save")
    os.makedirs(model_dir, exist_ok=True)
    out: dict[str, np.ndarray] = {}
    np_params = jax.tree.map(lambda x: np.asarray(x, dtype=np.float32), params)
    out["model.embed_tokens.weight"] = np.ascontiguousarray(np_params["embed_tokens"])
    out["model.norm.weight"] = np.ascontiguousarray(np_params["final_norm"])
    if not cfg.tie_word_embeddings:
        out["lm_head.weight"] = np.ascontiguousarray(np_params["lm_head"].T)
    for ours, hf, tr in _LAYER_KEYS:
        stacked = np_params["layers"][ours]
        for i in range(cfg.num_hidden_layers):
            t = stacked[i]
            out[f"model.layers.{i}.{hf}.weight"] = np.ascontiguousarray(
                t.T if tr else t
            )
    save_file(out, os.path.join(model_dir, "model.safetensors"))
    with open(os.path.join(model_dir, "config.json"), "w") as f:
        json.dump(cfg.to_dict(), f, indent=2)


def get_model(path_model: str) -> tuple[LlamaConfig, Optional[dict]]:
    """Reference-shaped entry (train_fsdp.py:171-174): resolve a model source.

    Returns (config, params). params is None when the source is a bare size
    config (caller should ``init_params``); a directory with safetensors loads
    real weights.
    """
    path = resolve_model_path(path_model)
    if os.path.isdir(path):
        cfg = load_config(path)
        return cfg, load_params(path, cfg)
    cfg = LlamaConfig.from_json(path)
    return cfg, None
