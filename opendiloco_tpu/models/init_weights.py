"""Materialize fresh Llama weights from a size config.

CLI parity with the reference's init_weights.py (open_diloco/init_weights.py:7-25):

    python -m opendiloco_tpu.models.init_weights \\
        --config 2m --output tests/models/llama-2m-fresh [--seed 42]

Writes an HF-compatible model directory (model.safetensors + config.json)
loadable by both this framework and ``transformers``.
"""

from __future__ import annotations

import argparse


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", required=True, help="size name (2m..1b) or config path")
    ap.add_argument("--output", required=True, help="output model directory")
    ap.add_argument("--seed", type=int, default=42)
    args = ap.parse_args(argv)

    import jax

    from opendiloco_tpu.models import hf_io
    from opendiloco_tpu.models.llama import init_params

    cfg = hf_io.load_config(args.config)
    params = init_params(jax.random.key(args.seed), cfg)
    hf_io.save_params(params, cfg, args.output)
    n = cfg.num_params()
    print(f"wrote {n:,}-param llama ({args.config}) to {args.output}")


if __name__ == "__main__":
    main()
