"""Functional Llama-for-causal-LM, TPU-first.

Capability parity with the reference's use of HF ``LlamaForCausalLM``
(open_diloco/train_fsdp.py:171-174) and the size configs under
open_diloco/configs/*.json -- but designed for XLA, not translated:

- Parameters are a plain pytree (nested dicts of jax.Arrays). Per-layer
  weights are **stacked along a leading layer axis** and the decoder runs as a
  single ``lax.scan`` over layers: one compiled block regardless of depth,
  fast compiles, and clean per-layer rematerialization.
- Compute dtype (bf16) is applied at the forward boundary; master params stay
  float32 (the "bf16-mixed" of train_fsdp.py:228 without a GradScaler --
  bf16 on TPU needs no loss scaling, as the reference README itself notes).
- Attention dispatches through opendiloco_tpu.ops.attention (XLA / Pallas
  flash / ring).
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
from typing import Any, Literal, Optional, Union

import jax
import jax.numpy as jnp

from opendiloco_tpu.ops.attention import (
    decode_attention,
    spec_tail_attention,
    xla_attention,
)
from opendiloco_tpu.ops.decode_kernels import (
    paged_decode_attention,
    spec_tail_attention_fused,
    w4_matmul,
    w4_matmul_supported,
)


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    """Model hyperparameters, JSON-compatible with HF llama configs
    (open_diloco/configs/config_{2m,14m,60m,150m,1b}.json)."""

    vocab_size: int = 32_000
    hidden_size: int = 1024
    intermediate_size: int = 2688
    num_hidden_layers: int = 12
    num_attention_heads: int = 16
    num_key_value_heads: Optional[int] = None
    max_position_embeddings: int = 2048
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10_000.0
    tie_word_embeddings: bool = False
    initializer_range: float = 0.02
    # Mixture-of-Experts (beyond the reference's dense-only zoo): 0 = dense
    # FFN; > 0 = Switch-style top-1 routed experts in every layer, sharded
    # over the "ep" mesh axis
    num_experts: int = 0
    expert_capacity_factor: float = 1.25
    router_aux_coef: float = 0.01

    @property
    def kv_heads(self) -> int:
        return self.num_key_value_heads or self.num_attention_heads

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @classmethod
    def from_json(cls, path: str) -> "LlamaConfig":
        with open(path) as f:
            raw = json.load(f)
        return cls.from_dict(raw)

    @classmethod
    def from_dict(cls, raw: dict[str, Any]) -> "LlamaConfig":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in raw.items() if k in fields})

    def to_dict(self) -> dict[str, Any]:
        d = dataclasses.asdict(self)
        if d["num_key_value_heads"] is None:
            d["num_key_value_heads"] = self.num_attention_heads
        d.update(
            architectures=["LlamaForCausalLM"],
            model_type="llama",
            hidden_act="silu",
            use_cache=False,
        )
        return d

    def num_params(self) -> int:
        return sum(x.size for x in jax.tree.leaves(shapes(self)))


def shapes(cfg: LlamaConfig) -> dict:
    """ShapeDtypeStructs of the parameter pytree (all float32 masters)."""
    D, F, V = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    L, Nh, Nkv, Dh = (
        cfg.num_hidden_layers,
        cfg.num_attention_heads,
        cfg.kv_heads,
        cfg.head_dim,
    )
    f32 = jnp.float32

    def s(*shape):
        return jax.ShapeDtypeStruct(shape, f32)

    E = cfg.num_experts
    ffn = (
        {
            "router": s(L, D, E),
            "gate_proj": s(L, E, D, F),
            "up_proj": s(L, E, D, F),
            "down_proj": s(L, E, F, D),
        }
        if E
        else {
            "gate_proj": s(L, D, F),
            "up_proj": s(L, D, F),
            "down_proj": s(L, F, D),
        }
    )
    tree = {
        "embed_tokens": s(V, D),
        "layers": {
            "input_norm": s(L, D),
            "post_attn_norm": s(L, D),
            "q_proj": s(L, D, Nh * Dh),
            "k_proj": s(L, D, Nkv * Dh),
            "v_proj": s(L, D, Nkv * Dh),
            "o_proj": s(L, Nh * Dh, D),
            **ffn,
        },
        "final_norm": s(D),
    }
    if not cfg.tie_word_embeddings:
        tree["lm_head"] = s(D, V)
    return tree


def init_params(rng: jax.Array, cfg: LlamaConfig) -> dict:
    """Fresh init matching HF llama conventions: normal(0, initializer_range)
    for projections/embeddings, ones for norms (init_weights.py parity)."""
    shp = shapes(cfg)
    # tree_util spelling: the jax.tree.flatten_with_path alias only exists
    # in newer jax releases and this is the one call site that needs it
    leaves, treedef = jax.tree_util.tree_flatten_with_path(shp)
    keys = jax.random.split(rng, len(leaves))
    out = []
    for key, (path, leaf) in zip(keys, leaves):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if "norm" in name:
            out.append(jnp.ones(leaf.shape, leaf.dtype))
        else:
            out.append(
                jax.random.normal(key, leaf.shape, leaf.dtype) * cfg.initializer_range
            )
    return jax.tree.unflatten(treedef, out)


# rematerialization policy accepted everywhere a `remat` argument appears:
# False/"none" saves all activations; True/"full" checkpoints per layer;
# "dots" saves MXU outputs and recomputes the elementwise chain;
# "dots_all" additionally saves batched dots (more memory, less recompute)
RematPolicy = Union[bool, Literal["none", "full", "dots", "dots_all"]]


def _maybe_remat(block, remat: RematPolicy):
    """Apply the rematerialization policy to a per-layer block function.

    remat=False/"none": save all activations (no recompute -- fastest when
    they fit); True/"full": save only layer boundaries (reference-style full
    checkpointing); "dots": save matmul/MXU outputs and recompute the cheap
    elementwise chain (norms, rope, silu) -- recovers most of full remat's
    memory while skipping the extra forward through the matmuls, which is
    where ~all the FLOPs are."""
    if remat in (False, None, "none"):
        return block
    if remat in (True, "full"):
        return jax.checkpoint(block)
    if remat in ("dots", "dots_all"):
        # also save the flash-attention outputs (tagged in
        # ops/flash_attention._flash_fwd): they are custom-calls, not dots,
        # so the dots policy alone would rerun the whole forward kernel
        # during backward just to rebuild its residuals. "dots_all" saves
        # batched dots too (the XLA-attention score/weighted-sum matmuls),
        # trading more HBM for less backward recompute
        dots = (
            jax.checkpoint_policies.dots_saveable
            if remat == "dots_all"
            else jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        )
        return jax.checkpoint(
            block,
            policy=jax.checkpoint_policies.save_from_both_policies(
                dots,
                jax.checkpoint_policies.save_only_these_names(
                    "attn_out", "attn_lse"
                ),
            ),
        )
    raise ValueError(f"unknown remat policy {remat!r}")


def _rms_norm(x: jax.Array, weight: jax.Array, eps: float) -> jax.Array:
    # variance in float32 for stability (HF llama semantics)
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    xf = xf * jax.lax.rsqrt(var + eps)
    return (xf * weight.astype(jnp.float32)).astype(x.dtype)


def _rope_tables(
    positions: jax.Array, d: int, theta: float
) -> tuple[jax.Array, jax.Array]:
    """(cos, sin) [B, T, 1, D/2] float32 for the given positions.

    Hoisted out of the layer scan: the tables are shared by every layer's
    q and k, so the cos/sin transcendentals run once per step instead of
    2*num_layers times."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [B, T, D/2]
    return jnp.cos(angles)[:, :, None, :], jnp.sin(angles)[:, :, None, :]


def _rope_apply(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Rotate [B, T, H, D] by precomputed tables (HF half-rotation layout).

    Rotation happens in x's dtype (HF llama applies rope in the input dtype
    too): the tables are f32 but cos/sin magnitudes are <= 1, so bf16
    rotation loses no more precision than the bf16 q/k it feeds -- and the
    [B, T, H, D] elementwise chain stays off the f32 HBM budget."""
    c = cos.astype(x.dtype)
    s = sin.astype(x.dtype)
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate((x1 * c - x2 * s, x2 * c + x1 * s), axis=-1)


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding over [B, T, H, D] with HF half-rotation layout."""
    cos, sin = _rope_tables(positions, x.shape[-1], theta)
    return _rope_apply(x, cos, sin)


def _switch_ffn(
    cfg: LlamaConfig, x: jax.Array, layer: dict
) -> tuple[jax.Array, jax.Array]:
    """Switch-Transformer top-1 routed expert FFN -> (out, aux_loss).

    Dispatch/combine are dense einsums over a [tokens, experts, capacity]
    one-hot, so sharding the expert dim over the "ep" mesh axis is a pure
    PartitionSpec concern -- pjit slices the expert matmuls per device, no
    hand-written all-to-all. Over-capacity tokens pass through the residual
    only (standard Switch semantics)."""
    B, T, D = x.shape
    E = cfg.num_experts
    N = B * T
    cap = max(1, math.ceil(N / E * cfg.expert_capacity_factor))
    xf = x.reshape(N, D)

    logits = (xf @ layer["router"]).astype(jnp.float32)  # [N, E]
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)  # [N]
    gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32)  # [N, E]

    # load-balance aux (Switch eq. 4): density * router-probability mass
    density = jnp.mean(onehot, axis=0)
    density_proxy = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(density * density_proxy)

    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0  # slot within expert
    # one_hot is already all-zero for pos = -1 (not routed here) and for
    # pos >= cap (over capacity), so it doubles as the keep mask
    dispatch = onehot[:, :, None] * jax.nn.one_hot(
        pos.astype(jnp.int32), cap, dtype=jnp.float32
    )  # [N, E, C]

    d = dispatch.astype(x.dtype)
    expert_in = jnp.einsum("nec,nd->ecd", d, xf)  # [E, C, D]
    h1 = jax.nn.silu(
        jnp.einsum("ecd,edf->ecf", expert_in, layer["gate_proj"])
    ) * jnp.einsum("ecd,edf->ecf", expert_in, layer["up_proj"])
    out_e = jnp.einsum("ecf,efd->ecd", h1, layer["down_proj"])
    combine = d * gate.astype(x.dtype)[:, None, None]
    y = jnp.einsum("nec,ecd->nd", combine, out_e)
    return y.reshape(B, T, D), aux


def _decoder_block(
    cfg: LlamaConfig,
    attn_fn,
    h: jax.Array,
    layer: dict,
    positions: jax.Array,
    rope: Optional[tuple[jax.Array, jax.Array]] = None,
) -> tuple[jax.Array, tuple[jax.Array, jax.Array]]:
    """Returns (hidden, (attn-output L2 norm, moe aux loss)). The norm is
    the activation probe the reference attaches via forward hooks on
    ``self_attn`` (utils.py:43-67, train_fsdp.py:65)."""
    B, T, D = h.shape
    Nh, Nkv, Dh = cfg.num_attention_heads, cfg.kv_heads, cfg.head_dim
    if rope is None:
        rope = _rope_tables(positions, cfg.head_dim, cfg.rope_theta)
    cos, sin = rope

    x = _rms_norm(h, layer["input_norm"], cfg.rms_norm_eps)
    q = (x @ layer["q_proj"]).reshape(B, T, Nh, Dh)
    k = (x @ layer["k_proj"]).reshape(B, T, Nkv, Dh)
    v = (x @ layer["v_proj"]).reshape(B, T, Nkv, Dh)
    q = _rope_apply(q, cos, sin)
    k = _rope_apply(k, cos, sin)
    attn = attn_fn(q, k, v)
    attn_out = attn.reshape(B, T, Nh * Dh) @ layer["o_proj"]
    attn_norm = jnp.sqrt(jnp.sum(attn_out.astype(jnp.float32) ** 2))
    h = h + attn_out

    x = _rms_norm(h, layer["post_attn_norm"], cfg.rms_norm_eps)
    if cfg.num_experts:
        ffn, aux = _switch_ffn(cfg, x, layer)
    else:
        ffn = (
            jax.nn.silu(x @ layer["gate_proj"]) * (x @ layer["up_proj"])
        ) @ layer["down_proj"]
        aux = jnp.float32(0.0)
    return h + ffn, (attn_norm, aux)


def forward(
    params: dict,
    input_ids: jax.Array,
    cfg: LlamaConfig,
    *,
    compute_dtype: jnp.dtype = jnp.bfloat16,
    attn_impl: str = "xla",
    remat: RematPolicy = True,
    positions: Optional[jax.Array] = None,
    return_aux: bool = False,
    return_hidden: bool = False,
    ring_mesh=None,  # the plan mesh: ring attention AND the SPMD kernel
    # wrappers key off it — a multi-device pallas caller MUST pass it (a
    # pallas operand with a sharded dim fails XLA compile otherwise)
    ring_axis: str = "sp",
    pp_mesh=None,
    pp_axis: str = "pp",
    pp_microbatches: Optional[int] = None,
    return_moe_aux: bool = False,
    batch_axes: tuple = (),
    tp_axis: Optional[str] = None,
    scan_unroll: Optional[int] = None,
):
    """input_ids [B, T] int32 -> logits [B, T, V] float32.

    return_hidden=True returns (final_hidden [B, T, D], head [D, V]) instead
    of logits -- the hook for fused lm-head losses (ops/fused_xent.py);
    with return_moe_aux=True it returns (final_hidden, head, moe_aux) so
    those losses can thread the router aux term.

    return_aux=True additionally returns activation-probe metrics
    {"attn_out_norm": [L], "lm_head_norm": scalar} (the reference's
    self_attn/lm_head hook probes, utils.py:43-67)."""
    B, T = input_ids.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))

    cparams = jax.tree.map(lambda x: x.astype(compute_dtype), params)

    if attn_impl == "xla":
        attn_fn = lambda q, k, v: xla_attention(q, k, v, causal=True)
    elif attn_impl == "pallas":
        from opendiloco_tpu.ops.flash_attention import (
            flash_attention,
            flash_attention_sharded,
        )

        if pp_mesh is None and ring_mesh is not None and ring_mesh.size > 1:
            # multi-device mesh: Mosaic kernels cannot be auto-partitioned,
            # so the kernel runs manual over the sharded activation axes
            # (flash_attention_sharded).
            mesh_ = ring_mesh
            attn_fn = lambda q, k, v: flash_attention_sharded(
                q, k, v, mesh=mesh_, batch_axes=batch_axes, tp_axis=tp_axis,
                causal=True,
            )
        elif pp_mesh is not None and any(
            s > 1 for a, s in pp_mesh.shape.items() if a not in (pp_axis, ring_axis)
        ):
            # pp composed with dp/fsdp/tp/ep: pipeline_hidden binds only
            # pp (and sp) manual, so those axes stay AUTO inside the
            # region and operands reach the kernel still sharded — Mosaic
            # cannot be auto-partitioned, and wrapping a shard_map here
            # would nest inside the pp-manual region, which has no jvp
            # lowering. Documented downgrade: XLA attention (fuses fine;
            # the pallas win is single-stage-measured ~+5-20%).
            attn_fn = lambda q, k, v: xla_attention(q, k, v, causal=True)
        else:
            attn_fn = lambda q, k, v: flash_attention(q, k, v, causal=True)
    elif attn_impl == "ring":
        from opendiloco_tpu.ops.ring_attention import ring_attention_auto

        attn_fn = lambda q, k, v: ring_attention_auto(
            q, k, v, mesh=ring_mesh, axis=ring_axis
        )
    else:
        raise ValueError(f"unknown attn_impl {attn_impl!r}")

    h = jnp.take(cparams["embed_tokens"], input_ids, axis=0)

    if pp_mesh is not None:
        # decoder stack staged over the pp mesh axis (parallel/pipeline.py);
        # activation probes are not threaded through the pipeline
        from opendiloco_tpu.parallel.pipeline import pipeline_hidden

        h, moe_aux = pipeline_hidden(
            cparams,
            h,
            positions,
            cfg,
            pp_mesh,
            microbatches=pp_microbatches or pp_mesh.shape[pp_axis],
            attn_fn=attn_fn,
            remat=remat,
            axis=pp_axis,
            # sp+pp composition: the pipeline binds the ring axis manual
            # too, and ring attention runs directly on the local chunks
            sp_axis=ring_axis if attn_impl == "ring" else None,
        )
        attn_norms = jnp.zeros((cfg.num_hidden_layers,), jnp.float32)
    else:
        rope = _rope_tables(positions, cfg.head_dim, cfg.rope_theta)
        block = lambda h, layer: _decoder_block(
            cfg, attn_fn, h, layer, positions, rope
        )
        block = _maybe_remat(block, remat)
        # Unroll the layer scan N-wide (N >= num layers removes the while
        # loop entirely). The trainer auto-resolves scan_unroll to FULL
        # unroll on TPU for dense stacks (measured +6.8% tok/s on the
        # HBM-bound 150m step -- cross-layer scheduling/fusion; round-5
        # live window). ODTP_SCAN_UNROLL overrides for experiments and for
        # scripts/aot_roofline.py -- cost analysis counts a while-loop body
        # ONCE, so per-layer FLOPs/bytes only become visible to the
        # compiled-HLO cost model when the stack is unrolled.
        env_unroll = os.environ.get("ODTP_SCAN_UNROLL")
        unroll = int(env_unroll) if env_unroll else (scan_unroll or 1)
        h, (attn_norms, layer_auxs) = jax.lax.scan(
            block, h, cparams["layers"], unroll=max(1, unroll)
        )
        moe_aux = jnp.mean(layer_auxs)

    h = _rms_norm(h, cparams["final_norm"], cfg.rms_norm_eps)
    head = (
        cparams["embed_tokens"].T
        if cfg.tie_word_embeddings
        else cparams["lm_head"]
    )
    if return_hidden:
        # composes with return_moe_aux so fused lm-head losses can thread
        # the router aux loss (trainer._loss_fn)
        return (h, head, moe_aux) if return_moe_aux else (h, head)
    logits = (h @ head).astype(jnp.float32)
    if return_aux:
        aux = {
            "attn_out_norm": attn_norms,
            "lm_head_norm": jnp.sqrt(jnp.sum(logits**2)),
            "moe_aux": moe_aux,
        }
        return logits, aux
    if return_moe_aux:
        return logits, moe_aux
    return logits


# ---------------------------------------------------------------------------
# serving: prefill / incremental decode over a slot-paged ring KV cache
# (opendiloco_tpu/serve). Dense stacks only — routed-expert decode would
# need capacity bookkeeping per step and no serving config uses MoE yet.
# ---------------------------------------------------------------------------


W4_BLOCK = 4096  # matches diloco.compression._BLOCK (pinned by tests)


@jax.tree_util.register_pytree_node_class
class PackedW4:
    """A matmul weight held blockwise-4-bit-packed at rest (serve
    ``weight_format=w4``): ``q`` [..., ceil(n/2)] uint8 packed nibbles and
    ``s`` [..., nblocks] uint16 fp16-bit scales per ``W4_BLOCK`` values —
    the PR 8 ``blockwise4bit`` codec geometry, applied per layer so the
    packed leaves keep the leading L axis and ride the decode layer scan.
    ``shape`` is the per-layer unpacked shape (static aux data, so scan
    reconstructs the node with it intact)."""

    def __init__(self, q, s, shape):
        self.q = q
        self.s = s
        self.shape = tuple(int(x) for x in shape)

    def tree_flatten(self):
        return (self.q, self.s), self.shape

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux)


def dequant_w4(q: jax.Array, s: jax.Array, shape: tuple, dtype) -> jax.Array:
    """Unpack one layer's 4-bit weight inside the jit'd forward.

    Bit-for-bit the ``native._dequant4_numpy`` math at f32: element 2i is
    the low nibble of byte i, value = (nibble - 8) * fp16(scale) / 7."""
    n = 1
    for x in shape:
        n *= int(x)
    nib = jnp.stack([q & jnp.uint8(0x0F), q >> 4], axis=-1).reshape(-1)[:n]
    qv = nib.astype(jnp.float32) - jnp.float32(8.0)
    sf = jax.lax.bitcast_convert_type(s, jnp.float16).astype(jnp.float32)
    sf = sf / jnp.float32(7.0)
    pad = (-n) % W4_BLOCK
    qp = jnp.pad(qv, (0, pad)).reshape(-1, W4_BLOCK)
    out = (qp * sf[:, None]).reshape(-1)[:n].reshape(shape)
    return out.astype(dtype)


def _wleaf(w, dtype):
    """Materialize a weight leaf for a matmul: packed leaves dequantize
    per-block here, inside the jit (fused dequant+matmul); plain arrays
    pass through (already cast by ``_cast_serving_params``)."""
    if isinstance(w, PackedW4):
        return dequant_w4(w.q, w.s, w.shape, dtype)
    return w


def _wmul(x, w, dtype, kernel="xla"):
    """One weight-matmul site: ``x @ materialized(w)``.

    On the Pallas decode path a packed leaf routes through the fused
    dequant-matmul kernel — nibbles dequantize in-registers per tile —
    instead of materializing the full weight via ``_wleaf``. Dense
    leaves and untileable packed shapes keep the XLA contraction."""
    if (
        kernel == "pallas"
        and isinstance(w, PackedW4)
        and w4_matmul_supported(w.shape)
    ):
        lead = x.shape[:-1]
        out = w4_matmul(x.reshape(-1, x.shape[-1]), w.q, w.s, w.shape, dtype)
        return out.reshape(*lead, w.shape[1])
    return x @ _wleaf(w, dtype)


def _cast_serving_params(params, dtype):
    """The forward-boundary cast, w4-aware: packed uint8/uint16 leaves
    stay packed (their dequant targets ``dtype`` at the matmul site)."""
    return jax.tree.map(
        lambda x: x if x.dtype in (jnp.uint8, jnp.uint16) else x.astype(dtype),
        params,
    )


def init_kv_cache(
    cfg: LlamaConfig,
    num_slots: int,
    max_context: int,
    dtype: jnp.dtype = jnp.bfloat16,
) -> dict:
    """Zeroed {"k","v"} cache pages [L, S, T, Nkv, Dh]: one fixed-size ring
    page per batch slot (the degenerate paged layout — page size == slot
    context). Writes wrap at T, so a sequence that outgrows its page keeps
    decoding with sliding-window attention over the last T tokens."""
    shape = (
        cfg.num_hidden_layers,
        num_slots,
        max_context,
        cfg.kv_heads,
        cfg.head_dim,
    )
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def _require_dense(cfg: LlamaConfig, what: str) -> None:
    if cfg.num_experts:
        raise NotImplementedError(f"{what} supports dense FFN stacks only")


def prefill_forward(
    params: dict,
    input_ids: jax.Array,
    length: jax.Array,
    cfg: LlamaConfig,
    *,
    compute_dtype: jnp.dtype = jnp.bfloat16,
    decode_kernel: str = "xla",
):
    """Prompt prefill for serving: ids [1, P] -> (last-token logits [1, V]
    f32, per-layer K/V [L, P, Nkv, Dh] in compute dtype).

    ``length`` (traced scalar) is the true prompt length; ``input_ids``
    may be right-padded to a compile-size bucket. Padding K/V rows do land
    in the returned stack (and hence the cache) but are never attended:
    the decode mask stops at the live length and every ring write
    overwrites index ``len % T`` before index ``len`` becomes visible."""
    _require_dense(cfg, "prefill_forward")
    B, P = input_ids.shape
    Nh, Nkv, Dh = cfg.num_attention_heads, cfg.kv_heads, cfg.head_dim
    positions = jnp.broadcast_to(jnp.arange(P, dtype=jnp.int32), (B, P))
    cparams = _cast_serving_params(params, compute_dtype)
    cos, sin = _rope_tables(positions, Dh, cfg.rope_theta)
    cd = compute_dtype
    dkn = decode_kernel

    def block(h, layer):
        x = _rms_norm(h, layer["input_norm"], cfg.rms_norm_eps)
        q = _wmul(x, layer["q_proj"], cd, dkn).reshape(B, P, Nh, Dh)
        k = _wmul(x, layer["k_proj"], cd, dkn).reshape(B, P, Nkv, Dh)
        v = _wmul(x, layer["v_proj"], cd, dkn).reshape(B, P, Nkv, Dh)
        q = _rope_apply(q, cos, sin)
        k = _rope_apply(k, cos, sin)
        attn = xla_attention(q, k, v, causal=True)
        h = h + _wmul(attn.reshape(B, P, Nh * Dh), layer["o_proj"], cd, dkn)
        x = _rms_norm(h, layer["post_attn_norm"], cfg.rms_norm_eps)
        ffn = _wmul(
            jax.nn.silu(_wmul(x, layer["gate_proj"], cd, dkn))
            * _wmul(x, layer["up_proj"], cd, dkn),
            layer["down_proj"], cd, dkn,
        )
        return h + ffn, (k[0], v[0])

    h = jnp.take(cparams["embed_tokens"], input_ids, axis=0)
    h, (ks, vs) = jax.lax.scan(block, h, cparams["layers"])
    h_last = jax.lax.dynamic_slice_in_dim(h, length - 1, 1, axis=1)
    h_last = _rms_norm(h_last, cparams["final_norm"], cfg.rms_norm_eps)
    head = (
        cparams["embed_tokens"].T
        if cfg.tie_word_embeddings
        else cparams["lm_head"]
    )
    logits = (h_last @ head).astype(jnp.float32)
    return logits[:, 0], ks, vs


def cache_insert(
    cache_k: jax.Array,
    cache_v: jax.Array,
    ks: jax.Array,
    vs: jax.Array,
    slot: jax.Array,
) -> tuple[jax.Array, jax.Array]:
    """Write a prefilled sequence's K/V [L, P, Nkv, Dh] into ``slot``
    (traced scalar) of the cache [L, S, T, Nkv, Dh] at ring positions
    [0, P). Stale entries from a previous tenant beyond P stay masked
    until decode's per-step ring write overwrites them."""
    L, P = ks.shape[0], ks.shape[1]
    if P > cache_k.shape[2]:
        raise ValueError(
            f"prefill length {P} exceeds slot context {cache_k.shape[2]}"
        )
    zero = jnp.int32(0)
    start = (zero, jnp.asarray(slot, jnp.int32), zero, zero, zero)
    ck = jax.lax.dynamic_update_slice(cache_k, ks[:, None].astype(cache_k.dtype), start)
    cv = jax.lax.dynamic_update_slice(cache_v, vs[:, None].astype(cache_v.dtype), start)
    return ck, cv


def decode_forward(
    params: dict,
    tokens: jax.Array,
    lens: jax.Array,
    cache_k: jax.Array,
    cache_v: jax.Array,
    cfg: LlamaConfig,
    *,
    compute_dtype: jnp.dtype = jnp.bfloat16,
    decode_kernel: str = "xla",
):
    """One incremental decode step over all S slots.

    tokens [S] int32 are each slot's current input token; lens [S] int32
    are the token counts already cached (== the new token's absolute
    position); cache_{k,v} are [L, S, T, Nkv, Dh]. Returns (logits [S, V]
    f32, new_cache_k, new_cache_v): the new K/V is written at ring index
    ``lens % T`` and attention covers the last ``min(lens + 1, T)``
    positions. Callers jit this with the caches donated — the cache
    update is in-place at HBM, never a fresh page copy."""
    _require_dense(cfg, "decode_forward")
    S = tokens.shape[0]
    L, _, T, Nkv, Dh = cache_k.shape
    Nh = cfg.num_attention_heads
    cparams = _cast_serving_params(params, compute_dtype)
    positions = lens[:, None].astype(jnp.int32)  # [S, 1]
    cos, sin = _rope_tables(positions, Dh, cfg.rope_theta)
    rows = jnp.arange(S)
    write_idx = jnp.mod(lens, T)
    cd = compute_dtype
    dkn = decode_kernel

    def block(h, xs):
        layer, ck, cv = xs  # ck/cv [S, T, Nkv, Dh]
        x = _rms_norm(h, layer["input_norm"], cfg.rms_norm_eps)
        q = _wmul(x, layer["q_proj"], cd, dkn).reshape(S, 1, Nh, Dh)
        k = _wmul(x, layer["k_proj"], cd, dkn).reshape(S, 1, Nkv, Dh)
        v = _wmul(x, layer["v_proj"], cd, dkn).reshape(S, 1, Nkv, Dh)
        q = _rope_apply(q, cos, sin)
        k = _rope_apply(k, cos, sin)
        ck = ck.at[rows, write_idx].set(k[:, 0].astype(ck.dtype))
        cv = cv.at[rows, write_idx].set(v[:, 0].astype(cv.dtype))
        if dkn == "pallas":
            attn = paged_decode_attention(q[:, 0], ck, cv, lens)
        else:
            attn = decode_attention(q[:, 0], ck, cv, lens)
        h = h + _wmul(attn.reshape(S, 1, Nh * Dh), layer["o_proj"], cd, dkn)
        x = _rms_norm(h, layer["post_attn_norm"], cfg.rms_norm_eps)
        ffn = _wmul(
            jax.nn.silu(_wmul(x, layer["gate_proj"], cd, dkn))
            * _wmul(x, layer["up_proj"], cd, dkn),
            layer["down_proj"], cd, dkn,
        )
        return h + ffn, (ck, cv)

    h = jnp.take(cparams["embed_tokens"], tokens, axis=0)[:, None]  # [S, 1, D]
    h, (new_ck, new_cv) = jax.lax.scan(
        block, h, (cparams["layers"], cache_k, cache_v)
    )
    h = _rms_norm(h, cparams["final_norm"], cfg.rms_norm_eps)
    head = (
        cparams["embed_tokens"].T
        if cfg.tie_word_embeddings
        else cparams["lm_head"]
    )
    logits = (h @ head).astype(jnp.float32)
    return logits[:, 0], new_ck, new_cv


def verify_forward(
    params: dict,
    tail: jax.Array,
    lens: jax.Array,
    cache_k: jax.Array,
    cache_v: jax.Array,
    cfg: LlamaConfig,
    *,
    compute_dtype: jnp.dtype = jnp.bfloat16,
    decode_kernel: str = "xla",
):
    """Batched multi-token verify pass for self-speculative decode.

    tail [S, K] int32 are K unverified tokens per slot (the current
    token followed by the draft's proposals) at absolute positions
    ``lens + i``; cache_{k,v} [L, S, T, Nkv, Dh] hold the ring pages as
    of BEFORE the tail. Returns (logits [S, K, V] f32, tail_ks, tail_vs
    [L, S, K, Nkv, Dh]): one full-depth greedy logit row per tail
    position, plus the tail's K/V — kept OUT of the ring here so
    rejected tokens need no rollback; the engine inserts only the
    accepted prefix via :func:`spec_cache_insert`.

    Also the continued-prefill primitive for shared-prefix KV reuse
    (S = 1, tail = the suffix tokens, lens = the reused prefix length).
    """
    _require_dense(cfg, "verify_forward")
    S, K = tail.shape
    L, _, T, Nkv, Dh = cache_k.shape
    Nh = cfg.num_attention_heads
    cparams = _cast_serving_params(params, compute_dtype)
    positions = lens[:, None] + jnp.arange(K, dtype=jnp.int32)[None]  # [S, K]
    cos, sin = _rope_tables(positions, Dh, cfg.rope_theta)
    cd = compute_dtype
    dkn = decode_kernel

    def block(h, xs):
        layer, ck, cv = xs  # ck/cv [S, T, Nkv, Dh]
        x = _rms_norm(h, layer["input_norm"], cfg.rms_norm_eps)
        q = _wmul(x, layer["q_proj"], cd, dkn).reshape(S, K, Nh, Dh)
        k = _wmul(x, layer["k_proj"], cd, dkn).reshape(S, K, Nkv, Dh)
        v = _wmul(x, layer["v_proj"], cd, dkn).reshape(S, K, Nkv, Dh)
        q = _rope_apply(q, cos, sin)
        k = _rope_apply(k, cos, sin)
        if dkn == "pallas":
            attn = spec_tail_attention_fused(q, ck, cv, k, v, lens)
        else:
            attn = spec_tail_attention(q, ck, cv, k, v, lens)
        h = h + _wmul(attn.reshape(S, K, Nh * Dh), layer["o_proj"], cd, dkn)
        x = _rms_norm(h, layer["post_attn_norm"], cfg.rms_norm_eps)
        ffn = _wmul(
            jax.nn.silu(_wmul(x, layer["gate_proj"], cd, dkn))
            * _wmul(x, layer["up_proj"], cd, dkn),
            layer["down_proj"], cd, dkn,
        )
        return h + ffn, (k, v)

    h = jnp.take(cparams["embed_tokens"], tail, axis=0)  # [S, K, D]
    h, (tail_ks, tail_vs) = jax.lax.scan(
        block, h, (cparams["layers"], cache_k, cache_v)
    )
    h = _rms_norm(h, cparams["final_norm"], cfg.rms_norm_eps)
    head = (
        cparams["embed_tokens"].T
        if cfg.tie_word_embeddings
        else cparams["lm_head"]
    )
    logits = (h @ head).astype(jnp.float32)
    return logits, tail_ks, tail_vs


def draft_propose(
    params: dict,
    tokens: jax.Array,
    lens: jax.Array,
    cache_k: jax.Array,
    cache_v: jax.Array,
    cfg: LlamaConfig,
    *,
    k_steps: int,
    draft_layers: int,
    compute_dtype: jnp.dtype = jnp.bfloat16,
    decode_kernel: str = "xla",
):
    """Self-speculative draft: propose ``k_steps`` greedy tokens per slot
    from the first ``draft_layers`` of the SAME weights (final norm and
    lm head shared with the full stack).

    The truncated stack's K/V for the proposed tail lives in registers
    (a [Ld, S, k, Nkv, Dh] buffer threaded between token steps), never
    the ring — the draft is a heuristic and dirties nothing; exactness
    is the verify pass's job. Returns proposals [S, k_steps] int32.
    """
    _require_dense(cfg, "draft_propose")
    S = tokens.shape[0]
    L, _, T, Nkv, Dh = cache_k.shape
    Nh = cfg.num_attention_heads
    Ld = int(draft_layers)
    if not 1 <= Ld <= L:
        raise ValueError(f"draft_layers {Ld} outside [1, {L}]")
    cparams = _cast_serving_params(params, compute_dtype)
    dlayers = jax.tree.map(lambda x: x[:Ld], cparams["layers"])
    dck, dcv = cache_k[:Ld], cache_v[:Ld]
    cd = compute_dtype
    dkn = decode_kernel
    head = (
        cparams["embed_tokens"].T
        if cfg.tie_word_embeddings
        else cparams["lm_head"]
    )

    tkb = jnp.zeros((Ld, S, k_steps, Nkv, Dh), cd)
    tvb = jnp.zeros((Ld, S, k_steps, Nkv, Dh), cd)
    cur = tokens
    proposals = []
    for i in range(k_steps):
        positions = (lens + jnp.int32(i))[:, None]  # [S, 1]
        cos, sin = _rope_tables(positions, Dh, cfg.rope_theta)

        def block(h, xs, i=i, cos=cos, sin=sin):
            layer, ck, cv, tk, tv = xs
            x = _rms_norm(h, layer["input_norm"], cfg.rms_norm_eps)
            q = _wmul(x, layer["q_proj"], cd, dkn).reshape(S, 1, Nh, Dh)
            k = _wmul(x, layer["k_proj"], cd, dkn).reshape(S, 1, Nkv, Dh)
            v = _wmul(x, layer["v_proj"], cd, dkn).reshape(S, 1, Nkv, Dh)
            q = _rope_apply(q, cos, sin)
            k = _rope_apply(k, cos, sin)
            tk = tk.at[:, i].set(k[:, 0])
            tv = tv.at[:, i].set(v[:, 0])
            if dkn == "pallas":
                attn = spec_tail_attention_fused(
                    q, ck, cv, tk, tv, lens, q_start=i
                )
            else:
                attn = spec_tail_attention(q, ck, cv, tk, tv, lens, q_start=i)
            h = h + _wmul(attn.reshape(S, 1, Nh * Dh), layer["o_proj"], cd, dkn)
            x = _rms_norm(h, layer["post_attn_norm"], cfg.rms_norm_eps)
            ffn = _wmul(
                jax.nn.silu(_wmul(x, layer["gate_proj"], cd, dkn))
                * _wmul(x, layer["up_proj"], cd, dkn),
                layer["down_proj"], cd, dkn,
            )
            return h + ffn, (tk, tv)

        h = jnp.take(cparams["embed_tokens"], cur, axis=0)[:, None]  # [S, 1, D]
        h, (tkb, tvb) = jax.lax.scan(block, h, (dlayers, dck, dcv, tkb, tvb))
        h = _rms_norm(h, cparams["final_norm"], cfg.rms_norm_eps)
        logits = (h @ head).astype(jnp.float32)
        cur = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
        proposals.append(cur)
    return jnp.stack(proposals, axis=1)  # [S, k_steps]


def spec_cache_insert(
    cache_k: jax.Array,
    cache_v: jax.Array,
    tail_ks: jax.Array,
    tail_vs: jax.Array,
    lens: jax.Array,
    accept: jax.Array,
) -> tuple[jax.Array, jax.Array]:
    """Positioned ring insert of the ACCEPTED tail prefix: per slot,
    tail tokens i <= accept[s] land at ring index ``(lens + i) % T``;
    rejected positions write their current cache value back (the
    no-copy rollback — the ring simply never learns about them).
    Requires K <= T so a tail never collides with itself."""
    L, S, T, Nkv, Dh = cache_k.shape
    K = tail_ks.shape[2]
    if K > T:
        raise ValueError(f"tail width {K} exceeds ring context {T}")
    rows = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[:, None], (S, K))
    pos = jnp.mod(lens[:, None] + jnp.arange(K, dtype=jnp.int32)[None], T)
    keep = (jnp.arange(K, dtype=jnp.int32)[None] <= accept[:, None])[
        None, :, :, None, None
    ]
    old_k = cache_k[:, rows, pos]  # [L, S, K, Nkv, Dh]
    old_v = cache_v[:, rows, pos]
    new_k = jnp.where(keep, tail_ks.astype(cache_k.dtype), old_k)
    new_v = jnp.where(keep, tail_vs.astype(cache_v.dtype), old_v)
    ck = cache_k.at[:, rows, pos].set(new_k)
    cv = cache_v.at[:, rows, pos].set(new_v)
    return ck, cv


def prefix_copy(
    cache_k: jax.Array,
    cache_v: jax.Array,
    src: jax.Array,
    dst: jax.Array,
    plen: jax.Array,
) -> tuple[jax.Array, jax.Array]:
    """Ring-copy the first ``plen`` cache rows of slot ``src`` into slot
    ``dst`` (shared-prefix KV reuse). Rows >= plen keep dst's previous
    bytes — stale and masked, same as any slot reuse."""
    T = cache_k.shape[2]
    keep = (jnp.arange(T) < plen)[:, None, None]
    src_k = jnp.take(cache_k, src, axis=1)
    src_v = jnp.take(cache_v, src, axis=1)
    dst_k = jnp.take(cache_k, dst, axis=1)
    dst_v = jnp.take(cache_v, dst, axis=1)
    ck = cache_k.at[:, dst].set(jnp.where(keep, src_k, dst_k))
    cv = cache_v.at[:, dst].set(jnp.where(keep, src_v, dst_v))
    return ck, cv


def suffix_insert(
    cache_k: jax.Array,
    cache_v: jax.Array,
    ks: jax.Array,
    vs: jax.Array,
    slot: jax.Array,
    start: jax.Array,
    count: jax.Array,
) -> tuple[jax.Array, jax.Array]:
    """Write a continued prefill's suffix K/V [L, P', Nkv, Dh] into
    ``slot`` at rows [start, start + count) — the positioned counterpart
    of :func:`cache_insert` (a prompt always fits its page, so no ring
    wrap here; padding rows beyond ``count`` are dropped)."""
    L, S, T, Nkv, Dh = cache_k.shape
    P = ks.shape[1]
    page_k = jnp.take(cache_k, slot, axis=1)  # [L, T, Nkv, Dh]
    page_v = jnp.take(cache_v, slot, axis=1)
    disp = jnp.arange(T, dtype=jnp.int32) - jnp.asarray(start, jnp.int32)
    valid = ((disp >= 0) & (disp < count))[:, None, None]
    gidx = jnp.clip(disp, 0, P - 1)
    page_k = jnp.where(valid, ks[:, gidx].astype(cache_k.dtype), page_k)
    page_v = jnp.where(valid, vs[:, gidx].astype(cache_v.dtype), page_v)
    ck = cache_k.at[:, slot].set(page_k)
    cv = cache_v.at[:, slot].set(page_v)
    return ck, cv


def causal_lm_loss(
    logits: jax.Array, labels: jax.Array, ignore_index: int = -100
) -> jax.Array:
    """Shifted next-token cross-entropy, mean over non-ignored targets
    (HF CausalLM loss semantics used by the reference drivers)."""
    shift_logits = logits[:, :-1]
    shift_labels = labels[:, 1:]
    mask = shift_labels != ignore_index
    safe_labels = jnp.where(mask, shift_labels, 0)
    logp = jax.nn.log_softmax(shift_logits, axis=-1)
    nll = -jnp.take_along_axis(logp, safe_labels[..., None], axis=-1)[..., 0]
    total = jnp.sum(nll * mask)
    count = jnp.maximum(jnp.sum(mask), 1)
    return total / count
