"""Delta-push weight publisher: the training side of the serving fleet.

One trainer process feeds N serving replicas. Shipping a full fp16
snapshot to every replica every outer epoch multiplies master→replica
traffic by the fleet size, which is exactly the cost the outer codecs
already solved for gradients — so pushes reuse them. Per replica the
publisher keeps a *shadow*: the replica's weight state tracked
bit-exactly on the publisher side (both ends apply the same
deterministic decode). After each outer epoch a push is either:

- a **keyframe** — every leaf, state-codec encoded (the same layout
  ``ServeEngine.install_wire`` consumes over the control port). Sent for
  a fresh/rejoining replica and every ``keyframe_every`` epochs; it
  wholesale-replaces the replica state, so delta-applied weights are
  bit-identical to a from-scratch install at every keyframe boundary by
  construction.
- a **delta frame** — ONE fragment per epoch on the staggered
  Streaming-DiLoCo schedule (``planner.fragment_partition`` over the
  leaf sizes, fragment ``epoch % n_frag``; arXiv 2501.18512): ``master −
  last-pushed master`` per leaf, encoded with the configured sub-8-bit
  codec plus a per-replica error-feedback residual, so quantization
  error re-enters that fragment's next push instead of accumulating in
  the replica (same EF contract as diloco/error_feedback.py). Each
  fragment turns over every ``n_frag`` epochs, so a blockwise4bit push
  costs ~``1/(4·n_frag)`` of the fp16 keyframe bytes (~1/16 at the
  default 4 fragments) and the replica serves a fragment-wise mosaic of
  recent epochs between keyframes — the serving-side mirror of how
  streaming fragments sync training.

The publisher is transport-agnostic: :meth:`frames` returns ``(meta,
payload)`` pairs and the fleet manager ships them over the push channel
(fleet/wire.py). :func:`apply_frame` is the single decode-side
implementation, shared by the replica runner and the bit-exactness
tests.
"""
from __future__ import annotations

import os
import threading
from typing import Callable, Optional

import numpy as np

from opendiloco_tpu import obs
from opendiloco_tpu.diloco.compression import get_codec, record_wire
from opendiloco_tpu.diloco.planner import fragment_partition

# snapshot_fn contract: () -> (epoch, [np leaves]) with leaves in
# params-flatten order — exactly DiLoCoOptimizer.master_snapshot.
SnapshotFn = Callable[[], tuple]


class FleetFrameError(RuntimeError):
    """A push frame does not apply to the receiver's current state."""


def _keyframe_codec_name(delta_codec_name: str) -> str:
    """Keyframes ride the onboarding state-codec policy (tcp.state_codec):
    fp16 unless the configured codec is already a full-state family or an
    ``ODTP_STATE_CODEC`` override says otherwise."""
    from opendiloco_tpu.diloco.tcp import state_codec

    return state_codec(get_codec(delta_codec_name)).name


def decode_leaf(codec, ent: dict, payload: bytes) -> np.ndarray:
    """Decode one ``leaves`` entry of a fleet frame to a flat f32 array."""
    seg = payload[int(ent["off"]) : int(ent["off"]) + int(ent["len"])]
    shape = tuple(ent["shape"])
    n = int(np.prod(shape)) if shape else 1
    return np.array(codec.decode(seg, (n,), ent["meta"]), np.float32)


def apply_frame(
    leaves: Optional[list], meta: dict, payload: bytes
) -> tuple[list, int]:
    """Apply one weight frame to a replica's flat f32 leaf list.

    ``keyframe`` returns a freshly decoded list (``leaves`` may be None);
    ``delta`` accumulates in place and requires ``meta["base_epoch"]`` to
    match the state the frame was computed against. Returns ``(leaves,
    epoch)``. The publisher updates its shadow with the *same* decode +
    add, so both ends stay bit-identical between keyframes too.
    """
    kind = meta.get("kind")
    if kind not in ("keyframe", "delta"):
        raise FleetFrameError(f"not a weight frame: {kind!r}")
    codec = get_codec(meta["codec"])
    if kind == "keyframe":
        return [decode_leaf(codec, ent, payload) for ent in meta["leaves"]], int(
            meta["epoch"]
        )
    if leaves is None:
        raise FleetFrameError("delta frame before any keyframe")
    for ent in meta["leaves"]:
        dec = decode_leaf(codec, ent, payload)
        np.add(leaves[int(ent["i"])], dec, out=leaves[int(ent["i"])])
    return leaves, int(meta["epoch"])


class _Channel:
    """Per-replica push state: shadow + EF residuals + byte accounting."""

    __slots__ = (
        "shadow",
        "epoch",
        "last_keyframe",
        "residual",
        "delta_bytes",
        "keyframe_bytes",
        "delta_frames",
        "keyframe_frames",
    )

    def __init__(self) -> None:
        self.shadow: Optional[list] = None
        self.epoch = -1
        self.last_keyframe = -1
        self.residual: dict[int, np.ndarray] = {}
        self.delta_bytes = 0
        self.keyframe_bytes = 0
        self.delta_frames = 0
        self.keyframe_frames = 0


class DeltaPublisher:
    def __init__(
        self,
        snapshot_fn: SnapshotFn,
        *,
        codec: str = "blockwise4bit",
        fragments: int = 4,
        keyframe_every: int = 8,
        error_feedback: bool = True,
    ):
        env = os.environ.get("ODTP_FLEET_KEYFRAME_EVERY")
        self.keyframe_every = max(1, int(env) if env else int(keyframe_every))
        self.snapshot_fn = snapshot_fn
        self.codec = get_codec(codec)
        self.kf_codec = get_codec(_keyframe_codec_name(codec))
        self.fragments = max(1, int(fragments))
        self.error_feedback = bool(error_feedback)
        self._channels: dict[str, _Channel] = {}
        self._lock = threading.Lock()
        self._partition: Optional[list] = None
        self._shapes: Optional[list] = None
        self.fp16_snapshot_bytes = 0  # full-snapshot equivalent, for gates
        self.last_epoch = -1

    # -- membership ----------------------------------------------------------

    def register(self, rid: str) -> None:
        with self._lock:
            self._channels.setdefault(rid, _Channel())

    def drop(self, rid: str) -> None:
        with self._lock:
            self._channels.pop(rid, None)

    def channel_epoch(self, rid: str) -> int:
        """Last epoch pushed to ``rid`` (-1 when untracked/fresh)."""
        with self._lock:
            ch = self._channels.get(rid)
            return -1 if ch is None else ch.epoch

    def reset(self, rid: str) -> None:
        """Forget the shadow: the replica lost state (restart / stale
        base), so the next push is a keyframe."""
        with self._lock:
            if rid in self._channels:
                self._channels[rid] = _Channel()

    # -- frame production ----------------------------------------------------

    def _masters(self) -> tuple[int, list]:
        epoch, leaves = self.snapshot_fn()
        flat = [np.asarray(m, np.float32).reshape(-1) for m in leaves]
        if self._shapes is None:
            self._shapes = [tuple(np.asarray(m).shape) for m in leaves]
            sizes = [f.size for f in flat]
            self._partition = fragment_partition(
                sizes, min(self.fragments, len(sizes))
            )
            self.fp16_snapshot_bytes = 2 * int(sum(sizes))
        self.last_epoch = int(epoch)
        return int(epoch), flat

    def frames(self, rid: str) -> list[tuple[dict, bytes]]:
        """Everything ``rid`` needs to catch up to the current masters:
        ``[]`` when already current, one keyframe, or one delta frame per
        fragment. Meta layouts are declared in diloco/schema.py
        (FLEET_KEYFRAME_META_FIELDS / FLEET_DELTA_META_FIELDS)."""
        with self._lock:
            ch = self._channels.setdefault(rid, _Channel())
            epoch, masters = self._masters()
            if ch.shadow is not None and ch.epoch >= epoch:
                return []
            if (
                ch.shadow is None
                or epoch - ch.last_keyframe >= self.keyframe_every
            ):
                return [self._keyframe(ch, rid, epoch, masters)]
            return self._deltas(ch, rid, epoch, masters)

    def _keyframe(
        self, ch: _Channel, rid: str, epoch: int, masters: list
    ) -> tuple[dict, bytes]:
        ents, parts, off = [], [], 0
        for i, (flat, shape) in enumerate(zip(masters, self._shapes)):
            payload, meta = self.kf_codec.encode(flat)
            ents.append(
                {
                    "i": i,
                    "shape": list(shape),
                    "off": off,
                    "len": len(payload),
                    "meta": meta,
                }
            )
            parts.append(payload)
            off += len(payload)
        frame_meta = {
            "kind": "keyframe",
            "epoch": epoch,
            "tepoch": epoch,
            "codec": self.kf_codec.name,
            "leaves": ents,
        }
        payload = b"".join(parts)
        # the shadow IS the decode of what was sent — apply_frame keeps
        # publisher and replica bit-identical by sharing the code path
        ch.shadow, ch.epoch = apply_frame(None, frame_meta, payload)
        ch.last_keyframe = epoch
        ch.residual.clear()
        ch.keyframe_bytes += off
        ch.keyframe_frames += 1
        obs.count("fleet_push_bytes", off, kind="keyframe", replica=rid)
        obs.count("fleet_push_frames", kind="keyframe", replica=rid)
        record_wire(self.kf_codec.name, self.fp16_snapshot_bytes * 2, off)
        return frame_meta, payload

    def _deltas(
        self, ch: _Channel, rid: str, epoch: int, masters: list
    ) -> list[tuple[dict, bytes]]:
        """One self-contained delta frame: the fragment whose staggered
        turn this epoch is (``epoch % n_frag``), carrying everything that
        fragment's leaves moved since their last push."""
        base = ch.epoch
        nfrag = len(self._partition)
        frag = epoch % nfrag
        ents, parts, off = [], [], 0
        for i in self._partition[frag]:
            d = masters[i] - ch.shadow[i]
            if self.error_feedback and i in ch.residual:
                d = d + ch.residual[i]
            payload, meta = self.codec.encode(d)
            dec = np.array(
                self.codec.decode(payload, d.shape, meta), np.float32
            )
            if self.error_feedback:
                ch.residual[i] = d - dec
            np.add(ch.shadow[i], dec, out=ch.shadow[i])
            ents.append(
                {
                    "i": i,
                    "shape": list(self._shapes[i]),
                    "off": off,
                    "len": len(payload),
                    "meta": meta,
                }
            )
            parts.append(payload)
            off += len(payload)
            record_wire(self.codec.name, d.nbytes, len(payload))
        ch.delta_bytes += off
        ch.delta_frames += 1
        ch.epoch = epoch
        obs.count("fleet_push_bytes", off, kind="delta", replica=rid)
        obs.count("fleet_push_frames", kind="delta", replica=rid)
        return [
            (
                {
                    "kind": "delta",
                    "epoch": epoch,
                    "tepoch": epoch,
                    "base_epoch": base,
                    "frag": frag,
                    "nfrag": nfrag,
                    "codec": self.codec.name,
                    "leaves": ents,
                },
                b"".join(parts),
            )
        ]

    # -- introspection -------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "epoch": self.last_epoch,
                "codec": self.codec.name,
                "keyframe_codec": self.kf_codec.name,
                "keyframe_every": self.keyframe_every,
                "error_feedback": self.error_feedback,
                "fp16_snapshot_bytes": self.fp16_snapshot_bytes,
                "replicas": {
                    rid: {
                        "epoch": ch.epoch,
                        "last_keyframe": ch.last_keyframe,
                        "delta_bytes": ch.delta_bytes,
                        "keyframe_bytes": ch.keyframe_bytes,
                        "delta_frames": ch.delta_frames,
                        "keyframe_frames": ch.keyframe_frames,
                    }
                    for rid, ch in self._channels.items()
                },
            }
