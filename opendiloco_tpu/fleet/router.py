"""Front-end router: one ingress over a galaxy of serving replicas.

Speaks the same two protocols as ``serve/server.py`` (HTTP ``POST
/generate`` + JSONL) so clients cannot tell a fleet from a single
replica. Dispatch is least-loaded with a prefix-affinity override: a
request sharing a long prompt prefix with something a replica recently
served routes there, where the KV prefix cache is warm (PR 11's
scheduler-side reuse), unless that replica is already clearly busier
than the least-loaded one.

Replica death is a non-event by design: a connection error (or a
retryable reject) marks the backend dead, trips the dead-peer watchdog,
and the in-flight request is re-dispatched to another replica — the
client sees one answer, never an error, as long as any replica lives.
A health-probe thread keeps polling dead backends' ``/healthz`` so a
rejoined (or respawned) replica resumes taking traffic without any
registration call, and replicas self-reporting ``stale`` (weight pushes
stalled past ``max_stale_rounds``) are dispatched to only when nothing
fresh is alive.

The router is engine-free and jax-free: it moves JSON lines between
sockets (``common_prefix_len`` from serve/kvcache.py is numpy-only).
"""
from __future__ import annotations

import collections
import json
import logging
import random
import socket
import threading
import time
from typing import Optional

from opendiloco_tpu import obs
from opendiloco_tpu.obs import reqtrace
from opendiloco_tpu.serve.kvcache import (
    common_prefix_len,
    prefix_grid_lengths,
    prefix_key,
)

log = logging.getLogger(__name__)


def _bind_with_fallback(host: str, port: int, what: str) -> socket.socket:
    """Same contract as serve.server.bind_with_fallback, duplicated here
    because importing serve.server pulls the jitted engine (jax) and the
    router must stay importable in an engine-free process."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    try:
        sock.bind((host, port))
    except OSError as e:
        if port == 0:
            sock.close()
            raise
        log.warning(
            "%s port %d unavailable (%s); falling back to an ephemeral port",
            what,
            port,
            e,
        )
        sock.bind((host, 0))
    return sock

_HTTP_VERBS = (b"GET ", b"POST", b"PUT ", b"HEAD", b"DELE", b"OPTI", b"PATC")

# replica-side rejects worth trying on another replica; anything else is
# the request's own fault (bad prompt, too long) and is returned as-is
_RETRYABLE = ("server stopped", "queue full", "timeout")


class _Backend:
    def __init__(self, rid: str, host: str, port: int):
        self.rid = rid
        self.host = host
        self.port = int(port)
        self.dead = False
        self.stale = False
        self.ready = True
        self.inflight = 0
        self.dispatched = 0
        # health-probe pacing: next due time and current interval. The
        # interval backs off exponentially while the backend stays dark
        # and snaps back on contact; jitter on every reschedule keeps a
        # mass revive from synchronizing into a probe thundering herd.
        self.probe_at = 0.0
        self.probe_backoff = 0.0
        self.lock = threading.Lock()
        self.pool: list[socket.socket] = []
        # recent prompts, newest last: the affinity signal for warm-KV
        # routing (mirrors what the replica's prefix cache may still hold)
        self.recent: collections.deque = collections.deque(maxlen=32)
        # prefix-directory advertisement: (key, glen) entries this replica
        # last reported resident in its host KV tier (wholesale-replaced
        # on every health frame — the replica is the source of truth)
        self.prefixes: set = set()

    def acquire(self, timeout: float) -> socket.socket:
        with self.lock:
            if self.pool:
                return self.pool.pop()
        conn = socket.create_connection((self.host, self.port), timeout=2.0)
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conn.settimeout(timeout)
        return conn

    def release(self, conn: socket.socket) -> None:
        with self.lock:
            if not self.dead and len(self.pool) < 8:
                self.pool.append(conn)
                return
        try:
            conn.close()
        except OSError:
            pass

    def close_pool(self) -> None:
        with self.lock:
            pool, self.pool = self.pool, []
        for conn in pool:
            try:
                conn.close()
            except OSError:
                pass


class FleetRouter:
    def __init__(
        self,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        request_timeout: float = 120.0,
        affinity_min_tokens: int = 8,
        affinity_max_extra_inflight: int = 2,
        probe_interval_s: float = 1.0,
        prefix_directory: bool = False,
    ):
        self.request_timeout = float(request_timeout)
        self.affinity_min_tokens = int(affinity_min_tokens)
        self.affinity_max_extra_inflight = int(affinity_max_extra_inflight)
        self.probe_interval_s = float(probe_interval_s)
        # fleet prefix-cache directory: (key, glen) -> rids holding that
        # prompt-prefix K/V in their host tier. Fed by replica health
        # advertisements (update_prefixes), consulted by _pick ahead of
        # the recent-prompt heuristic — an exact content-hash match beats
        # a guess — and invalidated on replica death/removal so a killed
        # holder's entries re-route instead of dangling.
        self.prefix_directory = bool(prefix_directory)
        self._prefix_dir: dict[tuple, set] = {}
        self.directory_hits = 0
        self.directory_misses = 0
        # dead-backend probes back off exponentially up to this cap
        self.probe_backoff_cap_s = max(8 * self.probe_interval_s, 10.0)
        self._rng = random.Random(0xD15C0)
        self._backends: dict[str, _Backend] = {}
        self._lock = threading.Lock()
        self.redispatches = 0
        self.deaths = 0
        self.shed = 0
        # latency floor: fastest recent completed dispatch. A request
        # whose remaining deadline budget is below even this is provably
        # unmeetable and is shed at the edge instead of queue-timing-out.
        self._done_lat: collections.deque = collections.deque(maxlen=128)
        self._stop = threading.Event()
        self._sock = _bind_with_fallback(host, port, "fleet-router")
        self._sock.listen(64)
        self.host = host
        self.port = self._sock.getsockname()[1]
        threading.Thread(
            target=self._accept_loop, name="odtp-fleet-router", daemon=True
        ).start()
        threading.Thread(
            target=self._probe_loop, name="odtp-fleet-probe", daemon=True
        ).start()

    # -- membership ----------------------------------------------------------

    def add_replica(self, rid: str, host: str, port: int) -> None:
        b = _Backend(rid, host, port)
        self._reschedule_probe(b)  # first probe one jittered interval out
        with self._lock:
            self._backends[rid] = b
        self._publish_live()

    def remove_replica(self, rid: str) -> None:
        with self._lock:
            b = self._backends.pop(rid, None)
            if b is not None:
                self._drop_directory_locked(b)
        if b is not None:
            b.close_pool()
        self._publish_live()

    # -- prefix-cache directory ----------------------------------------------

    def update_prefixes(self, rid: str, entries: list) -> None:
        """Adopt a replica's host-tier prefix advertisement (health-frame
        ``prefixes`` field): wholesale replace — entries the replica no
        longer reports (LRU-dropped, epoch-purged) leave the directory."""
        if not self.prefix_directory:
            return
        new = {(str(k), int(g)) for k, g in entries}
        with self._lock:
            b = self._backends.get(rid)
            if b is None:
                return
            for kk in b.prefixes - new:
                holders = self._prefix_dir.get(kk)
                if holders is not None:
                    holders.discard(rid)
                    if not holders:
                        del self._prefix_dir[kk]
            for kk in new - b.prefixes:
                self._prefix_dir.setdefault(kk, set()).add(rid)
            b.prefixes = new

    def _drop_directory_locked(self, b: _Backend) -> None:
        """Invalidate every directory entry naming ``b`` (caller holds
        self._lock): a dead/removed holder must not attract traffic."""
        for kk in b.prefixes:
            holders = self._prefix_dir.get(kk)
            if holders is not None:
                holders.discard(b.rid)
                if not holders:
                    del self._prefix_dir[kk]
        b.prefixes = set()

    def _directory_pick(self, prompt: list, cands: list) -> Optional[_Backend]:
        """Longest-prefix directory holder among ``cands`` within the
        affinity inflight slack, or None."""
        by_rid = {b.rid: b for b in cands}
        least = min(cands, key=lambda b: b.inflight)
        for glen in prefix_grid_lengths(len(prompt)):
            kk = (prefix_key(prompt, glen), glen)
            with self._lock:
                holders = list(self._prefix_dir.get(kk) or ())
            for rid in holders:
                b = by_rid.get(rid)
                if (
                    b is not None
                    and b.inflight
                    <= least.inflight + self.affinity_max_extra_inflight
                ):
                    self.directory_hits += 1
                    obs.count("fleet_directory_hits", replica=rid)
                    return b
        self.directory_misses += 1
        obs.count("fleet_directory_misses")
        return None

    def _publish_live(self) -> None:
        with self._lock:
            live = sum(1 for b in self._backends.values() if not b.dead)
        obs.gauge("fleet_replicas_live", live)

    # -- dispatch ------------------------------------------------------------

    def _candidates(self, exclude: set) -> list:
        with self._lock:
            backends = [
                b
                for b in self._backends.values()
                if b.rid not in exclude and not b.dead
            ]
        fresh = [b for b in backends if b.ready and not b.stale]
        return fresh or backends

    def _pick(self, prompt: list, exclude: set) -> Optional[_Backend]:
        cands = self._candidates(exclude)
        if not cands:
            return None
        if self.prefix_directory and len(prompt) >= self.affinity_min_tokens:
            b = self._directory_pick(prompt, cands)
            if b is not None:
                return b
        least = min(cands, key=lambda b: b.inflight)
        if len(prompt) >= self.affinity_min_tokens:
            best, best_p = None, 0
            for b in cands:
                for recent in b.recent:
                    p = common_prefix_len(prompt, recent)
                    if p > best_p:
                        best, best_p = b, p
            if (
                best is not None
                and best_p >= self.affinity_min_tokens
                and best.inflight
                <= least.inflight + self.affinity_max_extra_inflight
            ):
                obs.count("fleet_router_affinity_hits", replica=best.rid)
                return best
        return least

    def _forward(self, b: _Backend, payload: dict) -> dict:
        """One JSONL round trip on a pooled connection. The replica's
        JSONL handler answers one line at a time per connection, so a
        connection carries exactly one in-flight request."""
        conn = b.acquire(self.request_timeout)
        try:
            conn.sendall((json.dumps(payload) + "\n").encode())
            buf = b""
            while b"\n" not in buf:
                chunk = conn.recv(65536)
                if not chunk:
                    raise OSError("replica closed mid-request")
                buf += chunk
            line, _, rest = buf.partition(b"\n")
            out = json.loads(line.decode())
        except (OSError, ValueError):
            try:
                conn.close()
            except OSError:
                pass
            raise
        if rest:
            # a pooled conn must be quiescent; drop it rather than reuse
            try:
                conn.close()
            except OSError:
                pass
        else:
            b.release(conn)
        return out

    def _latency_floor_s(self) -> Optional[float]:
        """Fastest recent completed dispatch — the provable minimum a new
        request could possibly take."""
        with self._lock:
            lats = list(self._done_lat)
        return min(lats) if lats else None

    def _shed(self, payload: dict, reason: str) -> dict:
        """Edge rejection: the client gets a structured answer NOW (with
        a back-off hint) instead of a doomed wait in some replica queue."""
        self.shed += 1
        obs.count("fleet_router_shed", reason=reason)
        floor = self._latency_floor_s() or 0.25
        out = {
            "error": "shed",
            "reason": reason,
            "retry_after_s": round(max(0.1, min(30.0, 2 * floor)), 3),
        }
        if payload.get("id") is not None:
            out["id"] = payload["id"]
        return out

    def dispatch(self, payload: dict) -> dict:
        # trace context: adopt one minted upstream, else mint at this edge
        # (the sampler may decline). The SAME context rides every forward
        # attempt — a replica SIGKILL mid-flight re-dispatches the request
        # with its history intact, so one request yields ONE trace
        # spanning both replicas instead of losing the first leg.
        rt = reqtrace.ring()
        tid = None
        if rt is not None:
            ctx = reqtrace.ctx_of(payload)
            if ctx is not None:
                tid = rt.adopt(ctx, at="router")
            else:
                ctx = rt.mint(at="router", req_id=payload.get("id"))
                tid = ctx["id"] if ctx else None
            payload = reqtrace.attach(payload, ctx)
        t_admit = time.perf_counter()
        prompt = [int(t) for t in payload.get("prompt") or []]
        deadline_ms = payload.get("deadline_ms")
        t_deadline = None
        if deadline_ms is not None:
            deadline_ms = float(deadline_ms)
            t_deadline = time.monotonic() + deadline_ms / 1e3
            floor = self._latency_floor_s()
            if deadline_ms <= 0.0 or (
                floor is not None and deadline_ms / 1e3 < 0.9 * floor
            ):
                return self._traced_shed(
                    payload, "deadline unmeetable", rt, tid
                )
        tried: set = set()
        last_error = "no live replicas"
        with self._lock:
            attempts = max(1, 2 * len(self._backends))
        for _ in range(attempts):
            if t_deadline is not None:
                remaining = t_deadline - time.monotonic()
                if remaining <= 0:
                    return self._traced_shed(
                        payload, "deadline exhausted", rt, tid
                    )
                # the replica sees what budget is LEFT, not what the
                # client started with — its scheduler sheds the doomed
                payload = {
                    **payload, "deadline_ms": round(remaining * 1e3, 3),
                }
            b = self._pick(prompt, tried)
            if b is None:
                break
            if rt is not None and tid is not None:
                rt.span(
                    tid, "admit", t_admit, time.perf_counter(),
                    replica=b.rid, candidates=len(self._backends) - len(tried),
                    prompt_tokens=len(prompt),
                )
            b.inflight += 1
            t0 = time.monotonic()
            tf0 = time.perf_counter()
            try:
                out = self._forward(b, payload)
            except (OSError, ValueError) as e:
                last_error = f"replica {b.rid} failed: {e}"
                tried.add(b.rid)
                self._mark_dead(b)
                self.redispatches += 1
                obs.count("fleet_router_redispatch", replica=b.rid)
                if rt is not None and tid is not None:
                    rt.span(tid, "forward", tf0, time.perf_counter(),
                            replica=b.rid, error=str(e))
                    rt.event(tid, "redispatch", from_replica=b.rid,
                             cause="connection")
                t_admit = time.perf_counter()  # re-admission for the retry
                continue
            finally:
                b.inflight -= 1
            if out.get("error") == "deadline exceeded":
                if rt is not None and tid is not None:
                    rt.span(tid, "forward", tf0, time.perf_counter(),
                            replica=b.rid, error="deadline exceeded")
                return self._traced_shed(
                    payload, "deadline exceeded", rt, tid
                )
            if out.get("error") in _RETRYABLE:
                last_error = f"replica {b.rid}: {out['error']}"
                tried.add(b.rid)
                self.redispatches += 1
                obs.count("fleet_router_redispatch", replica=b.rid)
                if rt is not None and tid is not None:
                    rt.span(tid, "forward", tf0, time.perf_counter(),
                            replica=b.rid, error=out["error"])
                    rt.event(tid, "redispatch", from_replica=b.rid,
                             cause=out["error"])
                t_admit = time.perf_counter()
                continue
            if "error" not in out:
                self._done_lat.append(time.monotonic() - t0)
            b.dispatched += 1
            b.recent.append(prompt)
            obs.count("fleet_router_dispatch", replica=b.rid)
            if rt is not None and tid is not None:
                rt.span(tid, "forward", tf0, time.perf_counter(),
                        replica=b.rid)
                rt.finish(
                    tid,
                    "failed" if "error" in out else "done",
                    replica=b.rid,
                    tokens=len(out.get("tokens") or []),
                    redispatches=len(tried),
                )
            return out
        out = {"error": last_error}
        if payload.get("id") is not None:
            out["id"] = payload["id"]
        if rt is not None and tid is not None:
            rt.finish(tid, "failed", error=last_error,
                      redispatches=len(tried))
        return out

    def _traced_shed(
        self, payload: dict, reason: str, rt, tid
    ) -> dict:
        out = self._shed(payload, reason)
        if rt is not None and tid is not None:
            rt.event(tid, "shed", reason=reason)
            rt.finish(tid, "shed", reason=reason)
        return out

    def _mark_dead(self, b: _Backend) -> None:
        # idempotent under concurrency: two dispatch threads can watch the
        # same replica die mid-flight; exactly one performs the retire
        with self._lock:
            first = not b.dead
            b.dead = True
            if first:
                self.deaths += 1
                self._drop_directory_locked(b)
        if first:
            b.close_pool()
            obs.count("fleet_replica_deaths", replica=b.rid)
            wd = obs.anomaly.watchdog()
            if wd is not None:
                wd.fleet_replica_dead(b.rid)
            log.warning("fleet replica %s marked dead", b.rid)
        self._publish_live()

    # -- health probing ------------------------------------------------------

    def _probe(self, b: _Backend) -> None:
        try:
            conn = socket.create_connection((b.host, b.port), timeout=1.0)
        except OSError:
            if not b.dead:
                self._mark_dead(b)
            return
        try:
            conn.settimeout(2.0)
            conn.sendall(b"GET /healthz HTTP/1.0\r\n\r\n")
            raw = b""
            while True:
                chunk = conn.recv(65536)
                if not chunk:
                    break
                raw += chunk
            body = raw.partition(b"\r\n\r\n")[2]
            health = json.loads(body.decode() or "{}")
        except (OSError, ValueError):
            if not b.dead:
                self._mark_dead(b)
            return
        finally:
            try:
                conn.close()
            except OSError:
                pass
        if b.dead:
            log.info("fleet replica %s is back; resuming dispatch", b.rid)
            obs.count("fleet_replica_rejoins", replica=b.rid)
        b.dead = False
        b.stale = bool(health.get("stale", False))
        b.ready = bool(health.get("ready", True)) and bool(
            health.get("ok", True)
        )
        self._publish_live()

    def _reschedule_probe(self, b: _Backend) -> None:
        """Exponential backoff while dark, snap back on contact, ±25%
        jitter always — so an autoscaler mass revive never lines every
        probe up into a synchronized thundering herd."""
        if b.dead:
            base = b.probe_backoff or self.probe_interval_s
            b.probe_backoff = min(2 * base, self.probe_backoff_cap_s)
        else:
            b.probe_backoff = self.probe_interval_s
        jitter = 0.75 + 0.5 * self._rng.random()
        b.probe_at = time.monotonic() + b.probe_backoff * jitter

    def _probe_loop(self) -> None:
        tick = min(0.05, self.probe_interval_s / 4) or 0.05
        while not self._stop.wait(tick):
            now = time.monotonic()
            with self._lock:
                due = [
                    b for b in self._backends.values() if b.probe_at <= now
                ]
            for b in due:
                self._probe(b)
                self._reschedule_probe(b)

    # -- front-end server ----------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._handle, args=(conn,), daemon=True
            ).start()

    def _handle(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(self.request_timeout)
            head = conn.recv(4096)
            if not head:
                return
            if head[:4].ljust(4) in _HTTP_VERBS or head[:5] == b"PATCH":
                self._handle_http(conn, head)
            else:
                self._handle_jsonl(conn, head)
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _handle_http(self, conn: socket.socket, head: bytes) -> None:
        while b"\r\n\r\n" not in head and len(head) < 65536:
            chunk = conn.recv(4096)
            if not chunk:
                break
            head += chunk
        header, _, body = head.partition(b"\r\n\r\n")
        lines = header.split(b"\r\n")
        method, path = (lines[0].split(b" ") + [b"", b""])[:2]
        clen = 0
        for ln in lines[1:]:
            if ln.lower().startswith(b"content-length:"):
                clen = int(ln.split(b":", 1)[1].strip() or 0)
        while len(body) < clen:
            chunk = conn.recv(65536)
            if not chunk:
                break
            body += chunk
        if method == b"POST" and path.startswith(b"/generate"):
            try:
                payload = json.loads(body.decode() or "{}")
            except (ValueError, UnicodeDecodeError):
                self._respond(conn, 400, {"error": "malformed JSON body"})
                return
            out = self.dispatch(payload)
            if out.get("error") == "shed":
                self._respond(
                    conn, 503, out,
                    headers={"Retry-After": str(out["retry_after_s"])},
                )
            else:
                self._respond(conn, 400 if "error" in out else 200, out)
        elif method == b"GET" and path.startswith(b"/healthz"):
            with self._lock:
                live = sum(1 for b in self._backends.values() if not b.dead)
                total = len(self._backends)
            self._respond(
                conn, 200, {"ok": live > 0, "live": live, "replicas": total}
            )
        elif method == b"GET" and path.startswith(b"/stats"):
            self._respond(conn, 200, self.stats())
        else:
            self._respond(conn, 404, {"error": "unknown route"})

    def _respond(
        self,
        conn: socket.socket,
        status: int,
        obj: dict,
        headers: Optional[dict] = None,
    ) -> None:
        body = (json.dumps(obj) + "\n").encode()
        reason = {
            200: "OK",
            400: "Bad Request",
            404: "Not Found",
            503: "Service Unavailable",
        }.get(status, "Error")
        extra = "".join(f"{k}: {v}\r\n" for k, v in (headers or {}).items())
        head = (
            f"HTTP/1.0 {status} {reason}\r\n"
            "Content-Type: application/json\r\n"
            f"{extra}"
            f"Content-Length: {len(body)}\r\n\r\n"
        ).encode()
        conn.sendall(head + body)

    def _handle_jsonl(self, conn: socket.socket, buf: bytes) -> None:
        while True:
            while b"\n" in buf:
                line, _, buf = buf.partition(b"\n")
                line = line.strip()
                if not line:
                    continue
                try:
                    payload = json.loads(line.decode())
                except (ValueError, UnicodeDecodeError):
                    out = {"error": "malformed JSON line"}
                else:
                    out = self.dispatch(payload)
                conn.sendall((json.dumps(out) + "\n").encode())
            chunk = conn.recv(65536)
            if not chunk:
                return
            buf += chunk

    # -- introspection -------------------------------------------------------

    def dead_replicas(self) -> list:
        """Registered replicas currently marked dead (autoscaler input:
        these are replacement candidates, not scaling signals)."""
        with self._lock:
            return [rid for rid, b in self._backends.items() if b.dead]

    def live_replicas(self) -> list:
        with self._lock:
            return [rid for rid, b in self._backends.items() if not b.dead]

    def stats(self) -> dict:
        with self._lock:
            backends = dict(self._backends)
        with self._lock:
            dir_stats = (
                {
                    "entries": len(self._prefix_dir),
                    "hits": self.directory_hits,
                    "misses": self.directory_misses,
                }
                if self.prefix_directory
                else None
            )
        return {
            "port": self.port,
            "redispatches": self.redispatches,
            "deaths": self.deaths,
            "shed": self.shed,
            "prefix_directory": dir_stats,
            "replicas": {
                rid: {
                    "host": b.host,
                    "port": b.port,
                    "dead": b.dead,
                    "stale": b.stale,
                    "ready": b.ready,
                    "inflight": b.inflight,
                    "dispatched": b.dispatched,
                }
                for rid, b in backends.items()
            },
        }

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            backends = list(self._backends.values())
        for b in backends:
            b.close_pool()
