"""SLO-driven fleet autoscaler: a closed loop from health to headcount.

The serving fleet so far reacts to death (router re-dispatch, publisher
re-keyframe) but its size is an operator constant. This module closes
the loop: a control thread consumes the fleet health matrix the push
channel already refreshes (per-replica queue depth, slot occupancy,
decode p99, staleness — :meth:`FleetManager.health_matrix`, overlaid
with overseer gossip rows when the obs plane is armed) and steers the
router-registered replica count against a declared SLO::

    breach:  p99 > slo_p99_ms  OR  queue depth > slo_queue_depth
    clear:   p99 < slo_p99_ms/2 AND queues drained

Control-loop hygiene, because flapping is worse than either bound:

- **hysteresis** — ``scale_up_evals`` consecutive breach ticks before
  growing, ``scale_down_evals`` consecutive clear ticks before
  shrinking (up is eager, down is reluctant);
- **cooldown** — at most one scaling action per ``cooldown_s``, so the
  loop observes the effect of its last action before acting again;
- **bounds** — ``min_replicas``/``max_replicas`` clamp the fleet.

Scale-up prefers **warm spares**: replicas attached to the push channel
(pre-keyframed, following every delta) but unknown to the router. A
spare promotion is one ``router.add_replica`` call — mailbox adoption,
not a cold boot — so capacity arrives in milliseconds while a
replacement spare boots in the background. Scale-down *demotes* back to
spare when the spare pool has room (keeping the warmth), else retires.

Replica death is handled here too, and is **not** a scaling decision:
when the router marks a registered replica dead (connection error →
``fleet_replica_dead`` watchdog), the next tick retires the corpse and
promotes/boots a replacement at the same target count, with no operator
action and no cooldown (replacement restores capacity, it does not
change it).

Every action lands in a bounded decision log (``decisions``), the
``fleet_autoscale_decisions`` counter, and the flight recorder's
decision ring — a postmortem can line each scale/replace up against the
health rows that drove it.

Env overrides (all optional; config supplies defaults):

- ``ODTP_FLEET_SLO_P99_MS``        latency SLO in milliseconds
- ``ODTP_FLEET_WARM_SPARES``       warm-spare pool size
- ``ODTP_FLEET_SCALE_COOLDOWN_S``  seconds between scaling actions

The module is jax-free: it moves names and addresses, never weights.
"""
from __future__ import annotations

import collections
import logging
import os
import threading
import time
from typing import Callable, Optional

from opendiloco_tpu import obs

log = logging.getLogger(__name__)


class FleetAutoscaler:
    """Observe → decide → act loop over a FleetManager + FleetRouter.

    ``boot_fn(rid, register)`` must create a replica and attach it to
    the manager (``router_register=register``); ``retire_fn(rid)`` must
    detach and reap it. Both are supplied by ``build_fleet`` so the
    loop itself stays process-model agnostic (inprocess or subprocess)
    and unit-testable with fakes.
    """

    def __init__(
        self,
        manager,
        router,
        *,
        slo_p99_ms: float = 0.0,
        slo_queue_depth: int = 8,
        min_replicas: int = 1,
        max_replicas: int = 8,
        warm_spares: int = 0,
        cooldown_s: float = 5.0,
        eval_interval_s: float = 0.5,
        up_evals: int = 2,
        down_evals: int = 8,
        boot_fn: Optional[Callable[[str, bool], None]] = None,
        retire_fn: Optional[Callable[[str], None]] = None,
    ):
        env = os.environ.get("ODTP_FLEET_SLO_P99_MS")
        self.slo_p99_ms = float(env) if env else float(slo_p99_ms)
        env = os.environ.get("ODTP_FLEET_WARM_SPARES")
        self.warm_spares = int(env) if env else int(warm_spares)
        env = os.environ.get("ODTP_FLEET_SCALE_COOLDOWN_S")
        self.cooldown_s = float(env) if env else float(cooldown_s)
        self.manager = manager
        self.router = router
        self.slo_queue_depth = int(slo_queue_depth)
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.eval_interval_s = float(eval_interval_s)
        self.up_evals = max(1, int(up_evals))
        self.down_evals = max(1, int(down_evals))
        self._boot_fn = boot_fn
        self._retire_fn = retire_fn
        self._lock = threading.Lock()
        self.decisions: collections.deque = collections.deque(maxlen=256)
        self.ticks = 0
        self._up_streak = 0
        self._down_streak = 0
        self._last_scale = 0.0  # monotonic time of last scale action
        self._seq = 0  # autoscaled-replica name counter
        self._booting: set = set()  # spare boots in flight (background)
        self._booting_active: set = set()  # cold scale-up boots in flight
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "FleetAutoscaler":
        self._thread = threading.Thread(
            target=self._loop, name="odtp-fleet-autoscaler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def _loop(self) -> None:
        while not self._stop.wait(self.eval_interval_s):
            try:
                self.evaluate()
            except Exception:
                # the control loop must outlive any single bad tick; the
                # fleet keeps serving at its current size either way
                log.exception("autoscaler tick failed")

    # -- observe -------------------------------------------------------------

    def _active(self) -> list:
        """Router-registered replicas — the traffic-taking set."""
        return sorted(self.router.stats()["replicas"])

    def _fleet_load(self, active: list) -> tuple:
        """Worst-replica load over the active set: (p99_ms, queue_depth).
        Max, not mean — one hot replica violating the SLO is a breach
        even if its siblings idle (dispatch imbalance is real load)."""
        matrix = self.manager.health_matrix()
        p99s = [
            matrix[rid]["p99_ms"]
            for rid in active
            if matrix.get(rid, {}).get("p99_ms") is not None
        ]
        depths = [
            matrix[rid]["queue_depth"]
            for rid in active
            if matrix.get(rid, {}).get("queue_depth") is not None
        ]
        return (
            max(p99s) if p99s else None,
            max(depths) if depths else 0,
        )

    def ready_spares(self) -> list:
        return [
            rid for rid in self.manager.spares()
            if self.manager.spare_ready(rid)
        ]

    def _breach_evidence(self, active: list) -> tuple:
        """(worst-p99 replica, exemplar trace ids) behind a breach.

        Exemplars are the breaching replicas' slowest recent request
        traces (``slo_exemplars`` riding their health rows), worst
        replica first, falling back to this process's own reqtrace ring
        (in-process fleets share one ring) — so every scale-up decision
        names requests whose traces show *where* the latency went."""
        matrix = self.manager.health_matrix()
        rows = [(rid, matrix.get(rid) or {}) for rid in active]
        rows.sort(key=lambda kv: -(kv[1].get("p99_ms") or 0.0))
        worst = rows[0][0] if rows else ""
        exemplars: list = []
        for _, row in rows:
            for tid in row.get("slo_exemplars") or []:
                if tid not in exemplars:
                    exemplars.append(tid)
        if not exemplars:
            rt = obs.reqtrace.ring()
            if rt is not None:
                exemplars = [ex["id"] for ex in rt.exemplars()]
        return worst, exemplars[:5]

    # -- act -----------------------------------------------------------------

    def _record(self, action: str, **detail) -> dict:
        rec = {"action": action, "tick": self.ticks, **detail}
        with self._lock:
            self.decisions.append(rec)
        obs.count("fleet_autoscale_decisions", action=action)
        from opendiloco_tpu.obs import blackbox

        bb = blackbox.recorder()
        if bb is not None:
            bb.note_decision(rec)
        log.info("autoscale %s: %s", action, detail)
        return rec

    def _next_rid(self, prefix: str) -> str:
        with self._lock:
            return self._next_rid_locked(prefix)

    def _next_rid_locked(self, prefix: str) -> str:
        self._seq += 1
        return f"{prefix}{self._seq}"

    def _add_capacity(self) -> Optional[dict]:
        """One more traffic-taking replica: promote a ready spare
        (instant) or boot a cold one (slow). Cold boots run on a
        background thread — the control loop must keep ticking while a
        process boots, or a replica death during the boot would go
        unreplaced for the whole provisioning time. At most one cold
        boot is in flight at a time: a breach that persists while one
        races toward the router does not justify a second."""
        for rid in self.ready_spares():
            if self.manager.promote(rid):
                return {"replica": rid, "mode": "spare_promotion"}
        if self._boot_fn is None:
            return None
        with self._lock:
            if self._booting_active:
                return None
            rid = self._next_rid_locked("a")
            self._booting_active.add(rid)
        threading.Thread(
            target=self._boot_active, args=(rid,),
            name=f"odtp-fleet-boot-{rid}", daemon=True,
        ).start()
        return {"replica": rid, "mode": "cold_boot"}

    def _boot_active(self, rid: str) -> None:
        try:
            self._boot_fn(rid, True)
        except Exception:
            log.exception("replica %s failed to boot", rid)
        finally:
            with self._lock:
                self._booting_active.discard(rid)

    def _replenish_spares(self) -> None:
        """Keep the spare pool at its target. Boots run on background
        threads so a slow cold boot never stalls the control loop (a
        replacement decision mid-spike must not wait on provisioning),
        and never count against cooldown: spares take no traffic, so
        this is provisioning, not scaling."""
        if self._boot_fn is None:
            return
        with self._lock:
            short = (
                self.warm_spares
                - len(self.manager.spares())
                - len(self._booting)
            )
            rids = [self._next_rid_locked("s") for _ in range(max(0, short))]
            self._booting.update(rids)
        for rid in rids:
            threading.Thread(
                target=self._boot_spare, args=(rid,),
                name=f"odtp-fleet-boot-{rid}", daemon=True,
            ).start()
            self._record("boot_spare", replica=rid)

    def _boot_spare(self, rid: str) -> None:
        try:
            self._boot_fn(rid, False)
        except Exception:
            log.exception("spare %s failed to boot", rid)
        finally:
            with self._lock:
                self._booting.discard(rid)

    def _replace_dead(self) -> int:
        """Retire router-dead replicas and restore the same capacity.
        Not cooldown-gated: replacement holds the target size steady."""
        replaced = 0
        for rid in self.router.dead_replicas():
            if self._retire_fn is not None:
                self._retire_fn(rid)
            else:
                self.manager.detach(rid)
            sub = self._add_capacity()
            self._record("replace", dead=rid, **(sub or {"mode": "none"}))
            replaced += 1
        return replaced

    # -- decide --------------------------------------------------------------

    def evaluate(self) -> list:
        """One control tick; returns the decisions it made (tests drive
        this directly, the loop thread calls it on an interval)."""
        self.ticks += 1
        n0 = len(self.decisions)
        self._replace_dead()
        self._replenish_spares()

        active = self._active()
        p99, depth = self._fleet_load(active)
        breach = (
            self.slo_p99_ms > 0 and p99 is not None and p99 > self.slo_p99_ms
        ) or depth > self.slo_queue_depth
        clear = (
            self.slo_p99_ms <= 0
            or p99 is None
            or p99 < 0.5 * self.slo_p99_ms
        ) and depth <= max(1, self.slo_queue_depth // 4)
        self._up_streak = self._up_streak + 1 if breach else 0
        self._down_streak = self._down_streak + 1 if clear else 0

        exemplars: list = []
        if breach:
            worst, exemplars = self._breach_evidence(active)
            if (
                self.slo_p99_ms > 0
                and p99 is not None
                and p99 > self.slo_p99_ms
            ):
                wd = obs.anomaly.watchdog()
                if wd is not None:
                    # the breach record carries the offending trace ids:
                    # a p99 alarm resolves to actual request timelines
                    wd.slo_breach(
                        p99, self.slo_p99_ms, subject=worst,
                        exemplars=exemplars,
                    )

        now = time.monotonic()
        cooled = now - self._last_scale >= self.cooldown_s
        with self._lock:
            pending = len(self._booting_active)
        if (
            breach
            and self._up_streak >= self.up_evals
            and cooled
            and len(active) + pending < self.max_replicas
        ):
            sub = self._add_capacity()
            if sub is not None:
                self._last_scale = now
                self._up_streak = 0
                self._record(
                    "scale_up", p99_ms=p99, queue_depth=depth,
                    replicas=len(active) + 1, exemplars=exemplars, **sub,
                )
        elif (
            clear
            and self._down_streak >= self.down_evals
            and cooled
            and len(active) > self.min_replicas
        ):
            # shed the least-loaded replica; demote keeps it warm when
            # the spare pool has room, retire otherwise
            stats = self.router.stats()["replicas"]
            victim = min(
                (r for r in active if not stats[r]["dead"]),
                key=lambda r: (stats[r]["inflight"], stats[r]["dispatched"]),
                default=None,
            )
            if victim is not None:
                # with a spare pool configured, shrink by demotion: the
                # pool may transiently exceed its target (promotion
                # drains it first on the next breach), warmth is free
                if self.warm_spares > 0:
                    self.manager.demote(victim)
                    mode = "demote_to_spare"
                elif self._retire_fn is not None:
                    self._retire_fn(victim)
                    mode = "retire"
                else:
                    self.manager.detach(victim)
                    mode = "detach"
                self._last_scale = now
                self._down_streak = 0
                self._record(
                    "scale_down", p99_ms=p99, queue_depth=depth,
                    replica=victim, mode=mode, replicas=len(active) - 1,
                )

        obs.gauge("fleet_replicas_target", len(self._active()))
        obs.gauge("fleet_warm_spares_ready", len(self.ready_spares()))
        with self._lock:
            return list(self.decisions)[n0:]

    # -- introspection -------------------------------------------------------

    def status(self) -> dict:
        with self._lock:
            decisions = list(self.decisions)[-32:]
        return {
            "slo_p99_ms": self.slo_p99_ms,
            "slo_queue_depth": self.slo_queue_depth,
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "warm_spares": self.warm_spares,
            "ticks": self.ticks,
            "active": self._active(),
            "spares": self.manager.spares(),
            "spares_ready": self.ready_spares(),
            "decisions": decisions,
        }
