"""Serving fleet: a replica galaxy fed by delta pushes, behind one router.

The single-process serving plane (``serve/``) tops out at one engine per
trainer. This package fans it out: the trainer keeps training, a
:class:`~opendiloco_tpu.fleet.publisher.DeltaPublisher` encodes each
outer epoch's master movement as codec-compressed per-fragment deltas
(with error feedback and periodic keyframes), replica processes
(:mod:`~opendiloco_tpu.fleet.replica`) apply them into their own
engines, and a :class:`~opendiloco_tpu.fleet.router.FleetRouter` spreads
client traffic with least-loaded + prefix-affinity dispatch. Replica
join/leave/SIGKILL is absorbed by router re-dispatch and publisher
keyframe onboarding — the same elasticity posture as the training plane.

``build_fleet(fleet_cfg, model_cfg, params, diloco_opt)`` assembles the
whole thing (train.py calls it when ``config.fleet.enabled``);
:func:`status` is the control-port ``fleet`` frame's source of truth.
"""
from __future__ import annotations

import dataclasses
import json
import logging
import os
import socket
import subprocess
import sys
import tempfile
import threading
from typing import Any, Optional

from opendiloco_tpu import obs
from opendiloco_tpu.fleet.autoscaler import FleetAutoscaler
from opendiloco_tpu.fleet.publisher import DeltaPublisher, apply_frame  # noqa: F401
from opendiloco_tpu.fleet.router import FleetRouter
from opendiloco_tpu.fleet.wire import FleetWireError, recv_frame, send_frame

__all__ = [
    "DeltaPublisher",
    "FleetAutoscaler",
    "FleetManager",
    "FleetPlane",
    "FleetRouter",
    "apply_frame",
    "build_fleet",
    "spawn_replica",
    "status",
]

log = logging.getLogger(__name__)


class FleetManager:
    """Owns one pusher thread per replica: ships the publisher's frames
    over the push channel, pings when there is nothing to ship (so
    replica staleness accounting keeps moving), folds replica health
    replies into the overseer matrix, and re-keyframes a replica whose
    state no longer matches the publisher's shadow (restart, stale
    delta base)."""

    def __init__(
        self,
        publisher: DeltaPublisher,
        router: Optional[FleetRouter] = None,
        *,
        push_interval_s: float = 0.25,
    ):
        env = os.environ.get("ODTP_FLEET_PUSH_INTERVAL_S")
        self.push_interval_s = float(env) if env else float(push_interval_s)
        self.publisher = publisher
        self.router = router
        self._stops: dict[str, threading.Event] = {}
        self._threads: dict[str, threading.Thread] = {}
        self._last_reply: dict[str, dict] = {}
        self._addrs: dict[str, tuple[str, int]] = {}
        self._spares: set[str] = set()
        self._lock = threading.Lock()

    def attach(
        self,
        rid: str,
        serve_host: str,
        serve_port: int,
        push_host: str,
        push_port: int,
        *,
        router_register: bool = True,
    ) -> None:
        """Register ``rid`` on the push channel. ``router_register=False``
        makes it a warm spare: it follows keyframes/deltas like any
        replica but takes no traffic until :meth:`promote` hands its
        address to the router — so scale-up is a mailbox adoption, not a
        cold boot."""
        self.publisher.register(rid)
        with self._lock:
            self._addrs[rid] = (serve_host, int(serve_port))
            if not router_register:
                self._spares.add(rid)
        if router_register and self.router is not None:
            self.router.add_replica(rid, serve_host, serve_port)
        stop = threading.Event()
        t = threading.Thread(
            target=self._push_loop,
            args=(rid, push_host, push_port, stop),
            name=f"odtp-fleet-push-{rid}",
            daemon=True,
        )
        with self._lock:
            self._stops[rid] = stop
            self._threads[rid] = t
        t.start()

    def detach(self, rid: str) -> None:
        with self._lock:
            stop = self._stops.pop(rid, None)
            t = self._threads.pop(rid, None)
            self._addrs.pop(rid, None)
            self._spares.discard(rid)
            self._last_reply.pop(rid, None)
        if stop is not None:
            stop.set()
        if t is not None:
            t.join(timeout=2.0)
        self.publisher.drop(rid)
        if self.router is not None:
            self.router.remove_replica(rid)

    # -- warm spares ---------------------------------------------------------

    def spares(self) -> list:
        with self._lock:
            return sorted(self._spares)

    def addr(self, rid: str) -> Optional[tuple]:
        """(serve_host, serve_port) for an attached replica or spare."""
        with self._lock:
            return self._addrs.get(rid)

    def spare_ready(self, rid: str) -> bool:
        """A spare is adoptable once a push reply confirmed applied
        weights (a keyframe landed) and its health says ready."""
        with self._lock:
            if rid not in self._spares:
                return False
            rmeta = self._last_reply.get(rid)
        if not rmeta:
            return False
        h = rmeta.get("health") or {}
        return bool(rmeta.get("ready", h.get("ready"))) and int(
            rmeta.get("weights_epoch", -1)
        ) >= 0

    def promote(self, rid: str) -> bool:
        """Hand a warm spare's address to the router: it starts taking
        traffic with the weights it has been following all along."""
        if self.router is None:
            return False
        with self._lock:
            addr = self._addrs.get(rid)
            if rid not in self._spares or addr is None:
                return False
            self._spares.discard(rid)
        self.router.add_replica(rid, addr[0], addr[1])
        obs.count("fleet_spare_promotions", replica=rid)
        return True

    def demote(self, rid: str) -> bool:
        """Scale-down without losing warmth: pull ``rid`` out of the
        router (no more traffic) but keep its push loop following
        deltas, so it can be re-promoted instantly."""
        with self._lock:
            if rid in self._spares or rid not in self._addrs:
                return False
            self._spares.add(rid)
        if self.router is not None:
            self.router.remove_replica(rid)
        return True

    def _note_reply(self, rid: str, rmeta: dict) -> None:
        with self._lock:
            self._last_reply[rid] = rmeta
        st = rmeta.get("staleness")
        if st is not None:
            obs.count("fleet_staleness_rounds", 1, replica=rid, rounds=int(st))
            obs.gauge("fleet_replica_staleness", int(st), replica=rid)
        h = rmeta.get("health")
        if h:
            # prefix-cache directory feed: adopt the replica's host-tier
            # advertisement (absent key = nothing resident = clears its
            # directory entries; a no-op when the directory is off)
            if self.router is not None:
                self.router.update_prefixes(rid, h.get("prefixes") or [])
            if h.get("queue_depth") is not None:
                obs.gauge(
                    "fleet_replica_queue_depth", int(h["queue_depth"]),
                    replica=rid,
                )
            if h.get("p99_ms") is not None:
                obs.gauge(
                    "fleet_replica_p99_ms", float(h["p99_ms"]), replica=rid
                )
        vec = rmeta.get("rollup")
        if vec:
            ov = obs.overseer.plane()
            if ov is not None:
                ov.merge(f"replica:{rid}", vec)

    def health_matrix(self) -> dict:
        """rid -> latest load/health vector. Base truth is the push-reply
        ``health`` dict (refreshes at push cadence, works with obs
        unarmed); overseer matrix rows overlay it when the obs plane is
        armed, so gossip-merged fields win if fresher channels carry
        them. This is the autoscaler's entire view of the fleet."""
        out: dict[str, dict] = {}
        with self._lock:
            for rid, rmeta in self._last_reply.items():
                h = rmeta.get("health")
                if h:
                    out[rid] = dict(h)
        ov = obs.overseer.plane()
        if ov is not None:
            for peer, vec in ov.matrix().items():
                if not peer.startswith("replica:"):
                    continue
                rid = peer.split(":", 1)[1]
                row = out.setdefault(rid, {})
                for k in (
                    "queue_depth", "occupancy", "p99_ms", "staleness", "stale"
                ):
                    if vec.get(k) is not None:
                        row[k] = vec[k]
        return out

    def _push_loop(
        self, rid: str, host: str, port: int, stop: threading.Event
    ) -> None:
        sock: Optional[socket.socket] = None
        while not stop.is_set():
            try:
                if sock is None:
                    sock = socket.create_connection((host, port), timeout=2.0)
                    sock.setsockopt(
                        socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                    )
                    send_frame(sock, "hello", {"kind": "hello"})
                    _, rmeta, _ = recv_frame(sock, timeout=10.0)
                    # a restarted replica answers with a different epoch
                    # than our shadow tracks: forget it, re-keyframe
                    if int(rmeta.get("epoch", -1)) != self.publisher.channel_epoch(rid):
                        self.publisher.reset(rid)
                frames = self.publisher.frames(rid)
                for meta, payload in frames:
                    send_frame(sock, meta["kind"], meta, payload)
                    kind, rmeta, _ = recv_frame(sock, timeout=60.0)
                    if kind != "ok":
                        self.publisher.reset(rid)
                        break
                    self._note_reply(rid, rmeta)
                if not frames:
                    send_frame(
                        sock,
                        "ping",
                        {"kind": "ping", "tepoch": self.publisher.last_epoch},
                    )
                    kind, rmeta, _ = recv_frame(sock, timeout=10.0)
                    if kind == "ok":
                        self._note_reply(rid, rmeta)
            except (OSError, FleetWireError, ValueError):
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
                    sock = None
            stop.wait(self.push_interval_s)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def stop(self) -> None:
        with self._lock:
            rids = list(self._stops)
        for rid in rids:
            self.detach(rid)

    def status(self) -> dict:
        with self._lock:
            return {
                "replicas": dict(self._last_reply),
                "spares": sorted(self._spares),
            }


def spawn_replica(
    replica_id: str,
    model_cfg,
    *,
    serve: Optional[dict] = None,
    max_stale_rounds: int = 2,
    host: str = "127.0.0.1",
    serve_port: int = 0,
    push_port: int = 0,
    seed: int = 0,
    env: Optional[dict] = None,
    timeout: float = 120.0,
) -> tuple:
    """Start ``python -m opendiloco_tpu.fleet.replica`` and wait for its
    ready line. Returns ``(Popen, info)`` with the bound ports. Explicit
    ports let a respawned replica rejoin at its old address (the router
    probe and the manager's reconnect both dial the address they know)."""
    spec = {
        "replica_id": replica_id,
        "model": model_cfg.to_dict(),
        "serve": serve or {},
        "max_stale_rounds": int(max_stale_rounds),
        "host": host,
        "serve_port": int(serve_port),
        "push_port": int(push_port),
        "seed": int(seed),
    }
    fd, path = tempfile.mkstemp(prefix=f"odtp-replica-{replica_id}-", suffix=".json")
    with os.fdopen(fd, "w") as f:
        json.dump(spec, f)
    child_env = dict(os.environ)
    child_env.setdefault("JAX_PLATFORMS", "cpu")
    if env:
        child_env.update(env)
    proc = subprocess.Popen(
        [sys.executable, "-m", "opendiloco_tpu.fleet.replica", "--spec", path],
        stdout=subprocess.PIPE,
        env=child_env,
        text=True,
    )

    info: dict = {}

    def _read() -> None:
        line = proc.stdout.readline()
        if line:
            try:
                info.update(json.loads(line))
            except ValueError:
                pass

    reader = threading.Thread(target=_read, daemon=True)
    reader.start()
    reader.join(timeout=timeout)
    try:
        os.unlink(path)
    except OSError:
        pass
    if not info:
        proc.kill()
        raise RuntimeError(
            f"replica {replica_id} did not report ready within {timeout}s"
        )
    return proc, info


@dataclasses.dataclass
class FleetPlane:
    """The live fleet, with one-call teardown (train.py finally)."""

    publisher: DeltaPublisher
    router: FleetRouter
    manager: FleetManager
    replicas: dict  # rid -> Replica (inprocess) or subprocess.Popen
    autoscaler: Optional[FleetAutoscaler] = None

    @property
    def port(self) -> int:
        return self.router.port

    def status(self) -> dict:
        out = {
            "router": self.router.stats(),
            "publisher": self.publisher.stats(),
            "manager": self.manager.status(),
        }
        if self.autoscaler is not None:
            out["autoscaler"] = self.autoscaler.status()
        return out

    def stop(self) -> None:
        if self.autoscaler is not None:
            self.autoscaler.stop()
        self.manager.stop()
        self.router.stop()
        for rep in list(self.replicas.values()):
            if hasattr(rep, "stop"):
                rep.stop()
            else:
                rep.kill()
                rep.wait(timeout=5.0)


# control-port "fleet" frame source: the live plane of this process
_plane: Optional[FleetPlane] = None


def register_plane(plane: Optional[FleetPlane]) -> None:
    global _plane
    _plane = plane


def status() -> dict:
    if _plane is None:
        return {"enabled": False}
    return {"enabled": True, **_plane.status()}


def build_fleet(
    fleet_cfg,
    model_cfg,
    params,
    diloco_opt=None,
    *,
    compute_dtype=None,
) -> FleetPlane:
    """Assemble publisher + router + replicas from a ``config.FleetConfig``.
    ``diloco_opt`` supplies live masters (``master_snapshot``); None
    publishes the given params as a static epoch-0 snapshot."""
    import jax
    import numpy as np

    if diloco_opt is not None:
        snapshot_fn = diloco_opt.master_snapshot
    else:
        static = [
            np.asarray(x) for x in jax.tree.leaves(jax.device_get(params))
        ]
        snapshot_fn = lambda: (0, static)  # noqa: E731
    codec = os.environ.get("ODTP_FLEET_CODEC") or fleet_cfg.codec
    publisher = DeltaPublisher(
        snapshot_fn,
        codec=codec,
        fragments=fleet_cfg.fragments,
        keyframe_every=fleet_cfg.keyframe_every,
        error_feedback=fleet_cfg.error_feedback,
    )
    env_dir = os.environ.get("ODTP_PREFIX_DIRECTORY")
    prefix_directory = (
        bool(int(env_dir)) if env_dir else fleet_cfg.prefix_directory
    )
    router = FleetRouter(
        host=fleet_cfg.host,
        port=fleet_cfg.port,
        prefix_directory=prefix_directory,
    )
    manager = FleetManager(
        publisher, router, push_interval_s=fleet_cfg.push_interval_s
    )
    serve_geom = {
        "num_slots": fleet_cfg.max_batch,
        "max_context": fleet_cfg.max_context,
        "prefill_buckets": list(fleet_cfg.prefill_buckets),
        "max_queue": fleet_cfg.max_queue,
        "prefix_cache": fleet_cfg.prefix_cache,
        # the directory advertises host-tier entries, so turning it on
        # arms each replica's tier (live slots churn; the host store is
        # what outlives them)
        "kv_tier": prefix_directory,
    }
    replicas: dict[str, Any] = {}

    def _boot(rid: str, register: bool = True) -> None:
        """Create one replica and attach it; ``register=False`` keeps it
        a warm spare (push channel only). Shared by initial bring-up and
        the autoscaler's scale-up/replacement path."""
        if fleet_cfg.inprocess:
            from opendiloco_tpu.fleet.replica import Replica

            rep = Replica(
                rid,
                model_cfg,
                max_stale_rounds=fleet_cfg.max_stale_rounds,
                host=fleet_cfg.host,
                compute_dtype=compute_dtype,
                **serve_geom,
            )
            replicas[rid] = rep
            serve_port, push_port = rep.server.port, rep.push_port
        else:
            proc, info = spawn_replica(
                rid,
                model_cfg,
                serve=serve_geom,
                max_stale_rounds=fleet_cfg.max_stale_rounds,
                host=fleet_cfg.host,
            )
            replicas[rid] = proc
            serve_port, push_port = info["serve_port"], info["push_port"]
        manager.attach(
            rid, fleet_cfg.host, serve_port, fleet_cfg.host, push_port,
            router_register=register,
        )

    def _retire(rid: str) -> None:
        manager.detach(rid)
        rep = replicas.pop(rid, None)
        if rep is None:
            return
        if hasattr(rep, "stop"):
            rep.stop()
        else:
            rep.kill()
            rep.wait(timeout=5.0)

    for i in range(fleet_cfg.replicas):
        _boot(f"r{i}", True)

    autoscaler = None
    if fleet_cfg.autoscale or fleet_cfg.warm_spares > 0:
        autoscaler = FleetAutoscaler(
            manager,
            router,
            slo_p99_ms=fleet_cfg.slo_p99_ms,
            slo_queue_depth=fleet_cfg.slo_queue_depth,
            min_replicas=fleet_cfg.min_replicas,
            max_replicas=fleet_cfg.max_replicas,
            warm_spares=fleet_cfg.warm_spares,
            cooldown_s=fleet_cfg.scale_cooldown_s,
            eval_interval_s=fleet_cfg.scale_eval_interval_s,
            up_evals=fleet_cfg.scale_up_evals,
            down_evals=fleet_cfg.scale_down_evals,
            boot_fn=_boot,
            retire_fn=_retire,
        ).start()
    plane = FleetPlane(
        publisher=publisher,
        router=router,
        manager=manager,
        replicas=replicas,
        autoscaler=autoscaler,
    )
    register_plane(plane)
    return plane
