"""Synchronous ODTP framing for the fleet push channel.

Byte-identical to the asyncio control plane's frames (diloco/wire.py):
``[4B magic "ODTP"][4B BE header_len][header JSON][payload]`` with the
header carrying ``{"type", "meta", "payload_len"}``. The push channel is
a plain blocking socket per (publisher, replica) pair — no asyncio loop
on either side — so this module provides the sync twins of
``send_frame``/``read_frame``, importing every layout constant from
``diloco/schema.py`` (the wire-schema lint rejects struct literals
anywhere else).
"""
from __future__ import annotations

import json
import socket
from typing import Any, Optional

from opendiloco_tpu.diloco.schema import (  # single layout declaration
    FRAME_HDR as _HDR,
    MAGIC,
    MAX_HEADER,
)


class FleetWireError(RuntimeError):
    pass


def send_frame(
    sock: socket.socket,
    msg_type: str,
    meta: dict[str, Any],
    payload: bytes = b"",
) -> None:
    header = json.dumps(
        {"type": msg_type, "meta": meta, "payload_len": len(payload)}
    ).encode()
    # header and payload written separately: no large concat copy
    sock.sendall(_HDR.pack(MAGIC, len(header)) + header)
    if payload:
        sock.sendall(payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        k = sock.recv_into(view[got:], n - got)
        if k == 0:
            raise FleetWireError("connection closed mid-frame")
        got += k
    return bytes(buf)


def recv_frame(
    sock: socket.socket, *, timeout: Optional[float] = None
) -> tuple[str, dict[str, Any], bytes]:
    if timeout is not None:
        sock.settimeout(timeout)
    hdr = _recv_exact(sock, _HDR.size)
    magic, hlen = _HDR.unpack(hdr)
    if magic != MAGIC or hlen > MAX_HEADER:
        raise FleetWireError(f"bad frame header: magic={magic!r} hlen={hlen}")
    header = json.loads(_recv_exact(sock, hlen))
    payload = b""
    n = int(header.get("payload_len", 0))
    if n:
        payload = _recv_exact(sock, n)
    return header["type"], header.get("meta", {}), payload
