"""Replica runner: one serving engine fed by the fleet push channel.

A replica is ``ServeEngine + ContinuousBatcher + ServeServer`` plus a
push listener. The listener applies keyframe/delta frames
(:func:`fleet.publisher.apply_frame`) into a host-side flat f32 shadow
under a lock, and the engine adopts fully-applied epochs through its
normal ``snapshot_fn``/``maybe_swap`` path between decode steps — so
weight rebinds stay on the scheduler thread exactly like single-process
serving, and a half-pushed fragment set is never visible to decode.

Staleness has two levels here:

- the engine's ``epoch_fn`` tracks the *mailbox* (last fully-applied
  push), so ``maybe_swap`` adopts new weights eagerly;
- the replica's own :meth:`staleness` tracks the *trainer* epoch (pings
  advance it even when weight pushes stall) against
  ``max_stale_rounds`` — the health bound the router and overseer see.

Run in-process (tests, ``fleet.inprocess``) or as a subprocess::

    python -m opendiloco_tpu.fleet.replica --spec spec.json

which prints one ready line of JSON (``replica_id``, bound
``serve_port``/``push_port``, ``pid``) on stdout and serves until
killed. Replica death is the router's problem, not ours: SIGKILL simply
stops the sockets answering.
"""
from __future__ import annotations

import argparse
import json
import logging
import os
import socket
import threading
from typing import Optional

from opendiloco_tpu import obs
from opendiloco_tpu.fleet.publisher import FleetFrameError, apply_frame
from opendiloco_tpu.fleet.wire import FleetWireError, recv_frame, send_frame

log = logging.getLogger(__name__)


class Replica:
    def __init__(
        self,
        replica_id: str,
        model_cfg,
        *,
        num_slots: int = 4,
        max_context: int = 128,
        prefill_buckets=(16, 64),
        max_queue: int = 1024,
        max_stale_rounds: int = 2,
        host: str = "127.0.0.1",
        serve_port: int = 0,
        push_port: int = 0,
        prefix_cache: bool = True,
        kv_tier: bool = False,
        kv_tier_codec: str = "none",
        kv_host_slots: int = 32,
        compute_dtype=None,
        seed: int = 0,
        start_push_server: bool = True,
    ):
        import jax
        import jax.numpy as jnp

        from opendiloco_tpu.models.llama import init_params
        from opendiloco_tpu.serve.engine import ServeEngine
        from opendiloco_tpu.serve.kvcache import HostKVTier
        from opendiloco_tpu.serve.scheduler import ContinuousBatcher
        from opendiloco_tpu.serve.server import ServeServer, bind_with_fallback

        self.replica_id = str(replica_id)
        self.max_stale_rounds = int(max_stale_rounds)
        self.trainer_epoch = 0
        self._lock = threading.Lock()
        # mailbox: last fully-applied push (flat f32 leaves). The engine
        # pulls it between decode steps; weights stay random until the
        # first keyframe lands (ready() gates the router/bench on that).
        self._leaves: Optional[list] = None
        self._epoch = -1
        params = init_params(jax.random.PRNGKey(seed), model_cfg)
        self._shapes = [tuple(x.shape) for x in jax.tree.leaves(params)]
        self.engine = ServeEngine(
            model_cfg,
            params,
            num_slots=num_slots,
            max_context=max_context,
            prefill_buckets=prefill_buckets,
            compute_dtype=compute_dtype or jnp.float32,
            epoch=-1,
            snapshot_fn=self._pull,
            epoch_fn=lambda: self._epoch,
            max_stale_rounds=0,  # adopt every fully-applied push eagerly
        )
        self.batcher = ContinuousBatcher(
            engine=self.engine,
            max_queue=max_queue,
            prefix_cache=prefix_cache,
            kv_tier=(
                HostKVTier(
                    host_slots=int(kv_host_slots), codec=str(kv_tier_codec)
                )
                if kv_tier
                else None
            ),
        ).start()
        # explicit ports mean a respawn at a known address: retry the
        # bind while the dying predecessor's listener tears down instead
        # of falling back to an ephemeral port nobody dials
        bind_retry_s = 3.0 if serve_port else 0.0
        self.server = ServeServer(
            self.batcher,
            host=host,
            port=serve_port,
            identity=self._identity,
            bind_retry_s=bind_retry_s,
        )
        tr = obs.tracer()
        if tr is not None:
            tr.set_identity(worker=self.replica_id, role="fleet-replica")
        rt = obs.reqtrace.ring()
        if rt is not None:
            rt.set_identity(self.replica_id)
        self._stop = threading.Event()
        self._push_sock: Optional[socket.socket] = None
        self.push_port = 0
        if start_push_server:
            self._push_sock = bind_with_fallback(
                host, push_port, "fleet-push",
                retry_s=3.0 if push_port else 0.0,
            )
            self._push_sock.listen(8)
            self.push_port = self._push_sock.getsockname()[1]
            threading.Thread(
                target=self._push_accept,
                name=f"odtp-fleet-push-{self.replica_id}",
                daemon=True,
            ).start()

    # -- weight state --------------------------------------------------------

    def _pull(self) -> tuple[int, list, str]:
        """Engine snapshot_fn: the mailbox as raw-f32 install_wire blobs.
        Copies under the lock so a concurrent push never mutates bytes
        mid-install."""
        with self._lock:
            if self._leaves is None:
                return self._epoch, [], "none"
            blobs = [
                (lf.tobytes(), {}, shape)
                for lf, shape in zip(self._leaves, self._shapes)
            ]
            return self._epoch, blobs, "none"

    def apply(self, meta: dict, payload: bytes) -> int:
        """Apply one weight/ping frame; returns the mailbox epoch."""
        kind = meta.get("kind")
        with self._lock:
            if kind == "ping":
                self.trainer_epoch = max(
                    self.trainer_epoch, int(meta.get("tepoch", 0))
                )
                return self._epoch
            if kind == "delta" and int(meta["base_epoch"]) != self._epoch:
                raise FleetFrameError(
                    f"delta base epoch {meta['base_epoch']} != replica "
                    f"epoch {self._epoch} (need a keyframe)"
                )
            leaves, epoch = apply_frame(self._leaves, meta, payload)
            self._leaves = leaves
            # every frame is self-contained (a keyframe, or one staggered
            # fragment's whole delta), so the mailbox epoch advances per
            # frame and the engine never sees a half-applied push
            self._epoch = epoch
            self.trainer_epoch = max(
                self.trainer_epoch, int(meta.get("tepoch", epoch))
            )
            obs.count("fleet_frames_applied", kind=kind)
            return self._epoch

    # -- health --------------------------------------------------------------

    def ready(self) -> bool:
        return self.engine.weights_epoch >= 0

    def staleness(self) -> int:
        """Outer rounds the SERVING weights lag the trainer (pings keep
        the trainer epoch moving even when weight pushes stall)."""
        return max(0, self.trainer_epoch - self.engine.weights_epoch)

    def stale(self) -> bool:
        return self.staleness() > self.max_stale_rounds

    def _identity(self) -> dict:
        return {
            "worker": self.replica_id,
            "replica": self.replica_id,
            "trainer_epoch": self.trainer_epoch,
            "staleness": self.staleness(),
            "max_stale_rounds": self.max_stale_rounds,
            "ready": self.ready(),
            "stale": self.stale(),
        }

    def status(self) -> dict:
        return {
            **self._identity(),
            "weights_epoch": self.engine.weights_epoch,
            "mailbox_epoch": self._epoch,
            "serve_port": self.server.port,
            "push_port": self.push_port,
            "free_slots": self.batcher.slots.num_free,
            "completed": self.batcher.completed,
        }

    def health(self) -> dict:
        """Load/health vector the autoscaler steers on (queue depth,
        occupancy, p99, staleness). Rides every push-channel reply, so
        the manager's view refreshes at the push cadence even when the
        obs plane is unarmed."""
        out = {
            **self.batcher.health(),
            "staleness": self.staleness(),
            "stale": self.stale(),
            "ready": self.ready(),
        }
        # prefix-cache directory advertisement: host-tier resident prefix
        # hashes at the current weights epoch. A NEW dict key on the
        # health frame — old routers/managers ignore unknown keys, so
        # mixed fleets interoperate (pinned by tests/test_fleet interop)
        prefixes = self.batcher.resident_prefixes()
        if prefixes:
            out["prefixes"] = prefixes
        return out

    def rollup(self) -> Optional[dict]:
        """Overseer health vector for this replica (None when obs is
        unarmed) — the manager merges it into the trainer's matrix."""
        ov = obs.overseer.plane()
        if ov is None:
            return None
        h = self.batcher.health()
        return ov.rollup(
            role="fleet-replica",
            replica=self.replica_id,
            staleness=self.staleness(),
            weights_epoch=self.engine.weights_epoch,
            stale=self.stale(),
            queue_depth=h["queue_depth"],
            occupancy=h["occupancy"],
            p99_ms=h["p99_ms"],
            # cold-tier load (absent when the tier is off): odtp_top's
            # tier% column keys on this
            **(
                {"tier_occupancy": h["tier_occupancy"]}
                if "tier_occupancy" in h
                else {}
            ),
        )

    # -- push channel --------------------------------------------------------

    def _push_accept(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._push_sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._push_serve, args=(conn,), daemon=True
            ).start()

    def _push_serve(self, conn: socket.socket) -> None:
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            while not self._stop.is_set():
                try:
                    kind, meta, payload = recv_frame(conn)
                except (FleetWireError, OSError, ValueError):
                    return
                try:
                    if kind == "hello":
                        reply = {
                            "replica": self.replica_id,
                            "epoch": self._epoch,
                            "weights_epoch": self.engine.weights_epoch,
                        }
                    elif kind == "reqtrace":
                        # request-trace pull: snapshot of this replica's
                        # ring (odtp_top --requests, obs_report merge).
                        # Empty when the plane is unarmed; old peers that
                        # predate the frame kind answer "error", which
                        # callers treat as "no reqtrace plane".
                        rt = obs.reqtrace.ring()
                        reply = {
                            "replica": self.replica_id,
                            "reqtrace": (
                                rt.snapshot(
                                    recent=int(meta.get("recent", 32))
                                )
                                if rt is not None
                                else None
                            ),
                        }
                    else:
                        epoch = self.apply(meta, payload)
                        reply = {
                            "replica": self.replica_id,
                            "epoch": epoch,
                            "weights_epoch": self.engine.weights_epoch,
                            "staleness": self.staleness(),
                            "stale": self.stale(),
                            "ready": self.ready(),
                            "free_slots": self.batcher.slots.num_free,
                            "health": self.health(),
                        }
                        vec = self.rollup()
                        if vec is not None:
                            reply["rollup"] = vec
                    send_frame(conn, "ok", reply)
                except FleetFrameError as e:
                    send_frame(conn, "error", {"error": str(e)})
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def stop(self) -> None:
        self._stop.set()
        if self._push_sock is not None:
            try:
                self._push_sock.close()
            except OSError:
                pass
        self.server.stop()
        self.batcher.stop()


# -- subprocess entry ---------------------------------------------------------


def main(argv: Optional[list] = None) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--spec", required=True, help="JSON replica spec file")
    args = ap.parse_args(argv)
    with open(args.spec) as f:
        spec = json.load(f)

    from opendiloco_tpu.models.llama import LlamaConfig

    model_cfg = LlamaConfig.from_dict(spec["model"])
    serve = spec.get("serve", {})
    replica = Replica(
        spec["replica_id"],
        model_cfg,
        num_slots=int(serve.get("num_slots", 4)),
        max_context=int(serve.get("max_context", 128)),
        prefill_buckets=tuple(serve.get("prefill_buckets", (16, 64))),
        max_queue=int(serve.get("max_queue", 1024)),
        prefix_cache=bool(serve.get("prefix_cache", True)),
        kv_tier=bool(serve.get("kv_tier", False)),
        kv_tier_codec=str(serve.get("kv_tier_codec", "none")),
        kv_host_slots=int(serve.get("kv_host_slots", 32)),
        max_stale_rounds=int(spec.get("max_stale_rounds", 2)),
        host=spec.get("host", "127.0.0.1"),
        serve_port=int(spec.get("serve_port", 0)),
        push_port=int(spec.get("push_port", 0)),
        seed=int(spec.get("seed", 0)),
    )
    print(
        json.dumps(
            {
                "replica_id": replica.replica_id,
                "serve_port": replica.server.port,
                "push_port": replica.push_port,
                "pid": os.getpid(),
            }
        ),
        flush=True,
    )
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    replica.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
