"""OpenDiLoCo-TPU: a TPU-native framework for globally distributed
low-communication (DiLoCo) training.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of
PrimeIntellect-ai/OpenDiloco (reference: /root/reference, surveyed in
SURVEY.md). The inner per-worker training loop is a single jit-compiled
function over a sharded pytree on a TPU mesh; the DiLoCo outer loop runs
host-side over a pluggable DCN communication backend.

Layout:
    config     -- pydantic config tree + dotted-flag CLI parsing
    models/    -- functional Llama (scan-over-layers), HF safetensors IO
    ops/       -- attention kernels (XLA SDPA, Pallas flash, ring attention)
    parallel/  -- device mesh + sharding strategies (DDP/ZeRO/hybrid)
    diloco/    -- DiLoCo optimizer, averagers, progress tracker, backends
    data/      -- streaming/fake datasets with resumable state
    utils/     -- logging, metrics probes, misc
"""

__version__ = "0.1.0"

# Arm the runtime lock-order witness before any package lock is created.
# ODTP_LOCKCHECK unset (the default) makes this a single dict lookup.
from opendiloco_tpu.analysis import lockcheck as _lockcheck

_lockcheck.maybe_install()
