"""Metric loggers behind a small protocol.

Reference parity: open_diloco/utils.py:170-204 -- a ``Logger`` protocol with a
wandb backend and a pickle-based ``DummyLogger`` used as a metrics spy by the
integration tests (tests/test_training/test_train.py:59-83).
"""

from __future__ import annotations

import logging
import os
import pickle
from typing import Any, Protocol


class Logger(Protocol):
    def log(self, metrics: dict[str, Any]) -> None: ...

    def finish(self) -> None: ...


class WandbLogger:
    def __init__(self, project: str, config: dict[str, Any], resume: bool):
        import wandb

        wandb.init(
            project=project, config=config, resume="auto" if resume else None
        )
        self._wandb = wandb

    def log(self, metrics: dict[str, Any]) -> None:
        self._wandb.log(metrics)

    def finish(self) -> None:
        self._wandb.finish()


class DummyLogger:
    """Accumulates metric dicts and pickles them to ``project`` on finish()."""

    def __init__(self, project: str, config: dict[str, Any], *_args, **_kwargs):
        self.project = project
        self.config = config
        open(project, "wb").close()  # fail fast on unwritable path
        self.data: list[dict[str, Any]] = []

    def log(self, metrics: dict[str, Any]) -> None:
        self.data.append(metrics)

    def finish(self) -> None:
        with open(self.project, "wb") as f:
            pickle.dump(self.data, f)


def get_logger(
    logger_type: str, project: str, config: dict[str, Any], resume: bool = False
) -> Logger:
    if logger_type == "wandb":
        return WandbLogger(project=project, config=config, resume=resume)
    elif logger_type == "dummy":
        return DummyLogger(project=project, config=config)
    raise ValueError(f"unknown metric_logger_type {logger_type!r}")


_LOG_FORMAT = "%(asctime)s [%(levelname)s] [%(name)s] %(message)s"


def get_text_logger(name: str = "opendiloco_tpu") -> logging.Logger:
    """Rank-prefixed text logger (reference: train_fsdp.py:75-76)."""
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler()
        rank = os.environ.get("DILOCO_WORLD_RANK", "0")
        handler.setFormatter(logging.Formatter(f"[rank {rank}] {_LOG_FORMAT}"))
        logger.addHandler(handler)
        logger.setLevel(os.environ.get("OPENDILOCO_TPU_LOG_LEVEL", "INFO"))
        logger.propagate = False
    return logger
