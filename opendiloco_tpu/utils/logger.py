"""Metric loggers behind a small protocol.

Reference parity: open_diloco/utils.py:170-204 -- a ``Logger`` protocol with a
wandb backend and a pickle-based ``DummyLogger`` used as a metrics spy by the
integration tests (tests/test_training/test_train.py:59-83).

Every logger routes rows through :func:`normalize_row` so the on-disk schema
is flat JSON-typed scalars regardless of which backend produced the row
(numpy scalars and 0-d arrays are coerced, nested dicts are flattened with
``/`` separators, non-scalar leaves are stringified).
"""

from __future__ import annotations

import json
import logging
import os
import pickle
from typing import Any, Protocol


def normalize_row(metrics: dict[str, Any]) -> dict[str, Any]:
    """Coerce a metrics row to a flat dict of JSON-typed scalars.

    Shared by every logger backend so DummyLogger pickles, JSONL lines and
    wandb rows all carry the same schema: numpy scalars / 0-d arrays become
    python floats, bools and ints pass through, nested dicts flatten to
    ``outer/inner`` keys, and anything else is stringified.
    """
    out: dict[str, Any] = {}

    def put(key: str, value: Any) -> None:
        if isinstance(value, dict):
            for k, v in value.items():
                put(f"{key}/{k}", v)
            return
        if isinstance(value, bool) or value is None or isinstance(value, str):
            out[key] = value
            return
        if isinstance(value, int):
            out[key] = value
            return
        if isinstance(value, float):
            out[key] = value
            return
        # numpy scalars, 0-d arrays, jax scalars: anything float()-able
        try:
            out[key] = float(value)
            return
        except Exception:
            out[key] = str(value)

    for k, v in metrics.items():
        put(str(k), v)
    return out


class Logger(Protocol):
    def log(self, metrics: dict[str, Any]) -> None: ...

    def finish(self) -> None: ...


class WandbLogger:
    def __init__(self, project: str, config: dict[str, Any], resume: bool):
        import wandb

        wandb.init(
            project=project, config=config, resume="auto" if resume else None
        )
        self._wandb = wandb

    def log(self, metrics: dict[str, Any]) -> None:
        self._wandb.log(normalize_row(metrics))

    def finish(self) -> None:
        self._wandb.finish()


class DummyLogger:
    """Accumulates metric dicts and pickles them to ``project`` on finish().

    finish() is atomic (tmp file + ``os.replace``) so a SIGKILL mid-write --
    routine under the chaos plane's kill_worker fault -- can never leave a
    truncated pickle where the metrics spy expects a valid one.
    """

    def __init__(self, project: str, config: dict[str, Any], *_args, **_kwargs):
        self.project = project
        self.config = config
        open(project, "wb").close()  # fail fast on unwritable path
        self.data: list[dict[str, Any]] = []

    def log(self, metrics: dict[str, Any]) -> None:
        self.data.append(normalize_row(metrics))

    def finish(self) -> None:
        tmp = f"{self.project}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            pickle.dump(self.data, f)
        os.replace(tmp, self.project)


class JsonlLogger:
    """One JSON object per line, appended as rows arrive.

    Crash-tolerant by construction: every row is flushed on write, so a
    killed worker loses at most the final partial line (which readers skip).
    Selected with ``metric_logger_type="jsonl"``.
    """

    def __init__(self, project: str, config: dict[str, Any], *_args, **_kwargs):
        self.project = project
        self.config = config
        self._f = open(project, "a")

    def log(self, metrics: dict[str, Any]) -> None:
        self._f.write(json.dumps(normalize_row(metrics)) + "\n")
        self._f.flush()

    def finish(self) -> None:
        if not self._f.closed:
            self._f.close()


def read_jsonl(path: str) -> list[dict[str, Any]]:
    """Load a JsonlLogger file, skipping any trailing partial line."""
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return rows


def get_logger(
    logger_type: str, project: str, config: dict[str, Any], resume: bool = False
) -> Logger:
    if logger_type == "wandb":
        return WandbLogger(project=project, config=config, resume=resume)
    elif logger_type == "dummy":
        return DummyLogger(project=project, config=config)
    elif logger_type == "jsonl":
        return JsonlLogger(project=project, config=config)
    raise ValueError(f"unknown metric_logger_type {logger_type!r}")


_LOG_FORMAT = "%(asctime)s [%(levelname)s] [%(name)s] %(message)s"


def get_text_logger(name: str = "opendiloco_tpu") -> logging.Logger:
    """Rank-prefixed text logger (reference: train_fsdp.py:75-76)."""
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler()
        rank = os.environ.get("DILOCO_WORLD_RANK", "0")
        handler.setFormatter(logging.Formatter(f"[rank {rank}] {_LOG_FORMAT}"))
        logger.addHandler(handler)
        logger.setLevel(os.environ.get("OPENDILOCO_TPU_LOG_LEVEL", "INFO"))
        logger.propagate = False
    return logger
