"""Debugging utilities: tensor-content hashing for desync hunts.

Parity: the reference ships ``hash_tensor_content`` (open_diloco/utils.py:70-80)
to compare parameter state across workers when chasing divergence, plus a
schema-hash assertion that the optimizer's parameter layout didn't change
mid-epoch (hivemind_diloco.py:560-568).
"""

from __future__ import annotations

import hashlib
from typing import Any

import jax
import numpy as np


def hash_array(x) -> str:
    arr = np.ascontiguousarray(jax.device_get(x))
    h = hashlib.sha256()
    h.update(str(arr.dtype).encode())
    h.update(str(arr.shape).encode())
    h.update(arr.tobytes())
    return h.hexdigest()[:16]


def hash_pytree(tree: Any) -> str:
    """Content hash of an entire pytree: equal across workers iff every leaf
    (values, shapes, dtypes) and the tree structure are equal."""
    leaves, treedef = jax.tree.flatten(tree)
    h = hashlib.sha256()
    h.update(str(treedef).encode())
    for leaf in leaves:
        h.update(hash_array(leaf).encode())
    return h.hexdigest()[:16]


def schema_fingerprint(tree: Any) -> str:
    """Hash of shapes/dtypes/structure only (no values): cheap invariant for
    asserting the parameter layout is stable across an epoch."""
    leaves, treedef = jax.tree.flatten(tree)
    h = hashlib.sha256()
    h.update(str(treedef).encode())
    for leaf in leaves:
        h.update(f"{getattr(leaf, 'shape', ())}/{getattr(leaf, 'dtype', '?')}".encode())
    return h.hexdigest()[:16]
