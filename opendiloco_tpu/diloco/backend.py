"""Outer-loop communication backend interface.

This is the seam the reference keeps between ``DiLoCoOptimizer`` and the
hivemind averagers (hivemind_diloco.py:446-462): everything the outer loop
needs from the network, behind one interface, so the algorithm is testable
with an in-process backend (tests) and deployable over DCN (tcp backend).

Semantics carried over from the reference:
- ``all_reduce`` averages pseudo-gradient pytrees across whoever is in the
  group this round (elastic group size, like hivemind matchmaking).
- ``report_progress`` / ``peer_progress`` replace the DHT progress gossip
  (DiloCoProgressTracker, hivemind_diloco.py:174-282).
- ``fetch_state`` / ``serve_state`` replace ``load_state_from_peers``
  onboarding (train_fsdp.py:348-349, hivemind_diloco.py:528-531).
"""

from __future__ import annotations

import abc
import dataclasses
import time
from typing import Any, Callable, Optional

import numpy as np


@dataclasses.dataclass
class PeerProgress:
    peer_id: str
    epoch: int  # outer-step count
    samples: int  # samples accumulated inside the current inner phase
    samples_per_second: float
    timestamp: float

    def eta_to_epoch_end(self, target_samples: int) -> float:
        if self.samples_per_second <= 0:
            return float("inf")
        remaining = max(0, target_samples - self.samples)
        return remaining / self.samples_per_second


class AllReduceError(RuntimeError):
    pass


class OuterBackend(abc.ABC):
    """Host-side collective fabric between DiLoCo workers."""

    @property
    @abc.abstractmethod
    def peer_id(self) -> str: ...

    @abc.abstractmethod
    def num_peers(self) -> int:
        """Currently-known live peer count (including self)."""

    @abc.abstractmethod
    def all_reduce(
        self,
        arrays: list[np.ndarray],
        *,
        timeout: Optional[float] = None,
        tag: str = "grads",
        epoch: Optional[int] = None,
        group_cap: int = 0,
    ) -> tuple[list[np.ndarray], int]:
        """Average the arrays across the group; returns (averaged, group_size).

        Blocks until the group round completes; raises AllReduceError on
        timeout/failure. ``tag`` namespaces concurrent round types (gradient
        vs state averaging). ``epoch`` pins the round key explicitly (pass it
        when calling from a background thread -- reading the gossiped own
        progress there races with the training thread advancing it).
        ``group_cap`` > 0 partitions joiners into groups of at most that
        size (gossip mode). Wire compression is a backend concern.
        """

    @abc.abstractmethod
    def report_progress(self, progress: PeerProgress) -> None: ...

    @abc.abstractmethod
    def peer_progress(self) -> list[PeerProgress]:
        """Latest known progress of all peers (including self)."""

    def fetch_state(self) -> Optional[dict[str, Any]]:
        """Download current training state from an up-to-date peer
        (late-joiner onboarding). None if no peer can serve."""
        return None

    def serve_state(self, get_state: Callable[[], dict[str, Any]]) -> None:
        """Register a callback that provides state to late joiners."""

    def gossip_view(self) -> tuple[list[str], Optional[dict]]:
        """(sorted live member ids, link matrix or None) — the local view
        the gossip pair scheduler derives pairings from. Default: whoever
        has gossiped progress recently (no barrier, no extra messages)."""
        members = {p.peer_id for p in self.peer_progress()}
        members.add(self.peer_id)
        return sorted(members), None

    def pair_exchange(
        self,
        payload: bytes,
        meta: dict,
        *,
        partner_id: str,
        round_key: str,
        timeout: Optional[float] = None,
    ) -> tuple[dict, bytes]:
        """One symmetric push-pull with ``partner_id`` under ``round_key``:
        deposit own (meta, payload), return the partner's. Raises
        AllReduceError on partner death / timeout (the gossip plane treats
        that as a dropped round, a non-event)."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support gossip pair exchange"
        )

    def async_pair_match(
        self,
        *,
        frag_id: int,
        epoch: int,
        window: int,
        patience: Optional[float] = None,
    ) -> Optional[tuple[str, int, str]]:
        """Bounded-staleness matchmaking for free-running async gossip:
        find ANY available partner working fragment ``frag_id`` whose
        outer epoch is within ``window`` of ``epoch`` — no round
        alignment. Returns ``(partner_id, partner_epoch, match_key)``
        with both sides handed the SAME fresh ``match_key`` (the
        subsequent ``pair_exchange`` rides it), or None when no
        compatible partner turned up within ``patience`` seconds (the
        caller steps alone — a fast worker never blocks on a slow one).
        Default: async matching unsupported; callers fall back to the
        lockstep epoch-keyed pairing."""
        return None

    def barrier(self, *, timeout: Optional[float] = None) -> None:
        """Optional synchronization point (used by tests)."""

    def close(self) -> None: ...


def wait_for_peers(
    backend: OuterBackend,
    *,
    target_samples: int,
    own_epoch: int,
    strategy: str,
    timeout_waiting_for_peers: float,
    poll: float = 0.1,
    log=None,
) -> None:
    """WAIT_FOR_ALL straggler policy (reference: hivemind_diloco.py:579-608):
    poll peer progress until everyone is near the epoch boundary, or give up
    after ``timeout_waiting_for_peers`` and proceed without the stragglers.

    NO_WAIT returns immediately (fastest peer triggers the round).
    """
    if strategy == "no_wait":
        return
    deadline = time.monotonic() + timeout_waiting_for_peers
    first = True
    while time.monotonic() < deadline:
        others = [p for p in backend.peer_progress() if p.peer_id != backend.peer_id]
        if not others:
            if log is not None:
                log.debug("wait_for_peers: no other peers known; proceeding")
            return
        behind = [
            p
            for p in others
            # Peers >=2 epochs behind are NOT worth waiting for: they will
            # discard their stale phase and desync-onboard at their next
            # epoch start (optimizer._desynced, mirroring the reference's
            # hivemind_diloco.py:528-531 threshold), so stalling the round
            # on them buys nothing. Without this, a fresh joiner's
            # join-time announce (epoch 0, sps 0 -> eta inf) would stall
            # every established peer's boundary for the full
            # timeout_waiting_for_peers while the joiner sits in its first
            # cold compile.
            if own_epoch - p.epoch < 2
            and (
                p.epoch < own_epoch
                or (p.epoch == own_epoch and p.samples < target_samples)
            )
        ]
        if not behind:
            return
        if first and log is not None:
            log.debug(
                "wait_for_peers: %d peers behind: %s",
                len(behind),
                [(p.peer_id, p.epoch, p.samples) for p in behind],
            )
            first = False
        # everyone close enough (< poll horizon) also counts as ready
        etas = [p.eta_to_epoch_end(target_samples) for p in behind]
        if max(etas) <= poll:
            return
        time.sleep(min(poll, max(min(etas), 0.01)))
    if log is not None:
        log.warning(
            "timed out waiting %.0fs for slow peers; proceeding without them",
            timeout_waiting_for_peers,
        )
