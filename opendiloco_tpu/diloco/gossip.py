"""Barrier-free NoLoCo gossip outer plane (arXiv 2506.10911).

Replaces the global outer collective with one pairwise exchange per
(epoch, fragment): every worker derives the SAME pairing locally from a
shared epoch-keyed PRNG over the sorted live-membership view — no
rendezvous round, no barrier, no matchmaking messages. Two paired
workers push their (master, momentum, pseudo-grad) fragment to each
other on the existing bulk/wire stack and mix; the NoLoCo
modified-Nesterov correction is then a plain Nesterov step on the MIXED
state with the pair-averaged pseudo-gradient (outer_optimizer.noloco_step),
so per-round cost is flat in galaxy size.

Agreement without messaging:

  pair_schedule(sorted(members), key)   key = f"f{frag}-e{epoch}"

seeds ``random.Random`` with a string (hashed via sha512, stable across
processes and runs), so every worker holding the same membership view
computes the identical pairing. Views CAN diverge transiently under
churn — the two sides of a mismatched pair then wait on different round
keys, time out, and drop the round: a non-event by design (residual
retained, params keep local progress, next epoch re-pairs).

Link-aware sampling: published link vectors (linkstate gossip) bias the
partner draw toward fast pairs. Capacities are bucketed to powers of two
before weighting so transient EWMA wiggle cannot de-synchronize two
workers' schedules, and a weight floor guarantees slow pairs are sampled
forever (never starved — NoLoCo's mixing proof needs connectivity).

Odd galaxy: exactly one worker self-pairs per round. Policy "nesterov"
(default) runs the outer step on its own state (plain DiLoCo step, no
wire); "hold" skips the round entirely (master frozen, pg re-captured
next epoch).

Fully asynchronous rounds (``ODTP_ASYNC_STALENESS`` > 0): the epoch-
keyed pairing above still rate-limits a fast worker to whoever it draws
— both sides must reach the SAME (epoch, fragment) before either's
round completes. The async mode drops the shared key entirely: every
worker free-runs its inner loop and, at each of its own epoch
boundaries, asks the backend for ANY available partner on the same
fragment whose epoch is within the staleness window
(``backend.async_pair_match``; availability is discovered through the
progress/overseer gossip that already carries per-worker epochs). The
matched pair swaps fragments under a fresh match key on the unchanged
``pair_exchange`` wire, then mixes with a staleness-discounted weight
(``outer_optimizer.staleness_weight`` — bit-identical to the lockstep
pair average at distance 0). No in-window partner inside
``ODTP_ASYNC_PATIENCE_S`` means a self-round per the policy above, so a
fast worker pays at most patience per round while a 4x-slower worker
keeps contributing whenever it surfaces — aggregate throughput tracks
the SUM of per-worker rates instead of N times the slowest (banked in
ASYNC_BENCH.json).

Compression composes: masters/momentum ride the state codec (fp16
family), pseudo-grads ride the configured codec (blockwise4bit / topk /
...), with per-PARTNER error-feedback residuals — each pair link keeps
its own EF ledger, so the mass a lossy codec drops toward partner A is
replayed the next time A is drawn, not leaked into rounds with B.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import math
import os
import random
import threading
import time
from typing import Any, Optional

import numpy as np

from opendiloco_tpu import obs
from opendiloco_tpu.diloco.backend import AllReduceError
from opendiloco_tpu.diloco.compression import get_codec, record_wire
from opendiloco_tpu.diloco.error_feedback import ErrorFeedback
from opendiloco_tpu.diloco.outer_optimizer import (
    staleness_mix,
    staleness_weight,
)

log = logging.getLogger(__name__)

_HEALTH_LEDGER_CAP = 256


# -- knobs ---------------------------------------------------------------------


def gossip_seed() -> int:
    """ODTP_GOSSIP_SEED: shared pairing-PRNG seed (must match galaxy-wide)."""
    return int(os.environ.get("ODTP_GOSSIP_SEED", "0") or 0)


def link_bias() -> float:
    """ODTP_GOSSIP_LINK_BIAS: exponent on the normalized pair capacity when
    drawing partners (0 disables link awareness; higher prefers fast pairs
    harder)."""
    return float(os.environ.get("ODTP_GOSSIP_LINK_BIAS", "1.0") or 1.0)


def link_floor() -> float:
    """ODTP_GOSSIP_LINK_FLOOR: minimum relative draw weight for the slowest
    pair — keeps every pair reachable (never starved) under any bias."""
    return float(os.environ.get("ODTP_GOSSIP_LINK_FLOOR", "0.25") or 0.25)


def self_round_policy() -> str:
    """ODTP_GOSSIP_SELF_ROUND: odd-worker self-pair policy — "nesterov"
    steps on own state (default), "hold" skips the round."""
    return os.environ.get("ODTP_GOSSIP_SELF_ROUND", "nesterov") or "nesterov"


def async_staleness() -> int:
    """ODTP_ASYNC_STALENESS: bounded-staleness window, in outer epochs,
    for free-running async gossip — a worker finishing its inner phase
    mixes with ANY available partner whose epoch is within this distance,
    no round alignment. 0 (default) keeps the lockstep per-(epoch,
    fragment) pairing."""
    return int(os.environ.get("ODTP_ASYNC_STALENESS", "0") or 0)


def async_decay() -> float:
    """ODTP_ASYNC_DECAY: geometric discount on a staler partner's mixing
    weight per epoch of distance (weight = 0.5 * decay**d — exactly the
    symmetric pair average at distance 0)."""
    return float(os.environ.get("ODTP_ASYNC_DECAY", "0.5") or 0.5)


def async_patience_s() -> float:
    """ODTP_ASYNC_PATIENCE_S: how long a worker waits for ANY in-window
    partner before stepping alone (per the self-round policy). This bound
    is what kills the epoch lockstep: a fast worker pays at most patience
    per round, never a slow partner's full inner phase."""
    return float(os.environ.get("ODTP_ASYNC_PATIENCE_S", "2.0") or 2.0)


# -- pair scheduling -----------------------------------------------------------


def _pair_key(a: str, b: str) -> tuple[str, str]:
    return (a, b) if a <= b else (b, a)


def pair_schedule(
    members,
    key: str,
    *,
    weights: Optional[dict] = None,
    seed: int = 0,
) -> dict[str, str]:
    """Deterministic pairing of ``members`` for round ``key``.

    Returns a symmetric map id -> partner covering every member; with odd
    N exactly one member maps to itself. Every process computing this
    over the same member set gets the identical map: the PRNG is seeded
    with a string (hashed, process-stable) and the pool is sorted, so
    draw order is fixed. ``weights`` (optional) maps _pair_key(a, b) ->
    relative draw weight.
    """
    pool = sorted(set(members))
    rng = random.Random(f"odtp-gossip:{int(seed)}:{key}")
    pairs: dict[str, str] = {}
    while pool:
        a = pool.pop(0)
        if not pool:
            pairs[a] = a  # odd leftover: self-round
            break
        if weights:
            w = [
                max(float(weights.get(_pair_key(a, x), 1.0)), 1e-9)
                for x in pool
            ]
            b = rng.choices(pool, weights=w)[0]
        else:
            b = pool[rng.randrange(len(pool))]
        pool.remove(b)
        pairs[a] = b
        pairs[b] = a
    return pairs


def link_pair_weights(
    matrix: Optional[dict], members
) -> Optional[dict[tuple[str, str], float]]:
    """Pair draw weights from the gossiped link matrix.

    Published bps are bucketed to powers of two BEFORE weighting: the
    schedule must be identical on every worker, and bucketing makes the
    weight a step function of capacity, immune to the EWMA's last digit
    differing between two workers' snapshots. Unknown links weigh
    neutral (1.0 = fastest bucket): never punish what we haven't
    measured. Weight = max(floor, (bucket / max_bucket) ** bias).
    """
    bias = link_bias()
    if not matrix or bias <= 0:
        return None
    floor = max(0.0, min(1.0, link_floor()))
    ids = sorted(set(members))
    buckets: dict[tuple[str, str], Optional[int]] = {}
    top = 0
    for i, a in enumerate(ids):
        for b in ids[i + 1:]:
            bps = pair_bps(matrix, a, b)
            if bps and bps > 0:
                bk = 1 << max(0, int(math.log2(bps)))
                buckets[(a, b)] = bk
                top = max(top, bk)
            else:
                buckets[(a, b)] = None
    if top <= 0:
        return None
    return {
        k: 1.0 if bk is None else max(floor, (bk / top) ** bias)
        for k, bk in buckets.items()
    }


def pair_bps(matrix: dict, a: str, b: str) -> Optional[float]:
    """Symmetric pair capacity from a matrix-shaped link view
    ({pid: {"v", "peers": {pid: {"bps", ...}}}}): the max of whichever
    directional estimates have been published (either side's egress
    measurement is evidence about the path)."""
    vals = []
    for x, y in ((a, b), (b, a)):
        vec = matrix.get(x)
        if not isinstance(vec, dict):
            continue
        ent = (vec.get("peers") or {}).get(y)
        if isinstance(ent, dict):
            bps = ent.get("bps")
            if bps:
                vals.append(float(bps))
    return max(vals) if vals else None


# -- wire sections -------------------------------------------------------------


def _encode_leaves(codec, arrays) -> tuple[list[bytes], list[dict], int]:
    chunks: list[bytes] = []
    metas: list[dict] = []
    raw = 0
    for a in arrays:
        flat = np.ascontiguousarray(np.asarray(a, np.float32).reshape(-1))
        payload, meta = codec.encode(flat)
        b = bytes(payload)
        chunks.append(b)
        metas.append({"shape": list(np.shape(a)), "meta": meta, "len": len(b)})
        raw += flat.nbytes
    return chunks, metas, raw


def _decode_section(codec, metas, payload, off: int) -> tuple[list[np.ndarray], int]:
    out: list[np.ndarray] = []
    for m in metas:
        n = int(m["len"])
        raw = payload[off:off + n]
        shape = tuple(int(s) for s in m["shape"])
        size = int(np.prod(shape)) if shape else 1
        a = np.asarray(
            codec.decode(raw, (size,), m["meta"]), np.float32
        ).reshape(shape)
        out.append(np.array(a, np.float32))  # owned + writeable
        off += n
    return out, off


def _avg_sorted(first, second) -> list[np.ndarray]:
    # both sides add in the SAME (sorted-pair) operand order, so the mixed
    # state is bit-identical on both ends — paired masters never drift
    return [(x + y) * np.float32(0.5) for x, y in zip(first, second)]


# -- the plane -----------------------------------------------------------------


class GossipPlane:
    """Per-worker gossip state: pair scheduling inputs, per-partner error
    feedback, wire encode/decode, and round-health accounting. One
    instance per DiLoCoOptimizer; ``exchange`` is thread-safe (streaming
    calls it from per-fragment comm threads)."""

    def __init__(
        self,
        backend,
        n_leaves: int,
        *,
        compression: str = "none",
        error_feedback: bool = False,
    ):
        self.backend = backend
        self.n_leaves = int(n_leaves)
        self.codec = get_codec(compression)
        # masters/momentum are weights, not pseudo-grads: they ride the
        # state codec (fp16 family) like onboarding snapshots do
        from opendiloco_tpu.diloco.tcp import state_codec

        self.state_codec = state_codec(self.codec)
        self.error_feedback = bool(error_feedback)
        self.seed = gossip_seed()
        self.self_policy = self_round_policy()
        self._ef: dict[str, ErrorFeedback] = {}
        self._ef_lock = threading.Lock()

    # -- per-partner error feedback ----------------------------------------

    def _ef_for(self, partner: str) -> ErrorFeedback:
        with self._ef_lock:
            ef = self._ef.get(partner)
            if ef is None:
                ef = ErrorFeedback(self.codec, self.n_leaves)
                self._ef[partner] = ef
        return ef

    def abort_all(self) -> None:
        with self._ef_lock:
            efs = list(self._ef.values())
        for ef in efs:
            ef.abort_all()

    def host_state(self) -> Optional[dict]:
        """Checkpoint payload: partner id -> per-leaf residual list."""
        with self._ef_lock:
            items = list(self._ef.items())
        out = {}
        for pid, ef in items:
            res = ef.host_residuals()
            if res is not None:
                out[pid] = res
        return out or None

    def load(self, state: Optional[dict]) -> None:
        if not state:
            return
        for pid, res in state.items():
            self._ef_for(pid).load(res)

    def residual_mass(self) -> float:
        """Total |residual| mass across partners (soak conservation checks)."""
        total = 0.0
        with self._ef_lock:
            efs = list(self._ef.values())
        for ef in efs:
            for r in ef.residual:
                if r is not None:
                    total += float(np.abs(r, dtype=np.float64).sum())
        return total

    # -- scheduling --------------------------------------------------------

    def round_pairs(self, members, links, key: str) -> dict[str, str]:
        weights = link_pair_weights(links, members)
        return pair_schedule(members, key, weights=weights, seed=self.seed)

    # -- the round ---------------------------------------------------------

    def exchange(
        self,
        *,
        epoch: int,
        frag_id: int,
        idxs,
        masters: list[np.ndarray],
        bufs: Optional[list[np.ndarray]],
        pgs: list[np.ndarray],
        timeout: Optional[float] = None,
    ):
        """One pair round for fragment ``frag_id`` at outer ``epoch``.

        ``masters``/``bufs``/``pgs`` are the fragment's host f32 leaves
        (bufs None when momentum is off). Returns
        ``(mix_m, mix_b, avg_g, partner, n)`` — the pair-mixed master and
        momentum leaves plus pair-averaged pseudo-gradient, ready for
        ``outer_optimizer.noloco_step`` — or None when the round dropped
        (partner death / timeout / "hold" self-round): EF residual
        retained, nothing adopted, next epoch re-pairs.
        """
        t0 = time.perf_counter()
        window = async_staleness()
        if window > 0:
            return self._exchange_async(
                epoch=int(epoch), frag_id=int(frag_id), idxs=idxs,
                masters=masters, bufs=bufs, pgs=pgs, timeout=timeout,
                t0=t0, window=window,
            )
        key = f"f{int(frag_id)}-e{int(epoch)}"
        members, links = self.backend.gossip_view()
        own = self.backend.peer_id
        members = set(members)
        members.add(own)
        pairs = self.round_pairs(members, links, key)
        partner = pairs.get(own, own)

        if partner == own:
            if self.self_policy == "hold":
                self._record(key, partner=own, n=0, t0=t0, dropped=True)
                return None
            mix_m = [np.array(m, np.float32) for m in masters]
            mix_b = None if bufs is None else [
                np.array(b, np.float32) for b in bufs
            ]
            avg_g = [np.array(g, np.float32) for g in pgs]
            self._record(key, partner=own, n=1, t0=t0)
            return mix_m, mix_b, avg_g, own, 1

        lo, hi = sorted((own, partner))
        fp = hashlib.sha1(
            f"{key}|{lo}|{hi}|{self.seed}".encode()
        ).hexdigest()[:12]
        round_key = f"gossip-{key}:{fp}"
        ef = self._ef_for(partner) if self.error_feedback else None
        # EF folds the residual into the pg IN PLACE — work on owned copies
        gs = [np.array(np.asarray(g, np.float32)) for g in pgs]
        if ef is not None:
            ef.prepare(round_key, idxs, gs)
        try:
            (mine_m, mine_b, mine_g), (theirs_m, theirs_b, theirs_g), \
                wire, raw = self._transfer_and_decode(
                    partner=partner, round_key=round_key,
                    masters=masters, bufs=bufs, gs=gs, timeout=timeout,
                )
        except (AllReduceError, TimeoutError, asyncio.TimeoutError,
                OSError, KeyError, ValueError) as e:
            if ef is not None:
                ef.abort(round_key)
            log.warning(
                "gossip round dropped (frag %s epoch %s partner %s): %s",
                frag_id, epoch, partner, e,
            )
            self._record(key, partner=partner, n=0, t0=t0, dropped=True)
            return None

        if own == lo:
            mix_m = _avg_sorted(mine_m, theirs_m)
            mix_b = (
                None if mine_b is None or theirs_b is None
                else _avg_sorted(mine_b, theirs_b)
            )
            avg_g = _avg_sorted(mine_g, theirs_g)
        else:
            mix_m = _avg_sorted(theirs_m, mine_m)
            mix_b = (
                None if mine_b is None or theirs_b is None
                else _avg_sorted(theirs_b, mine_b)
            )
            avg_g = _avg_sorted(theirs_g, mine_g)
        if ef is not None:
            ef.commit(round_key)
        record_wire("gossip", raw, wire)
        self._record(key, partner=partner, n=2, t0=t0, wire=wire)
        return mix_m, mix_b, avg_g, partner, 2

    def _transfer_and_decode(
        self,
        *,
        partner: str,
        round_key: str,
        masters: list[np.ndarray],
        bufs: Optional[list[np.ndarray]],
        gs: list[np.ndarray],
        timeout: Optional[float],
    ):
        """Encode own (m, b, g) sections, swap frames with ``partner``
        under ``round_key``, decode BOTH sides through the codecs (own
        bytes roundtrip too, so paired mixes use identical operands).
        Returns ``((mine_m, mine_b, mine_g), (theirs_m, theirs_b,
        theirs_g), wire_bytes, raw_bytes)``; raises on transfer failure
        (caller aborts EF and drops the round)."""
        m_chunks, m_metas, raw_m = _encode_leaves(self.state_codec, masters)
        if bufs is not None:
            b_chunks, b_metas, raw_b = _encode_leaves(self.state_codec, bufs)
        else:
            b_chunks, b_metas, raw_b = [], None, 0
        g_chunks, g_metas, raw_g = _encode_leaves(self.codec, gs)
        payload = b"".join(m_chunks + b_chunks + g_chunks)
        meta = {
            "gossip": 1,
            "sections": {"m": m_metas, "b": b_metas, "g": g_metas},
            "codec": {
                "state": self.state_codec.name,
                "grad": self.codec.name,
            },
        }
        p_meta, p_payload = self.backend.pair_exchange(
            payload,
            meta,
            partner_id=partner,
            round_key=round_key,
            timeout=timeout,
        )
        mine_m, off = _decode_section(self.state_codec, m_metas, payload, 0)
        mine_b: Optional[list[np.ndarray]] = None
        if b_metas is not None:
            mine_b, off = _decode_section(
                self.state_codec, b_metas, payload, off
            )
        mine_g, _ = _decode_section(self.codec, g_metas, payload, off)

        p_sections = p_meta["sections"]
        p_state = get_codec(p_meta["codec"]["state"])
        p_grad = get_codec(p_meta["codec"]["grad"])
        theirs_m, poff = _decode_section(
            p_state, p_sections["m"], p_payload, 0
        )
        theirs_b: Optional[list[np.ndarray]] = None
        if p_sections.get("b") is not None:
            theirs_b, poff = _decode_section(
                p_state, p_sections["b"], p_payload, poff
            )
        theirs_g, _ = _decode_section(p_grad, p_sections["g"], p_payload, poff)
        if len(theirs_m) != len(mine_m) or len(theirs_g) != len(mine_g):
            raise AllReduceError(
                f"gossip section mismatch with {partner}: "
                f"{len(theirs_m)}/{len(theirs_g)} leaves vs "
                f"{len(mine_m)}/{len(mine_g)}"
            )
        return (
            (mine_m, mine_b, mine_g),
            (theirs_m, theirs_b, theirs_g),
            len(payload),
            raw_m + raw_b + raw_g,
        )

    def _exchange_async(
        self,
        *,
        epoch: int,
        frag_id: int,
        idxs,
        masters: list[np.ndarray],
        bufs: Optional[list[np.ndarray]],
        pgs: list[np.ndarray],
        timeout: Optional[float],
        t0: float,
        window: int,
    ):
        """One FREE-RUNNING pair round under the bounded-staleness window.

        No shared round key: the backend matches this worker with any
        partner on the same fragment within ``window`` epochs (or nobody,
        after patience — then the self-round policy applies and local
        progress continues). The matched transfer rides the ordinary
        ``pair_exchange`` under the match key both sides were handed, EF
        semantics unchanged: a missed or failed match is the dropped-
        round non-event with the residual retained exactly.
        """
        key = f"af{frag_id}-e{epoch}"
        own = self.backend.peer_id
        match = self.backend.async_pair_match(
            frag_id=frag_id, epoch=epoch, window=window,
            patience=async_patience_s(),
        )
        if match is None:
            # nobody compatible surfaced within patience: the free-running
            # analogue of the odd-galaxy self-round. Stepping alone here —
            # instead of parking on an epoch-aligned key — is the bound
            # that keeps fast workers off the slowest worker's clock.
            if self.self_policy == "hold":
                self._record(key, partner=own, n=0, t0=t0, dropped=True)
                return None
            mix_m = [np.array(m, np.float32) for m in masters]
            mix_b = None if bufs is None else [
                np.array(b, np.float32) for b in bufs
            ]
            avg_g = [np.array(g, np.float32) for g in pgs]
            self._record(key, partner=own, n=1, t0=t0)
            return mix_m, mix_b, avg_g, own, 1

        partner, p_epoch, round_key = match
        dist = abs(int(epoch) - int(p_epoch))
        ef = self._ef_for(partner) if self.error_feedback else None
        gs = [np.array(np.asarray(g, np.float32)) for g in pgs]
        if ef is not None:
            ef.prepare(round_key, idxs, gs)
        try:
            (mine_m, mine_b, mine_g), (theirs_m, theirs_b, theirs_g), \
                wire, raw = self._transfer_and_decode(
                    partner=partner, round_key=round_key,
                    masters=masters, bufs=bufs, gs=gs, timeout=timeout,
                )
        except (AllReduceError, TimeoutError, asyncio.TimeoutError,
                OSError, KeyError, ValueError) as e:
            if ef is not None:
                ef.abort(round_key)
            log.warning(
                "async gossip round dropped (frag %s epoch %s partner %s "
                "lag %s): %s", frag_id, epoch, partner, dist, e,
            )
            self._record(
                key, partner=partner, n=0, t0=t0, dropped=True, lag=dist
            )
            return None

        if dist == 0:
            # distance 0 IS the lockstep pair mix: route through the
            # sorted-pair average so it stays bit-identical on both ends
            # (and bit-identical to the epoch-aligned rounds)
            if own == min(own, partner):
                mix_m = _avg_sorted(mine_m, theirs_m)
                mix_b = (
                    None if mine_b is None or theirs_b is None
                    else _avg_sorted(mine_b, theirs_b)
                )
                avg_g = _avg_sorted(mine_g, theirs_g)
            else:
                mix_m = _avg_sorted(theirs_m, mine_m)
                mix_b = (
                    None if mine_b is None or theirs_b is None
                    else _avg_sorted(theirs_b, mine_b)
                )
                avg_g = _avg_sorted(theirs_g, mine_g)
        else:
            # staleness-discounted convex mix; both sides computed the
            # same distance (epochs rode the match), so the two updates
            # still sum to the pair's sum — galaxy mean preserved
            wgt = staleness_weight(dist, async_decay())
            mix_m = staleness_mix(mine_m, theirs_m, wgt)
            mix_b = (
                None if mine_b is None or theirs_b is None
                else staleness_mix(mine_b, theirs_b, wgt)
            )
            avg_g = staleness_mix(mine_g, theirs_g, wgt)
        if ef is not None:
            ef.commit(round_key)
        record_wire("gossip", raw, wire)
        self._record(key, partner=partner, n=2, t0=t0, wire=wire, lag=dist)
        return mix_m, mix_b, avg_g, partner, 2

    # -- health ------------------------------------------------------------

    def _record(
        self,
        key: str,
        *,
        partner: str,
        n: int,
        t0: float,
        wire: int = 0,
        dropped: bool = False,
        lag: Optional[int] = None,
    ) -> None:
        t1 = time.perf_counter()
        health = {
            "round": f"gossip-{key}",
            "group_size": n,
            # a pair round's full group IS the pair; elastic-ness is
            # "did it complete", not "how many showed up"
            "expected": 2 if partner != self.backend.peer_id else 1,
            "elastic": dropped,
            "retries": 0,
            "gossip": True,
            "partner": partner,
            "pair_s": round(t1 - t0, 6),
        }
        if dropped:
            health["dropped"] = True
        if wire:
            health["wire_bytes"] = int(wire)
        if lag is not None:
            # epoch distance of an async match (0 on aligned pairs); rides
            # the overseer roll-up so odtp_top can show live skew
            health["pair_lag"] = int(lag)
        try:
            self.backend.last_round_health = health
            led = self.backend.round_ledger
            led.append(health)
            if len(led) > _HEALTH_LEDGER_CAP:
                del led[:-_HEALTH_LEDGER_CAP]
        except AttributeError:
            pass
        tr = obs.tracer()
        if tr is not None:
            tr.add_span(
                "outer/gossip_pair", t0, t1,
                partner=partner, round=health["round"], dropped=dropped,
            )
            tr.instant("outer/round", worker=self.backend.peer_id, **health)
            tr.gauge("gossip_pair_s", t1 - t0)
            tr.count("gossip_pair_rounds")
            tr.count("outer_rounds")
            if dropped:
                tr.count("gossip_dropped_rounds")
            if wire:
                tr.count("gossip_wire_bytes", wire)
        ov = obs.overseer.plane()
        if ov is not None:
            ov.note_round(health, own_id=self.backend.peer_id)
