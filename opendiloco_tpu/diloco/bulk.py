"""Threaded bulk data plane for large tensor transfers.

The asyncio control plane (diloco/tcp.py) is right for matchmaking, gossip
and small frames, but for multi-hundred-MB butterfly parts it pays an
allocation and a copy per read and runs every byte through the event loop.
This module is the native data plane the reference delegates to hivemind's
libp2p daemon (SURVEY §2.3): plain blocking sockets on dedicated threads,
``sendall`` straight from the tensor buffer and ``recv`` straight into a
preallocated numpy buffer -- zero application-side copies. The byte pumping
itself runs in C (native/odtp_kernels.cpp ``odtp_sendall``/``odtp_recvall``)
with the GIL released when the native library is built.

Wire format: identical ODTP frames (diloco/wire.py), one connection per
peer pair, persistent across rounds; each frame is acknowledged with a
single byte so senders get backpressure parity with the RPC path.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
from typing import Callable, Optional

import numpy as np

from opendiloco_tpu import native
from opendiloco_tpu.diloco.wire import MAGIC, MAX_HEADER, WireError
from opendiloco_tpu.utils.logger import get_text_logger

log = get_text_logger(__name__)

_HDR = struct.Struct(">4sI")
_ACK = b"\x01"


def _tune(sock: socket.socket) -> None:
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 4 * 1024 * 1024)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4 * 1024 * 1024)
    except OSError:
        pass


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = np.empty(n, np.uint8)
    native.sock_recvall(sock, buf)
    return buf.tobytes()


def send_frame_sync(
    sock: socket.socket, msg_type: str, meta: dict, payload=b""
) -> None:
    nbytes = (
        payload.nbytes if isinstance(payload, np.ndarray) else len(payload)
    )
    header = json.dumps(
        {"type": msg_type, "meta": meta, "payload_len": nbytes}
    ).encode()
    native.sock_sendall(sock, _HDR.pack(MAGIC, len(header)) + header)
    if nbytes:
        native.sock_sendall(sock, payload)


def read_frame_sync(sock: socket.socket) -> tuple[str, dict, np.ndarray]:
    """Read one frame; the payload lands in a fresh numpy uint8 buffer
    (single allocation, received in place)."""
    hdr = _recv_exact(sock, _HDR.size)
    magic, hlen = _HDR.unpack(hdr)
    if magic != MAGIC or hlen > MAX_HEADER:
        raise WireError(f"bad bulk frame header: magic={magic!r} hlen={hlen}")
    header = json.loads(_recv_exact(sock, hlen))
    n = header.get("payload_len", 0)
    payload = np.empty(n, np.uint8)
    if n:
        native.sock_recvall(sock, payload)
    return header["type"], header.get("meta", {}), payload


class BulkServer:
    """Accepts persistent bulk connections; one handler thread each.

    ``deliver(msg, meta, payload)`` is called from handler threads for every
    received frame (payload is a numpy uint8 buffer).
    """

    def __init__(self, deliver: Callable[[str, dict, np.ndarray], None], host: str):
        self._deliver = deliver
        self._sock = socket.create_server((host, 0))
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._conns: set[socket.socket] = set()
        self._lock = threading.Lock()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="odtp-bulk-accept", daemon=True
        )
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # socket closed by stop()
            _tune(conn)
            with self._lock:
                self._conns.add(conn)
            threading.Thread(
                target=self._handle, args=(conn,), name="odtp-bulk-conn", daemon=True
            ).start()

    def _handle(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                try:
                    msg, meta, payload = read_frame_sync(conn)
                except (ConnectionError, OSError, WireError):
                    return
                self._deliver(msg, meta, payload)
                native.sock_sendall(conn, _ACK)
        except Exception:
            if not self._stop.is_set():
                log.exception("bulk handler error")
        finally:
            with self._lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            for c in list(self._conns):
                try:
                    c.close()
                except OSError:
                    pass


class BulkSender:
    """Persistent outgoing bulk connections, one per destination, with a
    per-destination lock serializing frames."""

    def __init__(self, connect_timeout: float = 10.0):
        self._timeout = connect_timeout
        self._conns: dict[tuple, socket.socket] = {}
        self._locks: dict[tuple, threading.Lock] = {}
        self._meta_lock = threading.Lock()

    def send(
        self,
        host: str,
        port: int,
        msg: str,
        meta: dict,
        payload,
        *,
        lock_timeout: float = 30.0,
    ) -> None:
        key = (host, port)
        with self._meta_lock:
            lock = self._locks.setdefault(key, threading.Lock())
        # bounded wait: a zombie transfer from a timed-out round must not
        # wedge the retry forever (the caller falls back / re-forms the group)
        if not lock.acquire(timeout=lock_timeout):
            raise TimeoutError(f"bulk destination {key} busy")
        try:
            for attempt in (0, 1):
                sock = self._conns.get(key)
                if sock is None:
                    sock = socket.create_connection(
                        (host, port), timeout=self._timeout
                    )
                    # keep the socket BLOCKING (settimeout would flip it to
                    # non-blocking and break the native C recv/send path);
                    # bound hangs with kernel-level timeouts instead
                    sock.settimeout(None)
                    tv = struct.pack("ll", 300, 0)
                    sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVTIMEO, tv)
                    sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDTIMEO, tv)
                    _tune(sock)
                    self._conns[key] = sock
                try:
                    send_frame_sync(sock, msg, meta, payload)
                    ack = np.empty(1, np.uint8)
                    native.sock_recvall(sock, ack)
                    if ack[0] != _ACK[0]:
                        raise WireError(f"bad bulk ack {ack[0]!r}")
                    return
                except (ConnectionError, OSError, WireError):
                    # stale pooled connection: reconnect once, then give up
                    self._drop(key)
                    if attempt == 1:
                        raise
        finally:
            lock.release()

    def _drop(self, key: tuple) -> None:
        sock = self._conns.pop(key, None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def close(self) -> None:
        with self._meta_lock:
            for key in list(self._conns):
                self._drop(key)
