"""Threaded bulk data plane for large tensor transfers.

The asyncio control plane (diloco/tcp.py) is right for matchmaking, gossip
and small frames, but for multi-hundred-MB butterfly parts it pays an
allocation and a copy per read and runs every byte through the event loop.
This module is the native data plane the reference delegates to hivemind's
libp2p daemon (SURVEY §2.3): plain blocking sockets on dedicated threads,
``sendall`` straight from the tensor buffer and ``recv`` straight into a
preallocated numpy buffer -- zero application-side copies. The byte pumping
itself runs in C (native/odtp_kernels.cpp ``odtp_sendall``/``odtp_recvall``)
with the GIL released when the native library is built.

Wire format: identical ODTP frames (diloco/wire.py), persistent connections
across rounds; each frame is acknowledged with a single byte so senders get
backpressure parity with the RPC path.

Large frames stripe over several parallel TCP streams (``ODTP_BULK_STREAMS``,
payloads >= ``ODTP_BULK_STRIPE_MIN`` bytes): a single TCP stream tops out
well below the path capacity (kernel-measured ~2.1 GB/s loopback here; WAN
paths are window/BBR-limited the same way), while k streams pump k slices
concurrently with the GIL released in the native sendall/recvall. The main
connection carries the frame header (with the stripe table and a session
id) plus slice 0 and the ack; sibling connections carry ``_stripe``
sub-frames that land via recv_into directly into their slice of the one
preallocated buffer -- reassembly is zero-copy.
"""

from __future__ import annotations

import itertools
import json
import os
import socket
import struct
import threading
import time
import uuid
from typing import Callable, Optional

import numpy as np

from opendiloco_tpu import native, obs
from opendiloco_tpu.diloco import chaos, linkstate
from opendiloco_tpu.diloco.schema import (
    BULK_ACK as _ACK,
    FRAME_HDR as _HDR,
    MAGIC,
    MAX_HEADER,
    SO_TIMEVAL_FMT,
)
from opendiloco_tpu.diloco.wire import WireError
from opendiloco_tpu.utils.logger import get_text_logger

log = get_text_logger(__name__)
def _stripe_wait_s() -> float:
    """Stripe channels must land within the transfer budget; tunable so a
    deployment with a known round budget can fail a lost stripe faster than
    the 5-minute default (the retry path then re-forms the group)."""
    try:
        return float(os.environ.get("ODTP_BULK_STRIPE_WAIT_S", "300"))
    except ValueError:
        return 300.0


_TOMBSTONE_S = 60.0  # how long finished session ids stay known-dead

# test seam: called with every received frame's type ("push", "result",
# "_stripe", ...) from BulkServer handler threads
_frame_observer: Optional[Callable[[str], None]] = None


class _BufferPool:
    """Pre-touched receive buffers, keyed by exact size.

    Receiving into a fresh ``np.empty`` pays a soft page fault per 4KB --
    ~100k faults on a 430MB frame, measured at 1.2 vs 2.1 GB/s loopback
    (the whole single-stream gap). Consumers hand buffers back through
    ``release_buffer`` once the payload is decoded; steady-state rounds
    then allocate nothing. Unreturned buffers are simply garbage-collected
    (the pool holds no reference to handed-out buffers).
    """

    def __init__(self, max_per_size: int = 4):
        self._free: dict[int, list[np.ndarray]] = {}
        self._lock = threading.Lock()
        self._max = max_per_size

    def get(self, n: int) -> np.ndarray:
        with self._lock:
            lst = self._free.get(n)
            if lst:
                return lst.pop()
        buf = np.empty(n, np.uint8)
        buf.fill(0)  # touch every page outside the receive path
        return buf

    def release(self, buf) -> None:
        # only whole pool-shaped buffers come back; views (codec "none"
        # decode output aliases the payload) and foreign types are ignored
        if (
            not isinstance(buf, np.ndarray)
            or buf.dtype != np.uint8
            or buf.base is not None
            or buf.ndim != 1
        ):
            return
        with self._lock:
            lst = self._free.setdefault(buf.size, [])
            if len(lst) < self._max:
                lst.append(buf)


_pool = _BufferPool()


def release_buffer(buf) -> None:
    """Return a bulk-received payload to the receive pool (no-op for
    payloads that did not come from it)."""
    _pool.release(buf)


class _TokenBucket:
    """Global egress rate cap emulating a constrained WAN link.

    ``ODTP_BULK_BANDWIDTH_BPS`` (bytes/second; unset or 0 = unlimited) caps
    the aggregate payload egress of this process across all bulk streams —
    the bench's stand-in for tc/netem where traffic shaping isn't
    permitted. Tokens are taken in chunks so concurrent stripes interleave
    fairly instead of one stream draining the bucket."""

    def __init__(self, rate_bps: float):
        self.rate = float(rate_bps)
        # ~50ms of burst, floor 1MB: small enough to shape the flow, large
        # enough not to turn every chunk into a sleep
        self.burst = max(self.rate * 0.05, float(1 << 20))
        self.tokens = self.burst
        self.t = time.monotonic()
        self.lock = threading.Lock()

    def acquire(self, n: int) -> None:
        remaining = float(n)
        while remaining > 0:
            take = min(remaining, self.burst)
            with self.lock:
                now = time.monotonic()
                self.tokens = min(
                    self.burst, self.tokens + (now - self.t) * self.rate
                )
                self.t = now
                if self.tokens >= take:
                    self.tokens -= take
                    remaining -= take
                    continue
                wait = (take - self.tokens) / self.rate
            time.sleep(min(wait, 0.25))


_rate_lock = threading.Lock()
_rate_bucket: Optional[_TokenBucket] = None
_rate_bps: float = -1.0


def egress_bucket() -> Optional[_TokenBucket]:
    """The process-wide egress bucket, rebuilt when the env knob changes
    (the bench sweeps several caps in one parent process). Shared with the
    asyncio RPC path: bytes that bypass the bulk plane (small frames, bulk
    fallback) must drain the same budget or capped bench rows lie.

    The chaos plane's ``egress_bps`` folds into the same bucket (the lower
    of the two caps binds): that is how a bench emulates a bandwidth-skewed
    galaxy — every worker shares ODTP_BULK_BANDWIDTH_BPS, one worker's
    ODTP_CHAOS tightens its own link."""
    global _rate_bucket, _rate_bps
    try:
        bps = float(os.environ.get("ODTP_BULK_BANDWIDTH_BPS", "0") or 0.0)
    except ValueError:
        bps = 0.0
    cp = chaos.plane()
    if cp is not None:
        cbps = cp.egress_bps()
        if cbps > 0:
            bps = min(bps, cbps) if bps > 0 else cbps
    with _rate_lock:
        if bps != _rate_bps:
            _rate_bps = bps
            _rate_bucket = _TokenBucket(bps) if bps > 0 else None
        return _rate_bucket


_wan_bucket: Optional[_TokenBucket] = None
_wan_bps: float = -1.0


def wan_bucket() -> Optional[_TokenBucket]:
    """The process-wide WAN-uplink bucket, armed only by the chaos plane's
    ``wan_bps``. Separate from :func:`egress_bucket` by design: frames to
    WAN-classified destinations (``chaos wan_peers`` globs, consulted by
    the tcp layer) drain BOTH buckets — a worker's NIC and its site's
    shared uplink are distinct constraints, and the hierarchical bench
    relies on intra-site traffic paying only the first."""
    global _wan_bucket, _wan_bps
    cp = chaos.plane()
    bps = cp.wan_bps() if cp is not None else 0.0
    with _rate_lock:
        if bps != _wan_bps:
            _wan_bps = bps
            _wan_bucket = _TokenBucket(bps) if bps > 0 else None
        return _wan_bucket


_THROTTLE_CHUNK = 1 << 20


def _send_payload(sock: socket.socket, data) -> None:
    """Payload sendall with the optional egress cap applied per-chunk."""
    bucket = egress_bucket()
    if bucket is None:
        native.sock_sendall(sock, data)
        return
    view = data if isinstance(data, memoryview) else memoryview(data)
    view = view.cast("B")
    for off in range(0, len(view), _THROTTLE_CHUNK):
        chunk = view[off : off + _THROTTLE_CHUNK]
        bucket.acquire(len(chunk))
        native.sock_sendall(sock, chunk)


def _num_streams() -> int:
    try:
        return max(1, int(os.environ.get("ODTP_BULK_STREAMS", "4")))
    except ValueError:
        return 1


def _stripe_min() -> int:
    try:
        return int(os.environ.get("ODTP_BULK_STRIPE_MIN", str(64 << 20)))
    except ValueError:
        return 64 << 20


def _tune(sock: socket.socket) -> None:
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 4 * 1024 * 1024)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4 * 1024 * 1024)
    except OSError:
        pass


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = np.empty(n, np.uint8)
    native.sock_recvall(sock, buf)
    return buf.tobytes()


def send_frame_sync(
    sock: socket.socket, msg_type: str, meta: dict, payload=b""
) -> None:
    nbytes = (
        payload.nbytes if isinstance(payload, np.ndarray) else len(payload)
    )
    header = json.dumps(
        {"type": msg_type, "meta": meta, "payload_len": nbytes}
    ).encode()
    cp = chaos.plane()
    if cp is not None and nbytes and cp.truncate("bulk_send"):
        # mid-transfer truncation: the header promises nbytes but only half
        # go out before the "link" dies. The receiver wedges in recvall
        # until the dropped connection resets it; the sender's retry /
        # RPC-fallback machinery owns recovery.
        native.sock_sendall(sock, _HDR.pack(MAGIC, len(header)) + header)
        view = memoryview(payload).cast("B")
        native.sock_sendall(sock, view[: nbytes // 2])
        raise ConnectionResetError("chaos: bulk payload truncated mid-transfer")
    native.sock_sendall(sock, _HDR.pack(MAGIC, len(header)) + header)
    if nbytes:
        _send_payload(sock, payload)
    obs.count("bulk_tx_bytes", nbytes)


def read_frame_sync(sock: socket.socket) -> tuple[str, dict, np.ndarray]:
    """Read one frame; the payload lands in a fresh numpy uint8 buffer
    (single allocation, received in place)."""
    hdr = _recv_exact(sock, _HDR.size)
    magic, hlen = _HDR.unpack(hdr)
    if magic != MAGIC or hlen > MAX_HEADER:
        raise WireError(f"bad bulk frame header: magic={magic!r} hlen={hlen}")
    header = json.loads(_recv_exact(sock, hlen))
    n = header.get("payload_len", 0)
    payload = np.empty(n, np.uint8)
    if n:
        native.sock_recvall(sock, payload)
    obs.count("bulk_rx_bytes", n)
    return header["type"], header.get("meta", {}), payload


class _Session:
    """Reassembly state for one striped frame.

    ``done`` / ``inflight`` exist for hedged transfers: a stripe may arrive
    twice (original + hedge copy, byte-identical), so completion is counted
    per stripe index, and the buffer is only handed to the consumer once no
    writer still holds a view into it."""

    __slots__ = ("views", "remaining", "failed", "done", "inflight", "hedged")

    def __init__(self, views: list, remaining: int, hedged: bool = False):
        self.views = views
        self.remaining = remaining
        self.failed = False
        self.done: set[int] = set()
        self.inflight = 0
        self.hedged = hedged


class BulkServer:
    """Accepts persistent bulk connections; one handler thread each.

    ``deliver(msg, meta, payload)`` is called from handler threads for every
    received frame (payload is a numpy uint8 buffer).
    """

    def __init__(self, deliver: Callable[[str, dict, np.ndarray], None], host: str):
        self._deliver = deliver
        self._sock = socket.create_server((host, 0))
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._conns: set[socket.socket] = set()
        self._lock = threading.Lock()
        self._sessions: dict[str, _Session] = {}
        # sid -> expiry: sessions that already completed or failed. A stripe
        # arriving after its session finished (sender retry, slow socket)
        # must fail fast instead of blocking its connection for the full
        # stripe wait while the sender's next round needs it.
        self._dead_sessions: dict[str, float] = {}
        self._sess_cond = threading.Condition()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="odtp-bulk-accept", daemon=True
        )
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # socket closed by stop()
            _tune(conn)
            with self._lock:
                self._conns.add(conn)
            threading.Thread(
                target=self._handle, args=(conn,), name="odtp-bulk-conn", daemon=True
            ).start()

    def _handle(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                try:
                    hdr = _recv_exact(conn, _HDR.size)
                    magic, hlen = _HDR.unpack(hdr)
                    if magic != MAGIC or hlen > MAX_HEADER:
                        raise WireError(f"bad bulk frame: magic={magic!r}")
                    header = json.loads(_recv_exact(conn, hlen))
                except ConnectionResetError:
                    return  # peer dropped the pooled connection: normal
                except (ConnectionError, OSError, WireError) as e:
                    # anything but a clean close means stream desync or a
                    # socket fault -- make it visible, the sender will see
                    # an unexplained EOF on its next ack read
                    log.warning("bulk conn dropped (%r)", e)
                    return
                if _frame_observer is not None:
                    _frame_observer(header["type"])
                tr = obs.tracer()
                if tr is not None:
                    tr.count("bulk_frames", kind=header["type"])
                    tr.count("bulk_rx_bytes", header.get("payload_len", 0))
                if header["type"] == "_stripe":
                    # stripe channel: bytes land straight in the session
                    # buffer; no ack (the main connection acks the frame)
                    self._read_stripe(conn, header)
                    continue
                n = header.get("payload_len", 0)
                if header.get("stripe_lens"):
                    payload = self._assemble(conn, header)
                else:
                    payload = _pool.get(n)
                    if n:
                        native.sock_recvall(conn, payload)
                self._deliver(header["type"], header.get("meta", {}), payload)
                native.sock_sendall(conn, _ACK)
        except Exception:
            if not self._stop.is_set():
                log.exception("bulk handler error")
        finally:
            with self._lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _read_stripe(self, conn: socket.socket, header: dict) -> None:
        sid, j = header["session"], header["stripe"]
        deadline = time.monotonic() + _stripe_wait_s()
        with self._sess_cond:
            while sid not in self._sessions:
                if sid in self._dead_sessions:  # tombstoned
                    if header.get("hedge") and header.get("len") is not None:
                        # late copy of a stripe whose sibling already
                        # completed the session: the bytes are in flight on
                        # this connection, so drain them to keep the stream
                        # in sync instead of killing the pooled connection
                        break
                    raise WireError(f"stripe {j} for finished session {sid}")
                left = deadline - time.monotonic()
                if left <= 0 or self._stop.is_set():
                    raise WireError(f"stripe {j} for unknown session {sid}")
                self._sess_cond.wait(timeout=min(left, 1.0))
            sess = self._sessions.get(sid)
            if sess is not None:
                sess.inflight += 1
        if sess is None:
            n = int(header["len"])
            scratch = _pool.get(n)
            try:
                if n:
                    native.sock_recvall(conn, scratch)
            finally:
                _pool.release(scratch)
            return
        try:
            # duplicate arrivals (hedge + original) carry identical bytes,
            # so receiving into the view unconditionally is benign; only
            # the first arrival advances ``remaining``
            native.sock_recvall(conn, sess.views[j])
        except Exception:
            with self._sess_cond:
                sess.inflight -= 1
                if not sess.hedged:
                    # a hedged sender may still deliver this stripe via its
                    # hedge copy; don't poison the session on one bad leg
                    sess.failed = True
                self._sess_cond.notify_all()
            raise
        with self._sess_cond:
            sess.inflight -= 1
            if j not in sess.done:
                sess.done.add(j)
                sess.remaining -= 1
            self._sess_cond.notify_all()

    def _assemble(self, conn: socket.socket, header: dict) -> np.ndarray:
        """Main-connection side of a striped frame: allocate the full
        buffer, register the session, receive slice 0, wait for siblings."""
        lens = header["stripe_lens"]
        sid = header["session"]
        payload = _pool.get(header["payload_len"])
        offs = [0]
        for ln in lens:
            offs.append(offs[-1] + ln)
        views = [payload[offs[i] : offs[i + 1]] for i in range(len(lens))]
        sess = _Session(
            views, remaining=len(lens) - 1, hedged=bool(header.get("hedged"))
        )
        with self._sess_cond:
            self._sessions[sid] = sess
            self._sess_cond.notify_all()
        try:
            native.sock_recvall(conn, views[0])
            deadline = time.monotonic() + _stripe_wait_s()
            with self._sess_cond:
                # wait for every stripe AND for every writer to let go of
                # its view: a slow duplicate writer must not scribble into
                # the buffer after it is handed out (and pooled/reused)
                while (
                    sess.remaining > 0 or sess.inflight > 0
                ) and not sess.failed:
                    left = deadline - time.monotonic()
                    if left <= 0 or self._stop.is_set():
                        raise WireError(f"striped frame {sid} timed out")
                    self._sess_cond.wait(timeout=min(left, 1.0))
                if sess.failed:
                    raise WireError(f"striped frame {sid} lost a stripe")
        finally:
            with self._sess_cond:
                self._sessions.pop(sid, None)
                now = time.monotonic()
                self._dead_sessions[sid] = now + _TOMBSTONE_S
                for k in [
                    k for k, t in self._dead_sessions.items() if t < now
                ]:
                    del self._dead_sessions[k]
                self._sess_cond.notify_all()
        return payload

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            for c in list(self._conns):
                try:
                    c.close()
                except OSError:
                    pass


class BulkSender:
    """Persistent outgoing bulk connections (a stream group per
    destination), with a per-destination lock serializing frames."""

    _session_counter = itertools.count()

    def __init__(self, connect_timeout: float = 10.0):
        self._timeout = connect_timeout
        self._conns: dict[tuple, list[socket.socket]] = {}
        self._locks: dict[tuple, threading.Lock] = {}
        self._meta_lock = threading.Lock()
        self._id = uuid.uuid4().hex[:12]
        # per-destination link estimates (bps, rtt_s) fed by the adaptive
        # layer (tcp.py) and a multiplicative stripe-count backoff applied
        # on top of the BDP plan when a striped send fails
        self._links: dict[tuple, tuple[float, float]] = {}
        self._stripe_scale: dict[tuple, float] = {}

    def set_link(self, host: str, port: int, bps: float, rtt_s: float) -> None:
        """Record the current link estimate toward one destination; used to
        derive stripe counts from bandwidth-delay product when
        ODTP_LINK_ADAPT is on."""
        with self._meta_lock:
            self._links[(host, port)] = (float(bps), float(rtt_s))

    def _plan_streams(self, key: tuple, nbytes: int) -> int:
        # a hint only exists when the owning backend runs adaptive (config
        # kwarg or ODTP_LINK_ADAPT) — its presence is the gate
        with self._meta_lock:
            hint = self._links.get(key)
            scale = self._stripe_scale.get(key, 1.0)
        if hint is not None and hint[0] > 0:
            streams = linkstate.stripes_for(nbytes, hint[0], hint[1])
        else:
            streams = _num_streams()
        return max(1, int(streams * scale))

    def _scale_stripes(self, key: tuple, ok: bool) -> None:
        """Multiplicative backoff on striped-send failure, slow recovery on
        success (halve / grow 25%, clamped to [1/8, 1])."""
        with self._meta_lock:
            s = self._stripe_scale.get(key, 1.0)
            s = min(1.0, s * 1.25) if ok else max(0.125, s * 0.5)
            self._stripe_scale[key] = s

    def _connect(self, host: str, port: int) -> socket.socket:
        sock = socket.create_connection((host, port), timeout=self._timeout)
        # keep the socket BLOCKING (settimeout would flip it to
        # non-blocking and break the native C recv/send path);
        # bound hangs with kernel-level timeouts instead
        sock.settimeout(None)
        tv = struct.pack(SO_TIMEVAL_FMT, 300, 0)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVTIMEO, tv)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDTIMEO, tv)
        _tune(sock)
        return sock

    def _get_conns(self, key: tuple, n: int) -> list[socket.socket]:
        conns = self._conns.setdefault(key, [])
        while len(conns) < n:
            conns.append(self._connect(*key))
        return conns

    def send(
        self,
        host: str,
        port: int,
        msg: str,
        meta: dict,
        payload,
        *,
        lock_timeout: float = 30.0,
        align: int = 1,
    ) -> None:
        key = (host, port)
        with self._meta_lock:
            lock = self._locks.setdefault(key, threading.Lock())
        # bounded wait: a zombie transfer from a timed-out round must not
        # wedge the retry forever (the caller falls back / re-forms the group)
        if not lock.acquire(timeout=lock_timeout):
            raise TimeoutError(f"bulk destination {key} busy")
        try:
            nbytes = (
                payload.nbytes if isinstance(payload, np.ndarray) else len(payload)
            )
            streams = self._plan_streams(key, nbytes)
            striped = streams > 1 and nbytes >= max(_stripe_min(), streams)
            cp = chaos.plane()
            for attempt in (0, 1):
                if cp is not None:
                    d = cp.delay_s("bulk_send") + cp.straggle_s()
                    if d:
                        time.sleep(d)
                    if cp.drop_conn("bulk_send"):
                        self._drop(key)
                        if attempt == 1:
                            raise ConnectionResetError(
                                "chaos: bulk connection dropped"
                            )
                        continue
                try:
                    if striped:
                        self._send_striped(
                            key, msg, meta, payload, streams, align
                        )
                        self._scale_stripes(key, ok=True)
                    else:
                        sock = self._get_conns(key, 1)[0]
                        send_frame_sync(sock, msg, meta, payload)
                        self._await_ack(sock)
                    return
                except (ConnectionError, OSError, WireError):
                    # stale pooled connections: reconnect once, then give up
                    self._drop(key)
                    if striped:
                        self._scale_stripes(key, ok=False)
                    if attempt == 1:
                        raise
        finally:
            lock.release()

    def _await_ack(self, sock: socket.socket) -> None:
        ack = np.empty(1, np.uint8)
        native.sock_recvall(sock, ack)
        if ack[0] != _ACK[0]:
            raise WireError(f"bad bulk ack {ack[0]!r}")

    def stream(
        self, host: str, port: int, *, lock_timeout: float = 30.0
    ) -> "BulkStream":
        """Open a pipelined chunk stream to one destination.

        The destination lock is held for the stream's whole lifetime (chunk
        frames from two rounds must not interleave on one connection);
        ``BulkStream.close`` releases it. On connect failure the lock is
        released here and the caller falls back to the RPC path."""
        key = (host, port)
        with self._meta_lock:
            lock = self._locks.setdefault(key, threading.Lock())
        if not lock.acquire(timeout=lock_timeout):
            raise TimeoutError(f"bulk destination {key} busy")
        try:
            sock = self._get_conns(key, 1)[0]
        except BaseException:
            self._drop(key)
            lock.release()
            raise
        return BulkStream(self, key, lock, sock)

    def _send_striped(
        self, key: tuple, msg: str, meta: dict, payload, streams: int,
        align: int = 1,
    ) -> None:
        """Pump ~equal contiguous slices over ``streams`` connections; the
        header (with the stripe table + session id) and slice 0 go on
        connection 0, which also carries the single ack.

        ``align`` (bytes) rounds the stripe step up so every boundary lands
        on a wire-record multiple of the payload's codec (f32/f16 element
        width, topk's u32+f32 records; packed-nibble payloads are already
        byte-granular) — stripe boundaries then never split an encoded
        record, whatever order the receiver lands them in.

        With a link estimate and ODTP_LINK_ADAPT on, the send is *hedged*:
        a stripe still in flight past a deadline derived from the estimated
        bandwidth/RTT is re-dispatched over an idle connection, first
        arrival wins (the receiver dedups per stripe index)."""
        data = memoryview(payload).cast("B")
        n = len(data)
        conns = self._get_conns(key, streams)
        sid = f"{self._id}-{next(self._session_counter)}"
        step = -(-n // streams)
        if align > 1:
            step += (-step) % align
        offs = [min(i * step, n) for i in range(streams + 1)]
        lens = [offs[i + 1] - offs[i] for i in range(streams)]

        hedge_s = 0.0
        with self._meta_lock:
            hint = self._links.get(key)
        if hint is not None and hint[0] > 0:
            hedge_s = linkstate.hedge_deadline_s(
                max(lens), hint[0], hint[1], streams
            )
        hedged = hedge_s > 0.0 and streams > 1

        header = json.dumps(
            {
                "type": msg,
                "meta": meta,
                "payload_len": n,
                "stripe_lens": lens,
                "session": sid,
                **({"hedged": 1} if hedged else {}),
            }
        ).encode()
        errors: list[BaseException] = []
        done = [threading.Event() for _ in range(streams)]

        def pump(j: int) -> None:
            try:
                sub = json.dumps(
                    {"type": "_stripe", "session": sid, "stripe": j,
                     "len": lens[j]}
                ).encode()
                native.sock_sendall(conns[j], _HDR.pack(MAGIC, len(sub)) + sub)
                if lens[j]:
                    _send_payload(conns[j], data[offs[j] : offs[j + 1]])
                done[j].set()
            except BaseException as e:  # surfaced on the main thread
                errors.append((j, e))

        threads = [
            threading.Thread(target=pump, args=(j,), daemon=True)
            for j in range(1, streams)
        ]
        for t in threads:
            t.start()
        native.sock_sendall(conns[0], _HDR.pack(MAGIC, len(header)) + header)
        if lens[0]:
            _send_payload(conns[0], data[offs[0] : offs[1]])
        done[0].set()
        if not hedged:
            for t in threads:
                t.join()
            if errors:
                raise errors[0][1]
            self._await_ack(conns[0])
            return
        # hedged path: give laggards until the deadline, then re-send any
        # stripe that has not completed (slow OR failed leg) over an idle
        # pooled connection / a fresh dial. The ack still rides conn 0 and
        # is the single source of truth for delivery.
        deadline = time.monotonic() + hedge_s
        for t in threads:
            t.join(max(0.0, deadline - time.monotonic()))
        laggards = [j for j in range(1, streams) if not done[j].is_set()]
        hedged_ok: set[int] = set()
        for hedge_idx, j in enumerate(laggards):
            try:
                self._hedge_stripe(
                    key, sid, j, data[offs[j] : offs[j + 1]],
                    conns, streams + hedge_idx,
                )
                hedged_ok.add(j)
                obs.count("bulk_stripe_hedges")
            except Exception as e:
                log.warning("stripe %d hedge to %s failed (%s)", j, key, e)
        # a stripe whose original leg already errored AND whose hedge failed
        # can never arrive -- fail fast instead of blocking on the ack
        dead = [e for j, e in list(errors) if j not in hedged_ok]
        if dead:
            raise dead[0]
        self._await_ack(conns[0])
        # bounded cleanup: original legs usually finish right behind the
        # hedge; a leg wedged past that is a dead socket — drop the pool so
        # the zombie writer errors out instead of corrupting a later frame
        for t in threads:
            t.join(5.0)
        if any(t.is_alive() for t in threads):
            log.warning("bulk stripes to %s wedged after hedge; dropping", key)
            self._drop(key)
        elif errors:
            # ack arrived, so delivery completed via the hedge copies; the
            # sockets behind the failed legs are still suspect for reuse
            log.warning(
                "bulk send to %s recovered via hedging (%d failed leg(s))",
                key, len(errors),
            )
            self._drop(key)

    def _hedge_stripe(
        self,
        key: tuple,
        sid: str,
        j: int,
        view,
        conns: list,
        idle_idx: int,
    ) -> None:
        """Re-dispatch stripe ``j`` over the fastest idle connection: a
        pooled connection beyond the active stripe set (already-warm TCP
        window) when one exists, else a fresh dial that joins the pool."""
        if idle_idx < len(conns):
            sock = conns[idle_idx]
        else:
            sock = self._connect(*key)
            conns.append(sock)
        sub = json.dumps(
            {"type": "_stripe", "session": sid, "stripe": j,
             "len": len(view), "hedge": 1}
        ).encode()
        native.sock_sendall(sock, _HDR.pack(MAGIC, len(sub)) + sub)
        if len(view):
            _send_payload(sock, view)

    def _drop(self, key: tuple) -> None:
        for sock in self._conns.pop(key, []):
            try:
                sock.close()
            except OSError:
                pass

    def close(self) -> None:
        with self._meta_lock:
            for key in list(self._conns):
                self._drop(key)


class BulkStream:
    """One destination's bulk connection held across a part's chunk frames.

    Frames are pipelined with a bounded ack window: chunk k's ack is only
    collected once k+`window` frames are on the wire, so the socket never
    idles between chunks while the server's per-frame ack still provides
    end-of-stream backpressure (``close`` drains the remainder). Any
    send/ack error poisons the stream and drops the pooled connection; the
    caller re-sends outstanding chunks through the RPC path."""

    def __init__(
        self,
        sender: BulkSender,
        key: tuple,
        lock: threading.Lock,
        sock: socket.socket,
        window: int = 2,
    ):
        self._sender = sender
        self._key = key
        self._lock = lock
        self._sock = sock
        self._window = max(1, window)
        self._pending = 0
        self._broken = False
        self._released = False

    def send(self, msg: str, meta: dict, payload) -> None:
        if self._broken:
            raise WireError(f"bulk stream to {self._key} is broken")
        cp = chaos.plane()
        if cp is not None:
            d = cp.delay_s("bulk_stream") + cp.straggle_s()
            if d:  # write-side latency on the pipelined chunk path
                time.sleep(d)
        try:
            send_frame_sync(self._sock, msg, meta, payload)
            self._pending += 1
            while self._pending >= self._window:
                self._sender._await_ack(self._sock)
                self._pending -= 1
        except BaseException:
            self._broken = True
            self._sender._drop(self._key)
            raise

    def close(self) -> None:
        """Drain outstanding acks and release the destination lock.

        A drain failure drops the pooled connection but does NOT raise:
        every frame was already written (send() errors are fatal and
        re-routed by the caller), and the acks are backpressure, not a
        delivery guarantee — delivery is enforced end-to-end by the
        receiver's mailbox timeout and the round retry machinery. Failing
        the sender's round here over a lost trailing ack was observed to
        desync an otherwise-complete 8-worker round: every receiver had the
        data, only this peer re-formed, and the swarm phase-shifted."""
        try:
            if not self._broken:
                try:
                    while self._pending:
                        self._sender._await_ack(self._sock)
                        self._pending -= 1
                except Exception as e:
                    self._broken = True
                    self._sender._drop(self._key)
                    log.warning(
                        "bulk stream to %s: %d trailing ack(s) lost at "
                        "close (%s); connection dropped",
                        self._key, self._pending, e,
                    )
        finally:
            if not self._released:
                self._released = True
                self._lock.release()
