"""Per-worker error feedback for lossy outer compression.

Sub-8-bit codecs (blockwise4bit, topk) drop real signal every round; error
feedback (Seide et al. 2014; Karimireddy et al. 2019 "EF signSGD") keeps
the per-worker quantization/sparsification error in a residual buffer and
adds it back into the NEXT round's pseudo-gradient before encoding, so the
dropped mass is delayed, not lost. The residual is keyed per LEAF, which
subsumes per-fragment streaming (a fragment is a set of leaf indices) and
the blocking one-fragment-per-boundary path alike.

Round protocol (the optimizer drives it around every wire launch):

  prepare(key, idxs, pgs)   pg += residual (host placement; the device
                            plane fuses the add into its pseudo-gradient
                            jit instead), then the codec roundtrip error
                            err = pg - decode(encode(pg)) is computed and
                            stashed PENDING under ``key``
  commit(key)               the round's result was adopted: pending errors
                            become the live residual
  abort(key)                the round was dropped (elastic timeout, state
                            adoption): pending errors are discarded and
                            the PREVIOUS residual stays live — the next
                            pseudo-gradient (master - params) re-captures
                            the dropped update, so the retained residual
                            is neither lost nor double-counted

Streaming fragment rounds prepare from comm threads concurrently (device
placement does the D2H on the comm thread), so the pending map is guarded
by a lock; at most one round is ever in flight per key (the optimizer's
``_pending`` slot / the stream scheduler's per-fragment ordering).

Multihost: every process that computes a pseudo-gradient (messenger, and
eager-mode followers — identical pg from the replicated master) runs the
same prepare/commit, so residuals stay process-symmetric; delayed-mode
followers never hold a pseudo-gradient and skip error feedback entirely.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional, Sequence

import numpy as np

from opendiloco_tpu import native, obs
from opendiloco_tpu.diloco.compression import Codec


class ErrorFeedback:
    """Residual accumulator + pending-round ledger for one worker.

    Host placement owns the canonical residual arrays here; device
    placement injects ``device_setter`` (the plane keeps the residuals in
    HBM and fuses the add into the pseudo-gradient jit) and this class
    only tracks the per-round error computation and commit/abort staging.
    """

    def __init__(
        self,
        codec: Codec,
        n_leaves: int,
        *,
        device_setter: Optional[
            Callable[[Sequence[int], list[np.ndarray]], None]
        ] = None,
    ):
        self.codec = codec
        self.n_leaves = int(n_leaves)
        self._device_setter = device_setter
        # host-placement canonical residuals; None until a leaf's first
        # committed round (device placement leaves this untouched — the
        # plane owns the live residuals, ef_host_state() snapshots them)
        self.residual: list[Optional[np.ndarray]] = [None] * self.n_leaves
        self._pending: dict = {}
        self._lock = threading.Lock()

    @property
    def on_device(self) -> bool:
        return self._device_setter is not None

    def prepare(self, key, idxs: Sequence[int], pgs: list[np.ndarray]) -> None:
        """Fold the residual into this round's pseudo-gradient (in place,
        host placement only — the device plane already added it in-jit)
        and stash the codec roundtrip error pending under ``key``."""
        errs: list[np.ndarray] = []
        for j, i in enumerate(idxs):
            pg = pgs[j]
            if not self.on_device:
                r = self.residual[i]
                if r is not None:
                    np.add(pg, r.reshape(pg.shape), out=pg)
            payload, meta = self.codec.encode(pg)
            dec = self.codec.decode(payload, pg.shape, meta)
            # reuse the decode buffer: err = pg - roundtrip(pg)
            err = np.subtract(pg, dec, out=np.asarray(dec, np.float32))
            errs.append(err)
        with self._lock:
            self._pending[key] = (list(idxs), errs)

    def commit(self, key) -> None:
        """Adopt the pending errors as the live residual (the round's
        compressed pseudo-gradient made it onto the wire and its average
        was applied). No-op when ``key`` was never prepared (delayed-mode
        followers)."""
        with self._lock:
            item = self._pending.pop(key, None)
        if item is None:
            return
        idxs, errs = item
        if self.on_device:
            self._device_setter(idxs, errs)
        else:
            for i, e in zip(idxs, errs):
                self.residual[i] = e
        tr = obs.tracer()
        if tr is not None:
            sq = 0.0
            for e in errs:
                sq += native.sqnorm(np.ascontiguousarray(e, np.float32).reshape(-1))
            tr.gauge("ef_residual_norm", float(np.sqrt(sq)))

    def abort(self, key) -> None:
        """Discard a dropped round's pending errors; the previous residual
        stays live (nothing was adopted, so nothing was double-counted)."""
        with self._lock:
            self._pending.pop(key, None)

    def abort_all(self) -> None:
        with self._lock:
            self._pending.clear()

    # -- checkpoint integration (host placement; device placement snapshots
    # through the plane's ef_host_state/load_ef instead) -------------------

    def host_residuals(self) -> Optional[list[Optional[np.ndarray]]]:
        """Per-leaf residual list for state_dict (None entries for leaves
        that never committed a round); None when nothing committed yet."""
        if all(r is None for r in self.residual):
            return None
        return [None if r is None else r.copy() for r in self.residual]

    def load(self, residuals: Optional[Sequence]) -> None:
        """Adopt checkpointed residuals (list may carry None entries)."""
        if residuals is None:
            self.residual = [None] * self.n_leaves
            return
        self.residual = [
            None if r is None else np.asarray(r, np.float32).copy()
            for r in residuals
        ]
