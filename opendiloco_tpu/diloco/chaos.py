"""Deterministic, seedable chaos fault-injection plane for the WAN stack.

One env spec scripts every fault class the outer data plane can hit::

    ODTP_CHAOS="seed=7;drop_conn=0.05;delay_ms=20..200;kill_worker=r3:w5;blackout_rdv=r2"

Grammar — ``;``-separated ``key=value`` items:

- ``seed=N``            RNG seed; same spec + seed => same fault sequence.
- ``drop_conn=P``       probability of refusing/resetting a connection-level
                        op (rendezvous RPC, peer RPC, bulk send, inbound
                        peer connection, loopback contribution).
- ``truncate=P``        probability of cutting a bulk transfer mid-payload
                        (half the bytes go out, then the socket dies).
- ``delay_ms=A..B``     read/write latency injected before WAN ops, drawn
                        uniformly from [A, B] ms (``delay_ms=50`` pins it).
- ``delay_p=P``         probability an op draws a delay at all (default 1
                        when ``delay_ms`` is set).
- ``kill_worker=rR:wW`` schedule entry: worker W should be SIGKILLed at
                        outer round R. The plane only *parses and exposes*
                        the schedule (``kill_schedule()``); an orchestrator
                        (scripts/chaos_soak.py, tests) does the killing.
                        Comma-separate for multiple entries.
- ``blackout_rdv=rR``   daemon-side: when the daemon observes its R-th
                        distinct matchmaking round (1-based), it goes dark —
                        drops every frame without replying — for
                        ``blackout_s`` seconds. Comma-separate for several.
- ``blackout_s=S``      blackout duration (default 3.0 s).
- ``straggle_ms=A..B``  extra latency for this process's outer contributions
                        (straggler throttling); scope with
                        ``straggle_worker=W`` + ``set_identity(W)``.
- ``straggle_inner_ms=A..B``  extra latency injected into every INNER
                        training step (slow-host emulation). Unlike
                        ``straggle_ms`` — whose delay the whole barrier-
                        synchronized round absorbs symmetrically — this
                        collapses the worker's own tokens/s, the
                        asymmetric signature the straggler watchdog keys
                        on. Scoped by ``straggle_worker`` too.
- ``straggle_inner_x=X``  sustained inner-step speed multiplier: worker
                        ranks in scope run their inner steps X times
                        slower (the bench/train hook stretches each
                        measured step by (X-1) of its own duration).
                        Scope with ``workers=w3,w7``, or give per-rank
                        factors directly: ``straggle_inner_x=w3:2.0,w7:4.0``.
                        Unlike the one-shot ``straggle_inner_ms`` delays
                        this expresses a deterministic rate skew (2x/4x
                        heterogeneous-galaxy emulation); lookups are pure
                        (NO RNG draw), so concurrent worker threads can
                        query their own factor without perturbing the
                        fault stream.
- ``workers=w3,w7``     rank scope for ``straggle_inner_x`` when given as
                        a single scalar factor.
- ``egress_bps=N``      cap this process's bulk/wire payload egress at N
                        bytes/second (token bucket, same machinery as
                        ``ODTP_BULK_BANDWIDTH_BPS``; when both are set the
                        LOWER cap binds). This is how a bench emulates a
                        bandwidth-skewed galaxy: give one worker's process
                        a chaos spec with a lower cap than its peers.
- ``wan_bps=N``         cap egress to WAN-classified destinations at N
                        bytes/second (separate token bucket, additive with
                        ``egress_bps``: the NIC cap and the site-uplink cap
                        both apply). Destinations are classified by
                        ``wan_peers``.
- ``wan_peers=G|G``     ``|``-separated fnmatch globs over destination peer
                        ids; a match means frames to that peer cross the
                        emulated WAN. Required for ``wan_bps`` to bite.

Design constraints:

- **Zero-cost when disabled.** Hook sites call :func:`plane` which is one
  ``os.environ`` dict hit plus a cached-string compare; when ``ODTP_CHAOS``
  is unset it returns ``None`` and the hook is a single ``is None`` branch
  (same idiom as ``bulk._frame_observer`` / ``bulk.egress_bucket``).
- **Deterministic.** Every fault decision consumes one draw from a single
  seeded RNG stream under a lock, so a fixed spec + seed replays the same
  decision sequence (test-enforced in tests/test_chaos.py).
- **Accountable.** Every injected fault is logged and counted
  (``counters``, bounded ``events`` list, ``snapshot()``); a soak can prove
  faults actually fired.
"""

from __future__ import annotations

import fnmatch
import os
import random
import threading
import time
from collections import Counter
from typing import Optional

from opendiloco_tpu.utils.logger import get_text_logger

log = get_text_logger(__name__)

_ENV = "ODTP_CHAOS"
_EVENTS_CAP = 4096


class ChaosSpecError(ValueError):
    """Malformed ODTP_CHAOS spec."""


def _parse_range(val: str) -> tuple[float, float]:
    if ".." in val:
        lo, hi = val.split("..", 1)
        lo_f, hi_f = float(lo), float(hi)
    else:
        lo_f = hi_f = float(val)
    if lo_f > hi_f or lo_f < 0:
        raise ChaosSpecError(f"bad range {val!r} (need 0 <= lo <= hi)")
    return lo_f, hi_f


def _parse_rounds(val: str) -> list[int]:
    out = []
    for item in val.split(","):
        item = item.strip().lstrip("rR")
        if item:
            out.append(int(item))
    return out


def _parse_kills(val: str) -> list[tuple[int, int]]:
    out = []
    for item in filter(None, (s.strip() for s in val.split(","))):
        try:
            r, w = item.split(":", 1)
            if r[:1] not in "rR" or w[:1] not in "wW":
                raise ValueError(item)
            out.append((int(r[1:]), int(w[1:])))
        except ValueError as e:
            raise ChaosSpecError(f"bad kill_worker entry {item!r} (want rR:wW)") from e
    return out


def parse_spec(spec: str) -> dict:
    """Parse the ODTP_CHAOS grammar into a normalized parameter dict."""
    p = {
        "seed": 0,
        "drop_conn": 0.0,
        "truncate": 0.0,
        "delay_ms": (0.0, 0.0),
        "delay_p": 1.0,
        "kill_worker": [],
        "blackout_rdv": [],
        "blackout_s": 3.0,
        "straggle_ms": (0.0, 0.0),
        "straggle_inner_ms": (0.0, 0.0),
        # rank -> sustained inner-step slowdown factor; key None holds a
        # scalar factor scoped by "workers" (empty scope = every rank)
        "straggle_inner_x": {},
        "workers": [],
        "straggle_worker": None,
        "egress_bps": 0.0,
        "wan_bps": 0.0,
        "wan_peers": [],
    }
    for item in filter(None, (s.strip() for s in spec.split(";"))):
        if "=" not in item:
            raise ChaosSpecError(f"chaos spec item {item!r} is not key=value")
        k, v = (s.strip() for s in item.split("=", 1))
        try:
            _parse_item(p, k, v)
        except ChaosSpecError:
            raise
        except ValueError as e:
            raise ChaosSpecError(f"bad chaos spec value {k}={v!r}") from e
    return p


def _parse_item(p: dict, k: str, v: str) -> None:
    if k == "seed":
        p["seed"] = int(v)
    elif k in ("drop_conn", "truncate", "delay_p"):
        p[k] = float(v)
        if not 0.0 <= p[k] <= 1.0:
            raise ChaosSpecError(f"{k}={v} outside [0, 1]")
    elif k in ("delay_ms", "straggle_ms", "straggle_inner_ms"):
        p[k] = _parse_range(v)
    elif k == "kill_worker":
        p["kill_worker"] = _parse_kills(v)
    elif k == "blackout_rdv":
        p["blackout_rdv"] = _parse_rounds(v)
    elif k == "blackout_s":
        p["blackout_s"] = float(v)
    elif k == "straggle_inner_x":
        table: dict = {}
        for item in filter(None, (s.strip() for s in v.split(","))):
            if ":" in item:
                w, x = item.split(":", 1)
                if w[:1] not in "wW":
                    raise ChaosSpecError(
                        f"bad straggle_inner_x entry {item!r} (want wW:X)")
                table[int(w[1:])] = float(x)
            else:
                table[None] = float(item)
        if any(x < 1.0 for x in table.values()):
            raise ChaosSpecError("straggle_inner_x factors must be >= 1.0")
        p["straggle_inner_x"] = table
    elif k == "workers":
        p["workers"] = sorted(
            int(w.lstrip("wW"))
            for w in filter(None, (s.strip() for s in v.split(",")))
        )
        if not p["workers"]:
            raise ChaosSpecError("workers needs at least one rank")
    elif k == "straggle_worker":
        p["straggle_worker"] = int(v.lstrip("wW"))
    elif k in ("egress_bps", "wan_bps"):
        p[k] = float(v)
        if p[k] < 0:
            raise ChaosSpecError(f"{k}={v} must be >= 0")
    elif k == "wan_peers":
        p["wan_peers"] = [g for g in (s.strip() for s in v.split("|")) if g]
        if not p["wan_peers"]:
            raise ChaosSpecError("wan_peers needs at least one glob")
    else:
        raise ChaosSpecError(f"unknown chaos spec key {k!r}")


class ChaosPlane:
    """Process-wide fault injector. All decisions draw from one seeded RNG
    stream; counters and a bounded event log account for every injection."""

    def __init__(self, spec: str):
        self.spec = spec
        self.params = parse_spec(spec)
        self.seed = self.params["seed"]
        self._rng = random.Random(self.seed)
        self._lock = threading.Lock()
        self.counters: Counter = Counter()
        self.events: list[dict] = []
        self.identity: Optional[int] = None  # worker rank, via set_identity()
        self._rdv_rounds: list[str] = []  # distinct matchmaking keys (daemon)
        self._blackout_until = 0.0

    # -- bookkeeping ---------------------------------------------------------

    def set_identity(self, worker: int) -> None:
        """Tell the plane which worker rank this process is (scopes
        straggle_worker / should_kill to the right process)."""
        self.identity = int(worker)

    def _draw(self) -> float:
        with self._lock:
            return self._rng.random()

    def _record(self, kind: str, site: str, **detail) -> None:
        with self._lock:
            self.counters[kind] += 1
            self.counters["total"] += 1
            if len(self.events) < _EVENTS_CAP:
                self.events.append({"kind": kind, "site": site, **detail})
        log.warning("chaos: injected %s at %s %s", kind, site, detail or "")
        # every injected fault lands in the flight recorder (and, rate-
        # limited, on disk): a postmortem can then correlate faults with
        # the spans they perturbed. No-op unless ODTP_OBS is armed; lazy
        # import keeps the fault-free path free of obs machinery.
        try:
            from opendiloco_tpu.obs import blackbox

            bb = blackbox.recorder()
            if bb is not None:
                bb.note_fault(kind, site, detail)
        except Exception:
            pass

    def snapshot(self) -> dict:
        """Counters + bounded event log, JSON-ready (soak/ledger reporting)."""
        with self._lock:
            return {"spec": self.spec, "counters": dict(self.counters),
                    "events": list(self.events)}

    # -- fault decisions (each consumes exactly one RNG draw when armed) -----

    def drop_conn(self, site: str) -> bool:
        p = self.params["drop_conn"]
        if p <= 0.0:
            return False
        if self._draw() < p:
            self._record("drop_conn", site)
            return True
        return False

    def truncate(self, site: str) -> bool:
        p = self.params["truncate"]
        if p <= 0.0:
            return False
        if self._draw() < p:
            self._record("truncate", site)
            return True
        return False

    def delay_s(self, site: str) -> float:
        lo, hi = self.params["delay_ms"]
        if hi <= 0.0:
            return 0.0
        if self.params["delay_p"] < 1.0 and self._draw() >= self.params["delay_p"]:
            return 0.0
        d = (lo + (hi - lo) * self._draw()) / 1000.0
        if d > 0.0:
            self._record("delay", site, ms=round(d * 1000.0, 3))
        return d

    def straggle_s(self) -> float:
        lo, hi = self.params["straggle_ms"]
        if hi <= 0.0:
            return 0.0
        w = self.params["straggle_worker"]
        if w is not None and self.identity != w:
            return 0.0
        d = (lo + (hi - lo) * self._draw()) / 1000.0
        if d > 0.0:
            self._record("straggle", "outer_round", ms=round(d * 1000.0, 3))
        return d

    def straggle_inner_s(self) -> float:
        """Slow-host emulation: seconds to sleep inside one inner training
        step (train loop hook). Consumed once per step so the worker's
        measured tokens/s — which rides the overseer roll-up — drops by
        exactly the injected factor."""
        lo, hi = self.params["straggle_inner_ms"]
        if hi <= 0.0:
            return 0.0
        w = self.params["straggle_worker"]
        if w is not None and self.identity != w:
            return 0.0
        d = (lo + (hi - lo) * self._draw()) / 1000.0
        if d > 0.0:
            self._record("straggle_inner", "inner_step", ms=round(d * 1000.0, 3))
        return d

    def straggle_inner_x(self, rank: Optional[int] = None) -> float:
        """Sustained inner-step slowdown factor for ``rank`` (1.0 = full
        speed). PURE lookup — no RNG draw, no counters: many worker
        threads in one process (loopback benches) query their own factor
        concurrently, and a draw here would perturb the deterministic
        fault stream the other injectors replay. The train-loop hook
        stretches each measured inner step by (factor - 1) of its own
        duration, so a factor of X shows up as exactly X-times-slower
        tokens/s and steps/s in the overseer roll-up."""
        table = self.params["straggle_inner_x"]
        if not table:
            return 1.0
        r = self.identity if rank is None else int(rank)
        if r in table:
            return float(table[r])
        x = table.get(None)
        if x is None:
            return 1.0
        scope = self.params["workers"]
        if scope and r not in scope:
            return 1.0
        return float(x)

    def egress_bps(self) -> float:
        """Emulated egress cap for this process (0 = none). Consumed by
        bulk.egress_bucket(), which folds it into the shared token bucket
        (lower of this and ODTP_BULK_BANDWIDTH_BPS binds) — so every
        payload path that honors the env cap honors the chaos cap too."""
        return float(self.params["egress_bps"])

    def wan_bps(self) -> float:
        """Emulated WAN-uplink cap (0 = none). Consumed by
        bulk.wan_bucket(); frames to ``is_wan_peer`` destinations drain it
        IN ADDITION to the egress bucket — a site's NIC and its shared
        uplink are separate constraints and both must bind."""
        return float(self.params["wan_bps"])

    def is_wan_peer(self, peer_id: str) -> bool:
        """Does a frame to this destination cross the emulated WAN?
        fnmatch against the wan_peers globs; no globs means no WAN
        classification (wan_bps never bites)."""
        globs = self.params["wan_peers"]
        return any(fnmatch.fnmatch(peer_id, g) for g in globs)

    # -- schedules -----------------------------------------------------------

    def kill_schedule(self) -> list[tuple[int, int]]:
        """[(round, worker_rank)] SIGKILL schedule for an orchestrator."""
        return list(self.params["kill_worker"])

    def should_kill(self, round_idx: int, worker: int) -> bool:
        return (int(round_idx), int(worker)) in set(self.params["kill_worker"])

    # -- daemon-side blackout ------------------------------------------------

    def rdv_blackout(self, round_key: Optional[str] = None) -> bool:
        """Daemon-side gate: True while the daemon should play dead.

        Distinct matchmaking round keys are counted as they arrive; when the
        count reaches an entry of ``blackout_rdv`` the daemon goes dark for
        ``blackout_s`` seconds (drops frames without replying), exercising
        worker failover + backoff. Non-matchmaking frames pass ``None`` and
        only honor an already-active blackout.
        """
        sched = self.params["blackout_rdv"]
        if not sched and self._blackout_until <= 0.0:
            return False
        now = time.monotonic()
        with self._lock:
            if round_key is not None and round_key not in self._rdv_rounds:
                self._rdv_rounds.append(round_key)
                if len(self._rdv_rounds) in sched:
                    self._blackout_until = now + self.params["blackout_s"]
                    log.warning(
                        "chaos: rendezvous blackout armed for %.1fs (round %d: %s)",
                        self.params["blackout_s"], len(self._rdv_rounds), round_key,
                    )
            active = now < self._blackout_until
        if active:
            self._record("blackout_rdv", "rendezvous", round=round_key)
        return active


# -- process-wide accessor (bulk.egress_bucket idiom) -------------------------

_plane: Optional[ChaosPlane] = None
_spec: Optional[str] = None
_plane_lock = threading.Lock()


def plane() -> Optional[ChaosPlane]:
    """The process-wide chaos plane, or None when ODTP_CHAOS is unset/empty.

    Re-reads the env var every call (one dict hit) and rebuilds only when
    the spec string changes, so hook sites stay zero-cost when disabled.
    """
    global _plane, _spec
    spec = os.environ.get(_ENV) or None
    if spec == _spec:
        return _plane
    with _plane_lock:
        if spec != _spec:
            _plane = ChaosPlane(spec) if spec else None
            _spec = spec
    return _plane


def reset() -> None:
    """Drop the cached plane so the next plane() re-parses ODTP_CHAOS
    (tests use this to get a fresh RNG stream)."""
    global _plane, _spec
    with _plane_lock:
        _plane = None
        _spec = None


def backoff_s(attempt: int, base: Optional[float] = None,
              cap: Optional[float] = None) -> float:
    """Bounded exponential backoff with jitter for round retries.

    sleep = U(0.5, 1.0) * min(cap, base * 2**attempt); knobs
    ODTP_RETRY_BASE_S (default 0.5) and ODTP_RETRY_CAP_S (default 15).
    When the chaos plane is armed its seeded RNG supplies the jitter so
    retry schedules replay deterministically under a fixed seed.
    """
    if base is None:
        base = float(os.environ.get("ODTP_RETRY_BASE_S", "0.5"))
    if cap is None:
        cap = float(os.environ.get("ODTP_RETRY_CAP_S", "15"))
    span = min(cap, base * (2 ** max(0, int(attempt))))
    p = plane()
    u = p._draw() if p is not None else random.random()
    return (0.5 + 0.5 * u) * span


def round_retries(default: int = 3) -> int:
    """How many times a failed outer round re-forms (ODTP_ROUND_RETRIES)."""
    return max(1, int(os.environ.get("ODTP_ROUND_RETRIES", str(default))))
