"""Wire codecs for the outer all-reduce.

Same menu as the reference's compression flag (open_diloco/utils.py:83-121,
mapping to hivemind compression classes): none / fp16 / scaled-fp16 /
uniform8bit / quantile8bit / blockwise8bit. Pure numpy host-side codecs --
the outer loop runs on host pytrees, never on TPU.

Each codec turns one float32 ndarray into (payload bytes, meta dict) and
back. Lossy codecs are used for the *pseudo-gradients* on the wire; the
averaged result is decoded back to float32 before the outer optimizer step.
"""

from __future__ import annotations

import numpy as np

_BLOCK = 4096


class Codec:
    name: str = "none"

    def encode(self, arr: np.ndarray) -> tuple[bytes, dict]:
        return arr.astype(np.float32).tobytes(), {}

    def decode(self, payload: bytes, shape: tuple[int, ...], meta: dict) -> np.ndarray:
        return np.frombuffer(payload, dtype=np.float32).reshape(shape).copy()


class Float16Codec(Codec):
    name = "fp16"

    def encode(self, arr):
        return arr.astype(np.float16).tobytes(), {}

    def decode(self, payload, shape, meta):
        return (
            np.frombuffer(payload, dtype=np.float16).astype(np.float32).reshape(shape)
        )


class ScaledFloat16Codec(Codec):
    """fp16 after normalizing by the tensor's abs-max (keeps outliers finite;
    hivemind ScaledFloat16Compression equivalent)."""

    name = "scaled-fp16"

    def encode(self, arr):
        scale = float(np.max(np.abs(arr))) if arr.size else 0.0
        scale = scale if scale > 0 else 1.0
        return (arr / scale).astype(np.float16).tobytes(), {"scale": scale}

    def decode(self, payload, shape, meta):
        out = np.frombuffer(payload, dtype=np.float16).astype(np.float32)
        return (out * meta["scale"]).reshape(shape)


class Uniform8BitCodec(Codec):
    """Linear min/max quantization to uint8."""

    name = "uniform8bit"

    def encode(self, arr):
        lo = float(arr.min()) if arr.size else 0.0
        hi = float(arr.max()) if arr.size else 0.0
        span = (hi - lo) or 1.0
        q = np.clip(np.round((arr - lo) / span * 255.0), 0, 255).astype(np.uint8)
        return q.tobytes(), {"lo": lo, "span": span}

    def decode(self, payload, shape, meta):
        q = np.frombuffer(payload, dtype=np.uint8).astype(np.float32)
        return (q / 255.0 * meta["span"] + meta["lo"]).reshape(shape)


class Quantile8BitCodec(Codec):
    """256-bucket quantile codebook quantization (hivemind
    Quantile8BitQuantization equivalent): robust to heavy-tailed grads."""

    name = "quantile8bit"

    def encode(self, arr):
        flat = arr.reshape(-1).astype(np.float32)
        if flat.size == 0:
            return b"", {"codebook": np.zeros(256, np.float32).tobytes()}
        # sample for speed on big tensors
        sample = flat if flat.size <= 100_000 else np.random.default_rng(0).choice(
            flat, 100_000, replace=False
        )
        edges = np.quantile(sample, np.linspace(0, 1, 257))
        codebook = ((edges[:-1] + edges[1:]) * 0.5).astype(np.float32)
        idx = np.clip(
            np.searchsorted(edges[1:-1], flat, side="right"), 0, 255
        ).astype(np.uint8)
        return idx.tobytes(), {"codebook": codebook.tobytes()}

    def decode(self, payload, shape, meta):
        codebook = np.frombuffer(meta["codebook"], dtype=np.float32)
        idx = np.frombuffer(payload, dtype=np.uint8)
        return codebook[idx].reshape(shape)


class Blockwise8BitCodec(Codec):
    """Per-block absmax int8 (bitsandbytes/hivemind BlockwiseQuantization
    style): one fp32 scale per 4096 values."""

    name = "blockwise8bit"

    def encode(self, arr):
        flat = arr.reshape(-1).astype(np.float32)
        pad = (-flat.size) % _BLOCK
        padded = np.pad(flat, (0, pad))
        blocks = padded.reshape(-1, _BLOCK)
        scales = np.max(np.abs(blocks), axis=1, keepdims=True)
        scales[scales == 0] = 1.0
        q = np.clip(np.round(blocks / scales * 127.0), -127, 127).astype(np.int8)
        return q.tobytes(), {"scales": scales.astype(np.float32).tobytes(), "pad": pad}

    def decode(self, payload, shape, meta):
        q = np.frombuffer(payload, dtype=np.int8).astype(np.float32).reshape(-1, _BLOCK)
        scales = np.frombuffer(meta["scales"], dtype=np.float32).reshape(-1, 1)
        flat = (q / 127.0 * scales).reshape(-1)
        pad = meta["pad"]
        if pad:
            flat = flat[:-pad]
        return flat.reshape(shape)


_CODECS = {
    c.name: c
    for c in [
        Codec(),
        Float16Codec(),
        ScaledFloat16Codec(),
        Uniform8BitCodec(),
        Quantile8BitCodec(),
        Blockwise8BitCodec(),
    ]
}


def get_codec(name: str) -> Codec:
    if name not in _CODECS:
        raise ValueError(f"unknown compression {name!r}; have {sorted(_CODECS)}")
    return _CODECS[name]


def compress_roundtrip(arr: np.ndarray, codec: Codec) -> np.ndarray:
    payload, meta = codec.encode(arr)
    return codec.decode(payload, arr.shape, meta)
