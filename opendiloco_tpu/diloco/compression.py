"""Wire codecs for the outer all-reduce.

Same menu as the reference's compression flag (open_diloco/utils.py:83-121,
mapping to hivemind compression classes): none / fp16 / scaled-fp16 /
uniform8bit / quantile8bit / blockwise8bit.

Design constraints:
- ``meta`` must be JSON-serializable (it rides the frame header,
  diloco/wire.py); binary side-channels (block scales, quantile codebooks)
  are prepended to the payload instead.
- Hot paths (fp16 conversion, blockwise quantization, decode+accumulate)
  dispatch to the native kernels (native/odtp_kernels.cpp) when built, with
  numpy fallbacks -- identical semantics either way.
- ``decode_accumulate`` fuses the butterfly collect step (decode + sum) into
  one pass over the buffer.
- Chunked encode (``chunk_state`` + ``encode_chunk``) splits a part into
  independently decodable chunk payloads for the pipelined data plane.
  Tensor-global codec state (scaled-fp16's abs-max, uniform8bit's lo/span,
  quantile8bit's codebook) is computed once over the whole part by
  ``chunk_state``, then reused per chunk, so the concatenated chunk decodes
  are bit-identical to the whole-tensor path — each chunk's (payload, meta)
  feeds the existing ``decode_accumulate`` / ``decode_into`` unchanged.
"""

from __future__ import annotations

import numpy as np

from opendiloco_tpu import native

_BLOCK = 4096


def chunk_bounds(n: int, chunk_elems: int, align: int = 1) -> list[int]:
    """Element offsets splitting an n-element part into pipeline chunks.

    Returns ``[0, c1, ..., n]``; always at least one chunk (an empty part
    yields a single empty chunk so the receiver's chunk loop still runs).
    ``align`` rounds the chunk size down to a codec's block granularity
    (blockwise8bit) so chunk payloads stay bit-identical to the whole-tensor
    encode."""
    ce = max(1, int(chunk_elems))
    if align > 1:
        ce = max(align, ce - (ce % align))
    if n <= 0:
        return [0, 0]
    return list(range(0, n, ce)) + [n]


class Codec:
    name: str = "none"
    # chunk offsets must be multiples of this many elements (blockwise8bit)
    chunk_align: int = 1

    def chunk_state(self, arr: np.ndarray) -> dict:
        """Tensor-global encode state, computed once per part before the
        per-chunk ``encode_chunk`` calls. Stateless codecs return {}."""
        return {}

    def encode_chunk(self, arr: np.ndarray, state: dict) -> tuple[bytes, dict]:
        """Encode one contiguous slice of a part using the shared ``state``.

        The returned (payload, meta) must decode through the whole-tensor
        ``decode_accumulate`` / ``decode_into`` on the matching destination
        slice, and the concatenation of chunk decodes must be bit-identical
        to decoding one whole-tensor encode."""
        return self.encode(arr)

    def encode(self, arr: np.ndarray) -> tuple[bytes, dict]:
        # zero-copy when already contiguous f32: a memoryview over the array
        # buffer goes straight to the socket (the array outlives the send)
        return memoryview(np.ascontiguousarray(arr, np.float32)).cast("B"), {}

    def decode(self, payload: bytes, shape: tuple[int, ...], meta: dict) -> np.ndarray:
        # read-only view over the received payload -- every consumer either
        # reduces it into an accumulator or copies it during reassembly
        return np.frombuffer(payload, dtype=np.float32).reshape(shape)

    def decode_accumulate(
        self, payload: bytes, meta: dict, dst: np.ndarray
    ) -> None:
        """dst += decode(payload); dst is float32, shape defines layout.

        Base implementation routes through ``self.decode`` so every codec is
        correct by construction; subclasses override with fused single-pass
        kernels where they exist."""
        native.add_inplace(dst, self.decode(payload, dst.shape, meta))

    def decode_into(self, payload: bytes, meta: dict, dst: np.ndarray) -> None:
        """dst[:] = decode(payload); dst is a contiguous float32 1-D view.

        The butterfly's result-collect path decodes every gathered part
        straight into its slice of the output buffer — one native pass, no
        intermediate array, no reassembly concatenate. Base implementation
        routes through ``self.decode``; subclasses write into dst directly."""
        np.copyto(dst, self.decode(payload, dst.shape, meta))


class Float16Codec(Codec):
    name = "fp16"

    def encode(self, arr):
        return native.f32_to_f16_bytes(arr), {}

    def decode(self, payload, shape, meta):
        return native.f16_bytes_to_f32(payload, int(np.prod(shape))).reshape(shape)

    def decode_accumulate(self, payload, meta, dst):
        native.f16_accumulate(payload, dst)

    def decode_into(self, payload, meta, dst):
        native.f16_bytes_to_f32(payload, dst.size, out=dst)


class ScaledFloat16Codec(Codec):
    """fp16 after normalizing by the tensor's abs-max (keeps outliers finite;
    hivemind ScaledFloat16Compression equivalent)."""

    name = "scaled-fp16"

    def encode(self, arr):
        # fused single-pass absmax + divide-and-convert: the old numpy
        # pipeline (abs temp, max pass, divided temp, convert) made this
        # codec slower than plain fp16 despite identical wire bytes
        arr = np.asarray(arr, np.float32)
        scale = native.absmax(arr) if arr.size else 0.0
        scale = scale if scale > 0 else 1.0
        return native.f32_to_f16_scaled_bytes(arr, scale), {"scale": scale}

    def chunk_state(self, arr):
        arr = np.asarray(arr, np.float32)
        scale = native.absmax(arr) if arr.size else 0.0
        return {"scale": scale if scale > 0 else 1.0}

    def encode_chunk(self, arr, state):
        scale = state["scale"]
        return (
            native.f32_to_f16_scaled_bytes(np.asarray(arr, np.float32), scale),
            {"scale": scale},
        )

    def decode(self, payload, shape, meta):
        return native.f16_bytes_to_f32_scaled(
            payload, float(meta["scale"]), int(np.prod(shape))
        ).reshape(shape)

    def decode_accumulate(self, payload, meta, dst):
        native.f16_accumulate_scaled(payload, float(meta["scale"]), dst)

    def decode_into(self, payload, meta, dst):
        native.f16_bytes_to_f32_scaled(
            payload, float(meta["scale"]), dst.size, out=dst
        )


class Uniform8BitCodec(Codec):
    """Linear min/max quantization to uint8 (native single-pass kernels:
    the numpy pipeline's astype + arithmetic allocations made this codec's
    collect phases several times slower than the wire)."""

    name = "uniform8bit"

    def encode(self, arr):
        payload, lo, span = native.quantize_uniform8(arr)
        return payload, {"lo": lo, "span": span}

    def chunk_state(self, arr):
        lo, span = native.minmax_span(arr)
        return {"lo": lo, "span": span}

    def encode_chunk(self, arr, state):
        payload = native.quantize_uniform8_given(arr, state["lo"], state["span"])
        return payload, {"lo": state["lo"], "span": state["span"]}

    def decode(self, payload, shape, meta):
        return native.dequantize_uniform8(
            payload, meta["lo"], meta["span"], int(np.prod(shape))
        ).reshape(shape)

    def decode_accumulate(self, payload, meta, dst):
        native.dequant_uniform8_accumulate(
            payload, meta["lo"], meta["span"], dst
        )

    def decode_into(self, payload, meta, dst):
        native.dequantize_uniform8(
            payload, meta["lo"], meta["span"], dst.size, out=dst
        )


class Quantile8BitCodec(Codec):
    """256-bucket quantile codebook quantization (hivemind
    Quantile8BitQuantization equivalent): robust to heavy-tailed grads.
    Payload layout: [256 x f32 codebook][n x u8 indices]."""

    name = "quantile8bit"

    def encode(self, arr):
        flat = np.asarray(arr, np.float32).reshape(-1)
        if flat.size == 0:
            return np.zeros(256, np.float32).tobytes(), {}
        # full encode is native: strided-sample + sort + interpolated
        # quantiles (odtp_quantile_edges), then branchless bucket assignment
        edges = native.quantile_edges(flat)
        codebook = ((edges[:-1] + edges[1:]) * 0.5).astype(np.float32)
        idx = native.quantile_assign(flat, edges[1:-1])
        return codebook.tobytes() + idx.tobytes(), {}

    def chunk_state(self, arr):
        # codebook is built over the whole part; each chunk payload carries
        # it (1 KB) so chunks stay independently decodable
        flat = np.asarray(arr, np.float32).reshape(-1)
        if flat.size == 0:
            return {
                "codebook": np.zeros(256, np.float32).tobytes(),
                "inner": np.zeros(255, np.float32),
            }
        edges = native.quantile_edges(flat)
        codebook = ((edges[:-1] + edges[1:]) * 0.5).astype(np.float32)
        return {"codebook": codebook.tobytes(), "inner": edges[1:-1]}

    def encode_chunk(self, arr, state):
        flat = np.asarray(arr, np.float32).reshape(-1)
        if flat.size == 0:
            return state["codebook"], {}
        idx = native.quantile_assign(flat, state["inner"])
        return state["codebook"] + idx.tobytes(), {}

    def decode(self, payload, shape, meta):
        codebook = np.frombuffer(payload[: 256 * 4], dtype=np.float32)
        return native.lut256_gather(
            payload[256 * 4 :], codebook, int(np.prod(shape))
        ).reshape(shape)

    def decode_accumulate(self, payload, meta, dst):
        codebook = np.frombuffer(payload[: 256 * 4], dtype=np.float32)
        native.lut256_accumulate(payload[256 * 4 :], codebook, dst)

    def decode_into(self, payload, meta, dst):
        codebook = np.frombuffer(payload[: 256 * 4], dtype=np.float32)
        native.lut256_gather(payload[256 * 4 :], codebook, dst.size, out=dst)


class Blockwise8BitCodec(Codec):
    """Per-block absmax int8 (bitsandbytes/hivemind BlockwiseQuantization
    style): one fp32 scale per 4096 values.
    Payload layout: [nblocks x f32 scales][n x i8]."""

    name = "blockwise8bit"
    # chunk boundaries on block multiples keep chunk-local blocks (and their
    # scales) identical to the whole-tensor block grid
    chunk_align = _BLOCK

    def encode(self, arr):
        arr = np.asarray(arr, np.float32).reshape(-1)
        q, scales = native.quantize_blockwise(arr, _BLOCK)
        return scales + q, {"nblocks": (arr.size + _BLOCK - 1) // _BLOCK}

    def _split(self, payload, meta):
        nb = int(meta["nblocks"])
        return payload[: nb * 4], payload[nb * 4 :]

    def decode(self, payload, shape, meta):
        scales, q = self._split(payload, meta)
        n = int(np.prod(shape))
        return native.dequantize_blockwise(q, scales, n, _BLOCK).reshape(shape)

    def decode_accumulate(self, payload, meta, dst):
        scales, q = self._split(payload, meta)
        native.dequant8_accumulate(q, scales, dst, _BLOCK)

    def decode_into(self, payload, meta, dst):
        scales, q = self._split(payload, meta)
        native.dequantize_blockwise(q, scales, dst.size, _BLOCK, out=dst)


_CODECS = {
    c.name: c
    for c in [
        Codec(),
        Float16Codec(),
        ScaledFloat16Codec(),
        Uniform8BitCodec(),
        Quantile8BitCodec(),
        Blockwise8BitCodec(),
    ]
}


def get_codec(name: str) -> Codec:
    if name not in _CODECS:
        raise ValueError(f"unknown compression {name!r}; have {sorted(_CODECS)}")
    return _CODECS[name]


def compress_roundtrip(arr: np.ndarray, codec: Codec) -> np.ndarray:
    payload, meta = codec.encode(arr)
    return codec.decode(payload, arr.shape, meta)


def device_wire_dtype(name: str) -> str | None:
    """Device-side encode hook for ``outer_placement=device``.

    Returns the dtype the device plane may pre-cast the pseudo-gradient to
    INSIDE jit so the D2H boundary copy moves wire-width bytes, or None
    when the codec offers no safe device pre-cast (full-width D2H).

    Only codecs whose host encode is idempotent under the pre-cast
    qualify: plain fp16's encode is f16(x) and f16(f32(f16(x))) == f16(x)
    bit-for-bit, so the bytes that ride the wire are unchanged vs the
    host placement. scaled-fp16 divides by a host-computed abs-max
    BEFORE its cast and the 8-bit codecs bucket full-precision values,
    so a device pre-cast would change the wire bytes on those paths.
    """
    return "float16" if name == "fp16" else None
