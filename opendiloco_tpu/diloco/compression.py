"""Wire codecs for the outer all-reduce.

Same menu as the reference's compression flag (open_diloco/utils.py:83-121,
mapping to hivemind compression classes): none / fp16 / scaled-fp16 /
uniform8bit / quantile8bit / blockwise8bit.

Design constraints:
- ``meta`` must be JSON-serializable (it rides the frame header,
  diloco/wire.py); binary side-channels (block scales, quantile codebooks)
  are prepended to the payload instead.
- Hot paths (fp16 conversion, blockwise quantization, decode+accumulate)
  dispatch to the native kernels (native/odtp_kernels.cpp) when built, with
  numpy fallbacks -- identical semantics either way.
- ``decode_accumulate`` fuses the butterfly collect step (decode + sum) into
  one pass over the buffer.
- Chunked encode (``chunk_state`` + ``encode_chunk``) splits a part into
  independently decodable chunk payloads for the pipelined data plane.
  Tensor-global codec state (scaled-fp16's abs-max, uniform8bit's lo/span,
  quantile8bit's codebook) is computed once over the whole part by
  ``chunk_state``, then reused per chunk, so the concatenated chunk decodes
  are bit-identical to the whole-tensor path — each chunk's (payload, meta)
  feeds the existing ``decode_accumulate`` / ``decode_into`` unchanged.
"""

from __future__ import annotations

import os

import numpy as np

from opendiloco_tpu import native

_BLOCK = 4096
_TOPK_DENSITY_ENV = "ODTP_TOPK_DENSITY"
_TOPK_DEFAULT_DENSITY = 0.03125  # 1/32 kept -> 0.25 B/elem on the wire


def chunk_bounds(n: int, chunk_elems: int, align: int = 1) -> list[int]:
    """Element offsets splitting an n-element part into pipeline chunks.

    Returns ``[0, c1, ..., n]``; always at least one chunk (an empty part
    yields a single empty chunk so the receiver's chunk loop still runs).
    ``align`` rounds the chunk size down to a codec's block granularity
    (blockwise8bit) so chunk payloads stay bit-identical to the whole-tensor
    encode."""
    ce = max(1, int(chunk_elems))
    if align > 1:
        ce = max(align, ce - (ce % align))
    if n <= 0:
        return [0, 0]
    return list(range(0, n, ce)) + [n]


class Codec:
    name: str = "none"
    # chunk offsets must be multiples of this many elements (blockwise8bit)
    chunk_align: int = 1
    # bulk stripe boundaries round to this many BYTES so a stripe never
    # splits one encoded wire record (f32 element here; fp16 = 2, u8 = 1,
    # topk's u32/f32 records = 4; packed nibbles are byte-granular already)
    wire_align_bytes: int = 4

    def chunk_state(self, arr: np.ndarray) -> dict:
        """Tensor-global encode state, computed once per part before the
        per-chunk ``encode_chunk`` calls. Stateless codecs return {}."""
        return {}

    def encode_chunk(self, arr: np.ndarray, state: dict) -> tuple[bytes, dict]:
        """Encode one contiguous slice of a part using the shared ``state``.

        The returned (payload, meta) must decode through the whole-tensor
        ``decode_accumulate`` / ``decode_into`` on the matching destination
        slice, and the concatenation of chunk decodes must be bit-identical
        to decoding one whole-tensor encode."""
        return self.encode(arr)

    def encode(self, arr: np.ndarray) -> tuple[bytes, dict]:
        # zero-copy when already contiguous f32: a memoryview over the array
        # buffer goes straight to the socket (the array outlives the send)
        return memoryview(np.ascontiguousarray(arr, np.float32)).cast("B"), {}

    def decode(self, payload: bytes, shape: tuple[int, ...], meta: dict) -> np.ndarray:
        # read-only view over the received payload -- every consumer either
        # reduces it into an accumulator or copies it during reassembly
        return np.frombuffer(payload, dtype=np.float32).reshape(shape)

    def decode_accumulate(
        self, payload: bytes, meta: dict, dst: np.ndarray
    ) -> None:
        """dst += decode(payload); dst is float32, shape defines layout.

        Base implementation routes through ``self.decode`` so every codec is
        correct by construction; subclasses override with fused single-pass
        kernels where they exist."""
        native.add_inplace(dst, self.decode(payload, dst.shape, meta))

    def decode_into(self, payload: bytes, meta: dict, dst: np.ndarray) -> None:
        """dst[:] = decode(payload); dst is a contiguous float32 1-D view.

        The butterfly's result-collect path decodes every gathered part
        straight into its slice of the output buffer — one native pass, no
        intermediate array, no reassembly concatenate. Base implementation
        routes through ``self.decode``; subclasses write into dst directly."""
        np.copyto(dst, self.decode(payload, dst.shape, meta))


class Float16Codec(Codec):
    name = "fp16"
    wire_align_bytes = 2

    def encode(self, arr):
        return native.f32_to_f16_bytes(arr), {}

    def decode(self, payload, shape, meta):
        return native.f16_bytes_to_f32(payload, int(np.prod(shape))).reshape(shape)

    def decode_accumulate(self, payload, meta, dst):
        native.f16_accumulate(payload, dst)

    def decode_into(self, payload, meta, dst):
        native.f16_bytes_to_f32(payload, dst.size, out=dst)


class ScaledFloat16Codec(Codec):
    """fp16 after normalizing by the tensor's abs-max (keeps outliers finite;
    hivemind ScaledFloat16Compression equivalent)."""

    name = "scaled-fp16"
    wire_align_bytes = 2

    def encode(self, arr):
        # fused single-pass absmax + divide-and-convert: the old numpy
        # pipeline (abs temp, max pass, divided temp, convert) made this
        # codec slower than plain fp16 despite identical wire bytes
        arr = np.asarray(arr, np.float32)
        scale = native.absmax(arr) if arr.size else 0.0
        scale = scale if scale > 0 else 1.0
        return native.f32_to_f16_scaled_bytes(arr, scale), {"scale": scale}

    def chunk_state(self, arr):
        arr = np.asarray(arr, np.float32)
        scale = native.absmax(arr) if arr.size else 0.0
        return {"scale": scale if scale > 0 else 1.0}

    def encode_chunk(self, arr, state):
        scale = state["scale"]
        return (
            native.f32_to_f16_scaled_bytes(np.asarray(arr, np.float32), scale),
            {"scale": scale},
        )

    def decode(self, payload, shape, meta):
        return native.f16_bytes_to_f32_scaled(
            payload, float(meta["scale"]), int(np.prod(shape))
        ).reshape(shape)

    def decode_accumulate(self, payload, meta, dst):
        native.f16_accumulate_scaled(payload, float(meta["scale"]), dst)

    def decode_into(self, payload, meta, dst):
        native.f16_bytes_to_f32_scaled(
            payload, float(meta["scale"]), dst.size, out=dst
        )


class Uniform8BitCodec(Codec):
    """Linear min/max quantization to uint8 (native single-pass kernels:
    the numpy pipeline's astype + arithmetic allocations made this codec's
    collect phases several times slower than the wire)."""

    name = "uniform8bit"
    wire_align_bytes = 1

    def encode(self, arr):
        payload, lo, span = native.quantize_uniform8(arr)
        return payload, {"lo": lo, "span": span}

    def chunk_state(self, arr):
        lo, span = native.minmax_span(arr)
        return {"lo": lo, "span": span}

    def encode_chunk(self, arr, state):
        payload = native.quantize_uniform8_given(arr, state["lo"], state["span"])
        return payload, {"lo": state["lo"], "span": state["span"]}

    def decode(self, payload, shape, meta):
        return native.dequantize_uniform8(
            payload, meta["lo"], meta["span"], int(np.prod(shape))
        ).reshape(shape)

    def decode_accumulate(self, payload, meta, dst):
        native.dequant_uniform8_accumulate(
            payload, meta["lo"], meta["span"], dst
        )

    def decode_into(self, payload, meta, dst):
        native.dequantize_uniform8(
            payload, meta["lo"], meta["span"], dst.size, out=dst
        )


class Quantile8BitCodec(Codec):
    """256-bucket quantile codebook quantization (hivemind
    Quantile8BitQuantization equivalent): robust to heavy-tailed grads.
    Payload layout: [256 x f32 codebook][n x u8 indices]."""

    name = "quantile8bit"
    wire_align_bytes = 1

    def encode(self, arr):
        flat = np.asarray(arr, np.float32).reshape(-1)
        if flat.size == 0:
            return np.zeros(256, np.float32).tobytes(), {}
        # full encode is native: strided-sample + sort + interpolated
        # quantiles (odtp_quantile_edges), then branchless bucket assignment
        edges = native.quantile_edges(flat)
        codebook = ((edges[:-1] + edges[1:]) * 0.5).astype(np.float32)
        idx = native.quantile_assign(flat, edges[1:-1])
        return codebook.tobytes() + idx.tobytes(), {}

    def chunk_state(self, arr):
        # codebook is built over the whole part; each chunk payload carries
        # it (1 KB) so chunks stay independently decodable
        flat = np.asarray(arr, np.float32).reshape(-1)
        if flat.size == 0:
            return {
                "codebook": np.zeros(256, np.float32).tobytes(),
                "inner": np.zeros(255, np.float32),
            }
        edges = native.quantile_edges(flat)
        codebook = ((edges[:-1] + edges[1:]) * 0.5).astype(np.float32)
        return {"codebook": codebook.tobytes(), "inner": edges[1:-1]}

    def encode_chunk(self, arr, state):
        flat = np.asarray(arr, np.float32).reshape(-1)
        if flat.size == 0:
            return state["codebook"], {}
        idx = native.quantile_assign(flat, state["inner"])
        return state["codebook"] + idx.tobytes(), {}

    def decode(self, payload, shape, meta):
        codebook = np.frombuffer(payload[: 256 * 4], dtype=np.float32)
        return native.lut256_gather(
            payload[256 * 4 :], codebook, int(np.prod(shape))
        ).reshape(shape)

    def decode_accumulate(self, payload, meta, dst):
        codebook = np.frombuffer(payload[: 256 * 4], dtype=np.float32)
        native.lut256_accumulate(payload[256 * 4 :], codebook, dst)

    def decode_into(self, payload, meta, dst):
        codebook = np.frombuffer(payload[: 256 * 4], dtype=np.float32)
        native.lut256_gather(payload[256 * 4 :], codebook, dst.size, out=dst)


class Blockwise8BitCodec(Codec):
    """Per-block absmax int8 (bitsandbytes/hivemind BlockwiseQuantization
    style): one fp32 scale per 4096 values.
    Payload layout: [nblocks x f32 scales][n x i8]."""

    name = "blockwise8bit"
    wire_align_bytes = 1
    # chunk boundaries on block multiples keep chunk-local blocks (and their
    # scales) identical to the whole-tensor block grid
    chunk_align = _BLOCK

    def encode(self, arr):
        arr = np.asarray(arr, np.float32).reshape(-1)
        q, scales = native.quantize_blockwise(arr, _BLOCK)
        return scales + q, {"nblocks": (arr.size + _BLOCK - 1) // _BLOCK}

    def _split(self, payload, meta):
        nb = int(meta["nblocks"])
        return payload[: nb * 4], payload[nb * 4 :]

    def decode(self, payload, shape, meta):
        scales, q = self._split(payload, meta)
        n = int(np.prod(shape))
        return native.dequantize_blockwise(q, scales, n, _BLOCK).reshape(shape)

    def decode_accumulate(self, payload, meta, dst):
        scales, q = self._split(payload, meta)
        native.dequant8_accumulate(q, scales, dst, _BLOCK)

    def decode_into(self, payload, meta, dst):
        scales, q = self._split(payload, meta)
        native.dequantize_blockwise(q, scales, dst.size, _BLOCK, out=dst)


class Blockwise4BitCodec(Codec):
    """Per-block absmax 4-bit quantization: packed nibbles with one fp16
    scale per 4096 values (0.504 B/elem, ~2x below the 8-bit codecs).
    Element 2i rides the low nibble of byte i, element 2i+1 the high
    nibble; quantization uses the fp16-ROUNDED scale so encode and decode
    agree exactly. Payload layout: [nblocks x u16 fp16-scales][ceil(n/2) x
    packed u8]."""

    name = "blockwise4bit"
    wire_align_bytes = 1
    # _BLOCK is even, so block-aligned chunk boundaries are also nibble
    # (byte) boundaries: every non-final chunk packs an even element count
    chunk_align = _BLOCK

    def encode(self, arr):
        arr = np.asarray(arr, np.float32).reshape(-1)
        q, scales = native.quantize_blockwise4(arr, _BLOCK)
        return scales + q, {"nblocks": (arr.size + _BLOCK - 1) // _BLOCK}

    def _split(self, payload, meta):
        nb = int(meta["nblocks"])
        return payload[: nb * 2], payload[nb * 2 :]

    def decode(self, payload, shape, meta):
        scales, q = self._split(payload, meta)
        n = int(np.prod(shape))
        return native.dequantize_blockwise4(q, scales, n, _BLOCK).reshape(shape)

    def decode_accumulate(self, payload, meta, dst):
        scales, q = self._split(payload, meta)
        native.dequant4_accumulate(q, scales, dst, _BLOCK)

    def decode_into(self, payload, meta, dst):
        scales, q = self._split(payload, meta)
        native.dequantize_blockwise4(q, scales, dst.size, _BLOCK, out=dst)


def topk_density() -> float:
    """Kept fraction for the topk codec, from ``ODTP_TOPK_DENSITY``
    (read lazily so tests and launch scripts can flip it)."""
    try:
        d = float(os.environ.get(_TOPK_DENSITY_ENV, _TOPK_DEFAULT_DENSITY))
    except ValueError:
        d = _TOPK_DEFAULT_DENSITY
    return min(1.0, max(d, 0.0))


class TopKCodec(Codec):
    """Per-tensor top-k magnitude sparsification: keep the k largest-|x|
    entries (k = max(1, n*density)), ship [k x u32 indices][k x f32
    values]. At the default 1/32 density that is 0.25 B/elem. Selection is
    deterministic: ties at the magnitude threshold resolve to the lowest
    indices, and the index payload is sorted ascending. Dropped mass is the
    error-feedback residual's job (config ``error_feedback``)."""

    name = "topk"
    # one wire record is (u32 index, f32 value) = 8 bytes; stripe
    # boundaries must not split a record (schema.CODEC_WIRE_GEOMETRY)
    wire_align_bytes = 8

    def _select(self, flat: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        n = flat.size
        if n == 0:
            return np.zeros(0, np.int64), np.zeros(0, np.float32)
        k = min(n, max(1, int(n * topk_density())))
        mag = np.abs(flat)
        thr = np.partition(mag, n - k)[n - k]
        idx = np.nonzero(mag > thr)[0]  # provably <= k-1 elements
        need = k - idx.size
        if need > 0:
            idx = np.concatenate([idx, np.nonzero(mag == thr)[0][:need]])
        idx.sort()
        return idx, flat[idx]

    def encode(self, arr):
        flat = np.ascontiguousarray(arr, np.float32).reshape(-1)
        idx, vals = self._select(flat)
        return (
            idx.astype(np.uint32).tobytes() + vals.tobytes(),
            {"k": int(idx.size)},
        )

    def chunk_state(self, arr):
        # top-k is a whole-tensor property: prescan selects globally, then
        # each chunk ships its slice of the selection (chunk-relative
        # indices), so the concatenated chunk decodes match the
        # whole-tensor encode exactly
        flat = np.ascontiguousarray(arr, np.float32).reshape(-1)
        idx, vals = self._select(flat)
        return {"base": flat, "idx": idx, "vals": vals}

    def encode_chunk(self, arr, state):
        chunk = np.asarray(arr)
        base = state["base"]
        off = chunk.ctypes.data - base.ctypes.data
        if (
            chunk.dtype != np.float32
            or not chunk.flags.c_contiguous
            or off < 0
            or off % 4
            or off // 4 + chunk.size > base.size
        ):
            raise ValueError(
                "topk encode_chunk needs a contiguous float32 view into the "
                "part passed to chunk_state"
            )
        lo = off // 4
        a = np.searchsorted(state["idx"], lo, side="left")
        b = np.searchsorted(state["idx"], lo + chunk.size, side="left")
        idx = (state["idx"][a:b] - lo).astype(np.uint32)
        vals = state["vals"][a:b]
        return idx.tobytes() + vals.tobytes(), {"k": int(idx.size)}

    def _split(self, payload, meta):
        k = int(meta["k"])
        return (
            np.frombuffer(payload[: k * 4], np.uint32).astype(np.int64),
            np.frombuffer(payload[k * 4 : k * 8], np.float32),
        )

    def decode(self, payload, shape, meta):
        idx, vals = self._split(payload, meta)
        out = np.zeros(int(np.prod(shape)), np.float32)
        out[idx] = vals
        return out.reshape(shape)

    def decode_accumulate(self, payload, meta, dst):
        if not dst.flags.c_contiguous or dst.dtype != np.float32:
            native.add_inplace(dst, self.decode(payload, dst.shape, meta))
            return
        idx, vals = self._split(payload, meta)
        # selected indices are unique, so fancy-index += is accumulate-safe
        dst.reshape(-1)[idx] += vals

    def decode_into(self, payload, meta, dst):
        idx, vals = self._split(payload, meta)
        dst[:] = 0.0
        dst[idx] = vals


_CODECS = {
    c.name: c
    for c in [
        Codec(),
        Float16Codec(),
        ScaledFloat16Codec(),
        Uniform8BitCodec(),
        Quantile8BitCodec(),
        Blockwise8BitCodec(),
        Blockwise4BitCodec(),
        TopKCodec(),
    ]
}

# running per-codec (raw, wire) byte totals; feeds the obs counters and the
# bench HEALTH line so wire savings are measurable per codec
_WIRE_TOTALS: dict[str, list[float]] = {}


def record_wire(name: str, raw_nbytes: int, wire_nbytes: int) -> None:
    """Account one encoded payload: per-codec wire/raw byte counters plus a
    running compression-ratio gauge. No-op-cheap when obs is disabled."""
    tot = _WIRE_TOTALS.setdefault(name, [0.0, 0.0])
    tot[0] += raw_nbytes
    tot[1] += wire_nbytes
    from opendiloco_tpu import obs  # deferred: obs is an optional plane

    tr = obs.tracer()
    if tr is None:
        return
    tr.count("outer_raw_bytes", raw_nbytes, codec=name)
    tr.count("outer_wire_bytes", wire_nbytes, codec=name)
    if tot[1] > 0:
        tr.gauge("outer_compression_ratio", tot[0] / tot[1], codec=name)


def get_codec(name: str) -> Codec:
    if name not in _CODECS:
        raise ValueError(f"unknown compression {name!r}; have {sorted(_CODECS)}")
    return _CODECS[name]


def compress_roundtrip(arr: np.ndarray, codec: Codec) -> np.ndarray:
    payload, meta = codec.encode(arr)
    return codec.decode(payload, arr.shape, meta)


def pack_blockwise4_stacked(arr: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Pack a stacked [L, ...] weight into the serve plane's 4-bit-resident
    layout: per LAYER blockwise-4bit quantization with the codec's exact
    geometry (``_BLOCK`` absmax blocks, packed nibbles, fp16-rounded
    scales, via the native kernels / bit-identical fallbacks).

    Returns (q [L, ceil(n/2)] uint8, scales [L, nblocks] uint16) with
    n = per-layer element count — stackable leaves, so the packed weight
    rides the decode layer scan and dequantizes per block inside the jit
    (``models.llama.dequant_w4``). Per-layer blocks rather than the wire
    codec's whole-leaf blocks: the two grids coincide exactly when n is
    a multiple of ``_BLOCK`` (see :func:`split_blockwise4_stacked`)."""
    a = np.ascontiguousarray(arr, np.float32)
    L = a.shape[0]
    n = int(a[0].size)
    nb = (n + _BLOCK - 1) // _BLOCK
    q = np.empty((L, (n + 1) // 2), np.uint8)
    s = np.empty((L, nb), np.uint16)
    for i in range(L):
        qb, sb = native.quantize_blockwise4(a[i].reshape(-1), _BLOCK)
        q[i] = np.frombuffer(qb, np.uint8)
        s[i] = np.frombuffer(sb, np.uint16)
    return q, s


def split_blockwise4_stacked(
    payload: bytes, meta: dict, L: int, n_layer: int
) -> tuple[np.ndarray, np.ndarray] | None:
    """Re-slice a whole-leaf ``blockwise4bit`` wire payload into the
    per-layer stacked layout of :func:`pack_blockwise4_stacked` WITHOUT a
    dequantize/requantize round trip — the cheap hot-swap install for
    w4-resident serving. Only exact when the wire codec's block grid
    lands on layer boundaries (n_layer % _BLOCK == 0, which also makes
    the nibble packing byte-aligned per layer); returns None otherwise
    and the caller takes the decode-then-repack path."""
    if n_layer <= 0 or n_layer % _BLOCK:
        return None
    nb_total = int(meta["nblocks"])
    scales, q = payload[: nb_total * 2], payload[nb_total * 2 :]
    if len(q) != (L * n_layer + 1) // 2 or nb_total != L * (n_layer // _BLOCK):
        return None
    qa = np.frombuffer(q, np.uint8).reshape(L, n_layer // 2)
    sa = np.frombuffer(scales, np.uint16).reshape(L, n_layer // _BLOCK)
    return qa.copy(), sa.copy()


def device_wire_dtype(name: str) -> str | None:
    """Device-side encode hook for ``outer_placement=device``.

    Returns the dtype the device plane may pre-cast the pseudo-gradient to
    INSIDE jit so the D2H boundary copy moves wire-width bytes, or None
    when the codec offers no safe device pre-cast (full-width D2H).

    Only codecs whose host encode is idempotent under the pre-cast
    qualify: plain fp16's encode is f16(x) and f16(f32(f16(x))) == f16(x)
    bit-for-bit, so the bytes that ride the wire are unchanged vs the
    host placement. scaled-fp16 divides by a host-computed abs-max
    BEFORE its cast and the 8-bit codecs bucket full-precision values,
    so a device pre-cast would change the wire bytes on those paths.
    """
    return "float16" if name == "fp16" else None
