"""In-process loopback backend: N worker threads, one shared world.

The testing analogue of the reference's loopback DHT swarm
(tests/test_diloco_hivemind.py:42-50) -- but deterministic and socket-free,
which the reference explicitly lacks (its straggler test is skipped as flaky,
test_diloco_hivemind.py:154-156). The whole DiLoCo algorithm runs against
this backend on CPU, making outer-loop logic unit-testable.

Elastic semantics match the production backend: a round completes when every
*live* peer has contributed; a peer that closes (drops) no longer blocks the
group, and the returned group size is the number of actual contributions --
so peer-drop detection (optimizer.py) is exercisable in tests.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional

import numpy as np

from opendiloco_tpu import obs
from opendiloco_tpu.diloco import chaos
from opendiloco_tpu.diloco.backend import (
    AllReduceError,
    OuterBackend,
    PeerProgress,
)
from opendiloco_tpu.diloco.compression import Codec, get_codec, record_wire


class LoopbackWorld:
    """Shared state for an in-process swarm with elastic membership."""

    def __init__(self, n_peers: int, compression: str = "none"):
        self.n_peers = n_peers
        self.codec: Codec = get_codec(compression)
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)
        self.progress: dict[str, PeerProgress] = {}
        self.state_provider: Optional[Callable[[], dict[str, Any]]] = None
        self.live: set[str] = set()
        # all-reduce round state, keyed by round key (f"{tag}-epoch-{epoch}").
        # Keyed slots are what let streaming fragment sync run several
        # tagged rounds CONCURRENTLY through one world; each slot carries
        # its own generation counter because keys legitimately repeat
        # (tag "state" resolves epoch from the peer's own progress, which
        # stays put across back-to-back state-averaging rounds).
        self._rounds: dict[str, dict] = {}
        # gossip round state: round_key -> {"_partition": [...], chunk: {...}}
        self._gossip: dict = {}
        # pair-exchange mailboxes: round_key -> {peer_id: (meta, payload)}
        # (NoLoCo gossip, diloco/gossip.py); "_taken" tracks pickup for GC
        self._pairbox: dict[str, dict] = {}
        # async-gossip offer board: frag_id -> {peer_id: offer}; an offer
        # is claimed ATOMICALLY under this lock (claimer pops it and sets
        # its "result"), so two claimers can never grab the same partner
        self._offers: dict[int, dict[str, dict]] = {}
        self._async_seq = 0  # match-key nonce (repeat matches never collide)

    def make_backends(self) -> list["LoopbackBackend"]:
        return [LoopbackBackend(self, f"peer-{i}") for i in range(self.n_peers)]


class LoopbackBackend(OuterBackend):
    def __init__(self, world: LoopbackWorld, peer_id: str):
        self.world = world
        self._peer_id = peer_id
        # round health ledger, same shape as TcpBackend's: loopback is the
        # oracle the chaos tests hold the TCP rescaling math against
        self.round_ledger: list[dict] = []
        self.last_round_health: dict = {}
        with world.lock:
            world.live.add(peer_id)

    def _chaos_gate(self) -> None:
        """Chaos hooks for the in-process backend: straggler/latency sleeps,
        plus transient contribution failures retried with the same bounded
        backoff the TCP round retry uses. Zero-cost when ODTP_CHAOS unset."""
        cp = chaos.plane()
        if cp is None:
            return
        d = cp.straggle_s() + cp.delay_s("loopback")
        if d:
            time.sleep(d)
        attempt = 0
        while cp.drop_conn("loopback"):
            time.sleep(min(chaos.backoff_s(attempt), 1.0))
            attempt += 1

    def _record_round_health(self, tag, epoch, group: int) -> None:
        expected = self.world.n_peers
        health = {
            "round": f"{tag}-epoch-{epoch}",
            "group_size": group,
            "expected": expected,
            "elastic": bool(group < expected),
            "retries": 0,
        }
        self.last_round_health = health
        self.round_ledger.append(health)
        if len(self.round_ledger) > 256:
            del self.round_ledger[:-256]
        tr = obs.tracer()
        if tr is not None:
            tr.instant("outer/round", worker=self._peer_id, **health)
            tr.count("outer_rounds")
            if health["elastic"]:
                tr.count("outer_rounds_elastic")

    @property
    def peer_id(self) -> str:
        return self._peer_id

    def num_peers(self) -> int:
        with self.world.lock:
            return len(self.world.live)

    def gossip_view(self):
        with self.world.lock:
            return sorted(self.world.live), None

    def pair_exchange(self, payload, meta, *, partner_id, round_key,
                      timeout=None):
        """Symmetric push-pull through a keyed in-world mailbox: deposit
        own frame, wait for the partner's. Partner close() mid-round (or a
        divergent pairing putting the partner on a different key) resolves
        as AllReduceError — the gossip plane's dropped-round non-event."""
        self._chaos_gate()
        w = self.world
        deadline = time.monotonic() + (timeout if timeout else 60.0)
        with w.cond:
            slot = w._pairbox.setdefault(round_key, {"_taken": set()})
            slot[self._peer_id] = (dict(meta), bytes(payload))
            w.cond.notify_all()
            while partner_id not in slot:
                if partner_id not in w.live:
                    slot.pop(self._peer_id, None)
                    self._pairbox_gc(round_key)
                    raise AllReduceError(
                        f"gossip partner {partner_id} left mid-round "
                        f"({round_key})"
                    )
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    slot.pop(self._peer_id, None)
                    self._pairbox_gc(round_key)
                    raise AllReduceError(
                        f"gossip pair round {round_key} timed out waiting "
                        f"for {partner_id}"
                    )
                w.cond.wait(timeout=min(remaining, 0.1))
            p_meta, p_payload = slot[partner_id]
            slot["_taken"].add(self._peer_id)
            self._pairbox_gc(round_key)
        return p_meta, p_payload

    def async_pair_match(self, *, frag_id, epoch, window, patience=None):
        """Bounded-staleness matchmaking through the in-world offer board.

        Claim the closest-epoch standing offer within ``window`` if one
        exists (deterministic tie-break by peer id); otherwise post our
        own offer and wait up to ``patience`` to be claimed. The claimer
        mints the match key, so both sides leave with the identical key
        and the transfer rides the ordinary ``pair_exchange`` mailbox.
        """
        w = self.world
        deadline = time.monotonic() + (patience if patience else 5.0)
        with w.cond:
            board = w._offers.setdefault(int(frag_id), {})
            cands = sorted(
                (abs(int(epoch) - o["epoch"]), pid)
                for pid, o in board.items()
                if pid != self._peer_id and o["result"] is None
                and pid in w.live
                and abs(int(epoch) - o["epoch"]) <= int(window)
            )
            if cands:
                _, pid = cands[0]
                other = board.pop(pid)
                w._async_seq += 1
                lo, hi = sorted((self._peer_id, pid))
                match_key = (
                    f"async-f{int(frag_id)}:{lo}|{hi}:{w._async_seq}"
                )
                other["result"] = (self._peer_id, int(epoch), match_key)
                w.cond.notify_all()
                return pid, other["epoch"], match_key
            offer: dict = {"epoch": int(epoch), "result": None}
            board[self._peer_id] = offer
            w.cond.notify_all()
            while offer["result"] is None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                w.cond.wait(timeout=min(remaining, 0.05))
            # withdraw if still standing (a claimer pops matched offers)
            if board.get(self._peer_id) is offer:
                board.pop(self._peer_id, None)
            return offer["result"]

    def _pairbox_gc(self, round_key: str) -> None:
        """Under world.lock: drop a fully-consumed (or abandoned) slot and
        cap the box so dropped rounds' deposits cannot accumulate."""
        box = self.world._pairbox
        slot = box.get(round_key)
        if slot is not None:
            deposited = set(slot) - {"_taken"}
            if not deposited or deposited <= slot["_taken"]:
                box.pop(round_key, None)
        while len(box) > 256:
            box.pop(next(iter(box)))

    def all_reduce(self, arrays, *, timeout=None, tag="grads", epoch=None, group_cap=0):
        """Average across live peers. The round completes when every live
        peer has contributed; dropped peers stop blocking the group the
        moment they close(). Lossy codecs are applied to each contribution
        to model wire compression faithfully. ``group_cap`` partitions the
        live peers into deterministic per-round groups (gossip mode)."""
        self._chaos_gate()
        # TcpBackend key parity: epoch=None resolves to this peer's own
        # reported epoch (default 0). Rounds are KEYED now — a raw None in
        # the key would split a round between callers that pass the epoch
        # explicitly (the optimizer) and ones that don't (state averaging,
        # tests), where the old single-slot world happily mixed them.
        if epoch is None:
            with self.world.lock:
                own = self.world.progress.get(self._peer_id)
            epoch = own.epoch if own else 0
        if group_cap:
            out, n = self._group_reduce(arrays, tag, epoch, group_cap, timeout)
            self._record_round_health(tag, epoch, n)
            return out, n
        w = self.world
        codec = w.codec
        # per-worker stage spans mirror the TCP taxonomy: encode (codec
        # roundtrip), reduce_wait (park until the round mean publishes),
        # adopt (copy the published result)
        tr = obs.tracer()
        round_key = f"{tag}-epoch-{epoch}"
        t0 = time.perf_counter() if tr is not None else 0.0
        compressed = [
            codec.decode(*_enc(codec, a)) for a in arrays
        ]  # simulate wire roundtrip
        if tr is not None:
            tr.add_span(
                "outer/encode", t0, time.perf_counter(),
                worker=self._peer_id, round=round_key,
            )
        deadline = time.monotonic() + (timeout or 3600.0)
        t_wait = time.perf_counter() if tr is not None else 0.0
        with w.cond:
            slot = w._rounds.setdefault(
                round_key,
                {
                    "round": 0,
                    "contrib": {},
                    "result": None,
                    "result_group": 0,
                    "result_round": -1,
                    "pending": set(),
                },
            )
            my_round = slot["round"]
            slot["contrib"][self._peer_id] = compressed
            w.cond.notify_all()
            while slot["result_round"] < my_round:
                if set(slot["contrib"]) >= w.live and slot["contrib"]:
                    # complete: first thread to notice publishes the mean
                    contribs = list(slot["contrib"].values())
                    n = len(contribs)
                    slot["result"] = [
                        np.sum([c[i] for c in contribs], axis=0) / n
                        for i in range(len(arrays))
                    ]
                    slot["result_group"] = n
                    slot["result_round"] = my_round
                    slot["round"] += 1
                    # collectors of this generation (slot GC: the key's
                    # state is dropped once every contributor -- or its
                    # survivor set, if some died -- has copied the result)
                    slot["pending"] = set(slot["contrib"])
                    slot["contrib"] = {}
                    w.cond.notify_all()
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    # give up: retract our contribution so a later round
                    # doesn't count a stale tensor from a dead peer
                    slot["contrib"].pop(self._peer_id, None)
                    w.cond.notify_all()
                    raise AllReduceError(f"{self._peer_id}: all-reduce timed out")
                w.cond.wait(timeout=min(remaining, 0.1))
            if tr is not None:
                tr.add_span(
                    "outer/reduce_wait", t_wait, time.perf_counter(),
                    worker=self._peer_id, round=round_key,
                )
            t_adopt = time.perf_counter() if tr is not None else 0.0
            result = [a.copy() for a in slot["result"]]
            group = slot["result_group"]
            # GC: keys repeat across epochs (and tags multiply with
            # streaming fragments) -- drop the slot once every live
            # contributor has collected and no next generation has begun
            slot["pending"] = {
                p for p in slot["pending"]
                if p != self._peer_id and p in w.live
            }
            if not slot["pending"] and not slot["contrib"]:
                w._rounds.pop(round_key, None)
        if tr is not None:
            tr.add_span(
                "outer/adopt", t_adopt, time.perf_counter(),
                worker=self._peer_id, round=round_key,
            )
        self._record_round_health(tag, epoch, group)
        return result, group

    def _group_reduce(self, arrays, tag, epoch, cap, timeout):
        """Partition live peers into per-round groups of <= cap and average
        within the group only (mirrors the rendezvous daemon's capped
        matchmaking). The FIRST arriver freezes the partition for the round
        so later joiners and membership churn can't split the groups."""
        import random

        w = self.world
        codec = w.codec
        key = f"{tag}-epoch-{epoch}"
        compressed = [codec.decode(*_enc(codec, a)) for a in arrays]
        deadline = time.monotonic() + (timeout or 3600.0)
        with w.cond:
            round_state = w._gossip.setdefault(key, {})
            if "_partition" not in round_state:
                members = sorted(w.live)
                random.Random(key).shuffle(members)
                round_state["_partition"] = [
                    tuple(sorted(members[i : i + cap]))
                    for i in range(0, len(members), cap)
                ]
            group = next(
                (g for g in round_state["_partition"] if self._peer_id in g), None
            )
            if group is None:
                # the partition was frozen before we were live: behave like
                # the TCP client's "group does not contain self" retry path
                raise AllReduceError(f"{self._peer_id}: not in gossip partition")
            slot = round_state.setdefault(group, {"contrib": {}, "done": set()})
            slot["contrib"][self._peer_id] = compressed
            w.cond.notify_all()
            while True:
                live_members = [
                    m for m in group if m in w.live or m in slot["contrib"]
                ]
                if set(slot["contrib"]) >= set(live_members):
                    contribs = [slot["contrib"][m] for m in live_members]
                    n = len(contribs)
                    result = [
                        np.sum([c[i] for c in contribs], axis=0) / n
                        for i in range(len(arrays))
                    ]
                    slot["done"].add(self._peer_id)
                    if slot["done"] >= set(live_members):
                        round_state.pop(group, None)
                        if not any(
                            isinstance(k, tuple) for k in round_state
                        ):
                            w._gossip.pop(key, None)
                    return [a.copy() for a in result], n
                if time.monotonic() >= deadline:
                    slot["contrib"].pop(self._peer_id, None)
                    w.cond.notify_all()
                    raise AllReduceError(f"{self._peer_id}: gossip round timed out")
                w.cond.wait(timeout=0.1)

    def report_progress(self, progress: PeerProgress) -> None:
        with self.world.lock:
            self.world.progress[progress.peer_id] = progress

    def peer_progress(self) -> list[PeerProgress]:
        with self.world.lock:
            live = self.world.live
            return [p for pid, p in self.world.progress.items() if pid in live]

    def fetch_state(self):
        with self.world.lock:
            provider = self.world.state_provider
        return provider() if provider else None

    def serve_state(self, get_state) -> None:
        with self.world.lock:
            self.world.state_provider = get_state

    def close(self) -> None:
        """Drop out of the swarm: stop blocking in-flight rounds."""
        with self.world.cond:
            self.world.live.discard(self._peer_id)
            self.world.progress.pop(self._peer_id, None)
            self.world.cond.notify_all()


def _enc(codec: Codec, a: np.ndarray):
    payload, meta = codec.encode(a)
    record_wire(codec.name, a.size * 4, len(payload))
    return payload, a.shape, meta
