"""In-process loopback backend: N worker threads, one shared world.

The testing analogue of the reference's loopback DHT swarm
(tests/test_diloco_hivemind.py:42-50) -- but deterministic and socket-free,
which the reference explicitly lacks (its straggler test is skipped as flaky,
test_diloco_hivemind.py:154-156). The whole DiLoCo algorithm runs against
this backend on CPU, making outer-loop logic unit-testable.

Elastic semantics match the production backend: a round completes when every
*live* peer has contributed; a peer that closes (drops) no longer blocks the
group, and the returned group size is the number of actual contributions --
so peer-drop detection (optimizer.py) is exercisable in tests.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional

import numpy as np

from opendiloco_tpu.diloco.backend import (
    AllReduceError,
    OuterBackend,
    PeerProgress,
)
from opendiloco_tpu.diloco.compression import Codec, get_codec


class LoopbackWorld:
    """Shared state for an in-process swarm with elastic membership."""

    def __init__(self, n_peers: int, compression: str = "none"):
        self.n_peers = n_peers
        self.codec: Codec = get_codec(compression)
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)
        self.progress: dict[str, PeerProgress] = {}
        self.state_provider: Optional[Callable[[], dict[str, Any]]] = None
        self.live: set[str] = set()
        # all-reduce round state
        self._round = 0
        self._contrib: dict[str, list[np.ndarray]] = {}
        self._result: Optional[list[np.ndarray]] = None
        self._result_group = 0
        self._result_round = -1

    def make_backends(self) -> list["LoopbackBackend"]:
        return [LoopbackBackend(self, f"peer-{i}") for i in range(self.n_peers)]


class LoopbackBackend(OuterBackend):
    def __init__(self, world: LoopbackWorld, peer_id: str):
        self.world = world
        self._peer_id = peer_id
        with world.lock:
            world.live.add(peer_id)

    @property
    def peer_id(self) -> str:
        return self._peer_id

    def num_peers(self) -> int:
        with self.world.lock:
            return len(self.world.live)

    def all_reduce(self, arrays, *, timeout=None, tag="grads", epoch=None):
        """Average across live peers. The round completes when every live
        peer has contributed; dropped peers stop blocking the group the
        moment they close(). Lossy codecs are applied to each contribution
        to model wire compression faithfully."""
        w = self.world
        codec = w.codec
        compressed = [
            codec.decode(*_enc(codec, a)) for a in arrays
        ]  # simulate wire roundtrip
        deadline = time.monotonic() + (timeout or 3600.0)
        with w.cond:
            my_round = w._round
            w._contrib[self._peer_id] = compressed
            w.cond.notify_all()
            while w._result_round < my_round:
                if set(w._contrib) >= w.live and w._contrib:
                    # complete: first thread to notice publishes the mean
                    contribs = list(w._contrib.values())
                    n = len(contribs)
                    w._result = [
                        np.sum([c[i] for c in contribs], axis=0) / n
                        for i in range(len(arrays))
                    ]
                    w._result_group = n
                    w._result_round = my_round
                    w._round += 1
                    w._contrib = {}
                    w.cond.notify_all()
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    # give up: retract our contribution so a later round
                    # doesn't count a stale tensor from a dead peer
                    w._contrib.pop(self._peer_id, None)
                    w.cond.notify_all()
                    raise AllReduceError(f"{self._peer_id}: all-reduce timed out")
                w.cond.wait(timeout=min(remaining, 0.1))
            result = [a.copy() for a in w._result]
            group = w._result_group
        return result, group

    def report_progress(self, progress: PeerProgress) -> None:
        with self.world.lock:
            self.world.progress[progress.peer_id] = progress

    def peer_progress(self) -> list[PeerProgress]:
        with self.world.lock:
            live = self.world.live
            return [p for pid, p in self.world.progress.items() if pid in live]

    def fetch_state(self):
        with self.world.lock:
            provider = self.world.state_provider
        return provider() if provider else None

    def serve_state(self, get_state) -> None:
        with self.world.lock:
            self.world.state_provider = get_state

    def close(self) -> None:
        """Drop out of the swarm: stop blocking in-flight rounds."""
        with self.world.cond:
            self.world.live.discard(self._peer_id)
            self.world.progress.pop(self._peer_id, None)
            self.world.cond.notify_all()


def _enc(codec: Codec, a: np.ndarray):
    payload, meta = codec.encode(a)
    return payload, a.shape, meta
