"""Host-side outer optimizer: Nesterov-momentum SGD on the master pytree.

Numerically matches torch.optim.SGD(lr=0.7, momentum=0.9, nesterov=True) --
the reference's outer optimizer (open_diloco/train_fsdp.py:253) -- since the
DiLoCo convergence results depend on its exact update rule:

    buf   = momentum * buf + grad
    d     = grad + momentum * buf        (nesterov)  |  d = buf (plain)
    param = param - lr * d

Runs in numpy on host RAM: the master copy never touches the TPU (the
equivalent of hivemind's offload_optimizer, hivemind_diloco.py:399-400).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from opendiloco_tpu import native


class OuterSGD:
    def __init__(
        self,
        lr: float = 0.7,
        momentum: float = 0.9,
        nesterov: bool = True,
    ):
        self.lr = lr
        self.momentum = momentum
        self.nesterov = nesterov
        self.bufs: Optional[list[np.ndarray]] = None

    def step(self, params: list[np.ndarray], grads: list[np.ndarray]) -> None:
        """In-place update of ``params`` given pseudo-gradients ``grads``."""
        self.step_indices(params, grads, range(len(params)))

    def step_indices(
        self,
        params: list[np.ndarray],
        grads: list[np.ndarray],
        idxs,
    ) -> None:
        """In-place update of ``params[i] for i in idxs`` given pseudo-
        gradients aligned to ``idxs``. The ONE copy of the numerically
        load-bearing SGD rule: the full-sync ``step`` delegates here with
        all indices; the streaming-fragment outer step passes its
        fragment (each fragment runs the same rule on its own staggered
        clock; untouched leaves keep their momentum frozen)."""
        if self.momentum == 0.0:
            for j, i in enumerate(idxs):
                params[i] -= self.lr * grads[j]
            return
        if self.bufs is None:
            self.bufs = [np.zeros_like(p) for p in params]
        for j, i in enumerate(idxs):
            p, g, buf = params[i], grads[j], self.bufs[i]
            # fused OMP kernel: one pass over (p, g, buf) instead of the
            # numpy body's two allocated temporaries (d and momentum*buf)
            if native.outer_sgd_step(
                p, g, buf, self.lr, self.momentum, self.nesterov
            ):
                continue
            np.multiply(buf, self.momentum, out=buf)
            buf += g
            if self.nesterov:
                d = g + self.momentum * buf
            else:
                d = buf
            p -= self.lr * d

    def step_mixed_indices(
        self,
        params: list[np.ndarray],
        mix_m: list[np.ndarray],
        mix_b: Optional[list[np.ndarray]],
        grads: list[np.ndarray],
        idxs,
    ) -> None:
        """NoLoCo modified-Nesterov step (arXiv 2506.10911) on a fragment:
        adopt the pair-MIXED master and momentum for ``idxs``, then run the
        unchanged Nesterov rule with the pair-averaged pseudo-gradient.
        Expressing the correction as a plain step on mixed state keeps the
        ONE copy of the update rule (``step_indices``) authoritative."""
        for j, i in enumerate(idxs):
            params[i] = np.asarray(mix_m[j], np.float32)
        if self.momentum != 0.0:
            if self.bufs is None:
                self.bufs = [np.zeros_like(p) for p in params]
            if mix_b is not None:
                for j, i in enumerate(idxs):
                    self.bufs[i] = np.asarray(mix_b[j], np.float32)
        self.step_indices(params, grads, idxs)

    def clone(self) -> "OuterSGD":
        """Deep copy (one buf copy, not the two of state_dict+load).
        Enables the copy-on-write discipline in DiLoCoOptimizer: step the
        clone, then rebind, so published buf arrays stay bit-stable."""
        new = OuterSGD(lr=self.lr, momentum=self.momentum, nesterov=self.nesterov)
        new.bufs = None if self.bufs is None else [b.copy() for b in self.bufs]
        return new

    def state_dict_refs(self) -> dict:
        """state_dict without the buf copies — arrays are shared with the
        live optimizer. Only safe while every mutation path rebinds rather
        than writing published arrays in place."""
        return {
            "lr": self.lr,
            "momentum": self.momentum,
            "nesterov": self.nesterov,
            "bufs": self.bufs,
        }

    def state_dict(self) -> dict:
        sd = self.state_dict_refs()
        sd["bufs"] = None if self.bufs is None else [b.copy() for b in self.bufs]
        return sd

    def load_state_dict(self, state: dict) -> None:
        self.lr = state["lr"]
        self.momentum = state["momentum"]
        self.nesterov = state["nesterov"]
        bufs = state["bufs"]
        self.bufs = None if bufs is None else [np.asarray(b).copy() for b in bufs]


def staleness_weight(distance: int, decay: float = 0.5) -> float:
    """Partner mixing weight under bounded-staleness async gossip.

    ``0.5 * decay**d`` for epoch distance ``d``: exactly the symmetric
    pair average at distance 0, geometrically discounting a staler
    partner's contribution. Both sides of a match compute the same
    ``d`` (the epochs ride the match handshake), so the mix stays
    mean-preserving: A' + B' = (1-w)A + wB + (1-w)B + wA = A + B.
    """
    return 0.5 * float(decay) ** max(0, int(distance))


def staleness_mix(
    mine: list[np.ndarray],
    theirs: list[np.ndarray],
    weight: float,
) -> list[np.ndarray]:
    """Convex per-leaf mix ``(1-w)*mine + w*theirs`` (fresh f32 arrays).

    The async analogue of gossip's ``_avg_sorted``; callers route the
    distance-0 case through the sorted-pair average instead so the
    in-window fast path stays bit-identical to the lockstep mix.
    """
    w = np.float32(weight)
    one_m_w = np.float32(1.0) - w
    return [
        np.asarray(a, np.float32) * one_m_w + np.asarray(b, np.float32) * w
        for a, b in zip(mine, theirs)
    ]


def noloco_step(
    mix_m: list[np.ndarray],
    mix_b: Optional[list[np.ndarray]],
    avg_g: list[np.ndarray],
    *,
    lr: float,
    momentum: float,
    nesterov: bool,
) -> tuple[list[np.ndarray], Optional[list[np.ndarray]]]:
    """Functional NoLoCo outer step: run the Nesterov rule on pair-mixed
    (master, momentum) with the pair-averaged pseudo-gradient, returning
    fresh ``(new_masters, new_bufs)`` without touching the inputs. The
    streaming gossip path lands through this (comm thread computes the
    result; the landing thread adopts it into the live optimizer)."""
    opt = OuterSGD(lr=lr, momentum=momentum, nesterov=nesterov)
    params = [np.array(m, np.float32) for m in mix_m]
    if momentum != 0.0:
        if mix_b is None:
            opt.bufs = [np.zeros_like(p) for p in params]
        else:
            opt.bufs = [np.array(b, np.float32) for b in mix_b]
    grads = [np.ascontiguousarray(np.asarray(g, np.float32)) for g in avg_g]
    opt.step(params, grads)
    return params, opt.bufs
