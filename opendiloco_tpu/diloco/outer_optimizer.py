"""Host-side outer optimizer: Nesterov-momentum SGD on the master pytree.

Numerically matches torch.optim.SGD(lr=0.7, momentum=0.9, nesterov=True) --
the reference's outer optimizer (open_diloco/train_fsdp.py:253) -- since the
DiLoCo convergence results depend on its exact update rule:

    buf   = momentum * buf + grad
    d     = grad + momentum * buf        (nesterov)  |  d = buf (plain)
    param = param - lr * d

Runs in numpy on host RAM: the master copy never touches the TPU (the
equivalent of hivemind's offload_optimizer, hivemind_diloco.py:399-400).
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class OuterSGD:
    def __init__(
        self,
        lr: float = 0.7,
        momentum: float = 0.9,
        nesterov: bool = True,
    ):
        self.lr = lr
        self.momentum = momentum
        self.nesterov = nesterov
        self.bufs: Optional[list[np.ndarray]] = None

    def step(self, params: list[np.ndarray], grads: list[np.ndarray]) -> None:
        """In-place update of ``params`` given pseudo-gradients ``grads``."""
        if self.momentum == 0.0:
            for p, g in zip(params, grads):
                p -= self.lr * g
            return
        if self.bufs is None:
            self.bufs = [np.zeros_like(p) for p in params]
        for p, g, buf in zip(params, grads, self.bufs):
            np.multiply(buf, self.momentum, out=buf)
            buf += g
            if self.nesterov:
                d = g + self.momentum * buf
            else:
                d = buf
            p -= self.lr * d

    def clone(self) -> "OuterSGD":
        """Deep copy (one buf copy, not the two of state_dict+load).
        Enables the copy-on-write discipline in DiLoCoOptimizer: step the
        clone, then rebind, so published buf arrays stay bit-stable."""
        new = OuterSGD(lr=self.lr, momentum=self.momentum, nesterov=self.nesterov)
        new.bufs = None if self.bufs is None else [b.copy() for b in self.bufs]
        return new

    def state_dict_refs(self) -> dict:
        """state_dict without the buf copies — arrays are shared with the
        live optimizer. Only safe while every mutation path rebinds rather
        than writing published arrays in place."""
        return {
            "lr": self.lr,
            "momentum": self.momentum,
            "nesterov": self.nesterov,
            "bufs": self.bufs,
        }

    def state_dict(self) -> dict:
        sd = self.state_dict_refs()
        sd["bufs"] = None if self.bufs is None else [b.copy() for b in self.bufs]
        return sd

    def load_state_dict(self, state: dict) -> None:
        self.lr = state["lr"]
        self.momentum = state["momentum"]
        self.nesterov = state["nesterov"]
        bufs = state["bufs"]
        self.bufs = None if bufs is None else [np.asarray(b).copy() for b in bufs]
