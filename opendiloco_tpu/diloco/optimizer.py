"""DiLoCoOptimizer: the algorithm orchestrator.

TPU-native re-design of the reference's ``DiLoCoOptimizer``
(open_diloco/hivemind_diloco.py:303-738) with the normative update rule of
the pure-torch driver (open_diloco/train_diloco_torch.py:336-353):

  every step:        inner AdamW step on device (jit, sharded)
  every local_steps: pseudo_grad = master - device_params        [D2H]
                     averaged    = backend.all_reduce(pseudo_grad)  [DCN]
                     outer Nesterov SGD updates host master
                     device_params <- master                     [H2D]

The master copy lives in host RAM as float32 numpy (the equivalent of
hivemind's CPU-offloaded outer optimizer, hivemind_diloco.py:399-400,
158-167). The inner jit step never changes shape/sharding across the outer
boundary, so the 500-step inner phases never recompile.
"""

from __future__ import annotations

import concurrent.futures
import functools
import threading
import time
from typing import TYPE_CHECKING, Any, Optional

import jax
import numpy as np

from opendiloco_tpu import native, obs
from opendiloco_tpu.config import DilocoConfig
from opendiloco_tpu.diloco import planner
from opendiloco_tpu.diloco.backend import OuterBackend, PeerProgress, wait_for_peers
from opendiloco_tpu.diloco.compression import get_codec
from opendiloco_tpu.diloco.error_feedback import ErrorFeedback
from opendiloco_tpu.diloco.gossip import GossipPlane
from opendiloco_tpu.diloco.outer_device import DeviceOuterPlane
from opendiloco_tpu.diloco.outer_optimizer import OuterSGD, noloco_step
from opendiloco_tpu.diloco.streaming import StreamScheduler
from opendiloco_tpu.parallel.world import HostWorld

if TYPE_CHECKING:  # annotation-only: a module-level import would close the
    # trainer -> obs -> diloco.schema -> diloco.optimizer -> trainer cycle
    from opendiloco_tpu.trainer import InnerTrainer
from opendiloco_tpu.utils.debug import schema_fingerprint
from opendiloco_tpu.utils.logger import get_text_logger

log = get_text_logger(__name__)

@functools.partial(jax.jit, donate_argnums=(0,))
def _frag_add(cur, delta):
    """params += delta over one fragment's leaves (streaming landing/
    launch). The old param buffers are donated — the caller rebinds the
    fragment entries to the fresh outputs, so they are dead either way."""
    return [a + b for a, b in zip(cur, delta)]


# join-keepalive cadence: must beat the rendezvous registration TTL (60 s
# default in both daemons) so a worker stuck in its first multi-minute XLA
# compile is never reaped as dead before taking a step
_ANNOUNCE_INTERVAL_S = 15.0


class PeerDropError(RuntimeError):
    """Raised when a DiLoCo worker disappears and fail_rank_drop is set
    (reference: train_fsdp.py:452-457)."""


def resolve_outer_placement(cfg: DilocoConfig, trainer, world) -> str:
    """Resolve ``outer_placement`` to 'host' or 'device'.

    'auto' picks device on TPU meshes (the master fits HBM there; the host
    offload is a GPU-memory artifact of the reference) and host elsewhere.
    Device placement requires single-process meshes (the plane is not
    collective-aware) — multihost falls back to host with a warning
    rather than failing the run. Gossip composes: a pair round fetches
    only its fragment's leaves (host_frag) and lands the mixed result
    back through the plane's donated jits."""
    if cfg.outer_placement == "host":
        return "host"
    if cfg.outer_placement == "auto":
        dev = trainer.plan.mesh.devices.flat[0]
        if "tpu" not in getattr(dev, "device_kind", "").lower():
            return "host"
    if world.process_count > 1:
        log.warning(
            "outer_placement=device is single-process only (multihost "
            "slices replicate the host master); falling back to host"
        )
        return "host"
    return "device"


class DiLoCoOptimizer:
    """Owns inner trainer state transitions + the outer DiLoCo loop."""

    def __init__(
        self,
        trainer: InnerTrainer,
        backend: Optional[OuterBackend],
        cfg: DilocoConfig,
        state: dict,
        batch_size: int,
        world: Optional[HostWorld] = None,
    ):
        self.trainer = trainer
        # world-messenger split (reference train_fsdp.py:183,205-212): only
        # the messenger process of a multihost slice owns a WAN backend;
        # follower processes meet it at mesh collectives (parallel/world.py)
        self.world = world if world is not None else HostWorld()
        if self.world.is_messenger and backend is None:
            raise ValueError("the world-messenger process needs a backend")
        self.backend = backend if self.world.is_messenger else None
        self.cfg = cfg
        self.batch_size = batch_size
        self.target_samples = batch_size * cfg.local_steps

        # outer data plane placement: host numpy master (reference
        # hivemind offload semantics) or a device-resident plane
        # (diloco/outer_device.py) with fused, donated boundary ops
        self.placement = resolve_outer_placement(cfg, trainer, self.world)
        # host master copy (float32). Flatten once; treedef is stable.
        # Under multihost the gather is a mesh collective: every process of
        # the slice holds the identical full replica.
        flat_dev, self.treedef = jax.tree.flatten(state["params"])
        self._plane: Optional[DeviceOuterPlane] = None
        if self.placement == "device":
            self._plane = DeviceOuterPlane(
                trainer,
                flat_dev,
                lr=cfg.outer_lr,
                momentum=cfg.outer_momentum,
                nesterov=cfg.outer_nesterov,
                compression=cfg.compression,
                # gossip keeps its per-PARTNER EF ledgers host-side in the
                # GossipPlane (the pair wire encode happens on host); the
                # device plane's in-jit residual add is per-worker and
                # would mix partners' residuals into every pair round
                error_feedback=cfg.error_feedback
                and cfg.outer_mode != "gossip",
            )
            # the plane owns master + momentum; the host list stays empty
            # (every device-mode path goes through self._plane)
            self.master: list[np.ndarray] = []
        else:
            self.master = [
                np.array(x, dtype=np.float32)
                for x in self.world.gather_params(flat_dev)
            ]
        # error feedback (diloco/error_feedback.py): per-leaf residual of
        # the codec's quantization/sparsification error, folded into the
        # next round's pseudo-gradient before encoding. Device placement
        # fuses the residual add into the plane's pseudo-gradient jit and
        # stores the residuals in HBM; host placement adds in prepare().
        self._ef: Optional[ErrorFeedback] = None
        if cfg.error_feedback and cfg.outer_mode != "gossip":
            self._ef = ErrorFeedback(
                get_codec(cfg.compression),
                len(flat_dev),
                device_setter=(
                    self._plane.set_ef_residuals
                    if self._plane is not None
                    else None
                ),
            )
        self.outer_opt = OuterSGD(
            lr=cfg.outer_lr, momentum=cfg.outer_momentum, nesterov=cfg.outer_nesterov
        )

        # NoLoCo gossip plane (diloco/gossip.py): pair scheduling + the
        # point-to-point push-pull + per-partner error feedback. Messenger
        # process only — followers receive the mixed result via fanout.
        self._gossip: Optional[GossipPlane] = None
        if cfg.outer_mode == "gossip" and self.backend is not None:
            self._gossip = GossipPlane(
                self.backend,
                len(flat_dev),
                compression=cfg.compression,
                error_feedback=cfg.error_feedback,
            )

        self._schema = schema_fingerprint(state["params"])
        # streaming fragment sync (arxiv 2501.18512): size-balanced
        # contiguous partition of leaf indices, derived from the (shared)
        # schema so every peer computes the identical partition with no
        # coordination; fragment synced at epoch e is e mod N. Sizes come
        # from the device leaves (identical to the master shapes) so both
        # placements derive the same partition.
        self._fragments: Optional[list[list[int]]] = None
        if cfg.streaming_fragments > 1:
            leaf_sizes = [int(x.size) for x in flat_dev]
            n_frag = min(cfg.streaming_fragments, len(leaf_sizes))
            # cross-peer-critical: every peer must derive the SAME n_frag
            # non-empty fragments or the fragment all-reduces desync; the
            # planner raises explicitly when it cannot (a bare assert
            # would vanish under `python -O`)
            self._fragments = planner.fragment_partition(leaf_sizes, n_frag)
        self.epoch = 0  # completed outer steps
        self.local_step = 0  # inner steps within current epoch
        self.samples_in_epoch = 0
        self.max_num_peers = 1
        self._epoch_t0 = time.monotonic()
        self.last_outer_metrics: dict[str, Any] = {}

        # overlapped-communication state (arxiv 2502.12996): at most one
        # outer all-reduce in flight while inner training continues
        self._pending: Optional[dict[str, Any]] = None
        # pre-round snapshot served while the BLOCKING outer_step mutates
        # the master in place (OuterSGD.step is in-place): without it a peer
        # onboarding mid-round could fetch a torn master with mixed
        # pre/post-update leaves (hivemind's load_state_from_peers always
        # returns a consistent epoch snapshot, hivemind_diloco.py:528-531)
        self._blocking_snap: Optional[dict[str, Any]] = None
        # serializes state serving against round-boundary publications
        self._serve_lock = threading.Lock()
        self._abandoned: Optional[Any] = None  # dropped round still running
        self._landed_metrics: Optional[dict[str, Any]] = None
        self._apply_delta = None
        # persistent pseudo-gradient buffers (reference: hivemind averages
        # into the outer optimizer's persistent grad buffers,
        # hivemind_diloco.py:68-119). Fresh model-sized allocations every
        # round hit kernel page-fault/compaction stalls at 1b scale; two
        # slots so the overlapped path never writes into buffers a wedged
        # abandoned round might still be streaming from
        self._pg_bufs: list[Optional[list[np.ndarray]]] = [None, None]
        # alternation is tracked explicitly, NOT by epoch parity: onboarding
        # (load_state_from_peers) teleports self.epoch to the swarm's value,
        # which could land the next round on the slot an abandoned round is
        # still streaming from
        self._pg_slot = 0

        # streaming x overlap (arxiv 2501.18512 + 2502.12996): staggered
        # in-phase fragment rounds with eager first-step estimates,
        # driven from a trainer post-dispatch hook so launches never
        # leave the inner loop. Single-process only (the scheduler lands
        # on the training thread and the device plane is not
        # collective-aware); multihost falls back to the blocking
        # fragment path, which outer_step already handles.
        self._stream: Optional[StreamScheduler] = None
        if self._fragments is not None and cfg.overlap_comm != "none":
            if self.world.process_count > 1:
                log.warning(
                    "streaming_fragments x overlap_comm is single-process "
                    "only; falling back to blocking fragment sync"
                )
            else:
                self._stream = StreamScheduler(self)
                trainer.add_post_dispatch_hook(self._stream_tick)

        if self.backend is not None:
            self.backend.serve_state(self._state_for_peers)
            # announce at join, BEFORE the first (slow) inner-step compile:
            # progress gossip is what makes this peer visible to the other
            # workers' WAIT_FOR_ALL polling (backend.py wait_for_peers). The
            # first in-step report only happens after the first train_step
            # returns (~minutes of XLA compile on a cold cache), and an
            # unannounced peer reads as "no other peers known" to a faster
            # worker, which then matchmakes a solo group — observed live on
            # TPU with two staggered 150m workers. The reference announces
            # tracker state on join (hivemind_diloco.py:174-282 progress
            # tracker starts reporting at construction). A single announce
            # is NOT enough: the rendezvous registration TTL (60 s default)
            # would expire during a multi-minute silent compile and the
            # daemon would reap the peer, so a background thread keeps
            # re-announcing until the first step() lands.
            try:
                self._announce(samples=0, sps=0.0)
            except Exception as e:  # never kill the joiner over gossip
                # same contract as the keepalive below: a flaky rendezvous
                # at construction time must not take down the worker — the
                # keepalive retries in seconds anyway
                log.warning("join announce failed: %s", e)
            self._first_step_evt = threading.Event()
            self._announce_lock = threading.Lock()
            # the keepalive pins the epoch it announced at JOIN: desync
            # onboarding teleports self.epoch to the swarm's value before
            # the first (slow) compile, and a keepalive announcing the
            # swarm epoch with samples=0 / sps=0 (eta inf) would stall
            # every established peer's WAIT_FOR_ALL for the full timeout;
            # announcing the join epoch keeps the compiling joiner behind
            # the >=2-epoch discount in backend.wait_for_peers until its
            # first real report
            join_epoch = self.epoch

            def _keepalive():
                failures = 0
                while not self._first_step_evt.wait(_ANNOUNCE_INTERVAL_S):
                    # check+announce atomic vs the first step's report: a
                    # tick already past wait() must not publish a stale
                    # samples=0 row AFTER the first in-step report landed
                    with self._announce_lock:
                        if self._first_step_evt.is_set():
                            return
                        try:
                            self._announce(samples=0, sps=0.0, epoch=join_epoch)
                            failures = 0
                        except Exception as e:  # never kill the joiner over gossip
                            failures += 1
                            log.warning("join keepalive announce failed: %s", e)
                            if failures >= 3:
                                # backend closed / rendezvous gone: stop
                                # warning forever; the in-step report path
                                # takes over if the worker ever steps
                                return

            t = threading.Thread(target=_keepalive, daemon=True)
            t.start()

    def _announce(
        self, *, samples: int, sps: float, epoch: Optional[int] = None
    ) -> None:
        """Report this peer's progress to the gossip fabric (the one
        construction site for PeerProgress: join announce, compile
        keepalive, and the in-step report all go through here)."""
        self.backend.report_progress(
            PeerProgress(
                peer_id=self.backend.peer_id,
                epoch=self.epoch if epoch is None else epoch,
                samples=samples,
                samples_per_second=sps,
                timestamp=time.time(),
            )
        )

    def _pseudo_grad_into(self, boundary: list, slot: int) -> list[np.ndarray]:
        """master - boundary, written into the persistent slot buffers."""
        bufs = self._pg_bufs[slot]
        if (
            bufs is None
            or len(bufs) != len(self.master)
            or any(b.shape != m.shape for b, m in zip(bufs, self.master))
        ):
            bufs = [np.empty(m.shape, np.float32) for m in self.master]
            self._pg_bufs[slot] = bufs
        return [
            native.sub(m, d, out=b)
            for m, d, b in zip(self.master, boundary, bufs)
        ]

    # ------------------------------------------------------------------
    # onboarding (reference: load_state_from_peers, train_fsdp.py:348-349)
    # ------------------------------------------------------------------

    def _state_refs_unlocked(self) -> tuple[list[np.ndarray], int, dict]:
        """(master, epoch, outer_opt state) as REFERENCES — no array copies.

        Safe to copy after the lock is released because every mutation path
        rebinds (fresh lists / cloned optimizers) instead of writing the
        published arrays in place; a captured reference stays bit-stable.
        """
        if self._pending is not None:
            # while a round is in flight, epoch is already advanced but the
            # master excludes that round's update; serve the consistent
            # pre-round snapshot so an onboarding peer never adopts a
            # (new epoch, old master) mismatch
            p = self._pending
            return p["master_snap"], p["epoch"], p["opt_snap"]
        snap = self._blocking_snap
        if snap is not None:
            # blocking outer step in progress: serve the consistent
            # pre-round snapshot, never the mid-round live master
            return snap["master"], snap["epoch"], snap["outer_opt"]
        return self.master, self.epoch, self.outer_opt.state_dict_refs()

    def _device_state_for_peers(self) -> dict[str, Any]:
        """Serve-thread snapshot in device placement: the host view is
        fetched lazily, only when a peer actually asks. Lock order is
        plane.lock -> _serve_lock everywhere. The pre-published host
        snapshot (state-averaging rounds) is checked first under
        _serve_lock alone so fetches never stall behind a WAN leg."""
        plane = self._plane
        with self._serve_lock:
            snap = self._blocking_snap
            if snap is not None:
                master = [m.copy() for m in snap["master"]]
                opt = snap["outer_opt"]
                bufs = opt.get("bufs")
                return {
                    "master": master,
                    "epoch": snap["epoch"],
                    "outer_opt": {
                        **{k: opt[k] for k in ("lr", "momentum", "nesterov")},
                        "bufs": None if bufs is None else [b.copy() for b in bufs],
                    },
                }
        # plane.lock held across the whole device fetch: the training
        # thread's donating apply deletes the old buffers, so a concurrent
        # device_get would read freed memory. Holding it also pins the
        # (masters, epoch) pair — every device-mode mutator advances the
        # epoch while still inside plane.lock.
        with plane.lock:
            with self._serve_lock:
                p = self._pending
                if p is not None and "plane_pre" in p:
                    # overlapped round in flight: epoch already advanced,
                    # plane possibly rebound to the eager estimate — serve
                    # the retained pre-round device arrays instead
                    m_refs, b_refs = p["plane_pre"]
                    epoch = p["epoch"]
                else:
                    m_refs, b_refs = plane.masters, plane.bufs
                    epoch = self.epoch
            master, bufs = plane.host_state((m_refs, b_refs))
        return {
            "master": master,
            "epoch": epoch,
            "outer_opt": {
                "lr": plane.lr,
                "momentum": plane.momentum,
                "nesterov": plane.nesterov,
                "bufs": bufs,
            },
        }

    def _state_for_peers(self) -> dict[str, Any]:
        if self._plane is not None:
            return self._device_state_for_peers()
        # the lock makes the flag checks + reference reads atomic against
        # the round-boundary publications (all of which also hold the lock):
        # without it, a fetch that passes the flag checks just before a
        # round completes could capture a (pre-round master, post-round
        # epoch) mix. The multi-GB array copies happen AFTER release so an
        # onboarding peer's fetch never blocks the training thread's
        # round-boundary publication (which needs the same lock).
        with self._serve_lock:
            master, epoch, opt_sd = self._state_refs_unlocked()
        bufs = opt_sd.get("bufs")
        return {
            "master": [m.copy() for m in master],
            "epoch": epoch,
            "outer_opt": {
                **opt_sd,
                "bufs": None if bufs is None else [b.copy() for b in bufs],
            },
        }

    # ------------------------------------------------------------------
    # serve-plane snapshot export (opendiloco_tpu/serve weight hot-swap)
    # ------------------------------------------------------------------

    def master_snapshot(
        self, wire_dtype: Optional[str] = None
    ) -> tuple[int, list[np.ndarray]]:
        """(epoch, master leaves) for the in-process serving plane — the
        weights-only sibling of ``_state_for_peers``: same epoch-consistency
        rules (pending / blocking rounds serve the pre-round snapshot), no
        momentum fetch, no array copies on the host path (mutators rebind,
        so captured references stay bit-stable).

        Device placement fetches under ``plane.lock``; ``wire_dtype``
        (plain-fp16 state codec only) narrows inside jit so the D2H copy
        moves half-width bytes."""
        plane = self._plane
        if plane is None:
            with self._serve_lock:
                master, epoch, _ = self._state_refs_unlocked()
            return epoch, list(master)
        # mirror _device_state_for_peers: the pre-published host snapshot
        # is served under _serve_lock alone so a swap pull never stalls
        # behind a blocking outer round's WAN leg
        with self._serve_lock:
            snap = self._blocking_snap
            if snap is not None:
                return snap["epoch"], [np.asarray(m) for m in snap["master"]]
        with plane.lock:
            with self._serve_lock:
                p = self._pending
                if p is not None and "plane_pre" in p:
                    m_refs, _ = p["plane_pre"]
                    epoch = p["epoch"]
                else:
                    m_refs, epoch = plane.masters, self.epoch
            masters = plane.host_masters(m_refs, wire_dtype=wire_dtype)
        return epoch, masters

    def master_snapshot_wire(self) -> tuple[int, list[tuple], str]:
        """Codec-encoded master snapshot: (epoch, blobs, codec_name) with
        ``blobs[i] = (payload, meta, shape)`` per master leaf in params
        flatten order — the serve engine's hot-swap feed.

        Reuses the onboarding ``state_codec`` (fp16 by default,
        ``ODTP_STATE_CODEC`` overrides) so a swap transfer moves
        half-width bytes, and the device plane pre-casts the D2H fetch to
        wire width when the codec's encode is idempotent under it."""
        from opendiloco_tpu.diloco.compression import device_wire_dtype, get_codec
        from opendiloco_tpu.diloco.tcp import state_codec

        codec = state_codec(get_codec(self.cfg.compression))
        epoch, masters = self.master_snapshot(
            wire_dtype=device_wire_dtype(codec.name)
        )
        blobs = []
        for m in masters:
            flat = np.ascontiguousarray(m).reshape(-1)
            payload, meta = codec.encode(flat)
            blobs.append((payload, meta, tuple(m.shape)))
        return epoch, blobs, codec.name

    def _broadcast_remote_state(self, remote: Optional[dict]) -> Optional[dict]:
        """Fan a fetched swarm state from the messenger to every process of
        the slice (collective: all processes call, followers pass None).
        Small header by value; master/momentum arrays over the mesh."""
        w = self.world
        header = None
        if remote is not None:
            opt = remote["outer_opt"]
            header = {
                "epoch": int(remote["epoch"]),
                "opt_scalars": {
                    k: opt[k] for k in ("lr", "momentum", "nesterov")
                },
                "has_bufs": opt.get("bufs") is not None,
            }
        header = w.broadcast_obj(header)
        if header is None:
            return None
        tmpl = [np.zeros(m.shape, np.float32) for m in self.master]
        master = w.broadcast_arrays(
            [np.asarray(m, np.float32) for m in remote["master"]]
            if remote is not None
            else tmpl
        )
        bufs = None
        if header["has_bufs"]:
            bufs = w.broadcast_arrays(
                [np.asarray(b, np.float32) for b in remote["outer_opt"]["bufs"]]
                if remote is not None
                else tmpl
            )
        return {
            "master": master,
            "epoch": header["epoch"],
            "outer_opt": {**header["opt_scalars"], "bufs": bufs},
        }

    def load_state_from_peers(self, state: dict) -> Optional[dict]:
        """Adopt a peer's master params/epoch; returns updated device state.
        Multihost: a collective — every process of the slice must call."""
        self.drop_pending()  # adopting remote state supersedes in-flight comm
        remote = (
            self.backend.fetch_state() if self.world.is_messenger else None
        )
        if self.world.process_count > 1:
            remote = self._broadcast_remote_state(remote)
        if remote is None:
            return None
        if self._plane is not None:
            opt = remote["outer_opt"]
            with self._plane.lock:
                self._plane.load(
                    remote["master"],
                    opt.get("bufs"),
                    lr=opt.get("lr"),
                    momentum=opt.get("momentum"),
                    nesterov=opt.get("nesterov"),
                )
                # scalar mirror only; the plane owns the momentum bufs
                self.outer_opt.load_state_dict({**opt, "bufs": None})
                with self._serve_lock:
                    self._blocking_snap = None
                    self.epoch = int(remote["epoch"])
                    self.local_step = 0
                    self.samples_in_epoch = 0
                leaves = self._plane.sync_params(jax.tree.leaves(state["params"]))
                state["params"] = jax.tree.unflatten(self.treedef, leaves)
            return self.trainer.force_step_position(
                state, self.epoch * self.cfg.local_steps
            )
        with self._serve_lock:
            self._blocking_snap = None  # superseded pre-round snapshot
            self.master = [
                np.asarray(m, np.float32).copy() for m in remote["master"]
            ]
            self.epoch = int(remote["epoch"])
            self.outer_opt.load_state_dict(remote["outer_opt"])
            self.local_step = 0
            self.samples_in_epoch = 0
        state = self._write_master_to_device(state)
        # resume the LR schedule where the swarm is, not at warmup step 0
        return self.trainer.force_step_position(
            state, self.epoch * self.cfg.local_steps
        )

    # ------------------------------------------------------------------
    # inner step
    # ------------------------------------------------------------------

    def _behind_swarm(self) -> bool:
        """True when another peer is >=2 epochs ahead: our pseudo-gradients
        would poison the average (desync detection, hivemind_diloco.py:528-531).
        One epoch of skew is normal near boundaries."""
        if self.backend is None:
            return False
        for p in self.backend.peer_progress():
            if p.peer_id != self.backend.peer_id and p.epoch >= self.epoch + 2:
                return True
        return False

    def _desynced(self) -> bool:
        """The desync decision, agreed across the slice: only the messenger
        sees peer progress, so its verdict is broadcast (one tiny collective
        per epoch start under multihost, a passthrough single-host). Every
        process must reach this in lockstep — it is called at local_step 0,
        which advances identically everywhere."""
        behind = self._behind_swarm() if self.world.is_messenger else False
        if self.world.process_count > 1:
            behind = bool(self.world.broadcast_obj(behind))
        return behind

    def step(self, state: dict, batch: dict) -> tuple[dict, dict]:
        """One inner optimizer step; triggers the outer step at the epoch
        boundary. Returns (state, metrics)."""
        if self._pending is not None:
            state = self._poll_pending(state, block=False)
        if self.local_step == 0 and self._desynced():
            # discard the stale local phase and adopt the swarm state before
            # burning compute on an epoch the group has moved past
            updated = self.load_state_from_peers(state)
            if updated is not None:
                state = updated
                log.warning(
                    "desynced from swarm; re-downloaded state at epoch %d",
                    self.epoch,
                )
        state, metrics = self.trainer.train_step(state, batch)
        self.local_step += 1
        self.samples_in_epoch += self.batch_size
        if self.backend is not None and not self._first_step_evt.is_set():
            # stop the join keepalive announcer; under the lock so an
            # in-flight keepalive tick finishes its (stale) announce BEFORE
            # this step's fresh report below can be overwritten by it
            with self._announce_lock:
                self._first_step_evt.set()

        # progress gossip is a synchronous rendezvous RPC on the TCP backend;
        # rate-limit it so the training loop never blocks on it per-step
        # (always report at the epoch boundary so matchmaking sees fresh state)
        now = time.monotonic()
        at_boundary = self.local_step >= self.cfg.local_steps
        if self.backend is not None and (
            at_boundary or now - getattr(self, "_last_report", 0.0) > 0.5
        ):
            self._last_report = now
            elapsed = max(now - self._epoch_t0, 1e-6)
            self._announce(
                samples=self.samples_in_epoch,
                sps=self.samples_in_epoch / elapsed,
            )

        metrics = dict(metrics)
        metrics["epoch"] = self.epoch
        if self._landed_metrics is not None:  # overlapped round completed
            metrics.update(self._landed_metrics)
            self._landed_metrics = None
        if self.local_step >= self.cfg.local_steps:
            if self._stream is not None:
                # streaming: the fragments already synced mid-phase (or
                # are still flying); the boundary is pure bookkeeping
                state, outer_metrics = self._stream.boundary(state)
            else:
                # the overlapped path is full-model; a fragmented config
                # (streaming under multihost fallback) takes the blocking
                # fragment path instead
                overlap = (
                    self.cfg.overlap_comm != "none"
                    and self._fragments is None
                    and not self._is_state_avg_epoch()
                )
                if overlap and self.cfg.outer_mode == "gossip":
                    # full-model overlapped gossip: the delta-landing
                    # machinery is pseudo-gradient-only. Overlapped gossip
                    # rides the streaming scheduler instead (set
                    # streaming_fragments > 1: each fragment pairs and
                    # lands mid-phase) — full-model boundaries block.
                    if not getattr(self, "_warned_gossip_overlap", False):
                        self._warned_gossip_overlap = True
                        log.warning(
                            "overlap_comm without streaming_fragments "
                            "falls back to blocking under outer_mode="
                            "'gossip'; set streaming_fragments>1 for "
                            "overlapped gossip rounds"
                        )
                    overlap = False
                if overlap:
                    state, outer_metrics = self._outer_step_overlapped(state)
                else:
                    state, outer_metrics = self.outer_step(state)
            metrics.update(outer_metrics)
            tr = obs.tracer()
            if tr is not None:
                # epoch rides the overseer roll-up; the watchdog's stall
                # deadline resets here so EVERY backend (loopback included,
                # where no TCP round-health hook fires) counts as progress
                tr.gauge("outer_epoch", self.epoch)
                wd = obs.anomaly.watchdog()
                if wd is not None:
                    wd.note_progress(self.epoch)
        return state, metrics

    def _stream_tick(self, state: dict) -> dict:
        """Trainer post-dispatch hook: the streaming scheduler's
        heartbeat. Fires after every inner dispatch and BEFORE step()
        increments local_step, so the just-dispatched inner step is
        ``local_step + 1``."""
        return self._stream.tick(state, self.local_step + 1)

    def _is_state_avg_epoch(self) -> bool:
        """Full-state-averaging epochs run the blocking path (they rewrite
        the master wholesale; overlapping them buys nothing)."""
        return (
            self.cfg.average_state_every > 0
            and (self.epoch + 1) % self.cfg.average_state_every == 0
        )

    # ------------------------------------------------------------------
    # overlapped outer step (Eager Updates for Overlapped Communication
    # and Computation in DiLoCo, arxiv 2502.12996)
    # ------------------------------------------------------------------

    def _outer_step_overlapped(self, state: dict) -> tuple[dict, dict]:
        """Launch the outer all-reduce in the background and keep training.

        Blocking DiLoCo rewrites the device from the boundary params theta_b
        to the new master M'. Overlapped, the device keeps stepping from
        theta_b; when the average lands we apply the SAME rewrite as a delta:
        params += (M' - theta_b). "eager" additionally applies the update
        estimated from the local pseudo-gradient immediately and corrects
        with (M'_true - M'_est) on arrival.
        """
        if self._plane is not None:
            return self._outer_step_overlapped_device(state)
        assert schema_fingerprint(state["params"]) == self._schema, (
            "parameter schema changed mid-epoch"
        )
        t0 = time.monotonic()
        tr = obs.tracer()
        t0p = time.perf_counter() if tr is not None else 0.0
        if self._pending is not None:  # at most one round in flight
            state = self._poll_pending(state, block=True)
        self._drain_abandoned()

        # overlap the boundary D2H with the straggler wait (same trick as
        # the blocking path): params are final at the boundary. Multihost:
        # the gather is a mesh collective issued by every process's fetcher
        # thread; the WAN launch below is messenger-only.
        fetch_result: list = []

        def _fetch():
            fetch_result.append(
                self.world.gather_params(jax.tree.leaves(state["params"]))
            )

        fetcher = threading.Thread(target=_fetch)
        fetcher.start()
        if self.world.is_messenger:
            wait_for_peers(
                self.backend,
                target_samples=self.target_samples,
                own_epoch=self.epoch,
                strategy=self.cfg.all_reduce_strategy,
                timeout_waiting_for_peers=self.cfg.timeout_waiting_for_peers,
                log=log,
            )
        wait_s = time.monotonic() - t0
        if tr is not None:
            tr.add_span(
                "outer/barrier_wait", t0p, time.perf_counter(),
                epoch=self.epoch,
            )
        fetcher.join()
        boundary = fetch_result[0]
        self._pg_slot ^= 1
        # the messenger puts the pseudo-gradient on the wire; in eager mode
        # every process also computes it (identical, from the replicated
        # master) for the local estimate below. A delayed-mode follower
        # needs neither — the landing path works from boundary/master_snap
        # — so it skips the full-model subtraction AND the two model-sized
        # slot buffers (~8 GB idle at 1b scale)
        pseudo_grad = (
            self._pseudo_grad_into(boundary, slot=self._pg_slot)
            if self.world.is_messenger or self.cfg.overlap_comm == "eager"
            else None
        )
        if self._ef is not None and pseudo_grad is not None:
            # residual folded into the wire pg (and the eager estimate
            # below, which must match what the swarm averages); the round's
            # roundtrip error stages pending until the landing commits it.
            # Eager followers run this too — identical pg from the
            # replicated master keeps residuals process-symmetric.
            self._ef.prepare("main", range(len(pseudo_grad)), pseudo_grad)

        pending: dict[str, Any] = {
            "master_snap": [m.copy() for m in self.master],
            "opt_snap": self.outer_opt.state_dict(),
            "boundary": boundary,
            "epoch": self.epoch,
            "t_launch": t0,
            # followers carry no future; landing is decided by the
            # messenger and broadcast (see _poll_pending)
            "future": (
                self._spawn_all_reduce(pseudo_grad, self.epoch)
                if self.world.is_messenger
                else None
            ),
        }

        if self.cfg.overlap_comm == "eager":
            # immediate update from the local pseudo-gradient (own epoch's
            # contribution stands in for the average until it arrives)
            est_opt = OuterSGD(
                lr=self.cfg.outer_lr,
                momentum=self.cfg.outer_momentum,
                nesterov=self.cfg.outer_nesterov,
            )
            est_opt.load_state_dict(pending["opt_snap"])
            est_master = [m.copy() for m in pending["master_snap"]]
            est_opt.step(est_master, pseudo_grad)
            delta = [e - b for e, b in zip(est_master, boundary)]
            state = self._apply_delta_to_device(state, delta)
            pending["est_master"] = est_master

        # publish atomically against the serve thread: the eager master
        # rebind, the pending round, and the epoch advance must appear
        # together (a fetch between them would pair an estimated master
        # with the old epoch, or a new epoch with no pending snapshot)
        with self._serve_lock:
            if "est_master" in pending:
                self.master = pending["est_master"]
            self._pending = pending
            self.epoch += 1
            self.local_step = 0
            self.samples_in_epoch = 0
        self._epoch_t0 = time.monotonic()
        outer_metrics = {
            "outer_step_s": time.monotonic() - t0,
            "outer_wait_s": wait_s,
            "outer_overlapped": 1,
        }
        if tr is not None:
            tr.add_span(
                "outer/launch", t0p, time.perf_counter(), epoch=self.epoch - 1
            )
            tr.gauge("outer_wait_s", wait_s)
        self.last_outer_metrics = outer_metrics
        return state, outer_metrics

    def _outer_step_overlapped_device(self, state: dict) -> tuple[dict, dict]:
        """Device-placement overlapped launch: pseudo-gradient and (eager)
        estimate are fused device ops; the boundary params never need a
        full-width D2H (the wire fetch is wire-width, the f32
        pseudo-gradient is retained ON DEVICE for the landing math instead
        of a host boundary/master snapshot)."""
        plane = self._plane
        assert schema_fingerprint(state["params"]) == self._schema, (
            "parameter schema changed mid-epoch"
        )
        t0 = time.monotonic()
        tr = obs.tracer()
        t0p = time.perf_counter() if tr is not None else 0.0
        if self._pending is not None:  # at most one round in flight
            state = self._poll_pending(state, block=True)
        self._drain_abandoned()

        # overlap the (wire-width) pseudo-gradient D2H with the straggler
        # wait; device placement is single-process, so this process IS the
        # messenger and both pg forms are always needed (host for the wire,
        # f32 device for the landing delta)
        device_leaves = jax.tree.leaves(state["params"])
        # device copy of the boundary params: both overlap modes compute the
        # deferred boundary rewrite as new_master - boundary (the SAME
        # associativity as the host path's (m - lr*d) - boundary); deriving
        # it from the pseudo-gradient instead rounds at pg scale and drifts
        # ~1e3 ulps over a few rounds once inner AdamW amplifies it
        eager = self.cfg.overlap_comm == "eager"
        boundary_dev = plane.copy_leaves(device_leaves)
        fetch_result: list = []

        def _fetch():
            fetch_result.append(
                plane.pseudo_grad(
                    device_leaves,
                    with_norm=tr is not None,
                    keep_device=eager,
                )
            )

        fetcher = threading.Thread(target=_fetch)
        fetcher.start()
        wait_for_peers(
            self.backend,
            target_samples=self.target_samples,
            own_epoch=self.epoch,
            strategy=self.cfg.all_reduce_strategy,
            timeout_waiting_for_peers=self.cfg.timeout_waiting_for_peers,
            log=log,
        )
        wait_s = time.monotonic() - t0
        if tr is not None:
            tr.add_span(
                "outer/barrier_wait", t0p, time.perf_counter(),
                epoch=self.epoch,
            )
        fetcher.join()
        pg_host, pg_norm, pg_dev = fetch_result[0]
        if tr is not None and pg_norm is not None:
            tr.gauge("pseudo_grad_norm", pg_norm)
        if self._ef is not None:
            # the plane's jit already added the residual (full-width D2H:
            # pg_host is the exact f32 the backend will encode); prepare
            # only stages the roundtrip error
            self._ef.prepare("main", range(len(pg_host)), pg_host)

        pending: dict[str, Any] = {
            "epoch": self.epoch,
            "t_launch": t0,
            "future": self._spawn_all_reduce(pg_host, self.epoch),
        }
        # the plane mutation (eager estimate), the pending publication, and
        # the epoch advance must appear atomically to the serve thread's
        # device path (which takes plane.lock then _serve_lock)
        with plane.lock:
            pending["plane_pre"] = (plane.masters, plane.bufs)
            if eager:
                # immediate update from the local pseudo-gradient; the
                # estimate rebinds the live plane (pg_dev and the boundary
                # copy are donated) and returns the device delta for the
                # inner params
                delta = plane.estimate(pg_dev, boundary_dev)
                state = self._apply_delta_to_device(state, delta)
            else:
                # delayed: the landing rewrites the boundary params to the
                # true new master, so it needs the retained boundary copy
                pending["boundary_dev"] = boundary_dev
            with self._serve_lock:
                self._pending = pending
                self.epoch += 1
                self.local_step = 0
                self.samples_in_epoch = 0
        self._epoch_t0 = time.monotonic()
        outer_metrics = {
            "outer_step_s": time.monotonic() - t0,
            "outer_wait_s": wait_s,
            "outer_overlapped": 1,
        }
        if tr is not None:
            tr.add_span(
                "outer/launch", t0p, time.perf_counter(), epoch=self.epoch - 1
            )
            tr.gauge("outer_wait_s", wait_s)
        self.last_outer_metrics = outer_metrics
        return state, outer_metrics

    def _drain_abandoned(self) -> None:
        """A dropped round may still be running (its reduce can't be
        cancelled); let it drain before writing fresh pseudo-gradients into
        slot buffers it might still be streaming from. Called by BOTH outer
        paths: the blocking path writes slot 0, which an abandoned overlapped
        round may own."""
        if self._abandoned is None:
            return
        drained = True
        try:
            self._abandoned.result(timeout=self.cfg.averaging_timeout + 60)
        except (TimeoutError, concurrent.futures.TimeoutError):
            # on 3.10 futures.TimeoutError is NOT the builtin; both must be
            # caught or a wedged round silently counts as drained
            drained = False
        except Exception:
            pass
        self._abandoned = None
        if not drained:
            # a truly wedged round may still be streaming from its
            # pseudo-grad buffers: surrender both slots to it and
            # allocate fresh ones rather than risk torn bytes on the
            # wire (leaks one buffer set, once, on a pathological path)
            self._pg_bufs = [None, None]

    def _spawn_all_reduce(self, pseudo_grad: list, epoch: int):
        """Run backend.all_reduce on a daemon thread (a wedged round must
        never block interpreter exit) with the round epoch pinned at submit
        time (the training thread advances self.epoch immediately after)."""
        fut: concurrent.futures.Future = concurrent.futures.Future()

        def _run():
            if not fut.set_running_or_notify_cancel():
                return  # dropped before the round started
            try:
                fut.set_result(
                    self.backend.all_reduce(
                        pseudo_grad,
                        timeout=self.cfg.averaging_timeout,
                        epoch=epoch,
                    )
                )
            except BaseException as e:  # surfaced via fut.result()
                fut.set_exception(e)

        threading.Thread(
            target=_run, name="odtp-outer-comm", daemon=True
        ).start()
        return fut

    def _messenger_fanout(self, produce, shapes):
        """THE multihost fan-out protocol (both the blocking and the
        overlapped outer paths ride it): run ``produce() -> (arrays, meta)``
        on the messenger, copy the result out of any pooled backend buffers,
        broadcast a small header first — so a messenger-side failure makes
        the whole slice raise in lockstep instead of followers hanging at
        the array fan-out — then broadcast the arrays (followers pass
        zero templates of ``shapes``). Returns ``(arrays, meta)``."""
        exc: Optional[BaseException] = None
        arrays, meta = None, {}
        if self.world.is_messenger:
            try:
                arrays, meta = produce()
                # own the data before the fan-out: backend results are
                # views into pooled buffers the next call reclaims
                # (np.array COPIES; asarray on an f32 view wouldn't)
                arrays = [np.array(a, dtype=np.float32) for a in arrays]
            except BaseException as e:
                exc = e
        header = self.world.broadcast_obj(
            {
                "err": None if exc is None else f"{type(exc).__name__}: {exc}",
                "meta": meta,
            }
            if self.world.is_messenger
            else None
        )
        if exc is not None:
            raise exc
        if header["err"] is not None:
            raise RuntimeError(f"messenger outer round failed: {header['err']}")
        arrays = self.world.broadcast_arrays(
            arrays
            if self.world.is_messenger
            else [np.zeros(s, np.float32) for s in shapes]
        )
        return arrays, header["meta"]

    def _overlap_result(self, pending: dict, *, block: bool):
        """(averaged, group_size) of an in-flight round. Single-host: the
        future's result. Multihost: the messenger resolves its future and
        fans the result out via _messenger_fanout."""
        fut = pending["future"]
        timeout = None if not block else self.cfg.averaging_timeout + 60
        if self.world.process_count == 1:
            return fut.result(timeout=timeout)

        def produce():
            avg, n = fut.result(timeout=timeout)
            return avg, {"n": n}

        avg, meta = self._messenger_fanout(
            produce, [m.shape for m in pending["master_snap"]]
        )
        return avg, int(meta["n"])

    def _poll_pending(self, state: dict, *, block: bool) -> dict:
        """Resolve an in-flight outer all-reduce if it completed (or wait
        for it when ``block``); applies the (corrected) outer update as a
        device delta. Multihost: whether the round landed is the
        messenger's host-local fact, so the verdict rides one tiny
        collective per poll — every process reaches here in lockstep (the
        poll sites are all step-count-deterministic)."""
        pending = self._pending
        if pending is None:
            return state
        fut = pending["future"]
        if not block:
            done = fut.done() if fut is not None else False
            if self.world.process_count > 1:
                done = bool(
                    self.world.broadcast_obj(
                        done if self.world.is_messenger else None
                    )
                )
            if not done:
                return state
        # keep _pending published until the landed master/opt are assigned:
        # the serve thread falls back to the live (still pre-round in the
        # delayed mode) master the moment _pending clears, so clearing
        # before the assignment would open a (new epoch, old master) window
        # for onboarding peers. The finally also clears on failure, where
        # the live state is the correct thing to serve.
        tr = obs.tracer()
        try:
            if tr is not None and block:
                t_wait = time.perf_counter()
                avg, group_size = self._overlap_result(pending, block=block)
                tr.add_span(
                    "outer/barrier_wait", t_wait, time.perf_counter(),
                    epoch=pending["epoch"],
                )
            else:
                avg, group_size = self._overlap_result(pending, block=block)
            self._check_group_size(group_size)
            if self._ef is not None:
                # the round's compressed pg was adopted by the swarm: its
                # roundtrip error becomes the live residual (no-op on
                # delayed-mode followers, which never prepared)
                self._ef.commit("main")

            t_apply = time.perf_counter() if tr is not None else 0.0
            if "plane_pre" in pending:
                # device placement: fused landing. plane.lock is held from
                # the donating land op until the pending round is cleared —
                # the serve thread's device path could otherwise pick up
                # the just-donated pre-round refs from _pending and
                # device_get freed buffers.
                plane = self._plane
                pre_m, pre_b = pending["plane_pre"]
                with plane.lock:
                    if "boundary_dev" in pending:  # delayed
                        delta = plane.land_delayed(
                            pre_m, pre_b, pending["boundary_dev"], avg
                        )
                    else:  # eager: correct the estimated update
                        delta = plane.land_eager(pre_m, pre_b, avg)
                    state = self._apply_delta_to_device(state, delta)
                    with self._serve_lock:
                        self._pending = None
            else:
                master = [m.copy() for m in pending["master_snap"]]
                opt = OuterSGD(
                    lr=self.cfg.outer_lr,
                    momentum=self.cfg.outer_momentum,
                    nesterov=self.cfg.outer_nesterov,
                )
                opt.load_state_dict(pending["opt_snap"])
                opt.step(master, avg)

                if "est_master" in pending:  # eager: correct the estimate
                    delta = [
                        t - e for t, e in zip(master, pending["est_master"])
                    ]
                else:  # delayed: the deferred boundary rewrite
                    delta = [t - b for t, b in zip(master, pending["boundary"])]
                state = self._apply_delta_to_device(state, delta)
                with self._serve_lock:
                    self.outer_opt = opt
                    self.master = master
            if tr is not None:
                tr.add_span(
                    "outer/apply", t_apply, time.perf_counter(),
                    epoch=pending["epoch"], group=group_size,
                )
        except BaseException:
            if self._ef is not None:
                # dropped round: discard the staged error, keep the
                # previous residual live (the next pseudo-gradient
                # re-captures the lost update — nothing double-counts)
                self._ef.abort("main")
            raise
        finally:
            with self._serve_lock:
                self._pending = None
        landed_s = time.monotonic() - pending["t_launch"]
        # surface the landing in the next metrics row (dashboards would
        # otherwise never see overlapped round size/latency)
        self._landed_metrics = {
            "outer_allreduce_s": landed_s,
            "num_peers": group_size,
            **self._round_health_metrics(),
        }
        if tr is not None:
            tr.instant(
                "outer/landed",
                epoch=pending["epoch"], group=group_size,
                landed_s=round(landed_s, 6),
            )
            tr.gauge("outer_allreduce_s", landed_s)
        self.last_outer_metrics = dict(self._landed_metrics)
        log.info(
            "outer step %d (overlapped): all-reduce over %d peers landed "
            "after %.3fs",
            pending["epoch"],
            group_size,
            landed_s,
        )
        return state

    def _round_health_metrics(self) -> dict:
        """Elastic-round fields from the backend's health ledger, merged
        into the metrics row of every landed outer round: dashboards and
        the chaos soak read partial groups as data, not as errors."""
        health = getattr(self.backend, "last_round_health", None) or {}
        out = {}
        if "elastic" in health:
            out["elastic"] = bool(health["elastic"])
            out["expected_peers"] = int(health.get("expected", 0))
        if health.get("retries"):
            out["round_retries"] = int(health["retries"])
        # adaptive-transport fields (tcp.py records them when armed): the
        # plan hash and per-part shares of the butterfly this round ran on
        if health.get("link_plan"):
            out["link_plan"] = health["link_plan"]
        if health.get("link_shares"):
            out["link_shares"] = list(health["link_shares"])
        # hierarchical-round fields: which aggregators this round's plan
        # elected. The chaos soak asserts aggregator re-election after a
        # SIGKILL straight from these rows.
        if health.get("hier"):
            out["hier_plan"] = health["hier"].get("plan")
            out["hier_aggregators"] = list(
                health["hier"].get("aggregators", [])
            )
        return out

    def _check_group_size(self, group_size: int) -> None:
        if group_size < self.max_num_peers:
            msg = f"Lost a diloco worker: {group_size} < {self.max_num_peers}"
            if self.cfg.fail_rank_drop:
                raise PeerDropError(msg)
            log.warning(msg)
        self.max_num_peers = max(self.max_num_peers, group_size)

    def drop_pending(self) -> None:
        """Abandon an in-flight round (its result will never be applied).
        A running reduce can't be cancelled; it is tracked so the next
        launch drains it before reusing the round key."""
        if self._stream is not None:
            self._stream.drop_all()
        if self._pending is not None:
            fut = self._pending["future"]
            if fut is not None and not fut.cancel():
                self._abandoned = fut
            self._pending = None
        if self._ef is not None:
            # abandoned rounds never commit; the live residual survives
            # state adoption (it is this worker's own compression debt)
            self._ef.abort_all()
        if self._gossip is not None:
            # same contract per partner: pending pair rounds are discarded,
            # committed residual ledgers survive
            self._gossip.abort_all()

    def flush(self, state: dict) -> dict:
        """Resolve any in-flight outer communication (call before
        checkpointing or shutdown so the master reflects every launched
        round)."""
        if self._stream is not None:
            state = self._stream.flush(state)
        return self._poll_pending(state, block=True)

    def _apply_frag_delta(self, state: dict, frag: list, delta: list) -> dict:
        """Apply a fragment-indexed delta to the live params: one donated
        jit add over the fragment's leaves; untouched leaves pass through
        live (the H2D — host placement only — moves one fragment, not the
        model). The jit cache is keyed by the fragment's avals, so a fixed
        partition compiles exactly N tiny executables."""
        leaves = jax.tree.leaves(state["params"])
        cur = [leaves[i] for i in frag]
        if delta and not isinstance(delta[0], jax.Array):
            sh = jax.tree.leaves(self.trainer.state_shardings["params"])
            delta = [
                jax.device_put(np.asarray(d, np.float32), sh[i])
                for d, i in zip(delta, frag)
            ]
        fresh = _frag_add(cur, delta)
        merged = list(leaves)
        for j, i in enumerate(frag):
            merged[i] = fresh[j]
        state = dict(state)
        state["params"] = jax.tree.unflatten(self.treedef, merged)
        return state

    def _apply_delta_to_device(self, state: dict, delta_flat: list) -> dict:
        if self._apply_delta is None:
            sh = self.trainer.state_shardings["params"]
            self._apply_delta = jax.jit(
                lambda p, d: jax.tree.map(lambda a, b: a + b, p, d),
                donate_argnums=(0,),
                in_shardings=(sh, sh),
                out_shardings=sh,
            )
        delta = self._leaves_to_device(delta_flat)
        state = dict(state)
        state["params"] = self._apply_delta(state["params"], delta)
        return state

    # ------------------------------------------------------------------
    # outer step (reference: _update_global_epoch, hivemind_diloco.py:570-679)
    # ------------------------------------------------------------------

    def _wan_all_reduce(
        self,
        arrays: list[np.ndarray],
        *,
        timeout: float,
        epoch: Optional[int] = None,
        tag: Optional[str] = None,
        group_cap: Optional[int] = None,
    ) -> tuple[list[np.ndarray], int, int]:
        """The WAN leg of an outer round: ``backend.all_reduce`` on the
        messenger, then a mesh broadcast of the averaged result to the
        follower processes — the TPU shape of the reference's
        post-outer-step fan-out (train_fsdp.py:410-413, NCCL broadcast
        from each worker's rank 0).

        Returns ``(averaged, group_size, live_peers)``; ``live_peers`` is
        the swarm's current peer count (the gossip health signal — pair
        size says nothing about the swarm). Multihost: a collective; every
        process calls with same-shaped ``arrays`` (follower inputs are
        shape templates — they computed the identical pseudo-gradient from
        their replicated master, so the arrays are already in hand). A
        messenger-side failure is re-broadcast so the whole slice raises
        in lockstep instead of followers hanging at the fan-out."""
        kw: dict[str, Any] = {"timeout": timeout}
        if epoch is not None:
            kw["epoch"] = epoch
        if tag is not None:
            kw["tag"] = tag
        if group_cap is not None:
            kw["group_cap"] = group_cap
        if self.world.process_count == 1:
            avg, n = self.backend.all_reduce(arrays, **kw)
            return avg, n, self.backend.num_peers()

        def produce():
            avg, n = self.backend.all_reduce(arrays, **kw)
            return avg, {"n": n, "live": self.backend.num_peers()}

        avg, meta = self._messenger_fanout(produce, [a.shape for a in arrays])
        return avg, int(meta["n"]), int(meta["live"])

    def _gossip_round(
        self,
        masters: list[np.ndarray],
        bufs: Optional[list[np.ndarray]],
        pgs: list[np.ndarray],
        *,
        idxs,
        frag_id: int,
        epoch: int,
    ):
        """One NoLoCo pair round through the gossip plane, with the same
        messenger/follower fan-out shape as ``_wan_all_reduce``.

        Returns ``(mix_m, mix_b, avg_g, pair_n, live_peers)``; ``pair_n``
        is 0 when the round dropped (partner death / timeout / "hold"
        self-round) — mix arrays are None then and the caller treats the
        boundary as a non-event (master untouched, EF residual retained).
        """
        k = len(masters)
        if self.world.process_count == 1:
            res = self._gossip.exchange(
                epoch=epoch, frag_id=frag_id, idxs=idxs,
                masters=masters, bufs=bufs, pgs=pgs,
                timeout=self.cfg.averaging_timeout,
            )
            live = self.backend.num_peers()
            if res is None:
                return None, None, None, 0, live
            mix_m, mix_b, avg_g, _partner, n = res
            return mix_m, mix_b, avg_g, n, live

        # momentum-armed-ness must be config-symmetric across processes:
        # follower shape templates are derived from it without messaging
        has_b = bufs is not None

        def produce():
            res = self._gossip.exchange(
                epoch=epoch, frag_id=frag_id, idxs=idxs,
                masters=masters, bufs=bufs, pgs=pgs,
                timeout=self.cfg.averaging_timeout,
            )
            live = self.backend.num_peers()
            if res is None:
                # dropped round: fan the INPUTS out (cheap, right shapes);
                # n=0 tells every process to ignore them
                return masters + (bufs or []) + pgs, {"n": 0, "live": live}
            mix_m, mix_b, avg_g, _partner, n = res
            return mix_m + (mix_b or []) + avg_g, {"n": n, "live": live}

        shapes = [a.shape for a in masters + (bufs or []) + pgs]
        arrays, meta = self._messenger_fanout(produce, shapes)
        n, live = int(meta["n"]), int(meta["live"])
        if n == 0:
            return None, None, None, 0, live
        mix_m = arrays[:k]
        mix_b = arrays[k:2 * k] if has_b else None
        avg_g = arrays[-k:]
        return mix_m, mix_b, avg_g, n, live

    def _outer_step_device(self, state: dict) -> tuple[dict, dict]:
        """Blocking outer round, device placement: the pseudo-gradient and
        the Nesterov apply are fused, donated jit ops; D2H moves wire-width
        bytes and H2D returns only the averaged pseudo-gradient. No
        clone-then-rebind and no pre-round host snapshot for normal rounds
        — donation makes the apply atomic under plane.lock, which the
        serve thread's device path also takes. State-averaging rounds do
        pre-publish a host snapshot (their WAN leg would otherwise stall
        onboarding fetches behind plane.lock)."""
        plane = self._plane
        if self._pending is not None:  # a blocking round supersedes overlap
            state = self._poll_pending(state, block=True)
        self._drain_abandoned()
        assert schema_fingerprint(state["params"]) == self._schema, (
            "parameter schema changed mid-epoch"
        )
        state_avg = self._is_state_avg_epoch()
        if state_avg:
            master_snap, buf_snap = plane.host_state()
            with self._serve_lock:
                self._blocking_snap = {
                    "master": master_snap,
                    "epoch": self.epoch,
                    "outer_opt": {
                        "lr": plane.lr,
                        "momentum": plane.momentum,
                        "nesterov": plane.nesterov,
                        "bufs": buf_snap,
                    },
                }
        t0 = time.monotonic()
        tr = obs.tracer()
        t0p = time.perf_counter() if tr is not None else 0.0

        frag: Optional[list[int]] = None
        device_leaves = jax.tree.leaves(state["params"])
        if self._fragments is not None:
            frag = self._fragments[self.epoch % len(self._fragments)]
        fetch_result: list = []

        def _fetch():
            # wire-width D2H of this boundary's fragment; the norm rides
            # the same jit as one HBM reduction when the tracer is armed
            fetch_result.append(
                plane.pseudo_grad(
                    device_leaves if frag is None
                    else [device_leaves[i] for i in frag],
                    frag,
                    with_norm=tr is not None,
                )
            )

        fetcher = threading.Thread(target=_fetch)
        fetcher.start()
        if self.cfg.outer_mode != "gossip":
            # gossip skips the straggler wait: a pair round has no group
            # to assemble (no global barrier); the pair push-pull itself
            # bounds how long a fast worker waits on its partner
            wait_for_peers(
                self.backend,
                target_samples=self.target_samples,
                own_epoch=self.epoch,
                strategy=self.cfg.all_reduce_strategy,
                timeout_waiting_for_peers=self.cfg.timeout_waiting_for_peers,
                log=log,
            )
        wait_s = time.monotonic() - t0
        if tr is not None:
            tr.add_span(
                "outer/barrier_wait", t0p, time.perf_counter(),
                epoch=self.epoch,
            )
        fetcher.join()
        if tr is not None:
            tr.add_span("outer/d2h", t0p, time.perf_counter(), epoch=self.epoch)
        pseudo_grad, pg_norm, _ = fetch_result[0]
        if tr is not None and pg_norm is not None:
            tr.gauge("pseudo_grad_norm", pg_norm)
        if self.cfg.outer_mode == "gossip":
            # pair-mix on host (the wire encode is host-side anyway), then
            # land the mixed fragment back through the plane's donated jits
            return self._outer_step_device_gossip(
                state, device_leaves, frag, pseudo_grad,
                t0=t0, t0p=t0p, wait_s=wait_s,
            )
        if self._ef is not None:
            # residual already added in the plane's jit; stage the error
            self._ef.prepare(
                "main",
                frag if frag is not None else range(len(pseudo_grad)),
                pseudo_grad,
            )

        t1 = time.monotonic()
        t1p = time.perf_counter() if tr is not None else 0.0
        try:
            averaged, group_size, _ = self._wan_all_reduce(
                pseudo_grad, timeout=self.cfg.averaging_timeout, epoch=self.epoch
            )
            self._check_group_size(group_size)
        except BaseException:
            if self._ef is not None:
                self._ef.abort("main")
            raise
        if self._ef is not None:
            self._ef.commit("main")
        allreduce_s = time.monotonic() - t1
        if tr is not None:
            tr.add_span(
                "outer/allreduce", t1p, time.perf_counter(),
                epoch=self.epoch, group=group_size,
            )
        t_apply = time.perf_counter() if tr is not None else 0.0
        log.info(
            "outer step %d: all-reduce over %d peers took %.3fs",
            self.epoch,
            group_size,
            allreduce_s,
        )

        if state_avg:
            # fused apply, then the full-state averaging leg: master D2H'd
            # on demand, averaged over the WAN, adopted back. The
            # pre-published _blocking_snap keeps onboarding fetches
            # consistent (and unblocked) throughout.
            plane.apply_average(averaged, frag)
            master_host, _ = plane.host_state()
            averaged_state, n, _ = self._wan_all_reduce(
                master_host, timeout=self.cfg.averaging_timeout, tag="state"
            )
            plane.load_masters(averaged_state)
            log.info(
                "averaged full state over %d peers at epoch %d", n, self.epoch
            )
            with plane.lock:
                leaves = plane.sync_params(device_leaves, frag)
                state["params"] = jax.tree.unflatten(self.treedef, leaves)
                with self._serve_lock:
                    self.epoch += 1
                    self.local_step = 0
                    self.samples_in_epoch = 0
                    self._blocking_snap = None
        else:
            # plane.lock spans the donating apply, the params sync, and
            # the epoch advance: a serve-thread fetch sees exactly the
            # pre- or the post-round (plane, epoch) pair, never a mix.
            # sync= folds the params <- master overwrite into the apply
            # jit (donating the old param buffers) — one dispatch and one
            # fewer full-model pass than apply + sync_params
            with plane.lock:
                leaves = plane.apply_average(
                    averaged, frag, sync=device_leaves
                )
                state["params"] = jax.tree.unflatten(self.treedef, leaves)
                with self._serve_lock:
                    self.epoch += 1
                    self.local_step = 0
                    self.samples_in_epoch = 0
        if tr is not None:
            tr.add_span(
                "outer/apply", t_apply, time.perf_counter(), epoch=self.epoch - 1
            )
        self._epoch_t0 = time.monotonic()
        outer_metrics = {
            "outer_step_s": time.monotonic() - t0,
            "outer_allreduce_s": allreduce_s,
            "outer_wait_s": wait_s,
            "num_peers": group_size,
            **self._round_health_metrics(),
        }
        if tr is not None:
            tr.add_span(
                "outer/step", t0p, time.perf_counter(),
                epoch=self.epoch - 1, group=group_size,
            )
            tr.gauge("outer_step_s", outer_metrics["outer_step_s"])
            tr.gauge("outer_allreduce_s", allreduce_s)
            tr.gauge("outer_wait_s", wait_s)
        self.last_outer_metrics = outer_metrics
        return state, outer_metrics

    def _outer_step_device_gossip(
        self,
        state: dict,
        device_leaves: list,
        frag: Optional[list[int]],
        pseudo_grad: list[np.ndarray],
        *,
        t0: float,
        t0p: float,
        wait_s: float,
    ) -> tuple[dict, dict]:
        """Gossip tail of the blocking device-placement round: the pair
        mix and NoLoCo step run on host f32 copies of this boundary's
        fragment (the pair wire encode is host-side regardless), then the
        mixed result lands back through the plane's donated H2D jits —
        the D2H/H2D still moves one fragment, not the model."""
        plane = self._plane
        tr = obs.tracer()
        idxs = frag if frag is not None else list(range(len(device_leaves)))
        masters_np, bufs_np = plane.host_frag(frag)
        if self.cfg.outer_momentum != 0.0 and bufs_np is None:
            # zeros when momentum never armed: wire shapes must be static
            bufs_np = [np.zeros_like(m) for m in masters_np]
        # NOTE: blocking-streaming keys the fragment to the epoch, so under
        # async bounded-staleness gossip two workers align on a fragment
        # only when their epoch distance is a multiple of the fragment
        # count (otherwise both self-round). The streaming-overlap path
        # syncs EVERY fragment each epoch and matches at any distance.
        frag_id = (
            self.epoch % len(self._fragments)
            if self._fragments is not None else 0
        )
        t1 = time.monotonic()
        t1p = time.perf_counter() if tr is not None else 0.0
        mix_m, mix_b, avg_g, group_size, live_peers = self._gossip_round(
            masters_np, bufs_np, pseudo_grad,
            idxs=idxs, frag_id=frag_id, epoch=self.epoch,
        )
        dropped = group_size == 0
        self._check_group_size(live_peers)
        allreduce_s = time.monotonic() - t1
        if tr is not None:
            tr.add_span(
                "outer/allreduce", t1p, time.perf_counter(),
                epoch=self.epoch, group=group_size,
            )
        t_apply = time.perf_counter() if tr is not None else 0.0
        log.info(
            "outer step %d: gossip exchange over %d peers took %.3fs",
            self.epoch, group_size, allreduce_s,
        )
        if self._is_state_avg_epoch() and not dropped:
            # NoLoCo pair mixing already averages the masters every round;
            # the periodic full-state leg would need a global collective
            # (exactly what gossip removes), so it is a no-op here
            log.debug(
                "average_state_every is redundant under gossip "
                "(masters mix every pair round); skipping"
            )
        if dropped:
            # non-event: master/momentum/EF stay put, params keep local
            # progress (next pseudo-gradient re-captures this epoch)
            with self._serve_lock:
                self.epoch += 1
                self.local_step = 0
                self.samples_in_epoch = 0
                self._blocking_snap = None
        else:
            new_m, new_b = noloco_step(
                mix_m, mix_b, avg_g,
                lr=self.cfg.outer_lr,
                momentum=self.cfg.outer_momentum,
                nesterov=self.cfg.outer_nesterov,
            )
            with plane.lock:
                leaves = plane.gossip_land(
                    frag, new_m, new_b, sync=device_leaves
                )
                state["params"] = jax.tree.unflatten(self.treedef, leaves)
                with self._serve_lock:
                    self.epoch += 1
                    self.local_step = 0
                    self.samples_in_epoch = 0
                    self._blocking_snap = None
        if tr is not None:
            tr.add_span(
                "outer/apply", t_apply, time.perf_counter(),
                epoch=self.epoch - 1,
            )
        self._epoch_t0 = time.monotonic()
        outer_metrics = {
            "outer_step_s": time.monotonic() - t0,
            "outer_allreduce_s": allreduce_s,
            "outer_wait_s": wait_s,
            "num_peers": group_size,
            **self._round_health_metrics(),
        }
        if tr is not None:
            tr.add_span(
                "outer/step", t0p, time.perf_counter(),
                epoch=self.epoch - 1, group=group_size,
            )
            tr.gauge("outer_step_s", outer_metrics["outer_step_s"])
            tr.gauge("outer_allreduce_s", allreduce_s)
            tr.gauge("outer_wait_s", wait_s)
        self.last_outer_metrics = outer_metrics
        return state, outer_metrics

    def outer_step(self, state: dict) -> tuple[dict, dict]:
        if self._plane is not None:
            return self._outer_step_device(state)
        if self._pending is not None:  # a blocking round supersedes overlap
            state = self._poll_pending(state, block=True)
        # an abandoned overlapped round (desync re-onboard -> drop_pending)
        # may still be streaming from slot 0; never write into it live
        self._drain_abandoned()
        # parameter layout must be stable across the epoch (schema-hash
        # assertion, hivemind_diloco.py:560-568,575) -- a changed pytree
        # here means silent desync, not a recoverable condition
        assert schema_fingerprint(state["params"]) == self._schema, (
            "parameter schema changed mid-epoch"
        )
        # publish the pre-round state for onboarding peers. Holds the master
        # list by reference (no copy): every mutation below rebinds
        # self.master to a freshly built list instead of writing into these
        # arrays, so the snapshot stays bit-stable for the serve thread.
        # Left in place on failure (the pre-round snapshot is the only
        # guaranteed-consistent state if the round aborts midway).
        with self._serve_lock:
            self._blocking_snap = {
                "master": self.master,
                "epoch": self.epoch,
                # refs, not copies: the round below clones-then-rebinds the
                # optimizer, so these buf arrays stay bit-stable
                "outer_opt": self.outer_opt.state_dict_refs(),
            }
        t0 = time.monotonic()
        tr = obs.tracer()
        t0p = time.perf_counter() if tr is not None else 0.0

        # overlap the D2H transfer with the straggler wait (SURVEY hard-part
        # 2): the params are final at the boundary, so fetch them while
        # polling slow peers instead of after. Streaming fragments fetch
        # ONLY this boundary's fragment -- the off-wire transfer savings
        # must match the on-wire ones
        frag: Optional[list[int]] = None
        device_leaves = jax.tree.leaves(state["params"])
        if self._fragments is not None:
            frag = self._fragments[self.epoch % len(self._fragments)]
        fetch_result: list = []

        def _fetch():
            src = (
                device_leaves
                if frag is None
                else [device_leaves[i] for i in frag]
            )
            # multihost: a mesh all-gather — every process's fetcher thread
            # issues the same collective, and each joins before the fan-out
            # broadcast below, so the per-process collective order is fixed
            fetch_result.append(self.world.gather_params(src))

        fetcher = threading.Thread(target=_fetch)
        fetcher.start()
        if self.world.is_messenger and self.cfg.outer_mode != "gossip":
            # followers skip the straggler wait: they have no peer view,
            # and they re-join the messenger at the fan-out collective.
            # Gossip skips it entirely — a pair round has no group to
            # assemble (THE point: no global barrier); the pair push-pull
            # itself bounds how long a fast worker waits on its partner.
            wait_for_peers(
                self.backend,
                target_samples=self.target_samples,
                own_epoch=self.epoch,
                strategy=self.cfg.all_reduce_strategy,
                timeout_waiting_for_peers=self.cfg.timeout_waiting_for_peers,
                log=log,
            )
        wait_s = time.monotonic() - t0
        if tr is not None:
            tr.add_span(
                "outer/barrier_wait", t0p, time.perf_counter(),
                epoch=self.epoch,
            )
        fetcher.join()
        if tr is not None:
            # D2H fetch runs concurrently with the straggler wait; the span
            # covers wait+join, i.e. until the host copy is actually ready
            tr.add_span("outer/d2h", t0p, time.perf_counter(), epoch=self.epoch)
        device_flat = fetch_result[0]

        if frag is not None:
            # streaming sync: only this boundary's fragment forms a
            # pseudo-gradient and rides the wire (fragment-sized arrays,
            # not the persistent full-model slots)
            pseudo_grad = [
                native.sub(self.master[i], d)
                for i, d in zip(frag, device_flat)
            ]
        else:
            # pseudo-gradient = master - current device params (persistent
            # slot buffer: the blocking path consumes it synchronously,
            # slot 0 only)
            pseudo_grad = self._pseudo_grad_into(device_flat, slot=0)
        if self._ef is not None:
            # residual folded into the wire pg in place (gossip keeps its
            # per-partner EF inside the GossipPlane instead, so self._ef
            # is None there and this is always the all-reduce path)
            self._ef.prepare(
                "main",
                frag if frag is not None else range(len(pseudo_grad)),
                pseudo_grad,
            )

        if tr is not None:
            # fused OMP dot (native fallback: np.dot) instead of a serial
            # per-leaf host reduction; device placement computes this norm
            # inside the pseudo-gradient jit instead (outer_device.py)
            sq = 0.0
            for g in pseudo_grad:
                sq += native.sqnorm(np.asarray(g, np.float32).reshape(-1))
            tr.gauge("pseudo_grad_norm", float(np.sqrt(sq)))

        t1 = time.monotonic()
        t1p = time.perf_counter() if tr is not None else 0.0
        gossip = self.cfg.outer_mode == "gossip"
        dropped = False
        mix_m: Optional[list[np.ndarray]] = None
        mix_b: Optional[list[np.ndarray]] = None
        if gossip:
            # NoLoCo (arxiv 2506.10911): mix (master, momentum) with ONE
            # locally-scheduled partner per round over a point-to-point
            # push-pull (diloco/gossip.py) — no barrier, no collective —
            # then run the unchanged Nesterov rule on the mixed state with
            # the pair-averaged pseudo-gradient (the modified-Nesterov
            # correction, expressed through step_mixed_indices)
            idxs = frag if frag is not None else list(range(len(self.master)))
            g_masters = [self.master[i] for i in idxs]
            g_bufs = None
            if self.cfg.outer_momentum != 0.0:
                oo = self.outer_opt
                # zeros when momentum never armed: wire shapes must be
                # static so both sides' sections always line up
                g_bufs = [
                    np.zeros_like(self.master[i]) if oo.bufs is None
                    else oo.bufs[i]
                    for i in idxs
                ]
            # under async staleness (ODTP_ASYNC_STALENESS > 0) the plane
            # free-runs: exchange matches any in-window partner on this
            # fragment instead of pairing per (epoch, fragment) — see the
            # fragment-alignment note in _outer_step_device_gossip
            frag_id = (
                self.epoch % len(self._fragments)
                if self._fragments is not None else 0
            )
            mix_m, mix_b, averaged, group_size, live_peers = (
                self._gossip_round(
                    g_masters, g_bufs, pseudo_grad,
                    idxs=idxs, frag_id=frag_id, epoch=self.epoch,
                )
            )
            dropped = group_size == 0
            # pair size says nothing about the swarm: peer-drop detection
            # (incl. fail_rank_drop) runs on the live-peer count instead
            self._check_group_size(live_peers)
        else:
            try:
                averaged, group_size, _ = self._wan_all_reduce(
                    pseudo_grad,
                    timeout=self.cfg.averaging_timeout,
                    epoch=self.epoch,
                )
                self._check_group_size(group_size)
            except BaseException:
                if self._ef is not None:
                    self._ef.abort("main")
                raise
            if self._ef is not None:
                self._ef.commit("main")
        allreduce_s = time.monotonic() - t1
        if tr is not None:
            tr.add_span(
                "outer/allreduce", t1p, time.perf_counter(),
                epoch=self.epoch, group=group_size,
            )
        t_apply = time.perf_counter() if tr is not None else 0.0
        log.info(
            "outer step %d: %s over %d peers took %.3fs",
            self.epoch,
            "gossip exchange" if gossip else "all-reduce",
            group_size,
            allreduce_s,
        )

        # clone-then-rebind: OuterSGD.step updates params AND momentum bufs
        # in place, and a serve-thread fetch may hold references to the
        # current master/buf arrays (copies happen outside the lock); every
        # live array must stay bit-stable once published
        if not dropped:
            new_master = [m.copy() for m in self.master]
            new_opt = self.outer_opt.clone()
            if gossip:
                new_opt.step_mixed_indices(
                    new_master, mix_m, mix_b, averaged,
                    frag if frag is not None else range(len(new_master)),
                )
            elif frag is not None:
                new_opt.step_indices(new_master, averaged, frag)
            else:
                new_opt.step(new_master, averaged)
            self.master = new_master
            self.outer_opt = new_opt

        # optional periodic full state averaging (hivemind
        # average_state_every, hivemind_diloco.py:634-638): corrects any
        # drift the lossy pseudo-gradient compression accumulates
        if self._is_state_avg_epoch():
            averaged_state, n, _ = self._wan_all_reduce(
                self.master, timeout=self.cfg.averaging_timeout, tag="state"
            )
            # np.array COPIES: the result views live in a pooled backend
            # buffer that the next all_reduce call reclaims (see the
            # lifetime contract on TcpBackend.all_reduce)
            self.master = [np.array(a, dtype=np.float32) for a in averaged_state]
            log.info("averaged full state over %d peers at epoch %d", n, self.epoch)

        if dropped:
            # dropped pair round: a non-event by design. Master, momentum,
            # and per-partner EF residual all stay put; the params KEEP
            # their local progress (writing the stale master back would
            # erase this epoch's inner training), so the next boundary's
            # pseudo-gradient (master - params) re-captures the update and
            # the fresh epoch key re-pairs.
            pass
        elif frag is not None:
            # streaming semantics: only the synced fragment resets to the
            # (freshly outer-stepped) master; every other leaf KEEPS its
            # local training progress AND stays on-device (the live jax
            # arrays pass through device_put untouched, so the H2D moves
            # one fragment, not the model). Its master stays frozen until
            # its own sync boundary comes around.
            merged = list(device_leaves)
            for i in frag:
                merged[i] = self.master[i]
            state["params"] = self._leaves_to_device(merged)
        else:
            state = self._write_master_to_device(state)  # [H2D]
        if tr is not None:
            # outer SGD (clone-then-rebind) + optional state averaging + H2D
            tr.add_span(
                "outer/apply", t_apply, time.perf_counter(), epoch=self.epoch
            )

        with self._serve_lock:
            self.epoch += 1
            self.local_step = 0
            self.samples_in_epoch = 0
            # master + epoch + outer_opt are all post-round now: resume
            # serving live state (a fetch sees exactly the pre- or the
            # post-round state, never a mix)
            self._blocking_snap = None
        self._epoch_t0 = time.monotonic()
        outer_metrics = {
            "outer_step_s": time.monotonic() - t0,
            "outer_allreduce_s": allreduce_s,
            "outer_wait_s": wait_s,
            "num_peers": group_size,
            **self._round_health_metrics(),
        }
        if tr is not None:
            tr.add_span(
                "outer/step", t0p, time.perf_counter(),
                epoch=self.epoch - 1, group=group_size,
            )
            tr.gauge("outer_step_s", outer_metrics["outer_step_s"])
            tr.gauge("outer_allreduce_s", allreduce_s)
            tr.gauge("outer_wait_s", wait_s)
        self.last_outer_metrics = outer_metrics
        return state, outer_metrics

    def _leaves_to_device(self, leaves: list) -> dict:
        """Flat host leaves -> sharded device params. Under multihost every
        process holds identical host values (replicated master discipline)
        and fills only its addressable shards; live jax.Arrays (streaming
        fragments' unsynced leaves) pass through untouched."""
        params = jax.tree.unflatten(self.treedef, leaves)
        shardings = self.trainer.state_shardings["params"]
        return jax.tree.map(
            lambda a, s: self.world.to_global(a, s), params, shardings
        )

    def _write_master_to_device(self, state: dict) -> dict:
        state["params"] = self._leaves_to_device(self.master)
        return state

    # ------------------------------------------------------------------
    # checkpoint integration (reference: hivemind_diloco.py:697-714)
    # ------------------------------------------------------------------

    def state_dict(self) -> dict:
        if self._pending is not None:
            log.warning(
                "state_dict() with an outer round in flight; call "
                "flush(state) first for a master that includes it"
            )
        if self._plane is not None:
            # host view either placement: checkpoints are
            # placement-portable (ckpt.py serializes numpy trees)
            master, bufs = self._plane.host_state()
            sd = {
                "master": master,
                "outer_opt": {
                    "lr": self._plane.lr,
                    "momentum": self._plane.momentum,
                    "nesterov": self._plane.nesterov,
                    "bufs": bufs,
                },
                "epoch": self.epoch,
                "local_step": self.local_step,
                "samples_in_epoch": self.samples_in_epoch,
            }
            if self._ef is not None:
                sd["ef_residual"] = self._plane.ef_host_state()
            if self._gossip is not None:
                sd["gossip_ef"] = self._gossip.host_state()
            return sd
        sd = {
            "master": [m.copy() for m in self.master],
            "outer_opt": self.outer_opt.state_dict(),
            "epoch": self.epoch,
            "local_step": self.local_step,
            "samples_in_epoch": self.samples_in_epoch,
        }
        if self._ef is not None:
            sd["ef_residual"] = self._ef.host_residuals()
        if self._gossip is not None:
            # per-partner residual ledgers (diloco/gossip.py): compression
            # debt owed to each pair link survives the checkpoint
            sd["gossip_ef"] = self._gossip.host_state()
        return sd

    def load_state_dict(self, sd: dict) -> None:
        if self._plane is not None:
            # lock order is plane.lock -> _serve_lock (the serve thread's
            # device path takes them in that order too)
            opt = sd["outer_opt"]
            with self._plane.lock:
                self._plane.load(
                    sd["master"],
                    opt.get("bufs"),
                    lr=opt.get("lr"),
                    momentum=opt.get("momentum"),
                    nesterov=opt.get("nesterov"),
                )
                if self._ef is not None:
                    # residuals are placement-portable: host-placement
                    # checkpoints may carry None entries (leaves that
                    # never committed), which load as zeros
                    self._plane.load_ef(sd.get("ef_residual"))
                # scalar mirror only; the plane owns the momentum bufs
                self.outer_opt.load_state_dict({**opt, "bufs": None})
                with self._serve_lock:
                    self._blocking_snap = None
                    self.epoch = int(sd["epoch"])
                    self.local_step = int(sd["local_step"])
                    self.samples_in_epoch = int(
                        sd.get(
                            "samples_in_epoch",
                            self.local_step * self.batch_size,
                        )
                    )
            if self._gossip is not None:
                self._gossip.load(sd.get("gossip_ef"))
            return
        with self._serve_lock:
            self._blocking_snap = None  # superseded pre-round snapshot
            self.master = [
                np.asarray(m, np.float32).copy() for m in sd["master"]
            ]
            self.outer_opt.load_state_dict(sd["outer_opt"])
            if self._ef is not None:
                self._ef.load(sd.get("ef_residual"))
            self.epoch = int(sd["epoch"])
            self.local_step = int(sd["local_step"])
            # older checkpoints lack samples_in_epoch; reconstruct so a
            # mid-epoch resume reports true progress and peers' wait_for_all
            # doesn't stall
            self.samples_in_epoch = int(
                sd.get("samples_in_epoch", self.local_step * self.batch_size)
            )
        if self._gossip is not None:
            self._gossip.load(sd.get("gossip_ef"))
