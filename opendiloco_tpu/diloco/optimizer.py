"""DiLoCoOptimizer: the algorithm orchestrator.

TPU-native re-design of the reference's ``DiLoCoOptimizer``
(open_diloco/hivemind_diloco.py:303-738) with the normative update rule of
the pure-torch driver (open_diloco/train_diloco_torch.py:336-353):

  every step:        inner AdamW step on device (jit, sharded)
  every local_steps: pseudo_grad = master - device_params        [D2H]
                     averaged    = backend.all_reduce(pseudo_grad)  [DCN]
                     outer Nesterov SGD updates host master
                     device_params <- master                     [H2D]

The master copy lives in host RAM as float32 numpy (the equivalent of
hivemind's CPU-offloaded outer optimizer, hivemind_diloco.py:399-400,
158-167). The inner jit step never changes shape/sharding across the outer
boundary, so the 500-step inner phases never recompile.
"""

from __future__ import annotations

import time
from typing import Any, Optional

import jax
import numpy as np

from opendiloco_tpu import native
from opendiloco_tpu.config import DilocoConfig
from opendiloco_tpu.diloco.backend import OuterBackend, PeerProgress, wait_for_peers
from opendiloco_tpu.diloco.outer_optimizer import OuterSGD
from opendiloco_tpu.trainer import InnerTrainer
from opendiloco_tpu.utils.debug import schema_fingerprint
from opendiloco_tpu.utils.logger import get_text_logger

log = get_text_logger(__name__)


class PeerDropError(RuntimeError):
    """Raised when a DiLoCo worker disappears and fail_rank_drop is set
    (reference: train_fsdp.py:452-457)."""


class DiLoCoOptimizer:
    """Owns inner trainer state transitions + the outer DiLoCo loop."""

    def __init__(
        self,
        trainer: InnerTrainer,
        backend: OuterBackend,
        cfg: DilocoConfig,
        state: dict,
        batch_size: int,
    ):
        self.trainer = trainer
        self.backend = backend
        self.cfg = cfg
        self.batch_size = batch_size
        self.target_samples = batch_size * cfg.local_steps

        # host master copy (float32). Flatten once; treedef is stable.
        params_np = jax.device_get(state["params"])
        flat, self.treedef = jax.tree.flatten(params_np)
        self.master: list[np.ndarray] = [
            np.array(x, dtype=np.float32) for x in flat
        ]
        self.outer_opt = OuterSGD(
            lr=cfg.outer_lr, momentum=cfg.outer_momentum, nesterov=cfg.outer_nesterov
        )

        self._schema = schema_fingerprint(state["params"])
        self.epoch = 0  # completed outer steps
        self.local_step = 0  # inner steps within current epoch
        self.samples_in_epoch = 0
        self.max_num_peers = 1
        self._epoch_t0 = time.monotonic()
        self.last_outer_metrics: dict[str, Any] = {}

        backend.serve_state(self._state_for_peers)

    # ------------------------------------------------------------------
    # onboarding (reference: load_state_from_peers, train_fsdp.py:348-349)
    # ------------------------------------------------------------------

    def _state_for_peers(self) -> dict[str, Any]:
        return {
            "master": [m.copy() for m in self.master],
            "epoch": self.epoch,
            "outer_opt": self.outer_opt.state_dict(),
        }

    def load_state_from_peers(self, state: dict) -> Optional[dict]:
        """Adopt a peer's master params/epoch; returns updated device state."""
        remote = self.backend.fetch_state()
        if remote is None:
            return None
        self.master = [np.asarray(m, np.float32).copy() for m in remote["master"]]
        self.epoch = int(remote["epoch"])
        self.outer_opt.load_state_dict(remote["outer_opt"])
        self.local_step = 0
        self.samples_in_epoch = 0
        state = self._write_master_to_device(state)
        # resume the LR schedule where the swarm is, not at warmup step 0
        return self.trainer.force_step_position(
            state, self.epoch * self.cfg.local_steps
        )

    # ------------------------------------------------------------------
    # inner step
    # ------------------------------------------------------------------

    def _behind_swarm(self) -> bool:
        """True when another peer is >=2 epochs ahead: our pseudo-gradients
        would poison the average (desync detection, hivemind_diloco.py:528-531).
        One epoch of skew is normal near boundaries."""
        for p in self.backend.peer_progress():
            if p.peer_id != self.backend.peer_id and p.epoch >= self.epoch + 2:
                return True
        return False

    def step(self, state: dict, batch: dict) -> tuple[dict, dict]:
        """One inner optimizer step; triggers the outer step at the epoch
        boundary. Returns (state, metrics)."""
        if self.local_step == 0 and self._behind_swarm():
            # discard the stale local phase and adopt the swarm state before
            # burning compute on an epoch the group has moved past
            updated = self.load_state_from_peers(state)
            if updated is not None:
                state = updated
                log.warning(
                    "desynced from swarm; re-downloaded state at epoch %d",
                    self.epoch,
                )
        state, metrics = self.trainer.train_step(state, batch)
        self.local_step += 1
        self.samples_in_epoch += self.batch_size

        # progress gossip is a synchronous rendezvous RPC on the TCP backend;
        # rate-limit it so the training loop never blocks on it per-step
        # (always report at the epoch boundary so matchmaking sees fresh state)
        now = time.monotonic()
        at_boundary = self.local_step >= self.cfg.local_steps
        if at_boundary or now - getattr(self, "_last_report", 0.0) > 0.5:
            self._last_report = now
            elapsed = max(now - self._epoch_t0, 1e-6)
            self.backend.report_progress(
                PeerProgress(
                    peer_id=self.backend.peer_id,
                    epoch=self.epoch,
                    samples=self.samples_in_epoch,
                    samples_per_second=self.samples_in_epoch / elapsed,
                    timestamp=time.time(),
                )
            )

        metrics = dict(metrics)
        metrics["epoch"] = self.epoch
        if self.local_step >= self.cfg.local_steps:
            state, outer_metrics = self.outer_step(state)
            metrics.update(outer_metrics)
        return state, metrics

    # ------------------------------------------------------------------
    # outer step (reference: _update_global_epoch, hivemind_diloco.py:570-679)
    # ------------------------------------------------------------------

    def outer_step(self, state: dict) -> tuple[dict, dict]:
        # parameter layout must be stable across the epoch (schema-hash
        # assertion, hivemind_diloco.py:560-568,575) -- a changed pytree
        # here means silent desync, not a recoverable condition
        assert schema_fingerprint(state["params"]) == self._schema, (
            "parameter schema changed mid-epoch"
        )
        t0 = time.monotonic()

        # overlap the D2H transfer with the straggler wait (SURVEY hard-part
        # 2): the params are final at the boundary, so fetch them while
        # polling slow peers instead of after
        fetch_result: list = []

        def _fetch():
            fetch_result.append(
                [
                    np.asarray(x, dtype=np.float32)
                    for x in jax.tree.leaves(jax.device_get(state["params"]))
                ]
            )

        import threading

        fetcher = threading.Thread(target=_fetch)
        fetcher.start()
        wait_for_peers(
            self.backend,
            target_samples=self.target_samples,
            own_epoch=self.epoch,
            strategy=self.cfg.all_reduce_strategy,
            timeout_waiting_for_peers=self.cfg.timeout_waiting_for_peers,
            log=log,
        )
        wait_s = time.monotonic() - t0
        fetcher.join()
        device_flat = fetch_result[0]

        # pseudo-gradient = master - current device params
        pseudo_grad = [native.sub(m, d) for m, d in zip(self.master, device_flat)]

        t1 = time.monotonic()
        averaged, group_size = self.backend.all_reduce(
            pseudo_grad, timeout=self.cfg.averaging_timeout
        )
        allreduce_s = time.monotonic() - t1
        log.info(
            "outer step %d: all-reduce over %d peers took %.3fs",
            self.epoch,
            group_size,
            allreduce_s,
        )

        if group_size < self.max_num_peers:
            msg = f"Lost a diloco worker: {group_size} < {self.max_num_peers}"
            if self.cfg.fail_rank_drop:
                raise PeerDropError(msg)
            log.warning(msg)
        self.max_num_peers = max(self.max_num_peers, group_size)

        self.outer_opt.step(self.master, averaged)

        # optional periodic full state averaging (hivemind
        # average_state_every, hivemind_diloco.py:634-638): corrects any
        # drift the lossy pseudo-gradient compression accumulates
        if (
            self.cfg.average_state_every > 0
            and (self.epoch + 1) % self.cfg.average_state_every == 0
        ):
            averaged_state, n = self.backend.all_reduce(
                self.master, timeout=self.cfg.averaging_timeout, tag="state"
            )
            self.master = [np.asarray(a, np.float32) for a in averaged_state]
            log.info("averaged full state over %d peers at epoch %d", n, self.epoch)

        state = self._write_master_to_device(state)  # [H2D]

        self.epoch += 1
        self.local_step = 0
        self.samples_in_epoch = 0
        self._epoch_t0 = time.monotonic()
        outer_metrics = {
            "outer_step_s": time.monotonic() - t0,
            "outer_allreduce_s": allreduce_s,
            "outer_wait_s": wait_s,
            "num_peers": group_size,
        }
        self.last_outer_metrics = outer_metrics
        return state, outer_metrics

    def _write_master_to_device(self, state: dict) -> dict:
        params = jax.tree.unflatten(self.treedef, self.master)
        state["params"] = jax.device_put(
            params, self.trainer.state_shardings["params"]
        )
        return state

    # ------------------------------------------------------------------
    # checkpoint integration (reference: hivemind_diloco.py:697-714)
    # ------------------------------------------------------------------

    def state_dict(self) -> dict:
        return {
            "master": [m.copy() for m in self.master],
            "outer_opt": self.outer_opt.state_dict(),
            "epoch": self.epoch,
            "local_step": self.local_step,
            "samples_in_epoch": self.samples_in_epoch,
        }

    def load_state_dict(self, sd: dict) -> None:
        self.master = [np.asarray(m, np.float32).copy() for m in sd["master"]]
        self.outer_opt.load_state_dict(sd["outer_opt"])
        self.epoch = int(sd["epoch"])
        self.local_step = int(sd["local_step"])
        # older checkpoints lack samples_in_epoch; reconstruct so a mid-epoch
        # resume reports true progress and peers' wait_for_all doesn't stall
        self.samples_in_epoch = int(
            sd.get("samples_in_epoch", self.local_step * self.batch_size)
        )
