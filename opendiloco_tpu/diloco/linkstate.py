"""Adaptive link layer for the outer data plane (``ODTP_LINK_ADAPT``).

The butterfly all-reduce historically split the flat pseudo-gradient into
*equal* parts and pumped every link with one global stripe/chunk policy —
so a single 4x-slower WAN link gated the whole galaxy (the NoLoCo
slowest-participant pathology, arXiv 2506.10911). This module closes the
measure->react loop on telemetry the planes already produce:

- :class:`LinkEstimator` keeps EWMA goodput + RTT per peer from the actual
  bulk/wire transfer timings (seeded by an optional micro-probe at first
  contact) and publishes a compact per-worker link vector.
- The vector gossips inside the worker's ``progress`` dict, which both the
  python and native rendezvous daemons store and replay VERBATIM — so a
  ``join_group`` reply already hands every member an identical snapshot of
  the galaxy's link matrix, with zero daemon changes.
- :func:`planner.plan_bounds` (diloco/planner.py — re-exported here) turns
  that shared snapshot into butterfly part bounds proportional to measured
  capacity (min-share floor, per-round re-planning); determinism comes
  from planning *only* from the shared group snapshot, and
  :func:`planner.plan_hash` rides every push/result frame so a divergent
  plan fails loudly instead of corrupting the reduce.
- :func:`stripes_for` / :func:`chunk_elems_for` derive per-link stripe
  counts and pipeline chunk sizes from bandwidth x RTT (BDP) instead of
  the global ``ODTP_BULK_STREAMS`` / ``ODTP_PIPELINE_CHUNK_MB`` knobs;
  :func:`hedge_deadline_s` gives the bulk plane its straggler-hedging
  deadline.

Everything is inert while ``ODTP_LINK_ADAPT`` is unset: the uniform
butterfly runs exactly as before (parity-tested in tests/test_linkstate.py).

Stability knobs (read per call so tests and benches can flip them):

- ``ODTP_LINK_ADAPT``        master switch (default off).
- ``ODTP_LINK_MIN_SHARE``    floor on a part's share of the uniform size
                             (default 0.25: a slow peer still owns >= 1/4
                             of an equal part — it must not be starved out
                             of the information flow entirely).
- ``ODTP_LINK_HYST``         publish-side hysteresis (default 0.25): a
                             peer's published estimate only moves when the
                             live EWMA drifts >25% from the published
                             value, so plans stay stable round to round.
- ``ODTP_LINK_ALPHA``        EWMA smoothing factor (default 0.4).
- ``ODTP_LINK_PROBE_BYTES``  micro-probe payload (default 256 KiB; 0
                             disables the bandwidth probe, RTT-only).
- ``ODTP_LINK_HEDGE_FACTOR`` stripe lateness multiple before a hedge
                             re-dispatch (default 3.0; 0 disables).
"""

from __future__ import annotations

import math
import os
import threading
from typing import Any, Optional

from opendiloco_tpu.utils.logger import get_text_logger

log = get_text_logger(__name__)

# link vectors carry a version so a future incompatible layout can coexist
# with old peers (a mismatched/missing version simply forces uniform plans)
LINK_VEC_VERSION = 1

# samples smaller than this are RTT-dominated and would poison the goodput
# EWMA (a 2 KB control frame "measures" the syscall, not the link)
_MIN_SAMPLE_BYTES = 64 * 1024

# samples this large get the full EWMA weight; smaller ones fold in
# proportionally less. Per-transfer elapsed time on a contended box is
# noise-dominated for short transfers (a scheduler stall is a fixed cost,
# so it distorts a 1 MB sample 8x harder than an 8 MB one) — and once the
# planner shrinks a part, that worker's fan-back samples get SMALLER,
# which un-weighted would spiral its estimate (and share) to the floor.
# Byte-weighting approximates total-bytes/total-time, which is the
# quantity the planner actually wants.
_FULL_WEIGHT_BYTES = 4 << 20

# the BDP->stripe conversion assumes one TCP stream keeps roughly a 4 MB
# window in flight (matches the SO_SNDBUF/SO_RCVBUF tuning in wire/bulk)
_STREAM_WINDOW_BYTES = 4 << 20


def enabled() -> bool:
    """Master switch; read per call (one env dict hit) like chaos.plane()."""
    return os.environ.get("ODTP_LINK_ADAPT", "").lower() in ("1", "true", "on")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def min_share() -> float:
    """Floor on a part's share of the uniform 1/n size, clamped to (0, 1]."""
    return min(1.0, max(0.01, _env_float("ODTP_LINK_MIN_SHARE", 0.25)))


def hysteresis() -> float:
    return max(0.0, _env_float("ODTP_LINK_HYST", 0.25))


def probe_bytes() -> int:
    return max(0, int(_env_float("ODTP_LINK_PROBE_BYTES", float(256 << 10))))


def hedge_factor() -> float:
    return max(0.0, _env_float("ODTP_LINK_HEDGE_FACTOR", 3.0))


class LinkEstimator:
    """Per-peer EWMA goodput/RTT from real transfer timings.

    Thread-safe: observations land from bulk executor threads and the
    asyncio event loop; publication happens on announce paths.

    ``publish()`` applies hysteresis: the *published* value for a peer only
    tracks the live EWMA once it drifts more than ``ODTP_LINK_HYST``
    relative — every consumer plans from published values, so the galaxy's
    plan doesn't flap on per-round measurement noise.
    """

    def __init__(self, own_id: str, alpha: Optional[float] = None):
        self.own_id = own_id
        self.alpha = alpha if alpha is not None else min(
            1.0, max(0.05, _env_float("ODTP_LINK_ALPHA", 0.4))
        )
        self._lock = threading.Lock()
        # peer_id -> [m_x, m_y, m_xx, m_xy, n_bps, rtt_s_ewma, n_rtt]:
        # exponentially-weighted moments of (nbytes, elapsed) samples.
        # The rate estimate fits elapsed = overhead + nbytes/rate, so a
        # fixed per-transfer cost (RTT, scheduler stall on a contended
        # box) lands in the intercept instead of biasing small transfers
        # slow — without this, a worker whose part the planner shrinks
        # MEASURES slower on its smaller sends and spirals to the floor.
        self._est: dict[str, list[float]] = {}
        # peer_id -> {"bps": ..., "rtt_ms": ...} as last published
        self._published: dict[str, dict[str, float]] = {}
        # latest remote vectors (peer_id -> their published vec), kept for
        # observability (the full link matrix view); planning reads the
        # join_group snapshot instead, which is the deterministic source
        self._remote: dict[str, dict] = {}

    # -- observations ------------------------------------------------------

    @staticmethod
    def _new_ent() -> list:
        return [0.0, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0]

    @staticmethod
    def _rate(ent: list) -> Optional[float]:
        """Rate estimate for one peer from the weighted moments.

        When the sample sizes vary (the adaptive regime: every worker
        sends both push parts and its own fan-back part, two distinct
        sizes per peer per round), the regression slope var(x)/cov(x, y)
        inverts to the link rate with the fixed overhead removed. When
        they don't (cold start, uniform plans), the ratio m_x/m_y — the
        byte-weighted mean goodput — is the best available figure and is
        exactly the old naive estimate."""
        if ent[4] == 0:
            return None
        m_x, m_y, m_xx, m_xy = ent[0], ent[1], ent[2], ent[3]
        if m_y <= 0.0:
            return None
        ratio = m_x / m_y
        var = m_xx - m_x * m_x
        cov = m_xy - m_x * m_y
        if var > 0.05 * m_x * m_x and cov > 0.0:
            rate = var / cov
            # a noise-dominated slope can explode; the ratio (which still
            # CONTAINS the overhead, so it underestimates) bounds it
            if 0.0 < rate < 20.0 * ratio and math.isfinite(rate):
                return rate
        return ratio if ratio > 0.0 and math.isfinite(ratio) else None

    def observe_send(self, peer_id: str, nbytes: int, seconds: float) -> None:
        """Fold one outbound transfer (payload bytes / wall seconds)."""
        if nbytes < _MIN_SAMPLE_BYTES or seconds <= 0.0:
            return
        if not math.isfinite(seconds):
            return
        x, y = float(nbytes), float(seconds)
        w = self.alpha * min(1.0, nbytes / _FULL_WEIGHT_BYTES)
        with self._lock:
            ent = self._est.setdefault(peer_id, self._new_ent())
            if ent[4] == 0:
                ent[0], ent[1], ent[2], ent[3] = x, y, x * x, x * y
            else:
                ent[0] = w * x + (1.0 - w) * ent[0]
                ent[1] = w * y + (1.0 - w) * ent[1]
                ent[2] = w * x * x + (1.0 - w) * ent[2]
                ent[3] = w * x * y + (1.0 - w) * ent[3]
            ent[4] += 1

    def observe_rtt(self, peer_id: str, seconds: float) -> None:
        if seconds <= 0.0 or not math.isfinite(seconds):
            return
        with self._lock:
            ent = self._est.setdefault(peer_id, self._new_ent())
            ent[5] = seconds if ent[6] == 0 else (
                self.alpha * seconds + (1.0 - self.alpha) * ent[5]
            )
            ent[6] += 1

    def seed(self, peer_id: str, bps: float, rtt_s: float) -> None:
        """Micro-probe seeding: only fills peers with no real samples yet
        (a probe must never override goodput measured on actual parts)."""
        with self._lock:
            ent = self._est.setdefault(peer_id, self._new_ent())
            if ent[4] == 0 and bps > 0 and math.isfinite(bps):
                # one synthetic full-weight sample at the probed rate
                x, y = float(_FULL_WEIGHT_BYTES), _FULL_WEIGHT_BYTES / bps
                ent[0], ent[1], ent[2], ent[3] = x, y, x * x, x * y
                ent[4] = 1
            if ent[6] == 0 and rtt_s > 0 and math.isfinite(rtt_s):
                ent[5] = rtt_s
                ent[6] = 1

    def needs_probe(self, peer_id: str) -> bool:
        with self._lock:
            ent = self._est.get(peer_id)
            return ent is None or ent[4] == 0

    # -- queries -----------------------------------------------------------

    def bps_to(self, peer_id: str) -> Optional[float]:
        with self._lock:
            ent = self._est.get(peer_id)
            return self._rate(ent) if ent else None

    def rtt_to(self, peer_id: str) -> Optional[float]:
        with self._lock:
            ent = self._est.get(peer_id)
            return ent[5] if ent and ent[6] else None

    # -- gossip ------------------------------------------------------------

    def publish(self) -> dict:
        """The link vector that rides this worker's progress announces.

        Hysteresis happens HERE, not at observation time: the EWMA keeps
        tracking reality, but the published (and therefore planned-on)
        value only follows once the drift exceeds the threshold.
        """
        hyst = hysteresis()
        with self._lock:
            for pid, ent in self._est.items():
                pub = self._published.setdefault(pid, {})
                bps = self._rate(ent)
                if bps is not None:
                    old = pub.get("bps", 0.0)
                    if old <= 0.0 or abs(bps - old) > hyst * old:
                        pub["bps"] = round(bps, 1)
                if ent[6]:
                    old_ms = pub.get("rtt_ms", 0.0)
                    new_ms = ent[5] * 1e3
                    if old_ms <= 0.0 or abs(new_ms - old_ms) > hyst * old_ms:
                        pub["rtt_ms"] = round(new_ms, 3)
            peers = {
                pid: dict(v) for pid, v in self._published.items() if v
            }
        return {"v": LINK_VEC_VERSION, "peers": peers}

    def published_capacity(self) -> Optional[float]:
        """Median published egress bps across peers — the one-number link
        capacity that rides this worker's overseer health roll-up (the
        per-peer vector already travels separately as ``links``)."""
        with self._lock:
            rates = sorted(
                v["bps"] for v in self._published.values() if v.get("bps")
            )
        if not rates:
            return None
        mid = len(rates) // 2
        return rates[mid] if len(rates) % 2 else 0.5 * (
            rates[mid - 1] + rates[mid])

    def merge_remote(self, peer_id: str, vec: Any) -> None:
        """Keep the latest remote link vector (observability only)."""
        if peer_id == self.own_id or not isinstance(vec, dict):
            return
        if int(vec.get("v", 0) or 0) != LINK_VEC_VERSION:
            return
        with self._lock:
            self._remote[peer_id] = vec

    def matrix(self) -> dict[str, dict]:
        """own + remote published vectors: the galaxy link matrix as this
        worker currently sees it (obs report / debugging)."""
        own = self.publish()
        with self._lock:
            out = {pid: dict(v) for pid, v in self._remote.items()}
        out[self.own_id] = own
        return out


# -- link-vector access (planner input) ---------------------------------------


def _member_links(member: dict) -> Optional[dict]:
    vec = (member.get("progress") or {}).get("links")
    if not isinstance(vec, dict):
        return None
    if int(vec.get("v", 0) or 0) != LINK_VEC_VERSION:
        return None
    peers = vec.get("peers")
    return peers if isinstance(peers, dict) else {}


def member_health(member: dict) -> Optional[dict]:
    """The overseer health roll-up riding a registry/group-snapshot
    member, if any. Version checking stays with the overseer's merge
    (obs/overseer.py) — this is pure extraction, kept here next to
    ``_member_links`` because the two ride the identical channel."""
    vec = (member.get("progress") or {}).get("health")
    return vec if isinstance(vec, dict) else None


# The partition-planning functions (group_capacities, plan_shares,
# plan_bounds, plan_hash, shares_of) moved to diloco/planner.py — the one
# module every transport plans through. Re-exported lazily below so
# existing callers (and the published linkstate API) keep working without
# a circular import at module load.

_PLANNER_EXPORTS = (
    "group_capacities", "plan_shares", "plan_bounds", "plan_hash", "shares_of",
)


def __getattr__(name: str):
    if name in _PLANNER_EXPORTS:
        from opendiloco_tpu.diloco import planner

        return getattr(planner, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


# -- BDP-derived transport parameters -----------------------------------------


def stripes_for(
    nbytes: int, bps: float, rtt_s: float, max_streams: Optional[int] = None
) -> int:
    """Stripe count for one bulk transfer from bandwidth x delay.

    One TCP stream sustains roughly window/RTT; the link needs
    ceil(BDP / window) streams to stay full. Clamped to [1, max_streams]
    (default: 2x the static ODTP_BULK_STREAMS knob) and never more than
    one stripe per MB of payload (tiny stripes cost more in thread/frame
    overhead than they recover)."""
    if max_streams is None:
        try:
            max_streams = 2 * max(
                1, int(os.environ.get("ODTP_BULK_STREAMS", "4"))
            )
        except ValueError:
            max_streams = 8
    if bps <= 0 or rtt_s < 0:
        return 1
    bdp = bps * max(rtt_s, 1e-4)
    want = int(math.ceil(bdp / _STREAM_WINDOW_BYTES))
    cap = max(1, nbytes // (1 << 20))
    return max(1, min(want, max_streams, cap))


def chunk_elems_for(bps: float, rtt_s: float, fallback: int, align: int = 1) -> int:
    """Pipeline chunk size (f32 elements) for one destination: grown from
    the static default toward one BDP per chunk, capped at 32 MiB of
    payload. Never SMALLER than ``fallback`` (the static chunk knob): BDP
    sizing exists to keep fat links full; shrinking chunks below the
    default only multiplies per-chunk overhead — and on a contended box
    that extra overhead feeds back into a lower goodput estimate, which
    would shrink the chunk further.

    ``align`` rounds the result down to the codec's ``chunk_align``
    granularity (never below ``align`` itself) so chunk boundaries stay on
    block/nibble multiples — blockwise codecs need block-grid-aligned
    chunks and 4-bit packing needs even element counts."""
    if bps <= 0:
        ce = fallback
    else:
        bdp = bps * max(rtt_s, 1e-3)
        nbytes = min(max(bdp, 4.0 * fallback), float(32 << 20))
        ce = max(fallback, int(nbytes) // 4)
    if align > 1:
        ce = max(align, ce - (ce % align))
    return ce


def hedge_deadline_s(nbytes: int, bps: float, rtt_s: float, streams: int) -> float:
    """How long a stripe may lag before it is re-dispatched over another
    connection. ``bps`` is the whole link's estimate; each of ``streams``
    concurrent stripes gets ~1/streams of it. 0 disables hedging."""
    factor = hedge_factor()
    if factor <= 0.0 or bps <= 0.0:
        return 0.0
    expected = nbytes * max(1, streams) / bps
    return factor * expected + 2.0 * max(rtt_s, 0.0) + 0.25
